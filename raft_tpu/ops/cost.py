"""Analytical cost model for the hand-written Pallas kernels.

The PerfLedger's roofline attribution (obs/perf.py, obs/cost.py) reads
flops / bytes from XLA's compiled cost analysis.  That works for the XLA
legs, but a Pallas kernel is an opaque custom call on TPU — XLA reports
nothing for it, so every ``kernel_path=pallas`` key used to show up in
``top_hotspots()`` with blank flops/s / bytes/s / roofline columns.

This module is the one owner of the per-kernel analytical cost formulas:

- each kernel wrapper **notes** its cost at trace time
  (:func:`note` inside a :func:`capture` scope opened by
  ``obs.cost.analyze_callable``), so compiled-cost reports can be
  supplemented exactly where XLA came back empty;
- the same :class:`KernelCost` converts to a ``pl.CostEstimate``
  (:meth:`KernelCost.as_pallas`) handed to ``pallas_call`` so the TPU
  scheduler sees honest numbers too.

Formulas count *algorithmic* work (VPU compare/select ops and MXU
multiply-adds) and *HBM-crossing* bytes — VMEM-resident scratch traffic is
deliberately excluded, matching what XLA's cost analysis counts for the
equivalent HLO and keeping the pallas/XLA roofline columns comparable.
"""

from __future__ import annotations

import contextlib
import threading
from dataclasses import dataclass
from typing import Iterator, List, Optional, Tuple


@dataclass(frozen=True)
class KernelCost:
    """flops / bytes_accessed / transcendentals of one kernel dispatch."""

    flops: int
    bytes_accessed: int
    transcendentals: int = 0

    def __add__(self, other: "KernelCost") -> "KernelCost":
        return KernelCost(
            self.flops + other.flops,
            self.bytes_accessed + other.bytes_accessed,
            self.transcendentals + other.transcendentals,
        )

    def as_pallas(self):
        """The ``pl.CostEstimate`` handed to ``pallas_call`` (imported
        lazily so this module stays importable without Pallas)."""
        from jax.experimental import pallas as pl

        return pl.CostEstimate(
            flops=int(self.flops),
            bytes_accessed=int(self.bytes_accessed),
            transcendentals=int(self.transcendentals),
        )


# ---------------------------------------------------------------------------
# per-kernel formulas (one owner each; kernels import these, never inline)


def select_k_cost(rows: int, n: int, k: int, *, itemsize: int = 4) -> KernelCost:
    """kernels/select_k.py: k rounds of masked min-extraction over
    [rows, n] — each round is ~6 elementwise compare/select passes (mask,
    min, tie-min, first-position, payload pick, removal)."""
    flops = 6 * rows * n * k
    # in: values + tie keys + payloads; out: k values + k ids per row
    bytes_accessed = rows * n * (itemsize + 8) + rows * k * (itemsize + 4)
    return KernelCost(int(flops), int(bytes_accessed))


def cagra_traverse_cost(
    tile: int, width: int, deg: int, d: int, itopk: int, *, itemsize: int = 4
) -> KernelCost:
    """kernels/cagra_traverse.py: one fused hop — per (query, parent):
    MXU scoring of deg neighbor rows (2·deg·d MACs), dedup membership
    (deg·itopk compares) and a fold_topk merge (itopk rounds over
    itopk+deg candidates)."""
    per_parent = (
        2 * deg * d                      # MXU candidate scoring
        + deg * itopk                    # visited-dedup membership
        + 6 * itopk * (itopk + deg)      # fold_topk extraction rounds
    )
    flops = tile * width * per_parent
    bytes_accessed = tile * width * (
        deg * d * itemsize               # dataset rows DMA'd per parent
        + deg * 4                        # neighbor-list block
    ) + tile * (d * itemsize + 3 * itopk * 4 * 2)  # queries + buffers in/out
    return KernelCost(int(flops), int(bytes_accessed))


def ivf_scan_cost(
    n_blocks: int, g: int, cap: int, rot: int, kk: int, *, itemsize: int = 4
) -> KernelCost:
    """kernels/ivf_scan.py (both schedules): per (block, list) — MXU
    scoring of a [g, cap] tile against [cap, rot] rows plus the VMEM
    fold; ``n_blocks`` counts (bucket) or (query-block · probe) steps."""
    per_block = 2 * g * cap * rot + 6 * kk * (kk + cap) * g
    flops = n_blocks * per_block
    bytes_accessed = n_blocks * (
        cap * rot * itemsize + cap * 8 + g * rot * 4
    ) + n_blocks * g * kk * 8
    return KernelCost(int(flops), int(bytes_accessed))


def fused_knn_cost(
    n_q: int, n: int, d: int, k: int, *, itemsize: int = 4
) -> KernelCost:
    """kernels/fused_knn.py: tiled brute-force distance + per-tile
    fold — 2·d MACs per (query, row) pair plus the running-k merge."""
    flops = n_q * n * (2 * d + 6 * k)
    bytes_accessed = (
        (n_q + n) * d * itemsize     # queries + dataset tiles
        + n * itemsize               # sqnorm row
        + n_q * k * (itemsize + 4)   # (value, id) outputs
    )
    return KernelCost(int(flops), int(bytes_accessed))


def fused_argmin_cost(
    n: int, n_centers: int, d: int, *, itemsize: int = 4
) -> KernelCost:
    """kernels/fused_argmin.py: 1-NN assignment — 2·d MACs per
    (row, center) pair plus the per-tile running argmin."""
    flops = n * n_centers * (2 * d + 3)
    bytes_accessed = (
        (n + n_centers) * d * itemsize
        + n_centers * itemsize
        + n * (itemsize + 4)
    )
    return KernelCost(int(flops), int(bytes_accessed))


# ---------------------------------------------------------------------------
# trace-time capture: kernels note their cost while a lowering is being
# traced; obs.cost.analyze_callable opens the scope and folds the noted
# totals into compiled-cost reports where XLA reported nothing (TPU's
# opaque custom-call case)

_tls = threading.local()


@contextlib.contextmanager
def capture() -> Iterator[List[Tuple[str, KernelCost]]]:
    """Collect every :func:`note` issued while the scope is open (e.g.
    during a ``jax.jit(...).lower(...)`` trace).  Nested scopes shadow —
    the inner scope owns the notes."""
    prev = getattr(_tls, "notes", None)
    _tls.notes = []
    try:
        yield _tls.notes
    finally:
        _tls.notes = prev


def note(name: str, cost: KernelCost) -> None:
    """Record one kernel dispatch's analytical cost (no-op outside a
    :func:`capture` scope — kernels call this unconditionally)."""
    notes = getattr(_tls, "notes", None)
    if notes is not None:
        notes.append((name, cost))


def noted_total(
    notes: List[Tuple[str, KernelCost]]
) -> Optional[KernelCost]:
    """Sum a capture scope's notes (None when nothing was noted)."""
    if not notes:
        return None
    total = KernelCost(0, 0, 0)
    for _, c in notes:
        total = total + c
    return total
