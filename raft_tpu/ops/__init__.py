"""Dense primitives: linear algebra + matrix ops (ref: raft/{linalg,matrix}/)."""

from raft_tpu.ops import linalg, matrix
from raft_tpu.ops.matrix import select_k

__all__ = ["linalg", "matrix", "select_k"]
