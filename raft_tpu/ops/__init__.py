"""Dense primitives: linear algebra + matrix ops (ref: raft/{linalg,matrix}/)."""

from raft_tpu.ops import cost, linalg, matrix
from raft_tpu.ops.matrix import select_k

__all__ = ["cost", "linalg", "matrix", "select_k"]
