"""Matrix primitives: batched k-selection, arg-reductions, gather, sampling.

TPU re-design of the reference matrix layer (ref: cpp/include/raft/matrix/ —
select_k.cuh, argmax.cuh, argmin.cuh, gather.cuh, sample_rows.cuh,
col_wise_sort.cuh, slice.cuh).

``select_k`` is the single most load-bearing primitive for vector search
(SURVEY §2.4): the reference ships radix ("AIR Top-k") and warpsort-bitonic
CUDA kernel families with a data-driven algorithm heuristic
(ref: matrix/detail/select_k-inl.cuh:47-75, select_radix.cuh,
select_warpsort.cuh). On TPU there are no warp shuffles or shared memory;
XLA's native ``lax.top_k`` lowers to an efficient sort-based TopK on the VPU,
and for tiny k a threshold-free iterative-argmax variant wins. We keep the
reference's *interface* (batched rows, select_min, optional input indices,
sorted output) and its heuristic-dispatch *idea*, with TPU-appropriate
algorithm choices.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from raft_tpu.core.trace import traced
from raft_tpu.core import validation


def _min_identity(dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.array(jnp.inf, dtype)
    return jnp.array(jnp.iinfo(dtype).max, dtype)


def _max_identity(dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.array(-jnp.inf, dtype)
    return jnp.array(jnp.iinfo(dtype).min, dtype)


#: Row length above which the two-stage chunked select beats one wide
#: ``lax.top_k`` (sort width drops from n to max(chunk, k·n/chunk)). The
#: TPU-measured analog of the reference's offline-trained decision tree
#: (ref: matrix/detail/select_k-inl.cuh:47-75); tune with
#: ``python -m raft_tpu.bench.prims --filter select_k``.
_CHUNKED_MIN_N = 8192
_CHUNK = 2048


def _select_k_chunked(scores: jax.Array, k: int, select_min: bool):
    """Multi-level tournament select for long rows: per-chunk top-k on
    [B, n/c, c] (one batched narrow sort per level), repeated while the
    survivor pool is still wide, then a final top-k. The TPU stand-in for
    the reference's multi-pass radix path (ref:
    matrix/detail/select_radix.cuh) — same goal (never one full-width
    sort), expressed as a few batched narrow sorts instead of histogram
    passes. The chunk width scales with k (≥4k) so every level shrinks the
    pool ≥4×, which keeps large-k selections (k≫_CHUNK) from degenerating
    into a full-width sort (VERDICT r2 weak: large-k coverage)."""
    b, n = scores.shape
    neg_fill = jnp.array(-jnp.inf, scores.dtype)
    c = max(_CHUNK, 4 * (1 << max(k - 1, 1).bit_length()))
    cur_v = -scores if select_min else scores
    cur_i = None  # None ⇒ identity position mapping
    while cur_v.shape[-1] > max(2 * c, 2 * k):
        n_cur = cur_v.shape[-1]
        n_chunks = -(-n_cur // c)
        if n_chunks * k >= n_cur:
            break  # a level must shrink the pool
        pad = n_chunks * c - n_cur
        if pad:
            cur_v = jnp.concatenate(
                [cur_v, jnp.full((b, pad), neg_fill, scores.dtype)], axis=-1
            )
        v1, i1 = lax.top_k(cur_v.reshape(b, n_chunks, c), k)
        base = (jnp.arange(n_chunks, dtype=jnp.int32) * c)[None, :, None]
        flat_i = (i1.astype(jnp.int32) + base).reshape(b, n_chunks * k)
        if cur_i is not None:
            flat_i = jnp.take_along_axis(cur_i, flat_i, axis=-1)
        cur_v = v1.reshape(b, n_chunks * k)
        cur_i = flat_i
    v2, i2 = lax.top_k(cur_v, k)
    idx = (
        jnp.take_along_axis(cur_i, i2, axis=-1)
        if cur_i is not None
        else i2.astype(jnp.int32)
    )
    vals = -v2 if select_min else v2
    return vals.astype(scores.dtype), idx.astype(jnp.int32)


def mask_row_k(
    vals: jax.Array,
    idx: jax.Array,
    row_k: jax.Array,
    *,
    select_min: bool = True,
) -> Tuple[jax.Array, jax.Array]:
    """Demote result columns past each row's own k: positions ≥ row_k[r]
    become (worst value, id −1).

    The ragged serving path runs every request at the bucket's static
    ``k_max`` — per-request ``k`` rides as data, so one executable covers
    any k mix — and this mask restores per-row semantics before futures
    slice their own top-k (SNIPPETS idiom: k as operand, not shape)."""
    kk = vals.shape[-1]
    pos = jnp.arange(kk, dtype=jnp.int32)
    keep = pos[None, :] < jnp.asarray(row_k, jnp.int32).reshape(-1, 1)
    worst = (
        _min_identity(vals.dtype) if select_min else _max_identity(vals.dtype)
    )
    return jnp.where(keep, vals, worst), jnp.where(keep, idx, jnp.int32(-1))


@traced("matrix.select_k")
def select_k(
    scores: jax.Array,
    k: int,
    *,
    select_min: bool = True,
    input_indices: Optional[jax.Array] = None,
    sorted: bool = True,
    algo: str = "auto",
    row_k: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Batched top-k selection (ref: matrix/select_k.cuh API).

    Args:
      scores: [batch, n] (or [n]) score matrix.
      k: number of elements to select per row (static).
      select_min: True → smallest-k (distances), False → largest-k.
      input_indices: optional [batch, n] source indices to emit instead of
        positions (the reference's ``in_idx`` — used by tiled kNN merges).
      sorted: whether rows of the result must be sorted (ascending for
        select_min, descending otherwise). XLA top_k always sorts, so this
        is free; the flag is kept for interface parity.
      algo: "auto" (heuristic, ref select_k-inl.cuh:47 idea), "topk"
        (single wide ``lax.top_k``), or "chunked" (two-stage tournament,
        the large-n analog of the reference's radix path).
      row_k: optional [batch] int per-row effective k ≤ k; columns past a
        row's own k are demoted via :func:`mask_row_k` (ragged batches).

    Returns:
      (values [batch, k], indices [batch, k]); indices are int32 positions
      into the row (or gathered from input_indices).

    Examples
    --------
    >>> import numpy as np
    >>> from raft_tpu.ops.matrix import select_k
    >>> v, i = select_k(np.asarray([[4.0, 1.0, 3.0, 2.0]]), 2)
    >>> np.asarray(v).tolist(), np.asarray(i).tolist()
    ([[1.0, 2.0]], [[1, 3]])
    """
    if algo not in ("auto", "topk", "chunked"):
        raise ValueError(f"unknown select_k algo {algo!r}")
    squeeze = scores.ndim == 1
    if squeeze:
        scores = scores[None, :]
    n = scores.shape[-1]
    if k > n:
        raise ValueError(f"k={k} larger than row length {n}")

    is_int = jnp.issubdtype(scores.dtype, jnp.integer)
    if is_int and algo == "chunked":
        # integer rows use the exact argsort path (top_k would need an
        # unsafe negate/float promotion); refuse rather than silently
        # ignore the explicit algorithm request
        raise validation.LogicError(
            "select_k algo='chunked' unsupported for integer dtypes"
        )
    if not is_int and (
        algo == "chunked"
        or (algo == "auto" and n >= _CHUNKED_MIN_N and 4 * k <= n)
    ):
        vals, idx = _select_k_chunked(scores, k, select_min)
        if input_indices is not None:
            if input_indices.ndim == 1:
                input_indices = input_indices[None, :]
            idx = jnp.take_along_axis(input_indices, idx, axis=-1)
        if row_k is not None:
            vals, idx = mask_row_k(vals, idx, row_k, select_min=select_min)
        if squeeze:
            return vals[0], idx[0]
        return vals, idx

    # fused Pallas k-selection (kernels/select_k.py): a VMEM-resident
    # masked-extraction top-k replaces the sort-based lax.top_k for the
    # serving shapes — exact match including the lowest-position-wins tie
    # break, so the routing is invisible to every caller.  Only the "auto"
    # heuristic routes; an explicit algo= request is honored verbatim.
    if not is_int and algo == "auto":
        from raft_tpu import kernels as _kernels

        if _kernels.use_pallas() and _kernels.select_k_enabled():
            from raft_tpu.kernels import select_k as _sk

            if _sk.select_k_supported(n, k, scores.dtype):
                ii = input_indices
                if ii is not None and ii.ndim == 1:
                    ii = ii[None, :]
                vals, idx = _sk.select_k_pallas(
                    scores, k, select_min=select_min, input_indices=ii,
                    interpret=_kernels.interpret_mode(),
                )
                if row_k is not None:
                    vals, idx = mask_row_k(
                        vals, idx, row_k, select_min=select_min
                    )
                if squeeze:
                    return vals[0], idx[0]
                return vals, idx

    if is_int:
        # integers can't be safely negated (INT_MIN) or promoted to float
        # (f32 loses exactness above 2^24); use an exact argsort instead
        order = jnp.argsort(scores, axis=-1)
        if not select_min:
            order = order[..., ::-1]
        idx = order[..., :k].astype(jnp.int32)
        vals = jnp.take_along_axis(scores, idx, axis=-1)
    elif select_min:
        # negate to reuse XLA's max-top_k; handles inf padding correctly
        vals, idx = lax.top_k(-scores, k)
        vals = (-vals).astype(scores.dtype)
        idx = idx.astype(jnp.int32)
    else:
        vals, idx = lax.top_k(scores, k)
        vals = vals.astype(scores.dtype)
        idx = idx.astype(jnp.int32)

    if input_indices is not None:
        if input_indices.ndim == 1:
            input_indices = input_indices[None, :]
        idx = jnp.take_along_axis(input_indices, idx, axis=-1)

    if row_k is not None:
        vals, idx = mask_row_k(vals, idx, row_k, select_min=select_min)

    if squeeze:
        return vals[0], idx[0]
    return vals, idx


def select_k_stable(
    scores: jax.Array,
    k: int,
    *,
    select_min: bool = True,
    input_indices: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Tie-stable k-selection over a (small) candidate pool.

    Like :func:`select_k`, but equal scores are resolved by the *smallest
    accompanying index* — a lexicographic ``(value, id)`` sort — instead of
    by position in the row.  This is the property cross-partition merges
    need: positional order in a concatenated candidate row depends on which
    shard/tile contributed each candidate, so positional tie-breaking makes
    the merged ids a function of the physical layout.  With id
    tie-breaking, the same logical candidate set yields the same ids no
    matter how it was partitioned.

    Implementation is one full-width two-key ``lax.sort`` — intended for
    merge widths (n_parts·k candidates), not for raw [batch, n] scans where
    :func:`select_k`'s top_k/chunked paths are cheaper.

    Note: for integer ``scores`` with ``select_min=False`` the key is
    negated in int64, which is exact for int32 and below (int64 inputs at
    INT64_MIN would overflow — unused by any caller).
    """
    squeeze = scores.ndim == 1
    if squeeze:
        scores = scores[None, :]
        if input_indices is not None and input_indices.ndim == 1:
            input_indices = input_indices[None, :]
    n = scores.shape[-1]
    if k > n:
        raise ValueError(f"k={k} larger than row length {n}")
    # fused Pallas stable selection (kernels/select_k.py, smallest-id tie
    # key): one routing point covers merge_topk, the cross-shard merge leg
    # (serve/shard.py _make_local) and the ragged mask_row_k path without
    # touching any call site — the kernel's full row stays in VMEM instead
    # of the two-key sort's HBM round-trip.
    if not jnp.issubdtype(scores.dtype, jnp.integer):
        from raft_tpu import kernels as _kernels

        if _kernels.use_pallas() and _kernels.select_k_enabled():
            from raft_tpu.kernels import select_k as _sk

            if _sk.select_k_supported(n, k, scores.dtype):
                vals, sids = _sk.select_k_pallas(
                    scores, k, select_min=select_min, stable=True,
                    input_indices=input_indices,
                    interpret=_kernels.interpret_mode(),
                )
                if squeeze:
                    return vals[0], sids[0]
                return vals, sids
    if input_indices is None:
        ids = jnp.broadcast_to(
            jnp.arange(n, dtype=jnp.int32), scores.shape
        )
    else:
        ids = input_indices.astype(jnp.int32)
    # sentinel candidates (id −1, worst distance) must lose ties against
    # real candidates: remap them past every real id for the sort key
    sentinel = jnp.iinfo(jnp.int32).max
    ids_key = jnp.where(ids < 0, jnp.int32(sentinel), ids)
    if jnp.issubdtype(scores.dtype, jnp.integer):
        key = scores.astype(jnp.int64)
    else:
        key = scores
    if not select_min:
        key = -key
    skey, sids = lax.sort(
        (key, ids_key), dimension=-1, num_keys=2, is_stable=False
    )
    skey, sids = skey[..., :k], sids[..., :k]
    sids = jnp.where(sids == sentinel, jnp.int32(-1), sids)
    vals = (-skey if not select_min else skey).astype(scores.dtype)
    if squeeze:
        return vals[0], sids[0]
    return vals, sids


def merge_topk(
    vals_a: jax.Array,
    idx_a: jax.Array,
    vals_b: jax.Array,
    idx_b: jax.Array,
    k: int,
    *,
    select_min: bool = True,
) -> Tuple[jax.Array, jax.Array]:
    """Merge two per-row top-k result sets into one (ref:
    neighbors/detail/knn_merge_parts.cuh — the cross-tile merge used by tiled
    brute-force kNN). Concatenate-then-select is optimal on TPU since top_k
    is sort-based.

    Ordering guarantee: the merged rows are sorted by value (ascending for
    ``select_min``, descending otherwise) and **ties are resolved by the
    smallest id**, not by which input part contributed the candidate.  The
    result is therefore a deterministic function of the logical candidate
    *set*: merging the same candidates partitioned differently (a vs b
    swapped, different shard boundaries in a cross-shard gather) yields
    identical (values, ids).  Sentinel candidates (id −1 at the worst
    distance) sort last and only surface when the pool underfills ``k``.
    """
    vals = jnp.concatenate([vals_a, vals_b], axis=-1)
    idx = jnp.concatenate([idx_a, idx_b], axis=-1)
    return select_k_stable(vals, k, select_min=select_min, input_indices=idx)


def argmax(m: jax.Array) -> jax.Array:
    """Per-row argmax (ref: matrix/argmax.cuh)."""
    return jnp.argmax(m, axis=-1).astype(jnp.int32)


def argmin(m: jax.Array) -> jax.Array:
    """Per-row argmin (ref: matrix/argmin.cuh)."""
    return jnp.argmin(m, axis=-1).astype(jnp.int32)


def gather(m: jax.Array, rows: jax.Array) -> jax.Array:
    """Row gather (ref: matrix/gather.cuh)."""
    return jnp.take(m, rows, axis=0)


def gather_if(m: jax.Array, rows: jax.Array, mask: jax.Array, fill=0) -> jax.Array:
    """Conditional row gather: masked-out rows are filled (ref:
    matrix/gather.cuh gather_if)."""
    out = jnp.take(m, rows, axis=0)
    return jnp.where(mask[:, None], out, jnp.asarray(fill, m.dtype))


def scatter(m: jax.Array, rows: jax.Array, updates: jax.Array) -> jax.Array:
    """Row scatter (ref: matrix/scatter.cuh)."""
    return m.at[rows].set(updates)


def sample_rows(key: jax.Array, m: jax.Array, n_samples: int) -> jax.Array:
    """Uniform random row subsample without replacement
    (ref: matrix/sample_rows.cuh)."""
    idx = jax.random.choice(key, m.shape[0], shape=(n_samples,), replace=False)
    return jnp.take(m, idx, axis=0)


def slice_matrix(m: jax.Array, row0: int, col0: int, row1: int, col1: int) -> jax.Array:
    """Submatrix copy (ref: matrix/slice.cuh)."""
    return m[row0:row1, col0:col1]


def col_wise_sort(m: jax.Array, *, ascending: bool = True) -> jax.Array:
    """Sort each column independently (ref: matrix/col_wise_sort.cuh)."""
    s = jnp.sort(m, axis=0)
    return s if ascending else s[::-1]


def linewise_op(m: jax.Array, vec: jax.Array, op, *, along_rows: bool) -> jax.Array:
    """Broadcast a vector op along rows or columns
    (ref: matrix/linewise_op.cuh, linalg/matrix_vector_op.cuh)."""
    if along_rows:
        return op(m, vec[None, :])
    return op(m, vec[:, None])


# ---- matrix misc ops (per-function reference cites below) ----------------


def threshold(m: jax.Array, value, *, below: bool = True, fill=0.0) -> jax.Array:
    """Zero (or ``fill``) entries on one side of a threshold
    (ref: matrix/threshold.cuh zero_small_values)."""
    mask = m < value if below else m > value
    return jnp.where(mask, jnp.asarray(fill, m.dtype), m)


def ratio(m: jax.Array) -> jax.Array:
    """Each element divided by the total sum (ref: matrix/ratio.cuh)."""
    total = jnp.sum(m)
    return m / jnp.where(total == 0, jnp.ones_like(total), total)


def reciprocal(m: jax.Array, *, scalar=1.0, setzero: bool = False, thres: float = 1e-15) -> jax.Array:
    """scalar / m with optional zeroing of tiny denominators
    (ref: matrix/reciprocal.cuh)."""
    out = jnp.asarray(scalar, m.dtype) / m
    if setzero:
        out = jnp.where(jnp.abs(m) <= thres, jnp.zeros_like(out), out)
    return out


def sign_flip(m: jax.Array) -> jax.Array:
    """Flip each column's sign so its max-|value| element is positive —
    deterministic eigenvector orientation (ref: matrix/sign_flip.cuh,
    linalg/detail/sign_flip as used by spectral/PCA paths)."""
    idx = jnp.argmax(jnp.abs(m), axis=0)
    signs = jnp.sign(m[idx, jnp.arange(m.shape[1])])
    signs = jnp.where(signs == 0, jnp.ones_like(signs), signs)
    return m * signs[None, :]


def triangular(m: jax.Array, *, upper: bool = True, k: int = 0) -> jax.Array:
    """Upper/lower triangular copy (ref: matrix/triangular.cuh)."""
    return jnp.triu(m, k) if upper else jnp.tril(m, k)


def eye(n: int, m: Optional[int] = None, dtype=jnp.float32) -> jax.Array:
    """Identity / rectangular eye (ref: matrix/init.cuh set_diagonal family)."""
    return jnp.eye(n, m, dtype=dtype)


def diagonal(m: jax.Array) -> jax.Array:
    """Main diagonal view-copy (ref: matrix/diagonal.cuh)."""
    return jnp.diagonal(m)


def set_diagonal(m: jax.Array, value) -> jax.Array:
    """Return a copy with the main diagonal set (ref: matrix/diagonal.cuh
    set_diagonal)."""
    n = min(m.shape[0], m.shape[1])
    idx = jnp.arange(n)
    return m.at[idx, idx].set(value)


def reverse(m: jax.Array, *, along_rows: bool = False) -> jax.Array:
    """Reverse row order (or each row) (ref: matrix/reverse.cuh)."""
    return m[:, ::-1] if along_rows else m[::-1]
