"""Dense linear algebra primitives (ref: cpp/include/raft/linalg/).

The reference wraps cuBLAS/cuSOLVER; here the MXU path is XLA's
``dot_general`` (gemm) and ``jnp.linalg`` (solvers). The keyed reductions
(``reduce_rows_by_key`` — the k-means centroid update) map to
``jax.ops.segment_sum``, which XLA lowers to sorted-scatter on TPU.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

# ---- BLAS level 3 (ref: linalg/gemm.cuh over cuBLAS/cuBLASLt) -------------


def gemm(
    a: jax.Array,
    b: jax.Array,
    *,
    trans_a: bool = False,
    trans_b: bool = False,
    alpha: float = 1.0,
    beta: float = 0.0,
    c: Optional[jax.Array] = None,
    precision=None,
) -> jax.Array:
    """alpha * op(A) @ op(B) + beta * C on the MXU."""
    if trans_a:
        a = a.T
    if trans_b:
        b = b.T
    out = jnp.matmul(a, b, precision=precision)
    if alpha != 1.0:
        out = alpha * out
    if beta != 0.0 and c is not None:
        out = out + beta * c
    return out


def gemv(a: jax.Array, x: jax.Array, *, trans: bool = False) -> jax.Array:
    return (a.T if trans else a) @ x


def dot(x: jax.Array, y: jax.Array) -> jax.Array:
    return jnp.vdot(x, y)


def axpy(alpha: float, x: jax.Array, y: jax.Array) -> jax.Array:
    return alpha * x + y


def transpose(m: jax.Array) -> jax.Array:
    """(ref: linalg/transpose.cuh via cublas geam)"""
    return m.T


# ---- norms / normalization (ref: linalg/norm.cuh, normalize.cuh) ----------

L1Norm, L2Norm, LinfNorm = "l1", "l2", "linf"


def norm(m: jax.Array, *, norm_type: str = L2Norm, axis: int = 1, squared: bool = False) -> jax.Array:
    if norm_type == L1Norm:
        return jnp.sum(jnp.abs(m), axis=axis)
    if norm_type == L2Norm:
        sq = jnp.sum(m * m, axis=axis)
        return sq if squared else jnp.sqrt(sq)
    if norm_type == LinfNorm:
        return jnp.max(jnp.abs(m), axis=axis)
    raise ValueError(f"unknown norm {norm_type}")


def row_normalize(m: jax.Array, *, norm_type: str = L2Norm, eps: float = 1e-12) -> jax.Array:
    n = norm(m, norm_type=norm_type, axis=1)
    return m / jnp.maximum(n, eps)[:, None]


# ---- reductions (ref: linalg/reduce.cuh family) ---------------------------


def reduce(m: jax.Array, *, axis: int = 1, op=jnp.sum) -> jax.Array:
    return op(m, axis=axis)


def map_then_reduce(map_op, m: jax.Array, *, axis: Optional[int] = None, reduce_op=jnp.sum) -> jax.Array:
    """(ref: linalg/map_then_reduce.cuh) — XLA fuses this chain anyway."""
    return reduce_op(map_op(m), axis=axis)


def mean_squared_error(a: jax.Array, b: jax.Array) -> jax.Array:
    d = a - b
    return jnp.mean(d * d)


def reduce_rows_by_key(
    m: jax.Array,
    keys: jax.Array,
    n_keys: int,
    *,
    weights: Optional[jax.Array] = None,
) -> jax.Array:
    """Sum rows of ``m`` grouped by ``keys`` → [n_keys, n_cols].

    The k-means centroid accumulation primitive
    (ref: linalg/reduce_rows_by_key.cuh, used by
    cluster/detail/kmeans_balanced.cuh centroid update). ``segment_sum``
    lowers to a sorted scatter-add, the TPU-efficient equivalent of the
    reference's atomics-based kernel.
    """
    if weights is not None:
        m = m * weights[:, None]
    return jax.ops.segment_sum(m, keys, num_segments=n_keys)


def reduce_cols_by_key(m: jax.Array, keys: jax.Array, n_keys: int) -> jax.Array:
    """(ref: linalg/reduce_cols_by_key.cuh)"""
    return jax.ops.segment_sum(m.T, keys, num_segments=n_keys).T


def binary_op(a: jax.Array, b: jax.Array, op) -> jax.Array:
    return op(a, b)


def unary_op(a: jax.Array, op) -> jax.Array:
    return op(a)


# ---- solvers (ref: linalg/{eig,qr,svd,rsvd,lstsq,cholesky_r1_update}.cuh) -


def eig_dc(m: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Symmetric eigendecomposition (ref: linalg/eig.cuh cusolver syevd).
    Returns (eigenvalues ascending, eigenvectors as columns)."""
    w, v = jnp.linalg.eigh(m)
    return w, v


def qr_q(m: jax.Array) -> jax.Array:
    q, _ = jnp.linalg.qr(m)
    return q


def qr(m: jax.Array) -> Tuple[jax.Array, jax.Array]:
    return jnp.linalg.qr(m)


def svd(m: jax.Array, *, full_matrices: bool = False) -> Tuple[jax.Array, jax.Array, jax.Array]:
    u, s, vt = jnp.linalg.svd(m, full_matrices=full_matrices)
    return u, s, vt


def rsvd(
    key: jax.Array,
    m: jax.Array,
    rank: int,
    *,
    n_oversamples: int = 10,
    n_iter: int = 4,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Randomized SVD (ref: linalg/rsvd.cuh): range finder with power
    iterations + small exact SVD. MXU-dominated."""
    n = m.shape[1]
    p = min(rank + n_oversamples, n)
    omega = jax.random.normal(key, (n, p), dtype=m.dtype)
    y = m @ omega
    q = qr_q(y)
    for _ in range(n_iter):
        q = qr_q(m.T @ q)
        q = qr_q(m @ q)
    b = q.T @ m
    ub, s, vt = jnp.linalg.svd(b, full_matrices=False)
    u = q @ ub
    return u[:, :rank], s[:rank], vt[:rank, :]


def lstsq(a: jax.Array, b: jax.Array) -> jax.Array:
    """Least squares via QR (ref: linalg/lstsq.cuh)."""
    return jnp.linalg.lstsq(a, b)[0]


def cholesky_r1_update(l: jax.Array, x: jax.Array) -> jax.Array:
    """Rank-1 Cholesky update: chol(L L^T + x x^T)
    (ref: linalg/cholesky_r1_update.cuh). Small-n host-style loop is fine —
    used by incremental solvers, not hot paths; implemented with lax.scan
    over columns for jit-ability."""
    n = l.shape[0]

    def body(carry, j):
        l_, x_ = carry
        ljj = l_[j, j]
        xj = x_[j]
        r = jnp.sqrt(ljj * ljj + xj * xj)
        c = r / ljj
        s = xj / ljj
        col = l_[:, j]
        mask = jnp.arange(n) > j
        new_col = jnp.where(mask, (col + s * x_) / c, col)
        new_col = new_col.at[j].set(r)
        x_new = jnp.where(mask, c * x_ - s * new_col, x_)
        return (l_.at[:, j].set(new_col), x_new), None

    (l_out, _), _ = lax.scan(body, (l, x), jnp.arange(n))
    return l_out
