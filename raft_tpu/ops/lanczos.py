"""Lanczos eigensolver for large symmetric operators.

Reference: ``linalg/detail/lanczos.cuh:749-1026`` — ``computeSmallestEigenvectors``
/ ``computeLargestEigenvectors`` driving spectral clustering
(spectral/eigen_solvers.cuh lanczos_solver_t).

TPU re-design: one Lanczos sweep with *full* reorthogonalization expressed as
a ``lax.scan`` over iterations — each step is a matvec (caller-supplied; for
sparse graphs that is the segment-sum spmv) plus two [n, m] GEMMs for the
re-orth (MXU work, replacing the reference's restart+partial-reorth logic,
which exists to limit GPU memory rather than FLOPs). The small tridiagonal
eigenproblem solves with jnp.linalg.eigh.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def _lanczos_basis(matvec, v0: jax.Array, restarts: jax.Array, m: int):
    """Run m Lanczos steps with full reorthogonalization.

    ``restarts`` [m, n]: random vectors used when the recurrence breaks down
    (invariant subspace found — e.g. disconnected graphs); the sweep then
    continues in a fresh orthogonal direction with beta recorded as 0, which
    block-decouples T exactly as restarted Lanczos should.

    Returns (V [m, n] orthonormal basis, alphas [m], betas [m-1])."""
    n = v0.shape[0]
    v0 = v0 / jnp.maximum(jnp.linalg.norm(v0), 1e-30)

    def body(carry, i):
        V, v_prev, v_cur, beta_prev = carry
        V = V.at[i].set(v_cur)
        w = matvec(v_cur)
        alpha = jnp.dot(v_cur, w)
        w = w - alpha * v_cur - beta_prev * v_prev
        # full reorthogonalization: project out every stored basis vector
        # (rows past i are zero, so the extra projections are no-ops)
        w = w - V.T @ (V @ w)
        w = w - V.T @ (V @ w)  # second pass for float32 robustness
        beta = jnp.linalg.norm(w)
        ok = beta > 1e-6
        r = restarts[i]
        r = r - V.T @ (V @ r)
        r = r / jnp.maximum(jnp.linalg.norm(r), 1e-30)
        v_next = jnp.where(ok, w / jnp.maximum(beta, 1e-30), r)
        beta_out = jnp.where(ok, beta, 0.0)
        return (V, v_cur, v_next, beta_out), (alpha, beta_out)

    V0 = jnp.zeros((m, n), v0.dtype)
    (V, _, _, _), (alphas, betas) = lax.scan(
        body, (V0, jnp.zeros_like(v0), v0, jnp.asarray(0.0, v0.dtype)),
        jnp.arange(m),
    )
    return V, alphas, betas[:-1]


def eigsh_lanczos(
    matvec: Callable[[jax.Array], jax.Array],
    n: int,
    k: int,
    *,
    which: str = "smallest",
    m: int = 0,
    seed: int = 0,
    dtype=jnp.float32,
) -> Tuple[jax.Array, jax.Array]:
    """Top/bottom-k eigenpairs of a symmetric operator.

    Returns (eigenvalues [k] ascending, eigenvectors [n, k])
    (ref: lanczos.cuh computeSmallest/LargestEigenvectors)."""
    if k > n:
        raise ValueError(f"k={k} > n={n}")
    m = m or min(n, max(2 * k + 8, 32))
    m = min(m, n)
    if m < k:
        raise ValueError(f"subspace size m={m} < k={k}")
    key = jax.random.PRNGKey(seed)
    k0, k1 = jax.random.split(key)
    v0 = jax.random.normal(k0, (n,), dtype)
    restarts = jax.random.normal(k1, (m, n), dtype)
    V, alphas, betas = _lanczos_basis(matvec, v0, restarts, m)
    T = (
        jnp.diag(alphas)
        + jnp.diag(betas, 1)
        + jnp.diag(betas, -1)
    )
    evals, evecs = jnp.linalg.eigh(T)  # ascending
    if which == "smallest":
        sel = jnp.arange(k)
    elif which == "largest":
        sel = jnp.arange(m - k, m)
    else:
        raise ValueError(f"which must be smallest|largest, got {which}")
    ritz_vals = evals[sel]
    ritz_vecs = (V.T @ evecs[:, sel])  # [n, k]
    # normalize columns (padding-robust)
    ritz_vecs = ritz_vecs / jnp.maximum(
        jnp.linalg.norm(ritz_vecs, axis=0, keepdims=True), 1e-30
    )
    return ritz_vals, ritz_vecs
