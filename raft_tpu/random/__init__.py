"""RNG + synthetic data generators (ref: cpp/include/raft/random/).

The reference carries stateful Philox/PCG generator state on the handle
(ref: random/rng_state.hpp:29-52); JAX's threefry keys are the functional
equivalent — ``Resources.prng_key()`` provides the per-handle stream.
Distribution *parity* (not bitwise equality) is the test target, matching
the reference's own test strategy (SURVEY §2.10).
"""

from raft_tpu.random.rng import (
    RngState,
    uniform,
    uniform_int,
    normal,
    gumbel,
    laplace,
    lognormal,
    exponential,
    rayleigh,
    bernoulli,
    sample_without_replacement,
    permute,
    multi_variable_gaussian,
)
from raft_tpu.random.datagen import make_blobs, make_regression, rmat

__all__ = [
    "RngState",
    "uniform",
    "uniform_int",
    "normal",
    "gumbel",
    "laplace",
    "lognormal",
    "exponential",
    "rayleigh",
    "bernoulli",
    "sample_without_replacement",
    "permute",
    "multi_variable_gaussian",
    "make_blobs",
    "make_regression",
    "rmat",
]
