"""Distribution sampling API (ref: cpp/include/raft/random/rng.cuh).

Each sampler takes an explicit key (threefry), mirroring the reference's
RngState-first signatures (ref: random/rng.cuh uniform/normal/gumbel/...).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp


class RngState:
    """Seed + subsequence counter (ref: random/rng_state.hpp:29-52).

    A thin stateful convenience over threefry keys for API parity; all
    samplers below are pure and take keys directly.
    """

    def __init__(self, seed: int = 0):
        self.seed = seed
        self._counter = 0

    def next_key(self) -> jax.Array:
        key = jax.random.fold_in(jax.random.PRNGKey(self.seed), self._counter)
        self._counter += 1
        return key


def uniform(key, shape, *, low=0.0, high=1.0, dtype=jnp.float32):
    return jax.random.uniform(key, shape, dtype=dtype, minval=low, maxval=high)


def uniform_int(key, shape, *, low=0, high=100, dtype=jnp.int32):
    return jax.random.randint(key, shape, low, high, dtype=dtype)


def normal(key, shape, *, mu=0.0, sigma=1.0, dtype=jnp.float32):
    return mu + sigma * jax.random.normal(key, shape, dtype=dtype)


def gumbel(key, shape, *, mu=0.0, beta=1.0, dtype=jnp.float32):
    return mu + beta * jax.random.gumbel(key, shape, dtype=dtype)


def laplace(key, shape, *, mu=0.0, scale=1.0, dtype=jnp.float32):
    return mu + scale * jax.random.laplace(key, shape, dtype=dtype)


def lognormal(key, shape, *, mu=0.0, sigma=1.0, dtype=jnp.float32):
    return jnp.exp(normal(key, shape, mu=mu, sigma=sigma, dtype=dtype))


def exponential(key, shape, *, lam=1.0, dtype=jnp.float32):
    return jax.random.exponential(key, shape, dtype=dtype) / lam


def rayleigh(key, shape, *, sigma=1.0, dtype=jnp.float32):
    u = jax.random.uniform(key, shape, dtype=dtype, minval=1e-12, maxval=1.0)
    return sigma * jnp.sqrt(-2.0 * jnp.log(u))


def bernoulli(key, shape, *, prob=0.5, dtype=jnp.bool_):
    return jax.random.bernoulli(key, prob, shape).astype(dtype)


def sample_without_replacement(
    key, population: int, n_samples: int, *, weights: Optional[jax.Array] = None
) -> jax.Array:
    """(ref: random/sample_without_replacement.cuh) — Gumbel-top-k trick when
    weighted, direct choice otherwise."""
    if weights is None:
        return jax.random.choice(key, population, shape=(n_samples,), replace=False)
    g = jax.random.gumbel(key, (population,)) + jnp.log(jnp.maximum(weights, 1e-30))
    return jax.lax.top_k(g, n_samples)[1].astype(jnp.int32)


def permute(key, n: int) -> jax.Array:
    """Random permutation (ref: random/permute.cuh)."""
    return jax.random.permutation(key, n)


def multi_variable_gaussian(
    key, mean: jax.Array, cov: jax.Array, n_samples: int
) -> jax.Array:
    """Sample N(mean, cov) (ref: random/multi_variable_gaussian.cuh, which
    uses cuSOLVER factorization; here jnp.linalg.cholesky)."""
    d = mean.shape[0]
    chol = jnp.linalg.cholesky(cov + 1e-8 * jnp.eye(d, dtype=cov.dtype))
    z = jax.random.normal(key, (n_samples, d), dtype=mean.dtype)
    return mean[None, :] + z @ chol.T
