"""Synthetic dataset generators (ref: raft/random/{make_blobs,make_regression,
rmat_rectangular_generator}.cuh). ``make_blobs`` is used pervasively by the
reference's own tests (SURVEY §2.10) and ours.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def make_blobs(
    key: jax.Array,
    n_samples: int,
    n_features: int,
    *,
    n_clusters: int = 5,
    cluster_std: float = 1.0,
    center_box: Tuple[float, float] = (-10.0, 10.0),
    centers: Optional[jax.Array] = None,
    shuffle: bool = True,
    dtype=jnp.float32,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Clustered Gaussian blobs (ref: random/make_blobs.cuh).

    Returns (data [n, d], labels [n], centers [k, d]).
    """
    kc, kl, kn, ks = jax.random.split(key, 4)
    if centers is None:
        centers = jax.random.uniform(
            kc, (n_clusters, n_features), dtype=dtype,
            minval=center_box[0], maxval=center_box[1],
        )
    else:
        centers = jnp.asarray(centers, dtype)
        n_clusters = centers.shape[0]
    labels = jax.random.randint(kl, (n_samples,), 0, n_clusters)
    noise = cluster_std * jax.random.normal(kn, (n_samples, n_features), dtype=dtype)
    data = centers[labels] + noise
    if shuffle:
        perm = jax.random.permutation(ks, n_samples)
        data, labels = data[perm], labels[perm]
    return data, labels.astype(jnp.int32), centers


def make_regression(
    key: jax.Array,
    n_samples: int,
    n_features: int,
    *,
    n_informative: int = 10,
    n_targets: int = 1,
    bias: float = 0.0,
    noise: float = 0.0,
    shuffle: bool = True,
    dtype=jnp.float32,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Linear-model regression problem (ref: random/make_regression.cuh).

    Returns (X [n, d], y [n, t], coef [d, t]).
    """
    n_informative = min(n_informative, n_features)
    kx, kw, kn, ks = jax.random.split(key, 4)
    x = jax.random.normal(kx, (n_samples, n_features), dtype=dtype)
    coef = jnp.zeros((n_features, n_targets), dtype)
    w = 100.0 * jax.random.uniform(kw, (n_informative, n_targets), dtype=dtype)
    coef = coef.at[:n_informative].set(w)
    y = x @ coef + bias
    if noise > 0:
        y = y + noise * jax.random.normal(kn, y.shape, dtype=dtype)
    if shuffle:
        perm = jax.random.permutation(ks, n_samples)
        x, y = x[perm], y[perm]
    return x, y, coef


def rmat(
    key: jax.Array,
    r_scale: int,
    c_scale: int,
    n_edges: int,
    *,
    theta: Optional[jax.Array] = None,
) -> jax.Array:
    """R-MAT rectangular graph generator
    (ref: random/rmat_rectangular_generator.cuh; Python ref:
    pylibraft.random.rmat). Returns [n_edges, 2] (src, dst) int32.

    Per-edge, each of max(r_scale, c_scale) levels picks a quadrant from the
    (possibly per-level) theta distribution [a, b, c, d]; row bit is set for
    quadrants c/d, col bit for b/d — vectorized across all edges at once.
    """
    max_scale = max(r_scale, c_scale)
    if theta is None:
        theta = jnp.tile(jnp.array([0.57, 0.19, 0.19, 0.05], jnp.float32), (max_scale, 1))
    else:
        theta = jnp.asarray(theta, jnp.float32).reshape(max_scale, 4)
    theta = theta / jnp.sum(theta, axis=1, keepdims=True)

    keys = jax.random.split(key, max_scale)
    src = jnp.zeros((n_edges,), jnp.int32)
    dst = jnp.zeros((n_edges,), jnp.int32)
    for lvl in range(max_scale):
        q = jax.random.categorical(keys[lvl], jnp.log(theta[lvl] + 1e-30), shape=(n_edges,))
        row_bit = ((q >= 2) & (lvl < r_scale)).astype(jnp.int32)
        col_bit = ((q % 2 == 1) & (lvl < c_scale)).astype(jnp.int32)
        if lvl < r_scale:
            src = (src << 1) | row_bit
        if lvl < c_scale:
            dst = (dst << 1) | col_bit
    return jnp.stack([src, dst], axis=1)
