"""Spectral graph partitioning and modularity maximization.

Reference: ``spectral/partition.cuh`` (partition + analyzePartition),
``spectral/modularity_maximization.cuh`` (modularity_maximization +
analyzeModularity), solvers ``spectral/eigen_solvers.cuh`` (lanczos_solver_t)
and ``spectral/cluster_solvers.cuh`` (kmeans_solver_t) — SURVEY §2.7.

TPU shape: Laplacian/modularity matvecs are segment-sum spmv programs
(sparse.linalg), the eigensolver is the full-reorth Lanczos scan
(ops.lanczos), and the embedding is clustered with the existing kmeans.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.cluster import kmeans
from raft_tpu.core.resources import Resources, ensure
from raft_tpu.ops.lanczos import eigsh_lanczos
from raft_tpu.sparse.formats import COO
from raft_tpu.sparse.linalg import laplacian, spmv_coo, weighted_degree
from raft_tpu.core.trace import traced


def _cluster_embedding(emb, n_clusters, seed, res):
    # row-normalize the spectral embedding before k-means — the reference
    # likewise scales observations ahead of its cluster solver
    # (spectral/detail/spectral_util.cuh transform_eigen_matrix); without it
    # eigenvector magnitudes dominate the cluster geometry
    emb = emb / jnp.maximum(jnp.linalg.norm(emb, axis=1, keepdims=True), 1e-12)
    params = kmeans.KMeansParams(n_clusters=n_clusters, seed=seed, n_init=3)
    centers, _, _ = kmeans.fit(params, emb, res=res)
    return kmeans.predict(centers, emb, res=res)


def fit_embedding(
    adj: COO,
    n_components: int,
    *,
    normalized: bool = False,
    seed: int = 0,
) -> jax.Array:
    """Smallest-eigenvector Laplacian embedding [n, n_components], skipping
    the trivial constant eigenvector (ref: sparse/linalg/spectral.cuh
    fit_embedding)."""
    n = adj.shape[0]
    lap = laplacian(adj, normalized=normalized)
    _, vecs = eigsh_lanczos(
        lambda v: spmv_coo(lap, v), n, n_components + 1,
        which="smallest", seed=seed,
    )
    return vecs[:, 1 : n_components + 1]


@traced("spectral.partition")
def partition(
    adj: COO,
    n_clusters: int,
    *,
    n_eigenvecs: int = 0,
    normalized: bool = True,
    seed: int = 0,
    res: Optional[Resources] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Spectral min-balanced-cut partition (ref: spectral/partition.cuh
    partition: Laplacian smallest eigenvectors → kmeans).

    Returns (labels [n], eigenvalues [k])."""
    res = ensure(res)
    n = adj.shape[0]
    k = n_eigenvecs or n_clusters
    lap = laplacian(adj, normalized=normalized)
    vals, vecs = eigsh_lanczos(
        lambda v: spmv_coo(lap, v), n, k, which="smallest", seed=seed
    )
    labels = _cluster_embedding(vecs, n_clusters, seed, res)
    return labels, vals


def analyze_partition(
    adj: COO, labels: jax.Array, n_clusters: int
) -> Tuple[jax.Array, jax.Array]:
    """(edge_cut_cost, min_cluster_size) — ref: spectral/partition.cuh
    analyzePartition."""
    n = adj.shape[0]
    lr = labels[jnp.clip(adj.rows, 0, n - 1)]
    lc = labels[jnp.clip(adj.cols, 0, n - 1)]
    cut = jnp.sum(jnp.where(adj.valid & (lr != lc), adj.data, 0)) / 2.0
    sizes = jnp.zeros(n_clusters, jnp.int32).at[labels].add(1)
    return cut, jnp.min(sizes)


@traced("spectral.modularity_maximization")
def modularity_maximization(
    adj: COO,
    n_clusters: int,
    *,
    n_eigenvecs: int = 0,
    seed: int = 0,
    res: Optional[Resources] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Cluster by the largest eigenvectors of the modularity matrix
    B = A − d·dᵀ/2m (ref: spectral/modularity_maximization.cuh; the matvec
    keeps B implicit — one spmv + one rank-1 correction).

    Returns (labels [n], eigenvalues [k])."""
    res = ensure(res)
    n = adj.shape[0]
    k = n_eigenvecs or n_clusters
    d = weighted_degree(adj)
    two_m = jnp.maximum(jnp.sum(d), 1e-30)

    def matvec(v):
        return spmv_coo(adj, v) - d * (jnp.dot(d, v) / two_m)

    vals, vecs = eigsh_lanczos(matvec, n, k, which="largest", seed=seed)
    labels = _cluster_embedding(vecs, n_clusters, seed, res)
    return labels, vals


def analyze_modularity(adj: COO, labels: jax.Array) -> jax.Array:
    """Modularity score Q of a labelling (ref: analyzeModularity)."""
    n = adj.shape[0]
    d = weighted_degree(adj)
    two_m = jnp.maximum(jnp.sum(d), 1e-30)
    lr = labels[jnp.clip(adj.rows, 0, n - 1)]
    lc = labels[jnp.clip(adj.cols, 0, n - 1)]
    a_in = jnp.sum(jnp.where(adj.valid & (lr == lc), adj.data, 0))
    k = int(jnp.max(labels)) + 1
    d_per = jnp.zeros(k, d.dtype).at[labels].add(d)
    return a_in / two_m - jnp.sum((d_per / two_m) ** 2)
