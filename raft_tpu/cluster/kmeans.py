"""Lloyd's k-means with kmeans++ init (ref: cpp/include/raft/cluster/
kmeans.cuh, detail/kmeans.cuh (1,255 LoC), kmeans_types.hpp;
Python ref: pylibraft.cluster.kmeans).

TPU shape: the assignment step is the fused distance+argmin (one MXU matmul
per tile, SURVEY §2.7), the update step is ``segment_sum`` (sorted
scatter-add). The whole Lloyd loop runs on-device inside ``lax.while_loop``
with a convergence test, so there is exactly one dispatch per ``fit``.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu.core.resources import Resources, ensure
from raft_tpu.distance.pairwise import distance_matrix_tile
from raft_tpu.core.trace import traced


@dataclass
class KMeansParams:
    """(ref: cluster/kmeans_types.hpp KMeansParams)"""

    n_clusters: int = 8
    max_iter: int = 300
    tol: float = 1e-4
    init: str = "kmeans++"  # kmeans++ | random | array
    n_init: int = 1
    seed: int = 0
    metric: str = "sqeuclidean"  # sqeuclidean | cosine (spherical k-means)
    batch_samples: int = 1 << 15  # assignment row-tile (bounds the [tile, k] matrix)


def _normalize_rows(x: jax.Array) -> jax.Array:
    return x / jnp.maximum(jnp.linalg.norm(x, axis=-1, keepdims=True), 1e-12)


def _assign(
    x: jax.Array, centers: jax.Array, tile: int = 0
) -> Tuple[jax.Array, jax.Array]:
    """(min_dist², label) per row — fused distance+argmin, row-tiled so the
    [tile, k] distance matrix (not [n, k]) bounds the workspace."""

    def one(t):
        d2 = distance_matrix_tile(t, centers, "sqeuclidean")
        return jnp.min(d2, axis=1), jnp.argmin(d2, axis=1).astype(jnp.int32)

    n = x.shape[0]
    if tile <= 0 or n <= tile:
        return one(x)
    n_tiles = (n + tile - 1) // tile
    pad = n_tiles * tile - n
    xp = jnp.pad(x, ((0, pad), (0, 0))).reshape(n_tiles, tile, x.shape[1])
    best, labels = lax.map(one, xp)
    return best.reshape(-1)[:n], labels.reshape(-1)[:n]


@traced("kmeans.plus_plus_init")
def kmeans_plus_plus_init(
    key: jax.Array, x: jax.Array, n_clusters: int, weights: Optional[jax.Array] = None
) -> jax.Array:
    """kmeans++ seeding (ref: detail/kmeans.cuh kmeansPlusPlus).

    Iteratively sample the next center ∝ weighted min-distance²; the
    incremental min-d² update keeps each step a single [n, d]·[d] pass.
    """
    n, d = x.shape
    w = jnp.ones((n,), x.dtype) if weights is None else weights
    k0, key = jax.random.split(key)
    first = jax.random.choice(k0, n, p=w / jnp.sum(w))
    centers0 = jnp.zeros((n_clusters, d), x.dtype).at[0].set(x[first])
    min_d2_0 = jnp.sum((x - x[first][None, :]) ** 2, axis=1)

    def body(i, carry):
        centers, min_d2, key = carry
        key, sub = jax.random.split(key)
        probs = w * min_d2
        probs = probs / jnp.maximum(jnp.sum(probs), 1e-30)
        nxt = jax.random.choice(sub, n, p=probs)
        c = x[nxt]
        centers = centers.at[i].set(c)
        min_d2 = jnp.minimum(min_d2, jnp.sum((x - c[None, :]) ** 2, axis=1))
        return centers, min_d2, key

    centers, _, _ = lax.fori_loop(1, n_clusters, body, (centers0, min_d2_0, key))
    return centers


@traced("kmeans.compute_new_centroids")
def compute_new_centroids(
    x: jax.Array,
    centroids: jax.Array,
    labels: Optional[jax.Array] = None,
    weights: Optional[jax.Array] = None,
) -> jax.Array:
    """One centroid-update step (Python ref:
    pylibraft.cluster.kmeans.compute_new_centroids)."""
    n_clusters = centroids.shape[0]
    if labels is None:
        _, labels = _assign(x, centroids)
    w = jnp.ones((x.shape[0],), x.dtype) if weights is None else weights
    sums = jax.ops.segment_sum(x * w[:, None], labels, num_segments=n_clusters)
    counts = jax.ops.segment_sum(w, labels, num_segments=n_clusters)
    return jnp.where(counts[:, None] > 0, sums / jnp.maximum(counts[:, None], 1e-30), centroids)


@functools.partial(jax.jit, static_argnames=("max_iter", "metric", "tile"))
def _lloyd(x, centers0, weights, max_iter: int, tol: float, metric: str, tile: int):
    n_clusters = centers0.shape[0]
    spherical = metric == "cosine"

    def cond(carry):
        _, it, prev, cur = carry
        # relative-change of the assignment inertia between iterations;
        # prev/cur start at +inf so the loop always takes ≥2 iterations
        # before the test can trigger
        return (it < max_iter) & ~(jnp.abs(prev - cur) <= tol * jnp.maximum(cur, 1e-30))

    def body(carry):
        centers, it, _, prev_inertia = carry
        best, labels = _assign(x, centers, tile)
        inertia = jnp.sum(weights * best)  # inertia of THIS assignment
        sums = jax.ops.segment_sum(x * weights[:, None], labels, num_segments=n_clusters)
        counts = jax.ops.segment_sum(weights, labels, num_segments=n_clusters)
        centers = jnp.where(
            counts[:, None] > 0, sums / jnp.maximum(counts[:, None], 1e-30), centers
        )
        if spherical:
            # spherical k-means: centers live on the unit sphere, so the
            # sqeuclidean argmin stays rank-equivalent to cosine
            centers = _normalize_rows(centers)
        return centers, it + 1, prev_inertia, inertia

    centers, n_iter, _, _ = lax.while_loop(
        cond, body, (centers0, jnp.int32(0), jnp.inf, jnp.inf)
    )
    # final inertia measured against the final centers
    best, _ = _assign(x, centers, tile)
    return centers, jnp.sum(weights * best), n_iter


@traced("kmeans.fit")
def fit(
    params: KMeansParams,
    x: jax.Array,
    sample_weights: Optional[jax.Array] = None,
    *,
    init_centers: Optional[jax.Array] = None,
    res: Optional[Resources] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Fit k-means; returns (centroids, inertia, n_iter)
    (Python ref: pylibraft.cluster.kmeans.fit — same return triple).

    ``n_init`` restarts keep the best inertia, like the reference.

    Examples
    --------
    >>> import numpy as np
    >>> from raft_tpu.cluster import kmeans
    >>> x = np.concatenate(
    ...     [np.zeros((50, 2)), np.ones((50, 2))]
    ... ).astype(np.float32)
    >>> c, inertia, n_iter = kmeans.fit(
    ...     kmeans.KMeansParams(n_clusters=2, seed=0), x
    ... )
    >>> c.shape
    (2, 2)
    >>> bool(inertia < 1e-3)  # two exact point-clusters
    True
    """
    res = ensure(res)
    if params.metric not in ("sqeuclidean", "euclidean", "l2", "cosine"):
        raise ValueError(f"kmeans supports sqeuclidean/cosine, got {params.metric}")
    metric = "cosine" if params.metric == "cosine" else "sqeuclidean"
    x = jnp.asarray(x, jnp.float32)
    if metric == "cosine":
        x = _normalize_rows(x)
    w = (
        jnp.ones((x.shape[0],), jnp.float32)
        if sample_weights is None
        else jnp.asarray(sample_weights, jnp.float32)
    )
    key = jax.random.fold_in(jax.random.PRNGKey(params.seed), 0)
    if params.init == "array" and init_centers is None:
        raise ValueError("init='array' requires init_centers")

    # deterministic restarts are identical — an explicit init runs once
    n_init = 1 if init_centers is not None else max(params.n_init, 1)
    best = None
    for trial in range(n_init):
        kt = jax.random.fold_in(key, trial)
        if init_centers is not None:
            c0 = jnp.asarray(init_centers, jnp.float32)
            if metric == "cosine":
                c0 = _normalize_rows(c0)
        elif params.init == "random":
            idx = jax.random.choice(kt, x.shape[0], shape=(params.n_clusters,), replace=False)
            c0 = x[idx]
        else:
            c0 = kmeans_plus_plus_init(kt, x, params.n_clusters, w)
        centers, inertia, n_iter = _lloyd(
            x, c0, w, params.max_iter, params.tol, metric, params.batch_samples
        )
        if best is None or float(inertia) < float(best[1]):
            best = (centers, inertia, n_iter)
    return best


@functools.lru_cache(maxsize=32)
def _lloyd_sharded_program(
    mesh, axis: str, max_iter: int, tol: float, metric: str, tile: int,
    reduce_dtype: str,
):
    """Build (and cache) the compiled sharded Lloyd loop per (mesh, axis,
    statics) — a fresh shard_map closure per fit would defeat jit's trace
    cache and re-trace the while_loop every call."""
    from jax.sharding import PartitionSpec as P

    from raft_tpu.core.compat import shard_map
    from raft_tpu.comms.quantized import quantized_psum

    def local(x, w, c0):
        x = x.astype(jnp.float32)
        if metric == "cosine":
            x = _normalize_rows(x)
        w = w.astype(jnp.float32)
        n_clusters, d = c0.shape
        spherical = metric == "cosine"

        def cond(carry):
            _, it, prev, cur = carry
            return (it < max_iter) & ~(
                jnp.abs(prev - cur) <= tol * jnp.maximum(cur, 1e-30)
            )

        def body(carry):
            centers, it, _, prev_inertia = carry
            best, labels = _assign(x, centers, tile)
            local_inertia = jnp.sum(w * best)
            sums = jax.ops.segment_sum(
                x * w[:, None], labels, num_segments=n_clusters
            )
            counts = jax.ops.segment_sum(w, labels, num_segments=n_clusters)
            # ONE collective per iteration: the [k, d] partial sums, the
            # counts column, and the inertia scalar ride a single packed
            # (optionally quantized) psum — the build loop's only
            # cross-device traffic
            side = jnp.zeros((n_clusters, 2), jnp.float32)
            side = side.at[:, 0].set(counts).at[0, 1].set(local_inertia)
            packed = quantized_psum(
                jnp.concatenate([sums, side], axis=1), axis, reduce_dtype
            )
            g_sums, g_counts = packed[:, :d], packed[:, d]
            inertia = packed[0, d + 1]
            centers = jnp.where(
                g_counts[:, None] > 0,
                g_sums / jnp.maximum(g_counts[:, None], 1e-30),
                centers,
            )
            if spherical:
                centers = _normalize_rows(centers)
            return centers, it + 1, prev_inertia, inertia

        centers, n_iter, _, _ = lax.while_loop(
            cond, body, (c0, jnp.int32(0), jnp.inf, jnp.inf)
        )
        # final inertia measured against the final centers (matches _lloyd)
        best, _ = _assign(x, centers, tile)
        inertia = lax.psum(jnp.sum(w * best), axis)
        return centers, inertia, n_iter

    return jax.jit(
        shard_map(
            local,
            mesh=mesh,
            in_specs=(P(axis, None), P(axis), P(None, None)),
            out_specs=(P(None, None), P(), P()),
            check_vma=False,
        )
    )


@traced("kmeans.fit_sharded")
def fit_sharded(
    comms,
    params: KMeansParams,
    data_sharded: jax.Array,
    sample_weights: Optional[jax.Array] = None,
    *,
    init_centers: Optional[jax.Array] = None,
    reduce_dtype: Optional[str] = None,
    res: Optional[Resources] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """:func:`fit` over data row-sharded across ``comms``' mesh axis.

    Semantically :func:`fit`'s Lloyd loop, distributed: each shard
    assigns its rows and computes partial centroid sums/counts; the
    partials merge in ONE packed ``psum`` per iteration (optionally
    bf16/int8-quantized via ``reduce_dtype`` /
    ``RAFT_TPU_BUILD_REDUCE_DTYPE``).  The training rows never funnel
    through one host — only [k, d+2] statistics travel.

    ``data_sharded`` is the global [n, d] array (sharded or shardable on
    the comms axis; n must divide the axis size — pad with zero-weight
    rows otherwise).  ``sample_weights`` shards alongside the rows.
    Init is on a replicated weight-aware subsample (rows travel once);
    ``init_centers`` bypasses it, giving runs that are comparable
    1:1 against a single-host :func:`fit` with the same init.

    Returns replicated (centroids, inertia, n_iter) like :func:`fit`.
    """
    res = ensure(res)
    if params.metric not in ("sqeuclidean", "euclidean", "l2", "cosine"):
        raise ValueError(
            f"kmeans supports sqeuclidean/cosine, got {params.metric}"
        )
    metric = "cosine" if params.metric == "cosine" else "sqeuclidean"
    n, _ = data_sharded.shape
    size = comms.get_size()
    if n % size != 0:
        raise ValueError(
            f"n={n} rows do not divide the {size}-way mesh axis; pad the "
            "shard with zero-weight rows (serve.build does this)"
        )
    if reduce_dtype is None:
        from raft_tpu.comms.quantized import reduce_dtype_from_env

        reduce_dtype = reduce_dtype_from_env()
    w = (
        jnp.ones((n,), jnp.float32)
        if sample_weights is None
        else jnp.asarray(sample_weights, jnp.float32)
    )
    key = jax.random.fold_in(jax.random.PRNGKey(params.seed), 0)
    if params.init == "array" and init_centers is None:
        raise ValueError("init='array' requires init_centers")

    run = _lloyd_sharded_program(
        comms.mesh, comms.axis, params.max_iter, float(params.tol), metric,
        params.batch_samples, reduce_dtype,
    )

    subsample = w_sub = None
    if init_centers is None:
        # replicated init subsample: rows travel once at init.  A
        # with-replacement draw is O(n_sub) — no full-n permutation of
        # the sharded dataset; collisions in an init sample are harmless
        k_sub, key = jax.random.split(key)
        n_sub = min(n, max(4 * params.n_clusters, 4096))
        idx = jax.random.randint(k_sub, (n_sub,), 0, n)
        subsample = jnp.asarray(data_sharded[idx], jnp.float32)
        if metric == "cosine":
            subsample = _normalize_rows(subsample)
        w_sub = w[idx]  # zero-weight padding rows are never seeds

    n_init = 1 if init_centers is not None else max(params.n_init, 1)
    best = None
    for trial in range(n_init):
        kt = jax.random.fold_in(key, trial)
        if init_centers is not None:
            c0 = jnp.asarray(init_centers, jnp.float32)
            if metric == "cosine":
                c0 = _normalize_rows(c0)
        elif params.init == "random":
            idx2 = jax.random.choice(
                kt, subsample.shape[0], shape=(params.n_clusters,),
                replace=subsample.shape[0] < params.n_clusters,
                p=w_sub / jnp.maximum(jnp.sum(w_sub), 1e-12),
            )
            c0 = subsample[idx2]
        else:
            c0 = kmeans_plus_plus_init(kt, subsample, params.n_clusters, w_sub)
        centers, inertia, n_iter = run(data_sharded, w, c0)
        if best is None or float(inertia) < float(best[1]):
            best = (centers, inertia, n_iter)
    return best


@traced("kmeans.predict")
def predict(
    centroids: jax.Array,
    x: jax.Array,
    *,
    metric: str = "sqeuclidean",
    batch_samples: int = 1 << 15,
    res: Optional[Resources] = None,
) -> jax.Array:
    """Nearest-centroid labels (Python ref: pylibraft kmeans predict path)."""
    x = jnp.asarray(x, jnp.float32)
    c = jnp.asarray(centroids, jnp.float32)
    if metric == "cosine":
        x, c = _normalize_rows(x), _normalize_rows(c)
    _, labels = _assign(x, c, batch_samples)
    return labels


@traced("kmeans.fit_predict")
def fit_predict(
    params: KMeansParams,
    x: jax.Array,
    sample_weights: Optional[jax.Array] = None,
    *,
    res: Optional[Resources] = None,
):
    centroids, inertia, n_iter = fit(params, x, sample_weights, res=res)
    labels = predict(
        centroids, x, metric=params.metric, batch_samples=params.batch_samples, res=res
    )
    return centroids, labels, inertia, n_iter


@traced("kmeans.transform")
def transform(centroids: jax.Array, x: jax.Array) -> jax.Array:
    """Distances to every centroid (ref: kmeans.cuh kmeans_transform)."""
    return distance_matrix_tile(
        jnp.asarray(x, jnp.float32), jnp.asarray(centroids, jnp.float32), "sqeuclidean"
    )


@traced("kmeans.cluster_cost")
def cluster_cost(
    x: jax.Array,
    centroids: jax.Array,
    *,
    batch_samples: int = 1 << 15,
    res: Optional[Resources] = None,
) -> jax.Array:
    """Total inertia (Python ref: pylibraft.cluster.kmeans.cluster_cost)."""
    best, _ = _assign(
        jnp.asarray(x, jnp.float32), jnp.asarray(centroids, jnp.float32), batch_samples
    )
    return jnp.sum(best)
