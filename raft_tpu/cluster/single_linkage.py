"""Single-linkage agglomerative clustering.

Reference: ``cluster/single_linkage.cuh`` → ``cluster/detail/single_linkage.cuh:53-124``
pipeline: pairwise/kNN connectivity graph → MST (with cross-component
connection passes) → dendrogram → flattened labels
(sparse/hierarchy/single_linkage.cuh; agglomerative label step
cluster/detail/agglomerative.cuh build_dendrogram_host).

TPU re-design: graph + MST phases are the batched device programs in
raft_tpu.sparse (brute-force kNN → COO, Borůvka with segment-mins); the
dendrogram walk is inherently sequential over n−1 merges, so — like the
reference, which builds the dendrogram on host — it runs as a numpy
union-find over the (already device-computed) sorted MST edges.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core.resources import Resources, ensure
from raft_tpu.sparse.formats import COO
from raft_tpu.sparse.neighbors import knn_graph
from raft_tpu.sparse.solver import cross_component_nn, mst
from raft_tpu.core.trace import traced


@dataclass
class SingleLinkageOutput:
    """(ref: single_linkage_output sparse/hierarchy/detail types)"""

    labels: jax.Array        # [n] cluster ids 0..n_clusters-1
    dendrogram: np.ndarray   # [n-1, 2] merged child pair per step
    deltas: np.ndarray       # [n-1] merge distances
    sizes: np.ndarray        # [n-1] merged cluster sizes
    n_clusters: int


@traced("single_linkage.single_linkage")
def single_linkage(
    x: jax.Array,
    *,
    n_clusters: int = 2,
    c: int = 15,
    metric: str = "sqeuclidean",
    res: Optional[Resources] = None,
) -> SingleLinkageOutput:
    """KNN-graph single-linkage (the reference's LinkageDistance::KNN_GRAPH
    mode with `c` controlling k; detail/single_linkage.cuh:53-124)."""
    res = ensure(res)
    x = jnp.asarray(x, jnp.float32)
    n = x.shape[0]
    if not (1 <= n_clusters <= n):
        raise ValueError(f"n_clusters {n_clusters} out of range [1, {n}]")

    # --- connectivity graph: symmetric kNN (k grows with c, ref uses
    # log(n)+c heuristics in cuml; we take c as k directly, min-clamped)
    k = min(n - 1, max(2, c))
    graph = knn_graph(x, k, metric=metric, res=res)

    # --- MST, with cross-component connection retries (ref:
    # detail/single_linkage.cuh connect_components loop — a kNN graph is not
    # guaranteed connected)
    rows = np.asarray(graph.rows)[: graph.nnz]
    cols = np.asarray(graph.cols)[: graph.nnz]
    data = np.asarray(graph.data)[: graph.nnz]
    for _ in range(32):
        g = COO(rows, cols, data, (n, n))
        mst_coo, comp, _ = mst(g, res=res)
        n_comp = len(np.unique(np.asarray(comp)))
        if n_comp == 1:
            break
        extra = cross_component_nn(x, comp, res=res)
        rows = np.concatenate([rows, np.asarray(extra.rows)])
        cols = np.concatenate([cols, np.asarray(extra.cols)])
        data = np.concatenate([data, np.asarray(extra.data)])
    else:
        raise RuntimeError("could not connect MST components")

    # --- dendrogram: sequential union-find over weight-sorted MST edges
    er = np.asarray(mst_coo.rows)[: mst_coo.nnz]
    ec = np.asarray(mst_coo.cols)[: mst_coo.nnz]
    ew = np.asarray(mst_coo.data)[: mst_coo.nnz]
    order = np.argsort(ew, kind="stable")
    er, ec, ew = er[order], ec[order], ew[order]

    parent = np.arange(2 * n - 1)
    cluster_of = np.arange(n)  # current cluster id of each root
    size = np.ones(2 * n - 1, np.int64)

    def find(u):
        while parent[u] != u:
            parent[u] = parent[parent[u]]
            u = parent[u]
        return u

    dendrogram = np.zeros((n - 1, 2), np.int64)
    deltas = np.zeros(n - 1, np.float64)
    sizes = np.zeros(n - 1, np.int64)
    nxt = n
    for i in range(n - 1):
        ra, rb = find(er[i]), find(ec[i])
        ca, cb = cluster_of[ra], cluster_of[rb]
        dendrogram[i] = (ca, cb)
        deltas[i] = ew[i]
        sz = size[ca] + size[cb]
        sizes[i] = sz
        parent[rb] = ra  # union by attaching b's root under a's
        cluster_of[ra] = nxt
        size[nxt] = sz
        nxt += 1

    # --- flatten: the last (n_clusters−1) merges are undone — i.e. stop the
    # union sequence early and read off component labels
    parent = np.arange(n)

    def find2(u):
        while parent[u] != u:
            parent[u] = parent[parent[u]]
            u = parent[u]
        return u

    for i in range(n - n_clusters):
        ra, rb = find2(er[i]), find2(ec[i])
        parent[rb] = ra
    roots = np.fromiter((find2(u) for u in range(n)), np.int64, n)
    _, labels = np.unique(roots, return_inverse=True)
    return SingleLinkageOutput(
        labels=jnp.asarray(labels.astype(np.int32)),
        dendrogram=dendrogram,
        deltas=deltas,
        sizes=sizes,
        n_clusters=n_clusters,
    )
