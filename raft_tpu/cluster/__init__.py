"""Clustering: kmeans, balanced kmeans, single-linkage, spectral
(ref: cpp/include/raft/cluster/)."""

from raft_tpu.cluster.kmeans import (
    KMeansParams,
    fit,
    fit_sharded,
    predict,
    fit_predict,
    transform,
    cluster_cost,
    compute_new_centroids,
    kmeans_plus_plus_init,
)
from raft_tpu.cluster import kmeans_balanced
from raft_tpu.cluster.single_linkage import SingleLinkageOutput, single_linkage
from raft_tpu.cluster import spectral
from raft_tpu.cluster.auto_find_k import find_k

__all__ = [
    "spectral",
    "find_k",
    "SingleLinkageOutput",
    "single_linkage",
    "KMeansParams",
    "fit",
    "fit_sharded",
    "predict",
    "fit_predict",
    "transform",
    "cluster_cost",
    "compute_new_centroids",
    "kmeans_plus_plus_init",
    "kmeans_balanced",
]
