"""Automatic k search for k-means.

Reference: ``cluster/kmeans_auto_find_k.cuh`` (find_k) — bisection over k
guided by the relative inertia improvement, stopping when adding clusters no
longer buys a ``threshold`` fraction of cost.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.cluster import kmeans
from raft_tpu.core.resources import Resources, ensure
from raft_tpu.core.trace import traced


@traced("cluster.find_k")
def find_k(
    x: jax.Array,
    kmax: int,
    *,
    kmin: int = 1,
    threshold: float = 0.05,
    max_iter: int = 100,
    seed: int = 0,
    res: Optional[Resources] = None,
) -> Tuple[int, jax.Array, jax.Array]:
    """Search [kmin, kmax] for the inertia elbow.

    Returns (k, centroids [k, d], inertia) (ref: kmeans_auto_find_k.cuh
    find_k — same bisection-on-improvement idea)."""
    res = ensure(res)
    x = jnp.asarray(x, jnp.float32)
    if not (1 <= kmin <= kmax <= x.shape[0]):
        raise ValueError(f"bad k range [{kmin}, {kmax}] for n={x.shape[0]}")

    def cost(k: int):
        params = kmeans.KMeansParams(
            n_clusters=k, max_iter=max_iter, seed=seed
        )
        centers, inertia, _ = kmeans.fit(params, x, res=res)
        return centers, float(inertia)

    cache = {}

    def cost_cached(k: int):
        if k not in cache:
            cache[k] = cost(k)
        return cache[k]

    lo, hi = kmin, kmax
    _, c_lo = cost_cached(lo)
    while hi - lo > 1:
        mid = (lo + hi) // 2
        _, c_mid = cost_cached(mid)
        # relative improvement per added cluster from lo → mid
        gain = (c_lo - c_mid) / max(c_lo, 1e-30) / max(mid - lo, 1)
        if gain > threshold:
            lo, c_lo = mid, c_mid
        else:
            hi = mid
    best_k = lo
    centers, inertia = cost_cached(best_k)
    return best_k, centers, jnp.asarray(inertia)
