"""Hierarchical *balanced* k-means — the coarse quantizer trainer used by all
IVF index builds.

Reference: ``cluster/detail/kmeans_balanced.cuh`` (1,089 LoC) —
``build_hierarchical`` (:952) trains ~√k mesoclusters, partitions the
trainset, trains fine clusters per mesocluster sized proportionally
(``build_fine_clusters`` :839), then runs balancing iterations where
``adjust_centers`` (:521) re-seeds under-populated clusters from populous
ones. The inner loop is fused-L2-argmin predict + reduce_rows_by_key update
(:83-164). Public API: ``fit/predict/fit_predict``
(cluster/kmeans_balanced.cuh:76-).

TPU shape: predict is an MXU matmul tile + argmin; update is segment_sum;
``adjust_centers`` is expressed as a jit-friendly masked teleport (small
clusters jump to a random point of an over-populated cluster). The
per-mesocluster fine fits share one compiled function over a padded member
buffer (weight-0 padding), so hierarchy costs one compile.
"""

from __future__ import annotations

import functools
import math
from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from raft_tpu.core.resources import Resources, ensure
from raft_tpu.distance.pairwise import argmin_tile_rows, tiled_argmin
from raft_tpu.core.trace import traced


@dataclass
class KMeansBalancedParams:
    """(ref: cluster/kmeans_balanced.cuh kmeans_balanced_params — n_iters is
    the reference's `kmeans_n_iters`, default 20 in ivf types)"""

    n_iters: int = 20
    metric: str = "sqeuclidean"  # sqeuclidean | cosine (spherical) | inner_product
    mesocluster_threshold: int = 256  # hierarchy kicks in above this many clusters
    seed: int = 0


def _maybe_normalize(x: jax.Array, metric: str) -> jax.Array:
    if metric == "cosine":
        return x / jnp.maximum(jnp.linalg.norm(x, axis=1, keepdims=True), 1e-12)
    return x


@functools.partial(jax.jit, static_argnames=("metric", "tile_rows"))
def _predict_jit(centers, x, metric: str, tile_rows: int):
    """Normalize + delegate to the shared workspace-tiled fused
    distance+argmin (pairwise.tiled_argmin — see its DEEP-scale memory
    rationale; the reference likewise batches predict,
    cluster/detail/kmeans_balanced.cuh predict's minibatch loop)."""
    x = _maybe_normalize(x.astype(jnp.float32), metric)
    c = _maybe_normalize(centers.astype(jnp.float32), metric)
    inner = "inner_product" if metric == "inner_product" else "sqeuclidean"
    return tiled_argmin(x, c, inner, tile_rows)


@traced("kmeans_balanced.predict")
def predict(
    centers: jax.Array,
    x: jax.Array,
    *,
    metric: str = "sqeuclidean",
    res: Optional[Resources] = None,
) -> jax.Array:
    """Labels via fused distance-argmin (ref: kmeans_balanced.cuh predict →
    predict_core :83-164, which uses fusedL2NNMinReduce for L2 and
    pairwise_distance+argmin for other metrics — the metric MUST match the
    one used at build so list membership and probe ranking agree)."""
    res = ensure(res)
    centers = jnp.asarray(centers)
    return _predict_jit(
        centers, jnp.asarray(x), metric,
        argmin_tile_rows(centers.shape[0], res),
    )


@functools.partial(
    jax.jit, static_argnames=("n_iters", "n_clusters", "metric", "tile_rows")
)
def _balanced_iterations(
    key: jax.Array,
    x: jax.Array,
    centers0: jax.Array,
    weights: jax.Array,
    n_iters: int,
    n_clusters: int,
    metric: str = "sqeuclidean",
    tile_rows: int = 1 << 16,
):
    """n_iters × (assign → update → adjust_centers).

    adjust_centers (ref: kmeans_balanced.cuh:521): clusters with
    count < average/ratio are re-seeded to a random trainset point drawn
    from the data mass (points in big clusters are proportionally more
    likely), keeping cluster sizes balanced — essential for IVF list
    uniformity.
    """
    n = x.shape[0]
    spherical = metric == "cosine"
    inner = "inner_product" if metric == "inner_product" else "sqeuclidean"

    def assign(centers):
        # shared workspace-tiled fused distance+argmin (pairwise.tiled_argmin)
        return tiled_argmin(x, centers, inner, tile_rows)

    def body(carry, key_i):
        centers = carry
        labels = assign(centers)
        sums = jax.ops.segment_sum(x * weights[:, None], labels, num_segments=n_clusters)
        counts = jax.ops.segment_sum(weights, labels, num_segments=n_clusters)
        centers = jnp.where(
            counts[:, None] > 0, sums / jnp.maximum(counts[:, None], 1e-30), centers
        )
        if spherical:
            centers = _maybe_normalize(centers, "cosine")
        # --- adjust: teleport starved clusters onto random data points,
        # uniform over positive-weight rows (weight-0 padding never chosen).
        # Inverse-CDF draw, NOT jax.random.categorical: categorical over n
        # logits with shape=(n_clusters,) materializes an [n_clusters, n]
        # gumbel tensor — ~1 GB/iteration at a 250k trainset and ~50 GB at
        # DEEP-scale (measured via compile memory_analysis; it was the
        # build pipeline's peak-memory term)
        total = jnp.sum(weights)
        avg = total / n_clusters
        starved = counts < avg / 8.0  # ref threshold: average/adjust ratio
        # int32 cumsum: an f32 running sum silently plateaus at 2^24 rows,
        # which would starve everything past ~16.7M of selection probability
        cum = jnp.cumsum((weights > 0).astype(jnp.int32))
        r = jax.random.randint(key_i, (n_clusters,), 1, cum[-1] + 1)
        # first idx with cum[idx] >= r: zero-weight rows own empty intervals
        picks = jnp.clip(jnp.searchsorted(cum, r), 0, n - 1)
        centers = jnp.where(starved[:, None], x[picks], centers)
        return centers, counts

    keys = jax.random.split(key, n_iters)
    centers, counts_hist = lax.scan(body, centers0, keys)
    # final clean update without adjustment
    labels = assign(centers)
    sums = jax.ops.segment_sum(x * weights[:, None], labels, num_segments=n_clusters)
    counts = jax.ops.segment_sum(weights, labels, num_segments=n_clusters)
    centers = jnp.where(
        counts[:, None] > 0, sums / jnp.maximum(counts[:, None], 1e-30), centers
    )
    if spherical:
        centers = _maybe_normalize(centers, "cosine")
    return centers, labels


@functools.partial(
    jax.jit, static_argnames=("n_clusters", "n_iters", "metric", "tile_rows")
)
def _fit_flat(
    key: jax.Array,
    x: jax.Array,
    n_clusters: int,
    n_iters: int,
    weights: jax.Array,
    metric: str = "sqeuclidean",
    tile_rows: int = 1 << 16,
) -> jax.Array:
    k_init, k_iter = jax.random.split(key)
    n = x.shape[0]
    # init ∝ weight, *without replacement*: distinct seeds, and weight-0
    # padding rows are never chosen while any positive-weight row remains
    idx = jax.random.choice(
        k_init, n, shape=(n_clusters,), replace=n < n_clusters,
        p=weights / jnp.maximum(jnp.sum(weights), 1e-12),
    )
    centers0 = x[idx]
    centers, _ = _balanced_iterations(
        k_iter, x, centers0, weights, n_iters, n_clusters, metric, tile_rows
    )
    return centers


@traced("kmeans_balanced.fit")
def fit(
    params: KMeansBalancedParams,
    x: jax.Array,
    n_clusters: int,
    *,
    res: Optional[Resources] = None,
) -> jax.Array:
    """Train n_clusters balanced centers (ref: kmeans_balanced.cuh fit →
    detail::build_hierarchical :952)."""
    res = ensure(res)
    metric = params.metric
    x = _maybe_normalize(jnp.asarray(x, jnp.float32), metric)
    n, d = x.shape
    key = jax.random.PRNGKey(params.seed)
    ones = jnp.ones((n,), jnp.float32)

    tile_rows = argmin_tile_rows(n_clusters, res)
    if n_clusters <= params.mesocluster_threshold or n < 4 * n_clusters:
        return _fit_flat(
            key, x, n_clusters, params.n_iters, ones, metric, tile_rows
        )

    # ---- hierarchical path (ref: build_hierarchical :952) -----------------
    n_meso = int(math.ceil(math.sqrt(n_clusters)))
    k_meso, k_fine, k_final = jax.random.split(key, 3)
    meso_centers = _fit_flat(
        k_meso, x, n_meso, params.n_iters, ones, metric, tile_rows
    )
    # x is already normalized for cosine (normalizing again is idempotent),
    # so this assignment matches the training metric
    meso_labels = np.asarray(predict(meso_centers, x, metric=metric, res=res))

    # fine cluster budget per mesocluster, proportional to its population;
    # empty mesoclusters get 0 fine clusters (ref: build_fine_clusters :839)
    counts = np.bincount(meso_labels, minlength=n_meso).astype(np.int64)
    fine_k = np.where(
        counts > 0,
        np.maximum(1, np.floor(n_clusters * counts / max(n, 1)).astype(np.int64)),
        0,
    )
    occupied = counts > 0
    while fine_k.sum() != n_clusters:  # fix rounding drift
        if fine_k.sum() < n_clusters:
            load = np.where(occupied, counts / np.maximum(fine_k, 1), -np.inf)
            fine_k[np.argmax(load)] += 1
        else:
            load = np.where(fine_k > 1, counts / np.maximum(fine_k, 1), np.inf)
            fine_k[np.argmin(load)] -= 1

    # one compiled, vmapped fine-fit over a padded member buffer for ALL
    # mesoclusters at once (one dispatch instead of n_meso sequential fits);
    # padding repeats the mesocluster's own members (weight 0) so random
    # seeds/teleports can never land outside the partition
    # bucket the padded shapes to stable sizes (next power of two members,
    # sublane-multiple fine count): the vmapped fine fit is compiled per
    # (max_members, max_fine) signature, and raw data-dependent values force
    # a fresh XLA compile for every dataset — measured 27 s per recompile
    # through the TPU tunnel. Extra lanes are weight-0 padding.
    max_members = min(int(counts.max()), n)
    max_members = 1 << max(5, (max_members - 1).bit_length())
    max_fine = int(-(-int(fine_k.max()) // 8) * 8)
    occ = np.nonzero((counts > 0) & (fine_k > 0))[0]
    sel = np.empty((len(occ), max_members), np.int64)
    wts = np.zeros((len(occ), max_members), np.float32)
    for row, m in enumerate(occ):
        members = np.nonzero(meso_labels == m)[0]
        pad = max_members - len(members)
        sel[row, : len(members)] = members
        sel[row, len(members):] = members[np.arange(pad) % len(members)]
        wts[row, : len(members)] = 1.0
    keys = jax.vmap(lambda m: jax.random.fold_in(k_fine, m))(jnp.asarray(occ))
    vfit = jax.vmap(
        lambda kk, sub, w: _fit_flat(
            kk, sub, max_fine, params.n_iters, w, metric, tile_rows
        )
    )
    # chunk the vmap so peak memory stays inside the workspace budget even
    # when one mesocluster holds most of the trainset (member buffer +
    # per-iteration distance tile per vmapped lane)
    per_meso = 4 * max_members * (x.shape[1] + max_fine)
    chunk = int(np.clip(res.workspace_limit_bytes // max(per_meso, 1), 1, len(occ)))
    parts = []
    for s in range(0, len(occ), chunk):
        idx = jnp.asarray(sel[s : s + chunk])
        parts.append(
            np.asarray(
                vfit(keys[s : s + chunk], x[idx], jnp.asarray(wts[s : s + chunk]))
            )
        )
    fine_np = np.concatenate(parts)
    centers = jnp.asarray(
        np.concatenate([fine_np[r, : int(fine_k[m])] for r, m in enumerate(occ)])
    )
    assert centers.shape[0] == n_clusters, (centers.shape, n_clusters)

    # final balancing passes over the full trainset (ref: :1016-1043)
    centers, _ = _balanced_iterations(
        k_final, x, centers, ones, max(2, params.n_iters // 10), n_clusters,
        metric, tile_rows,
    )
    return centers


@functools.lru_cache(maxsize=32)
def _balanced_sharded_program(
    mesh, axis: str, n_iters: int, n_clusters: int, metric: str,
    tile_rows: int, reduce_dtype: str,
):
    """Build (and cache) the compiled sharded balancing loop — the
    distributed counterpart of :func:`_balanced_iterations`.  Each shard
    assigns its rows and computes partial sums/counts; partials merge in
    ONE packed (optionally quantized) psum per iteration.  The starved-
    cluster teleport draws from a replicated weight-mass pool (the init
    subsample) instead of the full trainset — the draw must be identical
    on every shard, and shipping a cross-shard gather into the scan would
    reintroduce per-iteration row traffic."""
    from jax.sharding import PartitionSpec as P

    from raft_tpu.core.compat import shard_map
    from raft_tpu.comms.quantized import quantized_psum

    spherical = metric == "cosine"
    inner = "inner_product" if metric == "inner_product" else "sqeuclidean"

    def local(key, x, w, c0, pool, pool_w):
        x = _maybe_normalize(x.astype(jnp.float32), metric)
        w = w.astype(jnp.float32)
        d = c0.shape[1]
        m = pool.shape[0]

        def assign(centers):
            return tiled_argmin(x, centers, inner, tile_rows)

        def update(centers):
            labels = assign(centers)
            sums = jax.ops.segment_sum(
                x * w[:, None], labels, num_segments=n_clusters
            )
            counts = jax.ops.segment_sum(w, labels, num_segments=n_clusters)
            packed = quantized_psum(
                jnp.concatenate([sums, counts[:, None]], axis=1),
                axis, reduce_dtype,
            )
            g_sums, g_counts = packed[:, :d], packed[:, d]
            centers = jnp.where(
                g_counts[:, None] > 0,
                g_sums / jnp.maximum(g_counts[:, None], 1e-30),
                centers,
            )
            if spherical:
                centers = _maybe_normalize(centers, "cosine")
            return centers, labels, g_counts

        def body(carry, key_i):
            centers, _, g_counts = update(carry)
            # teleport starved clusters onto random pool rows (same
            # inverse-CDF weight-mass draw as _balanced_iterations);
            # replicated pool + replicated key → every shard teleports
            # identically, keeping centers replicated without a collective
            avg = jnp.sum(g_counts) / n_clusters
            starved = g_counts < avg / 8.0
            cum = jnp.cumsum((pool_w > 0).astype(jnp.int32))
            r = jax.random.randint(key_i, (n_clusters,), 1, cum[-1] + 1)
            picks = jnp.clip(jnp.searchsorted(cum, r), 0, m - 1)
            centers = jnp.where(starved[:, None], pool[picks], centers)
            return centers, g_counts

        keys = jax.random.split(key, n_iters)
        centers, _ = lax.scan(body, c0, keys)
        # final clean update without adjustment
        centers, labels, _ = update(centers)
        return centers, labels

    return jax.jit(
        shard_map(
            local,
            mesh=mesh,
            in_specs=(
                P(None), P(axis, None), P(axis), P(None, None),
                P(None, None), P(None),
            ),
            out_specs=(P(None, None), P(axis)),
            check_vma=False,
        )
    )


@traced("kmeans_balanced.fit_sharded")
def fit_sharded(
    comms,
    params: KMeansBalancedParams,
    data_sharded: jax.Array,
    n_clusters: int,
    sample_weights: Optional[jax.Array] = None,
    *,
    init_centers: Optional[jax.Array] = None,
    reduce_dtype: Optional[str] = None,
    res: Optional[Resources] = None,
) -> Tuple[jax.Array, jax.Array]:
    """:func:`fit` over data row-sharded across ``comms``' mesh axis.

    Seeding (the hierarchical/flat :func:`fit`) runs on a replicated
    weight-aware subsample — rows travel exactly once, bounded size —
    then the balancing iterations run distributed over the FULL sharded
    trainset: per-shard assign + partial sums, merged with one packed
    (optionally ``reduce_dtype``-quantized, env
    ``RAFT_TPU_BUILD_REDUCE_DTYPE``) psum per iteration.  The starved-
    cluster teleport draws from the replicated subsample (a weight-mass
    draw like the reference's adjust_centers) so all shards stay
    center-replicated without extra collectives.

    ``data_sharded`` is [n, d] with n a multiple of the axis size (pad
    with zero-weight rows otherwise).  Returns (centers [k, d]
    replicated, labels [n] sharded).
    """
    res = ensure(res)
    metric = params.metric
    n, _ = data_sharded.shape
    size = comms.get_size()
    if n % size != 0:
        raise ValueError(
            f"n={n} rows do not divide the {size}-way mesh axis; pad the "
            "shard with zero-weight rows (serve.build does this)"
        )
    if reduce_dtype is None:
        from raft_tpu.comms.quantized import reduce_dtype_from_env

        reduce_dtype = reduce_dtype_from_env()
    w = (
        jnp.ones((n,), jnp.float32)
        if sample_weights is None
        else jnp.asarray(sample_weights, jnp.float32)
    )
    key = jax.random.PRNGKey(params.seed)
    k_sub, k_iter = jax.random.split(key)

    # replicated pool: seeds the hierarchy AND feeds the teleport draws.
    # With-replacement draw — O(n_sub), no full-n permutation; host-side
    # filtering drops zero-weight padding rows so they never seed
    n_sub = min(n, max(8 * n_clusters, 8192))
    idx = np.asarray(
        jax.random.randint(k_sub, (n_sub,), 0, n), dtype=np.int64
    )
    w_np = np.asarray(w)
    idx = idx[w_np[idx] > 0]
    if idx.size == 0:
        raise ValueError("all sample weights are zero; nothing to cluster")
    pool = _maybe_normalize(
        jnp.asarray(data_sharded[jnp.asarray(idx)], jnp.float32), metric
    )
    pool_w = jnp.asarray(w_np[idx])

    if init_centers is None:
        c0 = fit(params, pool, n_clusters, res=res)
    else:
        c0 = _maybe_normalize(jnp.asarray(init_centers, jnp.float32), metric)

    run = _balanced_sharded_program(
        comms.mesh, comms.axis, max(1, params.n_iters), n_clusters, metric,
        argmin_tile_rows(n_clusters, res), reduce_dtype,
    )
    return run(k_iter, data_sharded, w, c0, pool, pool_w)


@traced("kmeans_balanced.fit_predict")
def fit_predict(
    params: KMeansBalancedParams,
    x: jax.Array,
    n_clusters: int,
    *,
    res: Optional[Resources] = None,
) -> Tuple[jax.Array, jax.Array]:
    centers = fit(params, x, n_clusters, res=res)
    return centers, predict(centers, x, metric=params.metric, res=res)
