"""Shared helpers for the IVF index family.

Padded-list packing, coarse cluster selection, and bitset-filter masking are
identical between IVF-Flat and IVF-PQ (ref: the reference shares them via
``neighbors/ivf_list.hpp`` + ``detail/ivf_common.cuh``)."""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.distance.pairwise import _PREC
from raft_tpu.ops.matrix import select_k


def round_up(v: int, m: int) -> int:
    return (v + m - 1) // m * m


def pack_padded_lists(
    payload: np.ndarray, ids: np.ndarray, labels: np.ndarray, n_lists: int
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Scatter rows into the padded [n_lists, cap, ...] layout (host-side;
    the analog of the reference's per-list code/vector packing,
    ivf_flat_build.cuh:88-154). Returns (list_payload, list_index, sizes);
    cap is the max list size rounded up to the sublane multiple (8)."""
    n = payload.shape[0]
    sizes = np.bincount(labels, minlength=n_lists)
    cap = max(8, round_up(int(sizes.max()) if n else 8, 8))
    list_payload = np.zeros((n_lists, cap) + payload.shape[1:], payload.dtype)
    list_index = np.full((n_lists, cap), -1, np.int32)
    order = np.argsort(labels, kind="stable")
    starts = np.zeros(n_lists + 1, np.int64)
    np.cumsum(sizes, out=starts[1:])
    within = np.arange(n) - starts[labels[order]]
    list_payload[labels[order], within] = payload[order]
    list_index[labels[order], within] = ids[order]
    return list_payload, list_index, sizes.astype(np.int32)


def unpack_lists(
    list_payload: np.ndarray, list_index: np.ndarray
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Inverse of pack_padded_lists → (payload, ids, labels) host arrays."""
    valid = list_index >= 0
    payload = list_payload[valid]
    ids = list_index[valid]
    labels = np.repeat(np.arange(list_index.shape[0]), valid.sum(1)).astype(np.int32)
    return payload, ids, labels


def coarse_select(
    queries: jax.Array, centers: jax.Array, metric: str, n_probes: int
) -> jax.Array:
    """Top-n_probes cluster ids per query: one MXU GEMM + select_k
    (ref: ivf_pq_search.cuh select_clusters:67, ivf_flat_search-inl.cuh:40)."""
    if metric == "cosine":
        qn = queries / jnp.maximum(jnp.linalg.norm(queries, axis=1, keepdims=True), 1e-12)
        cn = centers / jnp.maximum(jnp.linalg.norm(centers, axis=1, keepdims=True), 1e-12)
        coarse = -jnp.matmul(qn, cn.T, precision=_PREC)
    elif metric == "inner_product":
        coarse = -jnp.matmul(queries, centers.T, precision=_PREC)
    else:
        cnorm = jnp.sum(centers * centers, axis=1)
        coarse = cnorm[None, :] - 2.0 * jnp.matmul(queries, centers.T, precision=_PREC)
    _, probes = select_k(coarse, n_probes, select_min=True)
    return probes


def sorted_id_dedup(ids: jax.Array):
    """Shared sorted-id dedup idiom: stable-sort each row by id and flag every
    repeat after the first occurrence (the TPU replacement for visited
    hash-sets / bloom filters — one sort + one adjacent compare).

    Returns (order [n, m] int32 — the stable argsort, dup [n, m] bool in
    *sorted* space). Callers gather their payloads through ``order`` and
    demote slots where ``dup`` (first occurrence in the original layout wins,
    because stable sort preserves it)."""
    order = jnp.argsort(ids, axis=-1, stable=True)
    s = jnp.take_along_axis(ids, order, axis=-1)
    dup = jnp.concatenate(
        [jnp.zeros_like(s[..., :1], bool), s[..., 1:] == s[..., :-1]], axis=-1
    )
    return order, dup


def invalid_mask(ids: jax.Array, filter_words: Optional[jax.Array]) -> jax.Array:
    """Candidate mask: padding slots plus bitset-filtered ids
    (ref: neighbors/sample_filter_types.hpp bitset_filter)."""
    invalid = ids < 0
    if filter_words is not None:
        word = filter_words[jnp.clip(ids, 0, None) // 32]
        bit = (word >> (jnp.clip(ids, 0, None) % 32).astype(jnp.uint32)) & 1
        invalid = invalid | (bit == 0)
    return invalid
