"""Shared helpers for the IVF index family.

Padded-list packing, coarse cluster selection, and bitset-filter masking are
identical between IVF-Flat and IVF-PQ (ref: the reference shares them via
``neighbors/ivf_list.hpp`` + ``detail/ivf_common.cuh``)."""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.distance.pairwise import _PREC
from raft_tpu.ops.matrix import select_k


def round_up(v: int, m: int) -> int:
    return (v + m - 1) // m * m


def merge_split_lists(centers: np.ndarray, labels: np.ndarray):
    """Collapse split shards (bit-identical duplicated centroids) back to
    their parent list before a re-pack.

    Without this, repeated extend() calls inflate n_lists without bound:
    predict() ties on duplicated centroids send every new member to the
    first shard, which then re-splits each call. Returns
    (unique_idx [L_unique] — first occurrence of each distinct centroid in
    original order, new_labels mapped onto the unique set)."""
    centers = np.asarray(centers)
    _, first_idx, inverse = np.unique(
        centers, axis=0, return_index=True, return_inverse=True
    )
    # re-order the unique set by first occurrence so stable list ids persist
    order = np.argsort(first_idx)
    rank = np.empty_like(order)
    rank[order] = np.arange(len(order))
    unique_idx = first_idx[order]
    new_labels = rank[inverse[np.asarray(labels, np.int64)]]
    return unique_idx, new_labels.astype(np.int64)


def default_max_cap(n_rows: int, n_lists: int) -> int:
    """Per-list capacity bound: a slack factor over the mean occupancy
    (sublane-rounded).

    Padded storage costs ``slack × n_rows × row_bytes`` regardless of the
    list count, so the slack factor IS the memory multiplier.  2× leaves
    room for mild imbalance without splitting; at DEEP-100M scale that
    doubling breaks the one-chip budget (2 × 9.6 GB int8 > 16 GB HBM), and
    balanced-kmeans lists are even enough that 1.25× plus
    ``split_oversized_lists`` (which relabels overflow into shard lists —
    correctness never depends on the slack) is the right trade."""
    mean = max(1, -(-n_rows // max(1, n_lists)))
    slack_num, slack_den = (5, 4) if n_rows >= 50_000_000 else (2, 1)
    return max(32, round_up(slack_num * mean // slack_den, 8))


def split_oversized_lists(
    labels: np.ndarray, n_lists: int, max_cap: int
) -> Tuple[np.ndarray, np.ndarray]:
    """Bound list skew: relabel members of lists larger than ``max_cap`` into
    split sublists appended after the original lists.

    Returns (new_labels, center_map [n_lists'] int64) where
    ``center_map[l]`` is the original list whose centroid list ``l`` shares.
    Split sublists duplicate their parent's centroid, so coarse selection
    scores them identically and probes every shard of a hot cluster at equal
    rank — scan cost stays proportional to real data instead of global-max
    padding (the TPU answer to the reference's variable-length interleaved
    lists, ivf_flat_build.cuh:88-154; see VERDICT r1 weak #4)."""
    labels = np.asarray(labels, np.int64).copy()
    sizes = np.bincount(labels, minlength=n_lists)
    center_map = list(range(n_lists))
    next_id = n_lists
    for l in np.nonzero(sizes > max_cap)[0]:
        members = np.nonzero(labels == l)[0]
        n_parts = -(-len(members) // max_cap)  # ceil
        for p in range(1, n_parts):
            part = members[p * max_cap : (p + 1) * max_cap]
            labels[part] = next_id
            center_map.append(int(l))
            next_id += 1
    return labels, np.asarray(center_map, np.int64)


def subsample_trainset(dataset, n_train: int, seed: int):
    """Host-side no-replacement row subsample → gathered rows (input dtype).

    The indices are drawn with numpy: a device-side no-replacement
    ``jax.random.choice`` lowers to a full-n sort whose one-off XLA compile
    costs ~20 s through the TPU tunnel; only the O(n_train) gather runs on
    device. (ref: trainset subsampling, ivf_pq_build.cuh:1706-1766)"""
    import jax.numpy as _jnp

    n = dataset.shape[0]
    idx = np.random.default_rng(seed).choice(n, size=n_train, replace=False)
    if isinstance(dataset, np.ndarray):
        # host dataset (possibly a memmap): gather host-side, upload only
        # the trainset rows
        return _jnp.asarray(dataset[np.sort(idx)])
    return dataset[_jnp.asarray(np.sort(idx))]


def compute_list_layout(
    labels: np.ndarray,
    n_lists: int,
    max_cap: Optional[int] = None,
    headroom: bool = False,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, int]:
    """Per-row (list, slot) placement for the padded list layout — metadata
    only, no payload touched (so callers can stream the payload scatter
    device-side in bounded chunks instead of materializing padded host
    arrays; the 100M-scale path).

    Returns (lst [n], slot [n], sizes [n_lists'], center_map [n_lists'],
    cap). cap is the max list size rounded up to the sublane multiple (8) —
    plus ~12.5% growth headroom when ``headroom`` is set, so even the
    fullest list keeps spare slots and in-place extends
    (allocate_append_slots) don't immediately fall back to a repack. With
    ``max_cap`` set, oversized lists are split (split_oversized_lists) so
    cap ≤ round_up(max_cap, 8) regardless of cluster skew; center_map tells
    the caller how to expand its centroid rows."""
    from raft_tpu.core import native

    def with_headroom(base: int) -> int:
        cap = base + max(8, base // 8) if headroom else base
        cap = max(8, round_up(cap, 8))
        if max_cap is not None:
            cap = min(cap, round_up(max_cap, 8))
        return max(cap, round_up(max(base, 1), 8))  # never below actual max

    labels = np.asarray(labels, np.int64)
    n = labels.shape[0]
    if max_cap is not None and n and native.available():
        # native layout pass (threads/split logic in C++)
        slot, lst, center_map, cap = native.pack_list_layout(
            labels, n_lists, max_cap
        )
        cap = with_headroom(cap)
        sizes = np.bincount(lst, minlength=len(center_map)).astype(np.int32)
        return lst, slot, sizes, center_map, cap

    if max_cap is not None:
        labels, center_map = split_oversized_lists(labels, n_lists, max_cap)
        n_lists = len(center_map)
    else:
        center_map = np.arange(n_lists, dtype=np.int64)
    sizes = np.bincount(labels, minlength=n_lists)
    cap = with_headroom(int(sizes.max()) if n else 8)
    order = np.argsort(labels, kind="stable")
    starts = np.zeros(n_lists + 1, np.int64)
    np.cumsum(sizes, out=starts[1:])
    slot = np.empty(n, np.int64)
    slot[order] = np.arange(n) - starts[labels[order]]
    return labels, slot, sizes.astype(np.int32), center_map, cap


def pack_padded_lists(
    payload: np.ndarray,
    ids: np.ndarray,
    labels: np.ndarray,
    n_lists: int,
    max_cap: Optional[int] = None,
    headroom: bool = False,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Scatter rows into the padded [n_lists', cap, ...] layout (host-side;
    the analog of the reference's per-list code/vector packing,
    ivf_flat_build.cuh:88-154). Returns (list_payload, list_index, sizes,
    center_map). Layout policy (headroom / skew splitting) lives in
    compute_list_layout; the payload scatter here is numpy fancy indexing —
    use compute_list_layout directly + device scatters for datasets too big
    to duplicate host-side."""
    lst, slot, sizes, center_map, cap = compute_list_layout(
        labels, n_lists, max_cap=max_cap, headroom=headroom
    )
    n_lists = len(center_map)
    list_payload = np.zeros((n_lists, cap) + payload.shape[1:], payload.dtype)
    list_index = np.full((n_lists, cap), -1, np.int32)
    list_payload[lst, slot] = payload
    list_index[lst, slot] = ids
    return list_payload, list_index, sizes, center_map


def unpack_lists(
    list_payload: np.ndarray, list_index: np.ndarray
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Inverse of pack_padded_lists → (payload, ids, labels) host arrays."""
    valid = list_index >= 0
    payload = list_payload[valid]
    ids = list_index[valid]
    labels = np.repeat(np.arange(list_index.shape[0]), valid.sum(1)).astype(np.int32)
    return payload, ids, labels


def coarse_select(
    queries: jax.Array, centers: jax.Array, metric: str, n_probes: int
) -> jax.Array:
    """Top-n_probes cluster ids per query: one MXU GEMM + select_k
    (ref: ivf_pq_search.cuh select_clusters:67, ivf_flat_search-inl.cuh:40)."""
    if metric == "cosine":
        qn = queries / jnp.maximum(jnp.linalg.norm(queries, axis=1, keepdims=True), 1e-12)
        cn = centers / jnp.maximum(jnp.linalg.norm(centers, axis=1, keepdims=True), 1e-12)
        coarse = -jnp.matmul(qn, cn.T, precision=_PREC)
    elif metric == "inner_product":
        coarse = -jnp.matmul(queries, centers.T, precision=_PREC)
    else:
        cnorm = jnp.sum(centers * centers, axis=1)
        coarse = cnorm[None, :] - 2.0 * jnp.matmul(queries, centers.T, precision=_PREC)
    _, probes = select_k(coarse, n_probes, select_min=True)
    return probes


def sorted_id_dedup(ids: jax.Array):
    """Shared sorted-id dedup idiom: stable-sort each row by id and flag every
    repeat after the first occurrence (the TPU replacement for visited
    hash-sets / bloom filters — one sort + one adjacent compare).

    Returns (order [n, m] int32 — the stable argsort, dup [n, m] bool in
    *sorted* space). Callers gather their payloads through ``order`` and
    demote slots where ``dup`` (first occurrence in the original layout wins,
    because stable sort preserves it)."""
    order = jnp.argsort(ids, axis=-1, stable=True)
    s = jnp.take_along_axis(ids, order, axis=-1)
    dup = jnp.concatenate(
        [jnp.zeros_like(s[..., :1], bool), s[..., 1:] == s[..., :-1]], axis=-1
    )
    return order, dup


def resolve_pass_filter(sample_filter, deleted_mask):
    """Fold an optional tombstone mask into the pass-filter convention.

    ``sample_filter`` keeps set bits (ref: sample_filter_types.hpp
    bitset_filter); ``deleted_mask`` EXCLUDES set bits (the serving layer's
    tombstone convention, raft_tpu.serve.mutation).  Returns a single
    pass-filter Bitset/RowFilter or None.  Both masks must cover the same
    id space when combined (a RowFilter may cover a superset — ragged
    batches filter in the global id space, which extends past the main
    index rows the tombstones cover; the extra words pass through).
    """
    from raft_tpu.core.bitset import Bitset, RowFilter

    if deleted_mask is None:
        return sample_filter
    if sample_filter is None:
        return Bitset(~deleted_mask.words, deleted_mask.n_bits)
    if isinstance(sample_filter, RowFilter):
        if sample_filter.n_bits < deleted_mask.n_bits:
            raise ValueError(
                f"row filter covers {sample_filter.n_bits} ids but "
                f"deleted_mask covers {deleted_mask.n_bits}"
            )
        nw = deleted_mask.words.shape[0]
        live = ~deleted_mask.words
        words = sample_filter.words.at[:, :nw].set(
            sample_filter.words[:, :nw] & live[None, :]
        )
        table = sample_filter.table
        if table is not None:
            table = table.at[:, :nw].set(table[:, :nw] & live[None, :])
        return RowFilter(
            words,
            sample_filter.n_bits,
            fid=sample_filter.fid,
            table=table,
            pass_count=sample_filter.pass_count,
        )
    if sample_filter.n_bits != deleted_mask.n_bits:
        raise ValueError(
            f"sample_filter covers {sample_filter.n_bits} ids but "
            f"deleted_mask covers {deleted_mask.n_bits}"
        )
    return Bitset(sample_filter.words & ~deleted_mask.words, sample_filter.n_bits)


def invalid_mask(ids: jax.Array, filter_words: Optional[jax.Array]) -> jax.Array:
    """Candidate mask: padding slots plus bitset-filtered ids
    (ref: neighbors/sample_filter_types.hpp bitset_filter)."""
    invalid = ids < 0
    if filter_words is not None:
        word = filter_words[jnp.clip(ids, 0, None) // 32]
        bit = (word >> (jnp.clip(ids, 0, None) % 32).astype(jnp.uint32)) & 1
        invalid = invalid | (bit == 0)
    return invalid


def invalid_mask_rows(ids: jax.Array, row_words: jax.Array) -> jax.Array:
    """Per-row variant of :func:`invalid_mask` for ragged batches: ids
    [rows, ...] tested against row_words [rows, n_words] — query row r is
    filtered by its own word set, so heterogeneous predicates share one
    compiled scan."""
    r = ids.shape[0]
    clipped = jnp.clip(ids, 0, None)
    word = jnp.take_along_axis(
        row_words, (clipped // 32).reshape(r, -1), axis=1
    ).reshape(ids.shape)
    bit = (word >> (clipped % 32).astype(jnp.uint32)) & 1
    return (ids < 0) | (bit == 0)


def centroid_group_inverse(centers) -> np.ndarray:
    """Group id per list, where split shards of one oversized list (which
    duplicate their parent centroid, see split_oversized_lists) share a
    group. O(L·dim) — cache the result on the index for repeated appends."""
    _, inverse = np.unique(np.asarray(centers), axis=0, return_inverse=True)
    return inverse


def invert_probes(probes: jax.Array, n_lists: int, bucket: int):
    """Invert the (query, probe) relation into per-list query buckets — the
    shared front half of the probe-major scan schedule (SURVEY §7 hard
    part 2; used by the IVF-PQ and IVF-Flat probe-major kernels).

    Traced helper; ``bucket`` (G) must be static. Returns
    (bucket_list [B], bucket_query [B, G], bucket_pair [B, G], B) where
    B = q·p//G + n_lists is the static bucket-count bound, bucket_query
    rows are -1-padded, and bucket_pair holds each slot's original
    (query-major) pair index for the scatter-back merge."""
    q, p = probes.shape
    G = bucket
    P = q * p
    pair_list = probes.reshape(P)
    pair_query = jnp.repeat(jnp.arange(q, dtype=jnp.int32), p)
    order = jnp.argsort(pair_list, stable=True)
    sl = pair_list[order]
    sq = pair_query[order]
    first = jnp.searchsorted(sl, sl, side="left")
    pos = jnp.arange(P) - first                                  # rank in list
    counts = jax.ops.segment_sum(
        jnp.ones(P, jnp.int32), sl, num_segments=n_lists
    )
    nb = (counts + G - 1) // G                                   # buckets/list
    bucket_off = jnp.cumsum(nb) - nb                             # [n_lists]
    pair_bucket = bucket_off[sl] + pos // G                      # [P]
    slot = pos % G
    B = P // G + n_lists  # static bound: Σ ceil(c/G) ≤ P/G + #nonzero lists
    bucket_list = jnp.zeros(B, jnp.int32).at[pair_bucket].set(sl)
    bucket_query = jnp.full((B, G), -1, jnp.int32).at[pair_bucket, slot].set(sq)
    bucket_pair = jnp.full((B, G), -1, jnp.int32).at[pair_bucket, slot].set(
        order.astype(jnp.int32)
    )
    return bucket_list, bucket_query, bucket_pair, B


def select_scan_strategy(
    strategy: str,
    q: int,
    n_probes: int,
    n_lists: int,
    list_cap: int,
    row_dim: int,
    workspace_bytes: int,
    k: int = 10,
):
    """Resolve the IVF scan schedule + probe-major sizing — ONE copy of the
    auto rule and the bucket/bb arithmetic for both IVF indexes and the
    sharded scan (tuned from the on-chip ``ivf_scan_ab`` A/B; see
    SearchParams.strategy).

    Returns (strategy, bucket, bb, q_tile); bucket/bb are None for
    query_major. ``q_tile`` bounds the probe-major merge buffers
    (pair partials are O(q·n_probes·k)) — callers batch queries host-side
    at this tile like the query-major path does for its gathers.
    """
    if strategy == "auto":
        # probe-major pays off when the batch reuses lists heavily: every
        # list is then streamed ~once instead of once per probing query
        strategy = (
            "probe_major"
            if q >= 256 and q * n_probes >= 4 * n_lists
            else "query_major"
        )
    if strategy != "probe_major":
        return strategy, None, None, None
    # merge-buffer bound: pair partials + bucket metadata ≈ 24 B per
    # (pair, k-slot); allow 4× the workspace for these transients. The
    # floor is the probe-major minimum batch (256) — NOT a bound override:
    # huge n_probes·k on a small workspace must still tile hard.
    per_q = max(1, n_probes * max(k, 1) * 24)
    q_tile = int(np.clip(4 * workspace_bytes // per_q, 256, max(q, 256)))
    # bucket size comes from the reuse ratio of the ACTUAL per-call batch,
    # min(q, q_tile) — sizing from the full q would leave tiles mostly -1
    # padding (masked MXU slots) whenever q ≫ q_tile
    reuse = max(1.0, (min(q, q_tile) * n_probes) / max(n_lists, 1))
    bucket = int(np.clip(1 << int(np.ceil(np.log2(reuse))), 16, 512))
    # per-step workspace: bb × (list rows + [G, cap] scores/ids + queries)
    per_b = list_cap * (row_dim * 4 + bucket * 8) + bucket * row_dim * 4
    bb = int(np.clip(workspace_bytes // max(per_b, 1), 1, 64))
    return strategy, bucket, bb, q_tile


def merge_probe_major_partials(vs, is_, bucket_pair, q, n_probes, kk, k):
    """Scatter per-(pair) top-kk partials back to (query, probe) order and
    merge per query — the back half of the probe-major schedule. ``vs``/
    ``is_`` are [B_pad·G, kk]; padding slots carry bucket_pair −1 and are
    dropped."""
    P = q * n_probes
    flat_pair = bucket_pair.reshape(-1)
    dest = jnp.where(flat_pair >= 0, flat_pair, P)               # P = drop
    pair_v = jnp.full((P, kk), jnp.inf, jnp.float32).at[dest].set(
        vs, mode="drop"
    )
    pair_i = jnp.full((P, kk), -1, jnp.int32).at[dest].set(is_, mode="drop")
    return select_k(
        pair_v.reshape(q, n_probes * kk), k, select_min=True,
        input_indices=pair_i.reshape(q, n_probes * kk),
    )


def pallas_scan_enabled(
    metric: str, storage_dtype, *, allow_int8: bool = False
) -> bool:
    """ONE copy of the fused-Pallas-scan gate shared by ivf_pq and
    ivf_flat: opt-in via RAFT_TPU_PALLAS=1, L2 + inner-product + cosine,
    float/bf16 storage (the kernel upcasts in VMEM). Filtered searches
    ride the kernel's packed per-list word table (round 4 — see
    kernels/ivf_scan.pack_list_filter). ``allow_int8`` admits the
    quantized scan cache (ivf_pq only — the kernel's int8 leg dequantizes
    by scan_scale, which raw int8/uint8 ivf_flat datasets don't have)."""
    from raft_tpu.core import env as _env

    dtypes = (jnp.float32, jnp.bfloat16) + ((jnp.int8,) if allow_int8 else ())
    return (
        _env.env_str("RAFT_TPU_PALLAS") == "1"
        and metric in ("sqeuclidean", "euclidean", "inner_product", "cosine")
        and storage_dtype in dtypes
    )


def run_query_tiled(run_fn, queries, q_tile: int, extras=()):
    """Host-level query batching: run ``run_fn(q_tile_block, *extra_blocks)
    → (v, i)`` over fixed-size query tiles (tail zero-padded so every call
    shares one compiled shape) and concatenate. The single tiling
    implementation for every probe-major/sharded search entry. ``extras``
    are per-query arrays (leading dim = n_q, e.g. ragged filter ids) sliced
    and padded alongside the queries."""
    n_q = queries.shape[0]
    if q_tile >= n_q:
        return run_fn(queries, *extras)
    vs, is_ = [], []
    for s in range(0, n_q, q_tile):
        qt = queries[s : s + q_tile]
        ets = [e[s : s + q_tile] for e in extras]
        pad = q_tile - qt.shape[0]
        if pad:
            qt = jnp.pad(qt, ((0, pad), (0, 0)))
            ets = [
                jnp.pad(e, [(0, pad)] + [(0, 0)] * (e.ndim - 1)) for e in ets
            ]
        v, i = run_fn(qt, *ets)
        vs.append(v[: v.shape[0] - pad] if pad else v)
        is_.append(i[: i.shape[0] - pad] if pad else i)
    return jnp.concatenate(vs), jnp.concatenate(is_)


def run_probe_major(probes, n_lists: int, bucket: int, bb: int, kk: int,
                    k: int, score_fn):
    """The full probe-major schedule scaffold shared by the IVF-PQ,
    IVF-Flat, and sharded scans: invert the (query, probe) relation, pad
    buckets to whole steps, run one scan over bucket batches, and merge the
    partials per query.

    ``score_fn(bucket_lists [bb], bucket_queries [bb, G]) →
    (v [bb·G, kk], i [bb·G, kk])`` supplies the index-specific scoring; it
    must mask padding slots (bucket_queries < 0) to +inf itself.
    Traced helper; bucket/bb/kk/k static."""
    q, p = probes.shape
    G = bucket
    bucket_list, bucket_query, bucket_pair, B = invert_probes(
        probes, n_lists, G
    )
    n_steps = -(-B // bb)
    B_pad = n_steps * bb
    bucket_list = jnp.pad(bucket_list, (0, B_pad - B))
    bucket_query = jnp.pad(
        bucket_query, ((0, B_pad - B), (0, 0)), constant_values=-1
    )
    bucket_pair = jnp.pad(
        bucket_pair, ((0, B_pad - B), (0, 0)), constant_values=-1
    )

    def step(start):
        bl = jax.lax.dynamic_slice_in_dim(bucket_list, start, bb)
        bq = jax.lax.dynamic_slice_in_dim(bucket_query, start, bb)
        return score_fn(bl, bq)

    vs, is_ = jax.lax.map(step, jnp.arange(n_steps) * bb)
    return merge_probe_major_partials(
        vs.reshape(B_pad * G, kk), is_.reshape(B_pad * G, kk),
        bucket_pair, q, p, kk, k,
    )


def allocate_append_slots(centers, list_sizes, cap, labels, group_inverse=None):
    """Assign a (list, slot) to each new row for an in-place append, or
    return None when a centroid group is out of spare capacity.

    Split shards of a skewed list duplicate their parent centroid (see
    split_oversized_lists); rows whose predicted shard is full overflow
    into a sibling shard with space — they rank identically at probe
    selection, so placement among siblings is recall-neutral. Shared by the
    IVF-Flat/IVF-PQ fast extend paths (the TPU answer to the reference's
    device-side list growth, ivf_flat_build.cuh:163 / ivf_pq_build.cuh:1501).

    ``group_inverse`` — pass ``centroid_group_inverse(centers)`` cached by
    the caller to skip the O(L·dim) dedupe on every incremental append.

    Returns (lists [n], slots [n], counts_new [L]) — all numpy — or None.
    """
    centers = np.asarray(centers)
    sizes = np.asarray(list_sizes).copy()
    labels = np.asarray(labels, np.int64)
    L = centers.shape[0]
    if labels.size and labels.max() >= L:
        return None

    inverse = (
        group_inverse
        if group_inverse is not None
        else centroid_group_inverse(centers)
    )
    group_members: dict = {}
    for lst, g in enumerate(inverse):
        group_members.setdefault(int(g), []).append(lst)

    out_list = np.empty_like(labels)
    out_slot = np.empty_like(labels)
    for g in np.unique(inverse[labels]):
        rows = np.nonzero(inverse[labels] == g)[0]
        members = group_members[int(g)]
        if sum(cap - sizes[m] for m in members) < len(rows):
            return None  # group out of capacity → caller repacks
        i = 0
        for m in members:
            take = min(cap - sizes[m], len(rows) - i)
            if take <= 0:
                continue
            sel = rows[i : i + take]
            out_list[sel] = m
            out_slot[sel] = sizes[m] + np.arange(take)
            sizes[m] += take
            i += take
            if i == len(rows):
                break
    return out_list, out_slot, sizes - np.asarray(list_sizes)


@functools.partial(jax.jit, static_argnames=("metric", "n_probes"))
def _coarse_probes_jit(queries, centers, metric, n_probes):
    """Standalone coarse pass for the paged prefix (same math the search
    executables re-derive in-trace — deterministic, so both agree)."""
    return coarse_select(queries, centers, metric, n_probes)


def paged_lists_for_search(index, queries, metric: str, n_probes: int):
    """Paged-search prefix shared by ivf_flat/ivf_pq: run the coarse
    pass, key the pager by the probed lists (async prefetch hint, then
    blocking admission), and hand back the :class:`PagedLists` device
    view the unchanged search executables scan through.

    The coarse top-n_probes runs twice (here and inside the search
    executable) — one tiny [q, L] GEMM, cheap next to the list scan, and
    the price of keeping the scan executables byte-identical to the
    monolithic arm."""
    from raft_tpu.obs import explain as _explain
    from raft_tpu.store.paged import PagedLists, pages_for_lists

    tiered = index.paged
    explain_on = _explain.enabled()
    if tiered.slots == tiered.n_pages:
        # fully-resident pool: pin the identity mapping once and skip the
        # per-dispatch coarse/residency bookkeeping entirely — nothing can
        # ever be evicted, so the page table is immutable after the pin.
        # This is what keeps the HBM-resident paged arm within a few
        # percent of the monolithic control (bench.py paged).
        tiered.pin_identity()
        pool, page_slot = tiered.view()
        if explain_on:
            _explain.stamp_page_stats({
                "pager": tiered.name, "pinned": True,
                "hits": 0, "misses": 0,
            })
        return PagedLists(pool, page_slot, tiered.pages_per_list)
    probes = _coarse_probes_jit(queries, index.centers, metric, n_probes)
    lists = np.unique(np.asarray(probes))  # raft-tpu: ignore[HOSTSYNC] prefetch keying needs the probed lists on host before dispatch
    pages = pages_for_lists(lists, tiered.pages_per_list)
    h0 = m0 = 0
    if explain_on:
        # bracket the pager calls with the counters this dispatch already
        # maintains — the deltas are THIS batch's page attribution (no
        # extra syncs: `lists` is the host array computed above either way)
        h0, m0, _ = tiered.counters()
    tiered.prefetch(pages)
    tiered.ensure_resident(pages)
    if explain_on:
        h1, m1, resident = tiered.counters()
        _explain.stamp_page_stats({
            "pager": tiered.name, "pinned": False,
            "probed_lists": int(lists.size),
            "pages": int(pages.size),
            "hits": h1 - h0, "misses": m1 - m0,
            "resident": resident,
        })
    pool, page_slot = tiered.view()
    return PagedLists(pool, page_slot, tiered.pages_per_list)
