"""NN-descent: iterative kNN-graph construction.

Reference: ``neighbors/nn_descent.cuh`` — GPU GNND with sampled local join,
bloom-filter dedup, and warp-level distance tiles (``GnndGraph``
neighbors/detail/nn_descent.cuh:310-351, ``GNND`` :351; batch variant
nn_descent_batch.cuh). Used as one of CAGRA's two graph-build algorithms
(cagra_types.hpp:50-63 ``graph_build_algo::NN_DESCENT``).

TPU re-design
-------------
The reference's local join builds per-node new/old sample lists and joins
them with warp shuffles + a bloom filter for visited dedup — all
data-dependent scatter. The TPU formulation keeps NN-descent's *fixed point*
(the kNN graph is stable under "compare me against my neighbors'
neighbors") but re-expresses one iteration as three static-shape batched
stages:

1. **sample**: per node, pick ``sample_size`` current neighbors at random
   (VPU gather, no control flow);
2. **expand**: candidates = neighbors-of-sampled-neighbors [n, s*s] plus a
   reverse-edge sample (the reverse pass is what makes NN-descent converge
   on digraphs; computed with one segment-scatter over edge targets);
3. **merge**: exact distances query-vs-candidates on the MXU, then
   concat + sorted-id dedup + ``select_k`` back to degree k.

Every stage is jittable with static shapes; convergence is detected from
the update count (ref termination_threshold, nn_descent.cuh GnndGraph).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from raft_tpu.core.resources import Resources, ensure
from raft_tpu.distance.pairwise import DISTANCE_TYPES, _PREC
from raft_tpu.neighbors import brute_force
from raft_tpu.neighbors._common import sorted_id_dedup
from raft_tpu.ops.matrix import select_k
from raft_tpu.core.trace import traced


@dataclass
class IndexParams:
    """(ref: neighbors/nn_descent_types.hpp index_params)"""

    graph_degree: int = 64
    intermediate_graph_degree: int = 128
    max_iterations: int = 20
    termination_threshold: float = 0.0001
    metric: str = "sqeuclidean"
    sample_size: int = 0  # 0 → auto (min(deg, 16))
    seed: int = 0


@dataclass
class Index:
    """kNN graph result (ref: nn_descent index = host graph mdarray)."""

    graph: jax.Array      # [n, graph_degree] int32
    distances: jax.Array  # [n, graph_degree] f32


def _row_distance(x: jax.Array, cand: jax.Array, metric: str) -> jax.Array:
    """dist(x[i], cand[i, j]) for [n, d] vs [n, c, d] — batched row-vs-rows.
    Casts per gathered tile, so low-precision datasets stream as-is."""
    x = x.astype(jnp.float32)
    cand = cand.astype(jnp.float32)
    ip = jnp.einsum("nd,ncd->nc", x, cand, precision=_PREC)
    if metric == "inner_product":
        return -ip
    if metric == "cosine":
        xn = jnp.maximum(jnp.linalg.norm(x, axis=1), 1e-12)
        cn = jnp.maximum(jnp.linalg.norm(cand, axis=2), 1e-12)
        return 1.0 - ip / (xn[:, None] * cn)
    c2 = jnp.sum(cand * cand, axis=2)
    x2 = jnp.sum(x * x, axis=1)
    return jnp.maximum(x2[:, None] + c2 - 2.0 * ip, 0.0)


def _merge_dedup(
    ids_a, dists_a, ids_b, dists_b, k: int
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Merge candidate lists per row, drop duplicate ids (keep best), return
    top-k by distance. The sorted-id adjacent-compare replaces the
    reference's bloom filter (nn_descent.cuh dedup) with a static-shape sort.

    Returns (ids [n,k], dists [n,k], n_updates — rows*slots where a new id
    entered the list)."""
    ids = jnp.concatenate([ids_a, ids_b], axis=1)
    dists = jnp.concatenate([dists_a, dists_b], axis=1)
    # self/padding slots arrive as id −1 with inf distance
    order, dup = sorted_id_dedup(ids)
    ids_s = jnp.take_along_axis(ids, order, axis=1)
    dists_s = jnp.take_along_axis(dists, order, axis=1)
    # within equal-id runs argsort is stable ⇒ first occurrence keeps the
    # position; demote dups (and invalid ids) to inf
    dists_s = jnp.where(dup | (ids_s < 0), jnp.inf, dists_s)
    vals, idx = select_k(dists_s, k, select_min=True, input_indices=ids_s)
    was_present = jnp.any(idx[:, :, None] == ids_a[:, None, :], axis=2)
    new_mask = (vals < jnp.inf) & ~was_present
    return idx, vals, jnp.sum(new_mask)


@functools.partial(jax.jit, static_argnames=("metric", "sample", "tile"))
def _nn_descent_iter(key, dataset, graph_ids, graph_dists, metric: str,
                     sample: int, tile: int):
    """One NN-descent iteration: forward 2-hop expansion + reverse sample."""
    n, k = graph_ids.shape

    k1, k2 = jax.random.split(key)
    # --- sampled forward neighbors [n, s]
    cols = jax.random.randint(k1, (n, sample), 0, k)
    smp = jnp.take_along_axis(graph_ids, cols, axis=1)            # [n, s]

    # --- reverse-edge sample: scatter each edge (u→v) into v's slot bucket.
    # Random slot per edge; collisions just drop candidates (sampling).
    rev = jnp.full((n, sample), -1, jnp.int32)
    slot = jax.random.randint(k2, (n, k), 0, sample)
    src = jnp.broadcast_to(jnp.arange(n, dtype=jnp.int32)[:, None], (n, k))
    # invalid (-1) graph slots must not credit node 0 with reverse edges:
    # route them to the out-of-range row n, which mode="drop" discards
    tgt = jnp.where(graph_ids >= 0, graph_ids, n)
    rev = rev.at[tgt.ravel(), slot.ravel()].set(src.ravel(), mode="drop")

    def body(carry, args):
        g_ids, g_dists, upd = carry
        row0 = args
        rows = row0 + jnp.arange(tile)
        rows = jnp.clip(rows, 0, n - 1)
        my_smp = smp[rows]                                        # [t, s]
        safe = jnp.clip(my_smp, 0, n - 1)
        two_hop = graph_ids[safe].reshape(tile, -1)               # [t, s*k]
        my_rev = rev[rows]                                        # [t, s]
        cand = jnp.concatenate([two_hop, my_rev], axis=1)         # [t, c]
        # drop self-edges
        cand = jnp.where(cand == rows[:, None], -1, cand)
        vecs = dataset[jnp.clip(cand, 0, n - 1)]                  # [t, c, d]
        d = _row_distance(dataset[rows], vecs, metric)
        d = jnp.where(cand < 0, jnp.inf, d)
        m_ids, m_dists, nu = _merge_dedup(
            g_ids[rows], g_dists[rows], cand, d, k
        )
        g_ids = g_ids.at[rows].set(m_ids)
        g_dists = g_dists.at[rows].set(m_dists)
        return (g_ids, g_dists, upd + nu), None

    n_tiles = (n + tile - 1) // tile
    starts = jnp.arange(n_tiles) * tile
    (graph_ids, graph_dists, updates), _ = lax.scan(
        body, (graph_ids, graph_dists, jnp.zeros((), jnp.int32)), starts
    )
    return graph_ids, graph_dists, updates


def _init_graph(k_init, dataset, metric: str, k: int):
    """Random init graph (ref: GnndGraph random init), deduped so the
    merge invariants hold."""
    n = dataset.shape[0]
    init = jax.random.randint(k_init, (n, k), 0, n, jnp.int32)
    init = jnp.where(init == jnp.arange(n, dtype=jnp.int32)[:, None],
                     (init + 1) % n, init)
    vecs = dataset[init]
    dists = _row_distance(dataset, vecs, metric)
    graph_ids, graph_dists, _ = _merge_dedup(
        init, dists, jnp.full_like(init, -1), jnp.full_like(dists, jnp.inf), k
    )
    return graph_ids, graph_dists


def gnnd_fixed(
    key, dataset, *, metric: str, k: int, sample: int, tile: int, iters: int
):
    """Traceable fixed-iteration GNND (no early-exit host sync) — the
    per-batch worker the sharded CAGRA graph build maps over mesh devices
    (comms.distributed.sharded_cagra_build). Same iteration body as
    :func:`build`; the update-count early exit is dropped because SPMD
    workers must run a uniform program."""
    k_init, key = jax.random.split(key)
    graph_ids, graph_dists = _init_graph(k_init, dataset, metric, k)

    def step(carry, k_it):
        g_i, g_d = carry
        g_i, g_d, _ = _nn_descent_iter(
            k_it, dataset, g_i, g_d, metric, sample, tile
        )
        return (g_i, g_d), None

    (graph_ids, graph_dists), _ = lax.scan(
        step, (graph_ids, graph_dists), jax.random.split(key, iters)
    )
    return graph_ids, graph_dists


@traced("nn_descent.build")
def build(
    params: IndexParams,
    dataset: jax.Array,
    *,
    res: Optional[Resources] = None,
) -> Index:
    """Build an approximate kNN graph by NN-descent iterations
    (ref: nn_descent.cuh GNND::build)."""
    res = ensure(res)
    # keep the dataset in its input dtype; _row_distance casts per gather
    dataset = jnp.asarray(dataset)
    n, d = dataset.shape
    metric = DISTANCE_TYPES[params.metric]
    k = min(params.intermediate_graph_degree, n - 1)
    sample = params.sample_size or min(k, 16)

    key = jax.random.PRNGKey(params.seed)
    k_init, key = jax.random.split(key)
    graph_ids, graph_dists = _init_graph(k_init, dataset, metric, k)

    # tile sized so the [tile, c, d] gather fits the workspace
    c = sample * k + sample
    tile = max(1, min(n, res.workspace_rows(4 * c * (d + 4), cap=4096)))

    for it in range(params.max_iterations):
        key, k_it = jax.random.split(key)
        graph_ids, graph_dists, updates = _nn_descent_iter(
            k_it, dataset, graph_ids, graph_dists, metric, sample, tile
        )
        if int(updates) <= params.termination_threshold * n * k:
            break

    deg = min(params.graph_degree, k)
    return Index(graph=graph_ids[:, :deg], distances=graph_dists[:, :deg])


@traced("nn_descent.build_batch")
def build_batch(
    params: IndexParams,
    dataset: np.ndarray,
    *,
    n_clusters: int = 0,
    max_cluster_rows: int = 65_536,
    res: Optional[Resources] = None,
) -> Index:
    """Out-of-core NN-descent for datasets that don't fit device memory
    (ref: nn_descent_batch.cuh batch_build): balanced-kmeans cluster the
    dataset, assign every row to its TOP-2 clusters (the overlap is what
    stitches neighborhoods across cluster borders), run the in-memory
    GNND per cluster, and merge each cluster's local graph into a global
    host-resident graph row by row.

    TPU shape discipline: clusters are padded to ONE common row count
    (balanced kmeans keeps them near-equal) so every per-cluster GNND and
    every merge reuses a single compiled program; padding rows are a far
    sentinel vector (global id −1) that can never enter a real row's
    neighbor list. Peak device residency = one padded cluster + its local
    graph, independent of n.

    ``dataset`` should be a host numpy array (a memmap works — rows are
    gathered per cluster); L2 metrics only (the far-sentinel padding has
    no inner-product analog).
    """
    res = ensure(res)
    dataset = np.asarray(dataset)
    plan = plan_batches(
        params, dataset, n_clusters=n_clusters,
        max_cluster_rows=max_cluster_rows, res=res,
    )
    if plan is None:
        return build(params, jnp.asarray(dataset), res=res)
    return _run_batches(params, dataset, plan, res)


def plan_batches(
    params: IndexParams,
    dataset: np.ndarray,
    *,
    n_clusters: int = 0,
    max_cluster_rows: int = 65_536,
    force: bool = False,
    res: Optional[Resources] = None,
):
    """Host-side half of the batch build: balanced-kmeans clustering,
    top-2 assignment (with skew re-splits), one padded batch shape.
    Returns the plan dict the batch executors consume (``build_batch``'s
    sequential loop and ``comms.distributed.sharded_cagra_build``'s
    mesh-parallel map). When one cluster suffices, returns None —
    ``build_batch`` then prefers the plain early-exit GNND — unless
    ``force`` asks for a single-batch plan (the sharded executor always
    wants a plan so the same SPMD path runs regardless of scale)."""
    from raft_tpu.cluster import kmeans_balanced
    from raft_tpu.neighbors._common import subsample_trainset

    metric = DISTANCE_TYPES[params.metric]
    if metric not in ("sqeuclidean", "euclidean"):
        # the far-sentinel padding has no inner-product/cosine analog:
        # under -ip a huge-coordinate sentinel is every row's BEST
        # neighbor and would evict real edges
        raise ValueError(f"batch GNND supports L2 metrics, got {params.metric}")
    res = ensure(res)
    n, d = dataset.shape
    # each row lands in 2 clusters → rows/cluster ≈ 2n/c
    n_clusters = n_clusters or max(1, -(-2 * n // max_cluster_rows))
    if n_clusters <= 1:
        if not force:
            return None
        k_out = min(
            params.graph_degree, params.intermediate_graph_degree, n - 1
        )
        return {
            "batches": [np.arange(n, dtype=np.int64)],
            "pad_m": n,
            "sentinel": np.zeros((d,), np.float32),
            "k_out": k_out,
            "local_params": IndexParams(
                graph_degree=k_out,
                intermediate_graph_degree=min(
                    params.intermediate_graph_degree, n - 1
                ),
                max_iterations=params.max_iterations,
                termination_threshold=params.termination_threshold,
                metric=params.metric,
                sample_size=params.sample_size,
                seed=params.seed,
            ),
        }

    @functools.partial(jax.jit, static_argnames=())
    def _top2(xt, c):
        c2 = jnp.sum(c * c, axis=1)
        sc = c2[None, :] - 2.0 * jnp.matmul(xt, c.T, precision=_PREC)
        _, top = select_k(sc, 2, select_min=True)
        return top

    kb = kmeans_balanced.KMeansBalancedParams(
        n_iters=10, metric="sqeuclidean", seed=params.seed
    )
    # 1-2) centroids from a subsample (ref get_balanced_kmeans_centroids)
    # + streamed top-2 cluster assignment (ref get_global_nearest_k, k=2).
    # When top-2 skew leaves a cluster over budget, RE-SPLIT with more
    # clusters (the reference's resplit) rather than blindly chunking —
    # a chunk boundary would sever intra-cluster neighborhoods.
    for attempt in range(3):
        n_train = min(n, max(n_clusters * 64, 16_384))
        train = subsample_trainset(dataset, n_train, params.seed) \
            if n_train < n else jnp.asarray(dataset)
        centers = kmeans_balanced.fit(
            kb, train.astype(jnp.float32), n_clusters, res=res
        )
        tile = max(1, res.workspace_rows(4 * (n_clusters + d), cap=1 << 17))
        top2 = np.empty((n, 2), np.int32)
        absmax = 0.0  # dataset-wide |x| peak, same stream
        for s in range(0, n, tile):
            xt_np = np.asarray(dataset[s:s + tile], np.float32)
            absmax = max(absmax, float(np.abs(xt_np).max()))
            top2[s:s + tile] = np.asarray(_top2(jnp.asarray(xt_np), centers))
        counts = np.bincount(top2.reshape(-1), minlength=n_clusters)
        if int(counts.max()) <= max_cluster_rows or n_clusters >= n:
            break
        n_clusters = min(
            n, int(np.ceil(n_clusters * counts.max() / max_cluster_rows
                           * 1.25)),
        )

    # 3) inverted indices (host)
    flat = top2.reshape(-1)
    rows_of = np.repeat(np.arange(n, dtype=np.int64), 2)
    order = np.argsort(flat, kind="stable")
    starts = np.concatenate([[0], np.cumsum(counts)])

    # 4) one padded shape for every batch; clusters still over budget
    # after the re-split attempts fall back to pad_m-row chunks (bounded
    # residency wins over edge quality in that corner)
    pad_m = int(min(
        n,
        -(-int(counts.max()) // 1024) * 1024,
        -(-max_cluster_rows // 1024) * 1024,
    ))
    # far sentinel from the dataset-wide peak (a single-row estimate can
    # land inside the cloud and corrupt neighbor lists)
    sentinel = np.full(
        (d,), 4.0 * (absmax + 1.0) * max(1.0, np.sqrt(d)), np.float32
    )

    k_out = min(
        params.graph_degree, params.intermediate_graph_degree,
        pad_m - 1, n - 1,
    )

    local_params = IndexParams(
        graph_degree=k_out,
        intermediate_graph_degree=min(
            params.intermediate_graph_degree, pad_m - 1
        ),
        max_iterations=params.max_iterations,
        termination_threshold=params.termination_threshold,
        metric=params.metric,
        sample_size=params.sample_size,
        seed=params.seed,
    )

    batches = []
    for cid in range(n_clusters):
        all_rows = rows_of[order[starts[cid]:starts[cid + 1]]]
        for cs in range(0, all_rows.shape[0], pad_m):
            chunk = all_rows[cs:cs + pad_m]
            if chunk.shape[0]:
                batches.append(chunk)
    return {
        "batches": batches, "pad_m": pad_m, "sentinel": sentinel,
        "k_out": k_out, "local_params": local_params,
    }


def pad_batch(dataset: np.ndarray, rows: np.ndarray, plan) -> np.ndarray:
    """Materialize one batch at the plan's padded shape (sentinel rows
    fill the tail)."""
    m = rows.shape[0]
    xc = np.empty((plan["pad_m"], dataset.shape[1]), np.float32)
    xc[:m] = dataset[rows]
    xc[m:] = plan["sentinel"]
    return xc


@functools.partial(jax.jit, static_argnames=("k",))
def _merge_jit(gi, gd, ci, cd, k: int):
    ids, dists, _ = _merge_dedup(gi, gd, ci, cd, k)
    return ids, dists


def merge_local_graph(g_ids, g_dists, rows, li, ld, plan):
    """Fold one batch's local GNND graph into the host-resident global
    graph (local ids → global row ids; padding/sentinel neighbors drop to
    −1; dedup keeps the best copy of rows that live in both of their
    top-2 clusters — ref merge_subgraphs). Mutates g_ids/g_dists."""
    pad_m, k_out = plan["pad_m"], plan["k_out"]
    m = rows.shape[0]
    li = np.asarray(li)
    ld = np.asarray(ld)
    gi_cand = np.full((pad_m, k_out), -1, np.int32)
    gi_cand[:m] = np.where(
        (li[:m] >= 0) & (li[:m] < m), rows[np.clip(li[:m], 0, m - 1)], -1
    )
    ld = np.where(gi_cand >= 0, ld, np.inf).astype(np.float32)
    old_i = np.full((pad_m, k_out), -1, np.int32)
    old_d = np.full((pad_m, k_out), np.inf, np.float32)
    old_i[:m] = g_ids[rows]
    old_d[:m] = g_dists[rows]
    mi, md = _merge_jit(
        jnp.asarray(old_i), jnp.asarray(old_d),
        jnp.asarray(gi_cand), jnp.asarray(ld), k_out,
    )
    g_ids[rows] = np.asarray(mi)[:m]
    g_dists[rows] = np.asarray(md)[:m]


def finalize_global_graph(g_ids: np.ndarray, g_dists: np.ndarray) -> Index:
    """Drop self edges (possible via duplicate cluster memberships), sort
    each row by distance, wrap as an Index."""
    n = g_ids.shape[0]
    self_col = g_ids == np.arange(n, dtype=np.int32)[:, None]
    g_dists = np.where(self_col, np.inf, g_dists)
    g_ids = np.where(self_col, -1, g_ids)
    order2 = np.argsort(g_dists, axis=1, kind="stable")
    g_ids = np.take_along_axis(g_ids, order2, axis=1)
    g_dists = np.take_along_axis(g_dists, order2, axis=1)
    return Index(graph=jnp.asarray(g_ids), distances=jnp.asarray(g_dists))


def _run_batches(params, dataset, plan, res) -> Index:
    """Sequential batch executor: one padded cluster resident at a time."""
    n = dataset.shape[0]
    k_out = plan["k_out"]
    g_ids = np.full((n, k_out), -1, np.int32)
    g_dists = np.full((n, k_out), np.inf, np.float32)
    for rows in plan["batches"]:
        xc = pad_batch(dataset, rows, plan)
        # ref build_and_merge: local GNND on the cluster subset
        local = build(plan["local_params"], jnp.asarray(xc), res=res)
        merge_local_graph(
            g_ids, g_dists, rows, local.graph, local.distances, plan
        )
    return finalize_global_graph(g_ids, g_dists)


def build_exact(
    dataset: jax.Array, graph_degree: int, metric: str = "sqeuclidean",
    *, res: Optional[Resources] = None,
) -> Index:
    """Exact kNN graph via tiled brute force — the reference builds small
    graphs this way too (cagra_build.cuh build_knn_graph with ivf_pq is
    approximate; tests use exact ground truth)."""
    res = ensure(res)
    # brute_force.knn handles low-precision dtypes natively (int8 MXU Gram)
    dataset = jnp.asarray(dataset)
    dists, ids = brute_force.knn(
        dataset, dataset, graph_degree + 1, metric=metric, res=res
    )
    # drop self-match column
    self_col = ids == jnp.arange(dataset.shape[0], dtype=ids.dtype)[:, None]
    # rotate self hit (wherever ranked) out by pushing it to the end
    order = jnp.argsort(self_col, axis=1, stable=True)
    ids = jnp.take_along_axis(ids, order, axis=1)[:, :graph_degree]
    dists = jnp.take_along_axis(dists, order, axis=1)[:, :graph_degree]
    return Index(graph=ids, distances=dists)
