"""Exact (brute-force) k-nearest-neighbor search.

Reference: tiled pairwise-distance + per-tile select_k + cross-tile merge
(ref: cpp/include/raft/neighbors/detail/knn_brute_force.cuh:60-300
``tiled_brute_force_knn``; select_k at :240,:282; merge via
knn_merge_parts.cuh; index type neighbors/brute_force_types.hpp:49;
Python ref: pylibraft.neighbors.brute_force.knn).

TPU design: the dataset-tile loop is a ``lax.scan`` carrying the running
top-k per query (concat + top_k merge — the knn_merge_parts equivalent);
query tiles go through ``lax.map``. Distance tiles ride the MXU for
expanded metrics. All shapes static; tile sizes picked from the workspace
budget like the reference sizes tiles against its workspace resource.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import ClassVar, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu.core import serialize as ser
from raft_tpu.core import validation
from raft_tpu.core.resources import Resources, ensure
from raft_tpu.distance.pairwise import DISTANCE_TYPES, distance_matrix_tile
from raft_tpu.ops.matrix import select_k
from raft_tpu.core.trace import traced

_SERIALIZATION_VERSION = 1


@functools.partial(
    jax.jit, static_argnames=("k", "metric", "tile_cols", "query_tile", "select_min")
)
def _tiled_knn(
    queries: jax.Array,
    dataset: jax.Array,
    k: int,
    metric: str,
    p: float,
    tile_cols: int,
    query_tile: int,
    select_min: bool,
    filter_words: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    n_q, d = queries.shape
    n, _ = dataset.shape

    n_col_tiles = (n + tile_cols - 1) // tile_cols
    pad_n = n_col_tiles * tile_cols - n
    # pad dataset rows; padded distances forced to worst value via index mask
    ds = jnp.pad(dataset, ((0, pad_n), (0, 0)))
    ds_tiles = ds.reshape(n_col_tiles, tile_cols, d)
    worst = jnp.inf if select_min else -jnp.inf

    n_q_tiles = (n_q + query_tile - 1) // query_tile
    pad_q = n_q_tiles * query_tile - n_q
    q_tiles = jnp.pad(queries, ((0, pad_q), (0, 0))).reshape(n_q_tiles, query_tile, d)
    # per-row filters (ragged batches) tile alongside the queries so each
    # query row is masked by its own word set; ndim is static in trace
    per_row = filter_words is not None and filter_words.ndim == 2
    if per_row:
        fw_tiles = jnp.pad(filter_words, ((0, pad_q), (0, 0))).reshape(
            n_q_tiles, query_tile, -1
        )
    else:
        fw_tiles = jnp.zeros((n_q_tiles, 1, 1), jnp.uint32)  # unused carrier

    def per_query_tile(args):
        q, fw_t = args

        def scan_tile(carry, inp):
            best_v, best_i = carry
            tile, tile_idx = inp
            dist = distance_matrix_tile(q, tile, metric, p)
            col_ids = tile_idx * tile_cols + jnp.arange(tile_cols, dtype=jnp.int32)
            dist = jnp.where((col_ids < n)[None, :], dist, worst)
            sel_ids = jnp.broadcast_to(col_ids[None, :], dist.shape)
            if filter_words is not None:
                # post-filter (tombstones / sample filter): excluded rows
                # take the worst distance and surface as id −1, matching
                # the IVF family's filtered-candidate contract
                if per_row:
                    word = fw_t[:, jnp.clip(col_ids, 0) // 32]
                else:
                    word = filter_words[jnp.clip(col_ids, 0) // 32][None, :]
                passing = (
                    (word >> (col_ids % 32).astype(jnp.uint32)[None, :]) & 1
                ).astype(bool) & (col_ids < n)[None, :]
                dist = jnp.where(passing, dist, worst)
                sel_ids = jnp.where(passing, sel_ids, -1)
            tv, ti = select_k(
                dist, min(k, tile_cols), select_min=select_min,
                input_indices=sel_ids,
            )
            merged = jnp.concatenate([best_v, tv], axis=1)
            merged_i = jnp.concatenate([best_i, ti], axis=1)
            nv, ni = select_k(merged, k, select_min=select_min, input_indices=merged_i)
            return (nv, ni), None

        init_v = jnp.full((query_tile, k), worst, jnp.float32)
        init_i = jnp.zeros((query_tile, k), jnp.int32)
        (vals, idx), _ = lax.scan(
            scan_tile,
            (init_v, init_i),
            (ds_tiles, jnp.arange(n_col_tiles, dtype=jnp.int32)),
        )
        return vals, idx

    vals, idx = lax.map(per_query_tile, (q_tiles, fw_tiles))
    vals = vals.reshape(n_q_tiles * query_tile, k)[:n_q]
    idx = idx.reshape(n_q_tiles * query_tile, k)[:n_q]
    return vals, idx


@traced("brute_force.knn")
def knn(
    dataset: jax.Array,
    queries: jax.Array,
    k: int,
    *,
    metric: str = "sqeuclidean",
    p: float = 2.0,
    sample_filter=None,
    deleted_mask=None,
    res: Optional[Resources] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Exact kNN: (distances [n_q, k], indices [n_q, k]).

    (Python ref: pylibraft.neighbors.brute_force.knn — same order of
    returns.) ``inner_product`` selects largest, all distances smallest,
    matching the reference's select-direction logic.

    ``sample_filter`` (pass-bits kept) and ``deleted_mask`` (set bits
    excluded — the serve layer's tombstone convention) post-filter the
    candidate set; excluded rows surface as id −1 at the worst distance.

    Examples
    --------
    >>> import numpy as np
    >>> from raft_tpu.neighbors import brute_force
    >>> x = np.random.default_rng(0).random((1000, 16), dtype=np.float32)
    >>> dists, ids = brute_force.knn(x, x[:5], 3)
    >>> ids.shape
    (5, 3)
    >>> bool((np.asarray(ids)[:, 0] == np.arange(5)).all())  # self is 1-NN
    True
    """
    res = ensure(res)
    dataset = jnp.asarray(dataset)
    queries = jnp.asarray(queries)
    validation.check_in(metric, DISTANCE_TYPES, "metric")
    validation.check_matrix(dataset, "dataset")
    validation.check_matrix(queries, "queries")
    validation.check_same_cols(dataset, queries, "dataset", "queries")
    validation.check_positive(k, "k")
    validation.expects(
        k <= dataset.shape[0],
        f"k={k} larger than dataset size {dataset.shape[0]}",
    )
    canonical = DISTANCE_TYPES[metric]
    select_min = canonical != "inner_product"
    n, d = dataset.shape

    # perf-ledger attribution: brute force has no Pallas leg — every
    # dispatch is the tiled XLA matmul path
    from raft_tpu.kernels import stamp_kernel_path

    stamp_kernel_path("xla")

    from raft_tpu.neighbors._common import resolve_pass_filter

    pass_filter = resolve_pass_filter(sample_filter, deleted_mask)
    if pass_filter is not None and pass_filter.n_bits < n:
        raise ValueError(
            f"filter covers {pass_filter.n_bits} ids but dataset has {n} rows"
        )
    filter_words = None if pass_filter is None else pass_filter.words
    if filter_words is not None and filter_words.ndim == 2:
        validation.expects(
            filter_words.shape[0] == queries.shape[0],
            f"row filter has {filter_words.shape[0]} rows for "
            f"{queries.shape[0]} queries",
        )

    # Pallas fused distance+topk path (ref: the fusedL2Knn fast path,
    # spatial/knn/detail/fused_l2_knn-inl.cuh — fuses the distance tile and
    # selection so the [n_q, n] score matrix never reaches HBM). Opt-in via
    # RAFT_TPU_PALLAS=1 until the on-chip A/B vs the XLA formulation is
    # recorded (bench/prims); interpret mode keeps it testable on CPU.
    from raft_tpu.core import env as _env

    canonical_f32 = dataset.dtype == jnp.float32 and queries.dtype == jnp.float32
    if (
        _env.env_str("RAFT_TPU_PALLAS") == "1"
        and canonical in ("sqeuclidean", "euclidean", "inner_product")
        and k <= 128
        and canonical_f32
        and filter_words is None  # the fused kernel has no post-filter leg
    ):
        from raft_tpu.kernels import interpret_mode
        from raft_tpu.kernels.fused_knn import fused_l2_topk

        if canonical == "inner_product":
            vals, idx = fused_l2_topk(
                queries, dataset, jnp.zeros(n), int(k), mode="ip",
                interpret=interpret_mode(),
            )
            return -vals, idx
        xx = jnp.sum(dataset * dataset, axis=1)
        vals, idx = fused_l2_topk(
            queries, dataset, xx, int(k), interpret=interpret_mode()
        )
        q2 = jnp.sum(queries * queries, axis=1)
        vals = jnp.maximum(vals + q2[:, None], 0.0)
        if canonical == "euclidean":
            vals = jnp.sqrt(vals)
        return vals, idx

    # tile sizing against workspace (ref: knn_brute_force.cuh tile sizing).
    # Expanded metrics materialize [query_tile, tile_cols]; unexpanded ones
    # materialize the [query_tile, tile_cols, d] broadcast, so the per-column
    # cost includes both factors.
    from raft_tpu.distance.pairwise import _EXPANDED

    query_tile = int(min(max(queries.shape[0], 1), 1024))
    if canonical in _EXPANDED or canonical == "haversine":
        elem = 4 * max(d, query_tile)
    else:
        elem = 4 * d * query_tile
    tile_cols = int(min(n, max(512, res.workspace_rows(elem, cap=1 << 14))))
    # keep the dataset in its input dtype (int8/uint8/bf16/f32 — ref
    # low-precision dataset templates, ivf_flat_types.hpp:47): tiles are
    # cast (or int8-MXU dotted) inside distance_matrix_tile, so HBM holds
    # no fp32 copy of the dataset. Integer queries against an integer
    # dataset take the exact int-Gram path; mixed cases fall back to f32
    # queries with per-tile dataset casts.
    both_int = jnp.issubdtype(dataset.dtype, jnp.integer) and jnp.issubdtype(
        queries.dtype, jnp.integer
    )
    if not both_int and queries.dtype != jnp.float32:
        queries = queries.astype(jnp.float32)
    vals, idx = _tiled_knn(
        queries,
        dataset,
        int(k),
        canonical,
        p,
        tile_cols,
        query_tile,
        select_min,
        filter_words,
    )
    return vals, idx


@dataclass(frozen=True)
class EffortSpec:
    """Identity effort spec: exact search has no recall/throughput knob,
    so every actuator level maps to the same (full) effort.  Exists so
    the effort arbiter and frontier sweep treat all four backends
    uniformly (see ivf_flat.EffortSpec for the contract)."""

    backend: ClassVar[str] = "brute_force"

    @classmethod
    def from_params(cls, params=None, **extra) -> "EffortSpec":
        return cls()

    def apply(self, params=None):
        return params

    def degraded(self, level: int) -> "EffortSpec":
        return self

    def knobs(self):
        return {}


class Index:
    """Brute-force index: dataset + precomputed norms
    (ref: neighbors/brute_force_types.hpp:49)."""

    def __init__(self, dataset: jax.Array, metric: str = "sqeuclidean"):
        self.dataset = jnp.asarray(dataset)
        self.metric = metric

    @property
    def size(self) -> int:
        return self.dataset.shape[0]

    @property
    def dim(self) -> int:
        return self.dataset.shape[1]


@traced("brute_force.build")
def build(dataset: jax.Array, *, metric: str = "sqeuclidean", res=None) -> Index:
    """(ref: neighbors/brute_force.cuh build)"""
    return Index(dataset, metric)


@traced("brute_force.search")
def search(
    index: Index,
    queries: jax.Array,
    k: int,
    *,
    sample_filter=None,
    deleted_mask=None,
    res: Optional[Resources] = None,
) -> Tuple[jax.Array, jax.Array]:
    # paged index: every row is scanned each dispatch, so the whole
    # dataset must sit in the hot pool — identity-pin it once (single
    # host→HBM transfer; BudgetExceeded if the pool is short) and hand
    # the flat pool view to the unchanged knn (bitwise-identical rows)
    paged = getattr(index, "paged", None)
    if paged is not None:
        paged.pin_identity()
        pool, _ = paged.view()
        dataset = pool.reshape((-1,) + pool.shape[2:])[: index.size]
    else:
        dataset = index.dataset
    return knn(
        dataset, queries, k, metric=index.metric,
        sample_filter=sample_filter, deleted_mask=deleted_mask, res=res,
    )


class Batch:
    """One batch of a :class:`BatchKQuery`: neighbors
    ``[offset, offset+size)`` for every query, sorted by distance."""

    def __init__(self, distances: jax.Array, indices: jax.Array, offset: int):
        self._distances = distances
        self._indices = indices
        self.offset = offset

    def distances(self) -> jax.Array:
        return self._distances

    def indices(self) -> jax.Array:
        return self._indices

    @property
    def size(self) -> int:
        return self._indices.shape[1]


class BatchKQuery:
    """Incremental-k queries over a brute-force index: iterate each
    query's neighbor list in batches of ``batch_size`` — batch 0 is the
    nearest ``batch_size`` neighbors, batch 1 the next ``batch_size``,
    and so on, without deciding a final k up front.

    (ref: neighbors/brute_force.cuh:31-70 ``make_batch_k_query`` +
    detail/knn_brute_force_batch_k_query.cuh ``gpu_batch_k_query``.)
    The reference caches a device result matrix and grows the searched k
    exponentially when iteration passes the cached range; here the cached
    state is the jitted tiled-kNN result at the grown k, so stepping
    through b batches costs O(log b) searches, each a cache-hit
    compile.  Batches past the cached k re-search with
    ``k = max(2*cached, offset+size)`` — the reference's doubling rule
    (knn_brute_force_batch_k_query.cuh load_batch).
    """

    def __init__(self, index: Index, queries: jax.Array, batch_size: int,
                 *, res: Optional[Resources] = None):
        validation.check_positive(batch_size, "batch_size")
        self.index = index
        self.queries = jnp.asarray(queries)
        self.batch_size = int(batch_size)
        self._res = res
        self._cached_k = 0
        self._vals: Optional[jax.Array] = None
        self._ids: Optional[jax.Array] = None

    def _ensure(self, upto: int) -> None:
        upto = min(upto, self.index.size)
        if upto <= self._cached_k:
            return
        want = min(
            self.index.size,
            max(upto, 2 * self._cached_k, 2 * self.batch_size),
        )
        self._vals, self._ids = search(
            self.index, self.queries, want, res=self._res
        )
        self._cached_k = want

    def batch(self, offset: int, size: int) -> Batch:
        """Neighbors ``[offset, offset+size)`` for every query (clamped at
        the index size)."""
        validation.expects(offset >= 0, f"offset must be >= 0, got {offset}")
        size = max(0, min(size, self.index.size - offset))
        if size == 0:  # beyond the index (or size<=0): empty batch, no
            n_q = self.queries.shape[0]  # search and no None deref
            return Batch(jnp.zeros((n_q, 0), jnp.float32),
                         jnp.zeros((n_q, 0), jnp.int32), offset)
        self._ensure(offset + size)
        return Batch(
            self._vals[:, offset:offset + size],
            self._ids[:, offset:offset + size],
            offset,
        )

    def __iter__(self):
        offset = 0
        while offset < self.index.size:
            b = self.batch(offset, self.batch_size)
            yield b
            offset += b.size


def make_batch_k_query(
    index: Index,
    queries: jax.Array,
    batch_size: int,
    *,
    res: Optional[Resources] = None,
) -> BatchKQuery:
    """(ref: neighbors/brute_force.cuh:70 ``make_batch_k_query``)"""
    return BatchKQuery(index, queries, batch_size, res=res)


@traced("brute_force.save")
def save(filename: str, index: Index) -> None:
    """(ref: brute_force serialize — version-stamped, SURVEY §5 checkpoint)"""
    ser.save_tree(
        filename,
        "brute_force",
        _SERIALIZATION_VERSION,
        {"metric": index.metric},
        {"dataset": index.dataset},
    )


@traced("brute_force.load")
def load(filename: str) -> Index:
    scalars, arrays = ser.load_tree(filename, "brute_force", _SERIALIZATION_VERSION)
    return Index(jnp.asarray(arrays["dataset"]), scalars["metric"])
