"""Effort-spec dispatch: one uniform view of the four backends' typed
search-effort knobs.

Each backend module defines its own ``EffortSpec`` (ivf_flat / ivf_pq:
``n_probes`` + ``refine_ratio`` [+ ``lut_dtype``]; cagra: ``itopk_size``
+ ``search_width``; brute_force: identity) next to its ``SearchParams``.
This module maps a params instance — or a served index — back to the
spec class that knows how to move it, so generic machinery (the serve
``EffortArbiter``, the ``obs.autotune`` controller, the frontier sweep)
never hard-codes per-backend field names.

The contract every spec honors: knob values are host Python operands
that select among *already warmed* executables (the serving warmup
ladder precompiles one variant per (bucket, effort level)); they never
appear as static jit arguments — the analysis RECOMPILE rule rejects
any jit entry that marks an effort knob static.
"""

from __future__ import annotations

from typing import Optional

from raft_tpu.neighbors import brute_force, cagra, ivf_flat, ivf_pq

#: every backend's spec class, keyed by backend name
SPECS = {
    "brute_force": brute_force.EffortSpec,
    "ivf_flat": ivf_flat.EffortSpec,
    "ivf_pq": ivf_pq.EffortSpec,
    "cagra": cagra.EffortSpec,
}

#: knob field names that must never ride as static jit arguments
EFFORT_KNOBS = frozenset(
    {"n_probes", "refine_ratio", "lut_dtype", "itopk_size", "search_width"}
)

_BY_PARAMS = {
    ivf_flat.SearchParams: ivf_flat.EffortSpec,
    ivf_pq.SearchParams: ivf_pq.EffortSpec,
    cagra.SearchParams: cagra.EffortSpec,
}


def spec_class_for_params(params_cls):
    """The EffortSpec class owning a ``SearchParams`` class, or None for
    param types without effort semantics (hnsw, ball_cover, ...)."""
    return _BY_PARAMS.get(params_cls)


def spec_for_params(params, **extra):
    """EffortSpec capturing ``params``' current knob values, or None."""
    spec_cls = _BY_PARAMS.get(type(params))
    return spec_cls.from_params(params, **extra) if spec_cls else None


def spec_for_index(index) -> Optional[object]:
    """EffortSpec for a served index: from its ``search_params`` when it
    carries one, identity for brute-force, else None (unknown backend —
    callers treat it as effortless)."""
    base = getattr(index, "search_params", None)
    if base is not None:
        spec = spec_for_params(base)
        if spec is not None:
            return spec
    kind = getattr(index, "kind", None)  # MutableIndex carries a kind tag
    if kind in SPECS:
        return SPECS[kind].from_params(base)
    if type(index).__module__.endswith("brute_force"):
        return brute_force.EffortSpec()
    return None


def backend_for_index(index) -> Optional[str]:
    """Backend name ("ivf_flat", ...) for a served index, or None."""
    spec = spec_for_index(index)
    return spec.backend if spec is not None else None
