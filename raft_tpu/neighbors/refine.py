"""Refine: re-rank ANN candidates with exact distances.

Reference: ``neighbors/refine.cuh`` — takes a dataset, queries, and candidate
neighbor ids (typically from ivf_pq::search with k' > k), recomputes exact
distances for each (query, candidate) pair, and selects the top-k
(device impl ``detail/refine_device.cuh``; host/OpenMP impl
``detail/refine_host-inl.hpp``; used by CAGRA build
``detail/cagra/cagra_build.cuh:146-196``).

TPU shape: candidates are a static [q, k'] id matrix → one batched gather of
candidate vectors + a batched row-vs-row distance (VPU/MXU) + select_k.
There is no irregularity, so this is pure XLA. A ``host=True`` path mirrors
the reference's CPU refine (numpy, useful to overlap with device work).
"""

from __future__ import annotations

from typing import Optional, Tuple

import functools

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core.resources import Resources, ensure
from raft_tpu.distance.pairwise import DISTANCE_TYPES, _PREC
from raft_tpu.ops.matrix import select_k
from raft_tpu.core.trace import traced


#: per-tile candidate-gather budget: the [tile, k', d] f32 gather (plus
#: XLA's copy of it) must fit HBM next to the dataset — an unbounded
#: gather OOMed the chip at CAGRA-build scale (100k queries × 258
#: candidates × 96 dims → 30.8 GB program; ladder config4, round 4)
_REFINE_TILE_BYTES = 512 * 1024 * 1024


def _refine_query_tile(q: int, kprime: int, d: int) -> int:
    per_row = kprime * d * 4
    tile = max(8, _REFINE_TILE_BYTES // max(1, per_row))
    return min(q, 1 << (tile.bit_length() - 1))


@functools.partial(jax.jit, static_argnames=("k", "metric", "tile"))
def _refine_jit(dataset, queries, candidates, k: int, metric: str,
                tile: int | None = None):
    q, kprime = candidates.shape
    if tile is not None and tile < q:
        pad = -q % tile
        qs = jnp.pad(queries, ((0, pad), (0, 0))).reshape(
            -1, tile, queries.shape[1]
        )
        cs = jnp.pad(
            candidates, ((0, pad), (0, 0)), constant_values=-1
        ).reshape(-1, tile, kprime)
        v, i = jax.lax.map(
            lambda t: _refine_tile(dataset, t[0], t[1], k, metric), (qs, cs)
        )
        return v.reshape(-1, k)[:q], i.reshape(-1, k)[:q]
    return _refine_tile(dataset, queries, candidates, k, metric)


def _refine_tile(dataset, queries, candidates, k: int, metric: str):
    safe = jnp.clip(candidates, 0, dataset.shape[0] - 1)
    cand = dataset[safe].astype(jnp.float32)          # [q, k', d] gather
    qf = queries.astype(jnp.float32)
    ip = jnp.einsum("qd,qcd->qc", qf, cand, precision=_PREC)
    if metric == "inner_product":
        dist = -ip
    elif metric == "cosine":
        qn = jnp.maximum(jnp.linalg.norm(qf, axis=1), 1e-12)
        cn = jnp.maximum(jnp.linalg.norm(cand, axis=2), 1e-12)
        dist = 1.0 - ip / (qn[:, None] * cn)
    else:
        c2 = jnp.sum(cand * cand, axis=2)
        q2 = jnp.sum(qf * qf, axis=1)
        dist = jnp.maximum(q2[:, None] + c2 - 2.0 * ip, 0.0)
    dist = jnp.where(candidates < 0, jnp.inf, dist)
    v, i = select_k(dist, k, select_min=True, input_indices=candidates)
    if metric == "inner_product":
        v = -v
    elif metric == "euclidean":
        v = jnp.sqrt(jnp.maximum(v, 0.0))
    return v, i


@traced("refine.refine")
def refine(
    dataset: jax.Array,
    queries: jax.Array,
    candidates: jax.Array,
    k: int,
    *,
    metric: str = "sqeuclidean",
    host: bool = False,
    res: Optional[Resources] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Exact re-rank of ``candidates`` [q, k'] → top-k (distances, indices).

    Negative candidate ids are treated as invalid (distance +inf), matching
    the reference's handling of underfull candidate lists.
    """
    res = ensure(res)
    canonical = DISTANCE_TYPES[metric]
    candidates = jnp.asarray(candidates, jnp.int32)
    if k > candidates.shape[1]:
        raise ValueError(f"k={k} > candidate count {candidates.shape[1]}")
    if host:
        return _refine_host(
            np.asarray(dataset), np.asarray(queries), np.asarray(candidates), k, canonical
        )
    tile = _refine_query_tile(
        candidates.shape[0], candidates.shape[1], dataset.shape[1]
    )
    return _refine_jit(
        jnp.asarray(dataset), jnp.asarray(queries), candidates, int(k),
        canonical, tile=tile,
    )


def _refine_host(dataset, queries, candidates, k, metric):
    """CPU refine (ref: detail/refine_host-inl.hpp — OpenMP loop over
    queries). Uses the native threaded C++ entry point when the toolchain
    built it (raft_runtime parity); falls back to vectorized numpy."""
    from raft_tpu.core import native

    if (
        metric in native._METRIC_CODES
        and dataset.dtype == np.float32
        and dataset.flags.c_contiguous  # native path must not copy the dataset
        and native.available()
    ):
        v, i = native.refine_host(dataset, queries, candidates, k, metric)
        return jnp.asarray(v), jnp.asarray(i)
    safe = np.clip(candidates, 0, dataset.shape[0] - 1)
    cand = dataset[safe].astype(np.float32)
    qf = queries.astype(np.float32)
    ip = np.einsum("qd,qcd->qc", qf, cand)
    if metric == "inner_product":
        dist = -ip
    elif metric == "cosine":
        qn = np.maximum(np.linalg.norm(qf, axis=1), 1e-12)
        cn = np.maximum(np.linalg.norm(cand, axis=2), 1e-12)
        dist = 1.0 - ip / (qn[:, None] * cn)
    else:
        c2 = np.sum(cand * cand, axis=2)
        q2 = np.sum(qf * qf, axis=1)
        dist = np.maximum(q2[:, None] + c2 - 2.0 * ip, 0.0)
    dist = np.where(candidates < 0, np.inf, dist)
    order = np.argsort(dist, axis=1, kind="stable")[:, :k]
    v = np.take_along_axis(dist, order, axis=1)
    i = np.take_along_axis(candidates, order, axis=1)
    if metric == "inner_product":
        v = -v
    elif metric == "euclidean":
        v = np.sqrt(np.maximum(v, 0.0))
    return jnp.asarray(v), jnp.asarray(i)
