"""HNSW interop: export a CAGRA index to hnswlib's binary format and search
hnswlib-format files.

Reference: ``neighbors/hnsw.hpp:37-57`` + ``detail/hnsw.hpp:24-74`` (wrap a
CAGRA graph as the hnswlib base layer; CPU search through hnswlib) and the
writer ``detail/cagra/cagra_serialize.cuh serialize_to_hnswlib:96-203``
(field-for-field binary layout reproduced here: header of size_t/int fields,
then per-element [link_count:int32, links:uint32×deg, vector:f32×dim,
label:size_t], then one zero int per element for the absent upper levels).

The exported file loads in stock hnswlib (`hnswlib.Index(space='l2', dim=d)
.load_index(path)`). Since hnswlib is not bundled in this environment, the
module also parses the format back and searches it with the CAGRA beam
engine — the capability the reference gets from its hnswlib dependency.
"""

from __future__ import annotations

import struct
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core.resources import Resources
from raft_tpu.neighbors import cagra
from raft_tpu.core.trace import traced


def serialize_to_hnswlib(filename: str, index: "cagra.Index") -> None:
    """Write a CAGRA index as an hnswlib level-0-only index file
    (ref: cagra_serialize.cuh serialize_to_hnswlib)."""
    data = np.asarray(index.dataset, np.float32)
    graph = np.asarray(index.graph, np.uint32)
    n, dim = data.shape
    deg = graph.shape[1]
    size_data_per_element = deg * 4 + 4 + dim * 4 + 8
    with open(filename, "wb") as fh:
        fh.write(struct.pack("<Q", 0))                        # offset_level_0
        fh.write(struct.pack("<Q", n))                        # max_element
        fh.write(struct.pack("<Q", n))                        # curr_element_count
        fh.write(struct.pack("<Q", size_data_per_element))
        fh.write(struct.pack("<Q", size_data_per_element - 8))  # label_offset
        fh.write(struct.pack("<Q", deg * 4 + 4))              # offset_data
        fh.write(struct.pack("<i", 1))                        # max_level
        fh.write(struct.pack("<i", n // 2))                   # entrypoint_node
        fh.write(struct.pack("<Q", deg // 2))                 # max_M
        fh.write(struct.pack("<Q", deg))                      # max_M0
        fh.write(struct.pack("<Q", deg // 2))                 # M
        fh.write(struct.pack("<d", 0.42424242))               # mult (unused)
        fh.write(struct.pack("<Q", 500))                      # ef_construction
        # level-0 memory: one element at a time
        block = np.zeros(size_data_per_element, np.uint8)
        for i in range(n):
            off = 0
            block[0:4] = np.frombuffer(struct.pack("<i", deg), np.uint8)
            block[4 : 4 + deg * 4] = graph[i].view(np.uint8)
            off = 4 + deg * 4
            block[off : off + dim * 4] = data[i].view(np.uint8)
            off += dim * 4
            block[off : off + 8] = np.frombuffer(struct.pack("<Q", i), np.uint8)
            fh.write(block.tobytes())
        # upper-level link lists: all absent
        fh.write(np.zeros(n, np.int32).tobytes())


def load(filename: str, dim: int, *, metric: str = "sqeuclidean") -> "cagra.Index":
    """Parse an hnswlib index file's base layer into a searchable index
    (ref: hnsw.hpp from_cagra/deserialize — the inverse wrapper). Elements
    are re-ordered by their stored labels so returned neighbor ids are
    labels, like hnswlib's knn_query."""
    with open(filename, "rb") as fh:
        header = fh.read(8 * 6)
        (_, max_el, n, size_per, label_off, offset_data) = struct.unpack(
            "<6Q", header
        )
        _max_level, _entry = struct.unpack("<2i", fh.read(8))
        max_m, max_m0, _m = struct.unpack("<3Q", fh.read(24))
        _mult = struct.unpack("<d", fh.read(8))[0]
        _efc = struct.unpack("<Q", fh.read(8))[0]
        level0 = np.frombuffer(fh.read(n * size_per), np.uint8).reshape(n, size_per)
    deg = (offset_data - 4) // 4
    if label_off != size_per - 8 or offset_data + dim * 4 != label_off:
        raise ValueError(
            f"file geometry inconsistent with dim={dim}: "
            f"size_per={size_per}, offset_data={offset_data}"
        )
    # hnswlib packs the link count as uint16 with flags (delete mark) in the
    # upper bytes of the 4-byte field — reading int32 would corrupt counts
    # for marked-deleted elements
    counts = level0[:, 0:2].copy().view(np.uint16)[:, 0].astype(np.int64)
    links = level0[:, 4 : 4 + deg * 4].copy().view(np.uint32).reshape(n, deg)
    data = level0[:, offset_data : offset_data + dim * 4].copy().view(np.float32)
    data = data.reshape(n, dim)
    labels = level0[:, label_off:].copy().view(np.uint64)[:, 0].astype(np.int64)
    # mask unused link slots with self (valid, harmless for beam search)
    slot = np.arange(deg)[None, :]
    self_col = np.arange(n, dtype=np.uint32)[:, None]
    links = np.where(slot < counts[:, None], links, self_col)
    # order by labels so row id == label
    order = np.argsort(labels)
    inv = np.empty(n, np.int64)
    inv[order] = np.arange(n)
    data = data[order]
    links = inv[links.astype(np.int64)][order].astype(np.int32)
    return cagra.from_graph(metric, jnp.asarray(data), jnp.asarray(links))


def search(
    index: "cagra.Index",
    queries: jax.Array,
    k: int,
    *,
    ef: int = 64,
    res: Optional[Resources] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Search an hnsw-loaded (or any CAGRA) index; ``ef`` maps to the beam
    width (ref: hnsw.hpp search_params{ef})."""
    params = cagra.SearchParams(itopk_size=max(ef, k))
    return cagra.search(params, index, queries, k, res=res)
