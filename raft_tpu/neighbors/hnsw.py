"""HNSW interop: export a CAGRA index to hnswlib's binary format and search
hnswlib-format files.

Reference: ``neighbors/hnsw.hpp:37-57`` + ``detail/hnsw.hpp:24-74`` (wrap a
CAGRA graph as the hnswlib base layer; CPU search through hnswlib) and the
writer ``detail/cagra/cagra_serialize.cuh serialize_to_hnswlib:96-203``
(field-for-field binary layout reproduced here: header of size_t/int fields,
then per-element [link_count:int32, links:uint32×deg, vector:f32×dim,
label:size_t], then one zero int per element for the absent upper levels).

The exported file loads in stock hnswlib (`hnswlib.Index(space='l2', dim=d)
.load_index(path)`). Since hnswlib is not bundled in this environment, the
module also parses the format back and searches it with the CAGRA beam
engine — the capability the reference gets from its hnswlib dependency.

Two independent engines can read the files this module writes:

* :func:`load` — the Python parser here, searched with the CAGRA beam.
* :func:`load_native` — the from-scratch C++ parser + true hierarchical
  HNSW search in ``cpp/src/hnsw.cc`` (greedy upper-level descent +
  ef-bounded best-first, the hnswlib algorithm re-implemented from the
  paper). It shares nothing with the writer, so agreement between the two
  is a cross-language validation of the binary layout.
"""

from __future__ import annotations

import struct
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core.resources import Resources
from raft_tpu.neighbors import cagra
from raft_tpu.core.trace import traced


def _build_hierarchy(data: np.ndarray, max_m: int, seed: int,
                     metric: str = "sqeuclidean"):
    """Geometric level assignment + per-level kNN links — the upper layers
    a real HNSW carries (Malkov & Yashunin §4: P(level ≥ l) = M^-l, each
    layer a kNN graph over its members).

    The reference's exporter writes NO upper levels
    (cagra_serialize.cuh:196-202 emits one zero int per element), so a
    single-entry search over its files has no long-range hops and fails on
    strongly clustered data. Building the hierarchy at export time fixes
    that for every consumer — stock hnswlib included. Levels draw from a
    fixed-seed RNG so exports are reproducible.

    Returns (levels [n] int64, {level: (member_ids, links [m, ≤max_m])}).
    """
    from raft_tpu.neighbors import brute_force

    n = data.shape[0]
    mult = 1.0 / np.log(max(max_m, 2))
    rng = np.random.default_rng(seed)
    u = rng.random(n)
    levels = np.floor(-np.log(np.maximum(u, 1e-300)) * mult).astype(np.int64)
    # cap at ~log_M(n): deeper draws add empty layers, not navigability
    cap = max(1, int(np.log(max(n, 2)) * mult) + 1)
    levels = np.minimum(levels, cap)
    upper = {}
    for lvl in range(1, int(levels.max()) + 1):
        members = np.flatnonzero(levels >= lvl)
        k_l = min(max_m, len(members) - 1)
        if k_l <= 0:
            upper[lvl] = (members, np.zeros((len(members), 0), np.uint32))
            continue
        sub = data[members]
        # neighbors under the INDEX metric (an L2 hierarchy over an
        # inner-product graph routes descent to the wrong region); self
        # usually lands at rank 0 — request one extra and drop it.
        # brute_force.knn tiles device-side, so the per-level cost is the
        # exact-kNN of the ~n/M^l member subset, not an n x n scan.
        _, nb = brute_force.knn(sub, sub, k_l + 1, metric=metric)
        nb = np.asarray(nb).astype(np.int64)
        # drop self per row, vectorized: stable-sort self slots last, keep
        # the first k_l (original neighbor order preserved for the rest)
        is_self = nb == np.arange(len(members))[:, None]
        order = np.argsort(is_self, axis=1, kind="stable")
        keep = np.take_along_axis(nb, order, 1)[:, :k_l]
        upper[lvl] = (members, members[keep].astype(np.uint32))
    return levels, upper


def _as_deleted_bools(deleted, n: int) -> Optional[np.ndarray]:
    """Normalize a tombstone spec (Bitset, bool mask, or id list) to a
    [n] bool array; None stays None."""
    if deleted is None:
        return None
    from raft_tpu.core.bitset import Bitset

    if isinstance(deleted, Bitset):
        if deleted.n_bits < n:
            raise ValueError(
                f"tombstone mask covers {deleted.n_bits} ids, index has {n}"
            )
        words = np.asarray(deleted.words).astype(np.uint32)
        bits = np.unpackbits(words.view(np.uint8), bitorder="little")
        return bits[:n].astype(bool)
    deleted = np.asarray(deleted)
    if deleted.dtype == bool:
        if deleted.shape != (n,):
            raise ValueError(f"bool mask shape {deleted.shape} != ({n},)")
        return deleted
    out = np.zeros(n, bool)
    out[deleted.astype(np.int64)] = True
    return out


@traced("hnsw.serialize_to_hnswlib")
def serialize_to_hnswlib(
    filename: str, index: "cagra.Index", *, hierarchy: bool = True,
    seed: int = 0, deleted=None,
) -> None:
    """Write a CAGRA index as an hnswlib index file
    (ref: cagra_serialize.cuh serialize_to_hnswlib:96-203).

    With ``hierarchy=True`` (default) real upper HNSW layers are built at
    export (see :func:`_build_hierarchy`) so single-entry hierarchical
    searchers — stock hnswlib, :func:`load_native` — navigate clustered
    data; ``hierarchy=False`` reproduces the reference exporter's
    level-0-only layout byte for byte.

    ``deleted`` marks elements with hnswlib's delete flag (bit 0x01 of the
    uint16 flags half of the link-count field — what markDelete() sets), so
    a serve-layer tombstone mask survives export: stock hnswlib skips the
    marked elements, and :func:`load` round-trips them back into a
    :class:`~raft_tpu.core.bitset.Bitset`.  Accepts a Bitset (set bit =
    deleted, the serve convention), a [n] bool mask, or an id list."""
    data = np.asarray(index.dataset, np.float32)
    graph = np.asarray(index.graph, np.uint32)
    n, dim = data.shape
    del_bools = _as_deleted_bools(deleted, n)
    deg = graph.shape[1]
    max_m = deg // 2
    if hierarchy:
        levels, upper = _build_hierarchy(data, max_m, seed,
                                         metric=getattr(index, "metric",
                                                        "sqeuclidean"))
        max_level = int(levels.max())
        entrypoint = int(np.argmax(levels))
    else:
        levels = np.zeros(n, np.int64)
        upper = {}
        max_level = 1
        entrypoint = n // 2
    size_data_per_element = deg * 4 + 4 + dim * 4 + 8
    per_level = 4 + max_m * 4  # [u32 count][max_M links] per upper level
    with open(filename, "wb") as fh:
        fh.write(struct.pack("<Q", 0))                        # offset_level_0
        fh.write(struct.pack("<Q", n))                        # max_element
        fh.write(struct.pack("<Q", n))                        # curr_element_count
        fh.write(struct.pack("<Q", size_data_per_element))
        fh.write(struct.pack("<Q", size_data_per_element - 8))  # label_offset
        fh.write(struct.pack("<Q", deg * 4 + 4))              # offset_data
        fh.write(struct.pack("<i", max_level))
        fh.write(struct.pack("<i", entrypoint))
        fh.write(struct.pack("<Q", max_m))                    # max_M
        fh.write(struct.pack("<Q", deg))                      # max_M0
        fh.write(struct.pack("<Q", max_m))                    # M
        fh.write(struct.pack("<d", 1.0 / np.log(max(max_m, 2))))  # mult
        fh.write(struct.pack("<Q", 500))                      # ef_construction
        # level-0 memory: one element at a time
        block = np.zeros(size_data_per_element, np.uint8)
        for i in range(n):
            off = 0
            # uint16 link count + uint16 flags (bit 0x01 = deleted), packed
            # in the same 4 bytes hnswlib uses
            flags = 1 if del_bools is not None and del_bools[i] else 0
            block[0:4] = np.frombuffer(
                struct.pack("<HH", deg, flags), np.uint8
            )
            block[4 : 4 + deg * 4] = graph[i].view(np.uint8)
            off = 4 + deg * 4
            block[off : off + dim * 4] = data[i].view(np.uint8)
            off += dim * 4
            block[off : off + 8] = np.frombuffer(struct.pack("<Q", i), np.uint8)
            fh.write(block.tobytes())
        # upper-level link lists: per element, u32 byte count then one
        # [u32 count][max_M links (zero padded)] block per level it reaches
        if not hierarchy:
            fh.write(np.zeros(n, np.int32).tobytes())
            return
        # member id → row in its level's link table, per level
        pos = {
            lvl: {int(m): r for r, m in enumerate(mem)}
            for lvl, (mem, _) in upper.items()
        }
        for i in range(n):
            lv = int(levels[i])
            fh.write(struct.pack("<I", lv * per_level))
            for lvl in range(1, lv + 1):
                mem, links = upper[lvl]
                row = links[pos[lvl][i]]
                fh.write(struct.pack("<I", len(row)))
                padded = np.zeros(max_m, np.uint32)
                padded[: len(row)] = row
                fh.write(padded.tobytes())


@traced("hnsw.load")
def load(
    filename: str, dim: int, *, metric: str = "sqeuclidean",
    return_deleted: bool = False,
):
    """Parse an hnswlib index file's base layer into a searchable index
    (ref: hnsw.hpp from_cagra/deserialize — the inverse wrapper). Elements
    are re-ordered by their stored labels so returned neighbor ids are
    labels, like hnswlib's knn_query.

    With ``return_deleted=True`` returns ``(index, deleted_mask)`` where
    the mask is the file's delete flags as a
    :class:`~raft_tpu.core.bitset.Bitset` (set bit = deleted — pass it
    straight to :func:`search`/``cagra.search`` or the serve layer)."""
    with open(filename, "rb") as fh:
        header = fh.read(8 * 6)
        (_, max_el, n, size_per, label_off, offset_data) = struct.unpack(
            "<6Q", header
        )
        _max_level, _entry = struct.unpack("<2i", fh.read(8))
        max_m, max_m0, _m = struct.unpack("<3Q", fh.read(24))
        _mult = struct.unpack("<d", fh.read(8))[0]
        _efc = struct.unpack("<Q", fh.read(8))[0]
        level0 = np.frombuffer(fh.read(n * size_per), np.uint8).reshape(n, size_per)
    deg = (offset_data - 4) // 4
    if label_off != size_per - 8 or offset_data + dim * 4 != label_off:
        raise ValueError(
            f"file geometry inconsistent with dim={dim}: "
            f"size_per={size_per}, offset_data={offset_data}"
        )
    # hnswlib packs the link count as uint16 with flags (delete mark) in the
    # upper bytes of the 4-byte field — reading int32 would corrupt counts
    # for marked-deleted elements
    counts = level0[:, 0:2].copy().view(np.uint16)[:, 0].astype(np.int64)
    # flags half of the packed field: bit 0x01 is hnswlib's delete mark
    flags = level0[:, 2:4].copy().view(np.uint16)[:, 0]
    deleted = (flags & 1).astype(bool)
    links = level0[:, 4 : 4 + deg * 4].copy().view(np.uint32).reshape(n, deg)
    data = level0[:, offset_data : offset_data + dim * 4].copy().view(np.float32)
    data = data.reshape(n, dim)
    labels = level0[:, label_off:].copy().view(np.uint64)[:, 0].astype(np.int64)
    # mask unused link slots with self (valid, harmless for beam search)
    slot = np.arange(deg)[None, :]
    self_col = np.arange(n, dtype=np.uint32)[:, None]
    links = np.where(slot < counts[:, None], links, self_col)
    # order by labels so row id == label
    order = np.argsort(labels)
    inv = np.empty(n, np.int64)
    inv[order] = np.arange(n)
    data = data[order]
    links = inv[links.astype(np.int64)][order].astype(np.int32)
    index = cagra.from_graph(metric, jnp.asarray(data), jnp.asarray(links))
    if return_deleted:
        from raft_tpu.core.bitset import Bitset

        return index, Bitset.from_mask(jnp.asarray(deleted[order]))
    return index


@traced("hnsw.search")
def search(
    index: "cagra.Index",
    queries: jax.Array,
    k: int,
    *,
    ef: int = 64,
    sample_filter=None,
    deleted_mask=None,
    res: Optional[Resources] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Search an hnsw-loaded (or any CAGRA) index; ``ef`` maps to the beam
    width (ref: hnsw.hpp search_params{ef}).  ``deleted_mask`` is the
    shared tombstone convention (set bit = skip) — e.g. the mask
    :func:`load` recovers from a file's delete flags."""
    params = cagra.SearchParams(itopk_size=max(ef, k))
    return cagra.search(
        params, index, queries, k,
        sample_filter=sample_filter, deleted_mask=deleted_mask, res=res,
    )


def load_native(filename: str, dim: int):
    """Load an hnswlib index file into the native C++ engine
    (ref: the hnswlib dependency's role in hnsw.hpp — CPU search over the
    exported graph). Returns a handle with ``.search(queries, k, ef=,
    metric=)`` → (distances, labels) and ``.info`` / ``.element(i)`` for
    format introspection. Raises RuntimeError if the native toolchain is
    unavailable or the file is inconsistent with ``dim``."""
    from raft_tpu.core import native

    if not native.available():
        raise RuntimeError("native core unavailable (no toolchain?)")
    return native.HnswNativeIndex(filename, dim)
