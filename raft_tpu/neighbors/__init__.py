"""Nearest-neighbor indexes (ref: cpp/include/raft/neighbors/)."""

from raft_tpu.neighbors import brute_force, ivf_flat, ivf_pq
from raft_tpu.neighbors.refine import refine

__all__ = ["brute_force", "ivf_flat", "ivf_pq", "refine"]
