"""Nearest-neighbor indexes (ref: cpp/include/raft/neighbors/)."""

from raft_tpu.neighbors import brute_force

__all__ = ["brute_force"]
