"""Nearest-neighbor indexes (ref: cpp/include/raft/neighbors/)."""

from raft_tpu.neighbors import (
    ball_cover,
    brute_force,
    cagra,
    effort,
    extras,
    hnsw,
    ivf_flat,
    ivf_pq,
    nn_descent,
    vpq_dataset,
)
from raft_tpu.neighbors.extras import (
    BatchKQuery,
    epsilon_neighborhood,
    masked_l2_nn,
)
from raft_tpu.neighbors.refine import refine

__all__ = [
    "ball_cover",
    "brute_force",
    "cagra",
    "effort",
    "extras",
    "hnsw",
    "ivf_flat",
    "ivf_pq",
    "nn_descent",
    "vpq_dataset",
    "refine",
    "BatchKQuery",
    "epsilon_neighborhood",
    "masked_l2_nn",
]
