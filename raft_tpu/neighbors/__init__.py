"""Nearest-neighbor indexes (ref: cpp/include/raft/neighbors/)."""

from raft_tpu.neighbors import brute_force, cagra, ivf_flat, ivf_pq, nn_descent
from raft_tpu.neighbors.refine import refine

__all__ = ["brute_force", "cagra", "ivf_flat", "ivf_pq", "nn_descent", "refine"]
