"""VPQ (vector-product-quantized) compressed datasets.

Reference: ``neighbors/vpq_dataset.cuh`` / ``detail/vpq_dataset.cuh`` — a
two-level compression for CAGRA datasets: coarse vector quantization
(vq_n_centers Lloyd centers) plus product quantization of the residuals;
CAGRA search then computes distances against decoded codes
(``detail/cagra/compute_distance_vpq.cuh``). Params mirror
``neighbors/dataset.hpp:37-259`` vpq_params.

TPU re-design: codes are stored unpacked (one byte per sub-quantizer, int32
per VQ id) so decode is pure gathers: row = vq_center[vq_code] +
concat_j codebook[j, pq_code_j] — exactly the shape the beam search's
candidate gather wants. Training reuses the batched-Lloyd codebook trainer
from ivf_pq (one compiled program trains all subspaces)."""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.cluster import kmeans_balanced
from raft_tpu.core.resources import Resources, ensure
from raft_tpu.core.trace import traced
from raft_tpu.distance.pairwise import _PREC
from raft_tpu.neighbors.ivf_pq import _train_codebooks_lloyd


@dataclass
class VpqParams:
    """(ref: neighbors/dataset.hpp vpq_params)"""

    vq_n_centers: int = 0      # 0 → auto (~√n, clipped)
    pq_dim: int = 0            # 0 → auto (dim/2 for vpq)
    pq_bits: int = 8
    kmeans_n_iters: int = 25
    vq_kmeans_trainset_fraction: float = 1.0
    pq_kmeans_trainset_fraction: float = 1.0
    seed: int = 0


@jax.tree_util.register_pytree_node_class
class VpqDataset:
    """Compressed dataset: decode(ids) reproduces rows approximately."""

    def __init__(self, vq_centers, pq_codebook, vq_codes, pq_codes, dim: int):
        self.vq_centers = vq_centers    # [V, dim]
        self.pq_codebook = pq_codebook  # [pq_dim, 2**bits, pq_len]
        self.vq_codes = vq_codes        # [n] int32
        self.pq_codes = pq_codes        # [n, pq_dim] uint8
        self.dim = dim

    def tree_flatten(self):
        return (self.vq_centers, self.pq_codebook, self.vq_codes, self.pq_codes), (self.dim,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, aux[0])

    @property
    def shape(self) -> Tuple[int, int]:
        return (self.vq_codes.shape[0], self.dim)

    @property
    def pq_dim(self) -> int:
        return self.pq_codes.shape[1]

    @property
    def pq_len(self) -> int:
        return self.pq_codebook.shape[2]

    def decode(self, ids: jax.Array) -> jax.Array:
        """Decoded rows for arbitrary id tensors: [..., dim]
        (ref: compute_distance_vpq.cuh decodes inside the distance kernel)."""
        n = self.vq_codes.shape[0]
        safe = jnp.clip(ids, 0, n - 1)
        base = self.vq_centers[self.vq_codes[safe]]             # [..., dim]
        codes = self.pq_codes[safe].astype(jnp.int32)           # [..., pq_dim]
        j = jnp.arange(self.pq_dim)
        resid = self.pq_codebook[j, codes]                      # [..., pq_dim, pq_len]
        resid = resid.reshape(resid.shape[:-2] + (self.pq_dim * self.pq_len,))
        return base + resid[..., : self.dim]


def _auto_vq_centers(n: int) -> int:
    return int(np.clip(int(np.sqrt(n)), 16, 1 << 16))


@traced("vpq_dataset.build")
def build(
    params: VpqParams,
    dataset: jax.Array,
    *,
    res: Optional[Resources] = None,
) -> VpqDataset:
    """Train VQ + PQ and encode the dataset
    (ref: detail/vpq_dataset.cuh vpq_build: train_vq → train_pq → process)."""
    res = ensure(res)
    if not (4 <= params.pq_bits <= 8):
        # codes are stored one byte per sub-quantizer (ref vpq_params caps
        # pq_bits at 8 too); >8 would silently wrap in the uint8 cast
        raise ValueError(f"pq_bits must be in [4, 8], got {params.pq_bits}")
    x = jnp.asarray(dataset, jnp.float32)
    n, dim = x.shape
    V = params.vq_n_centers or _auto_vq_centers(n)
    pq_dim = params.pq_dim or max(1, dim // 2)
    pq_len = max(1, (dim + pq_dim - 1) // pq_dim)
    pad = pq_dim * pq_len - dim
    key = jax.random.PRNGKey(params.seed)
    k_vq, k_pq = jax.random.split(key)

    # --- coarse VQ (balanced kmeans, like the IVF coarse quantizers)
    frac = params.vq_kmeans_trainset_fraction
    n_train = min(n, max(V * 4, int(n * frac)))
    train = x if n_train >= n else x[
        jax.random.choice(k_vq, n, shape=(n_train,), replace=False)
    ]
    kb = kmeans_balanced.KMeansBalancedParams(
        n_iters=params.kmeans_n_iters, seed=params.seed
    )
    vq_centers = kmeans_balanced.fit(kb, train, V, res=res)
    vq_codes = kmeans_balanced.predict(vq_centers, x, res=res)

    # --- PQ on residuals (zero-pad dim up to pq_dim*pq_len)
    resid = x - vq_centers[vq_codes]
    if pad:
        resid = jnp.pad(resid, ((0, 0), (0, pad)))
    # honor the PQ trainset-fraction knob (ref vpq_params bounds PQ training
    # cost independently of the VQ pass)
    pq_frac = params.pq_kmeans_trainset_fraction
    n_pq = min(n, max(1 << params.pq_bits, int(n * pq_frac)))
    if n_pq < n:
        k_pq, k_sub = jax.random.split(k_pq)
        pq_train = resid[jax.random.choice(k_sub, n, shape=(n_pq,), replace=False)]
    else:
        pq_train = resid
    sub = jnp.transpose(pq_train.reshape(-1, pq_dim, pq_len), (1, 0, 2))
    codebook = _train_codebooks_lloyd(
        k_pq, sub, 1 << params.pq_bits, params.kmeans_n_iters
    )

    # --- encode
    ip = jnp.einsum("njl,jkl->njk", resid.reshape(n, pq_dim, pq_len),
                    codebook, precision=_PREC)
    cb2 = jnp.sum(codebook * codebook, axis=2)
    pq_codes = jnp.argmin(cb2[None] - 2.0 * ip, axis=2).astype(jnp.uint8)
    return VpqDataset(vq_centers, codebook, vq_codes, pq_codes, dim)


def compression_ratio(ds: VpqDataset) -> float:
    """Bytes of f32 rows / bytes of codes (codebooks excluded, like the
    reference's storage accounting)."""
    n, dim = ds.shape
    raw = n * dim * 4
    packed = n * (4 + ds.pq_dim)
    return raw / packed
