"""Random ball cover (RBC) nearest neighbors.

Reference: ``neighbors/ball_cover.cuh`` + ``spatial/knn/detail/ball_cover/``
— sample √n landmarks, assign every point to its closest landmark, and at
query time prune landmark balls with the triangle inequality
(``registers.cuh`` kernels). Supports haversine/L2 (SURVEY §2.8).

TPU re-design: the index is the same (landmarks from random sampling, then
closest-landmark assignment packed into padded per-landmark lists — the IVF
layout from ``_common.pack_padded_lists``). The query replaces per-thread
triangle pruning with *probe ranking*: rank landmarks by query→landmark
distance and scan the closest ``n_probes`` balls with dense batched
distances + select_k. The triangle inequality shows up as the probe bound:
with all points in their closest ball, scanning the k_landmark-nearest balls
gives the reference's "approximate" mode; n_probes = all landmarks is exact.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from raft_tpu.core.resources import Resources, ensure
from raft_tpu.distance.pairwise import DISTANCE_TYPES, _PREC, pairwise_distance
from raft_tpu.neighbors._common import pack_padded_lists, subsample_trainset
from raft_tpu.ops.matrix import select_k
from raft_tpu.core.trace import traced

_SUPPORTED = ("sqeuclidean", "euclidean", "haversine")


def _dist(a: jax.Array, b: jax.Array, metric: str) -> jax.Array:
    """Plain [m, n] distance for the RBC metrics — delegates to the shared
    pairwise kernels (only the fused gathered-rows form in _query_jit needs
    a custom expression)."""
    return pairwise_distance(a, b, metric=metric)


class BallCoverIndex:
    """(ref: neighbors/ball_cover_types.hpp BallCoverIndex)"""

    def __init__(self, metric, landmarks, list_vecs, list_index, list_sizes, radii):
        self.metric = metric
        self.landmarks = landmarks        # [L, d]
        self.list_vecs = list_vecs        # [L, cap, d]
        self.list_index = list_index      # [L, cap]
        self.list_sizes = list_sizes      # [L]
        self.radii = radii                # [L] max dist landmark→member

    @property
    def n_landmarks(self) -> int:
        return self.landmarks.shape[0]

    @property
    def dim(self) -> int:
        return self.landmarks.shape[1]


@traced("ball_cover.build")
def build(
    dataset: jax.Array,
    *,
    metric: str = "sqeuclidean",
    n_landmarks: int = 0,
    seed: int = 0,
    res: Optional[Resources] = None,
) -> BallCoverIndex:
    """(ref: ball_cover.cuh build_index: sample √n landmarks → assign)"""
    res = ensure(res)
    x = jnp.asarray(dataset, jnp.float32)
    n, d = x.shape
    canonical = DISTANCE_TYPES.get(metric, metric)
    if canonical not in _SUPPORTED:
        raise ValueError(f"ball_cover supports {_SUPPORTED}, got {metric}")
    L = n_landmarks or max(1, int(np.sqrt(n)))
    # host-side landmark draw (see _common.subsample_trainset: a device
    # no-replacement choice compiles a full-n sort, ~20 s via the tunnel)
    landmarks = subsample_trainset(x, L, seed)
    base = "haversine" if canonical == "haversine" else "sqeuclidean"
    dists = _dist(x, landmarks, base)
    labels = jnp.argmin(dists, axis=1).astype(jnp.int32)
    member_d = jnp.take_along_axis(dists, labels[:, None], axis=1)[:, 0]
    list_vecs, list_index, sizes, _ = pack_padded_lists(
        np.asarray(x), np.arange(n, dtype=np.int32), np.asarray(labels), L
    )
    radii = jnp.zeros(L, jnp.float32).at[labels].max(member_d)
    return BallCoverIndex(
        canonical, landmarks, jnp.asarray(list_vecs), jnp.asarray(list_index),
        jnp.asarray(sizes), radii,
    )


@functools.partial(jax.jit, static_argnames=("k", "n_probes", "metric"))
def _query_jit(landmarks, list_vecs, list_index, queries,
               k: int, n_probes: int, metric: str):
    base = "haversine" if metric == "haversine" else "sqeuclidean"
    L, cap, d = list_vecs.shape
    ql = _dist(queries, landmarks, base)                   # [q, L]
    _, probes = select_k(ql, n_probes, select_min=True)    # [q, p]
    vecs = list_vecs[probes]                               # [q, p, cap, d]
    ids = list_index[probes]                               # [q, p, cap]
    ip = jnp.einsum("qd,qpcd->qpc", queries, vecs, precision=_PREC)
    if base == "haversine":
        # haversine is cheap enough to evaluate directly on the gathered rows
        q_e = queries[:, None, None, :]
        sdlat = jnp.sin((vecs[..., 0] - q_e[..., 0]) / 2)
        sdlon = jnp.sin((vecs[..., 1] - q_e[..., 1]) / 2)
        h = sdlat * sdlat + jnp.cos(q_e[..., 0]) * jnp.cos(vecs[..., 0]) * sdlon * sdlon
        dist = 2.0 * jnp.arcsin(jnp.sqrt(jnp.clip(h, 0.0, 1.0)))
    else:
        v2 = jnp.sum(vecs * vecs, axis=3)
        q2 = jnp.sum(queries * queries, axis=1)
        dist = jnp.maximum(q2[:, None, None] + v2 - 2.0 * ip, 0.0)
    dist = jnp.where(ids < 0, jnp.inf, dist)
    flat_d = dist.reshape(queries.shape[0], -1)
    flat_i = ids.reshape(queries.shape[0], -1)
    v, i = select_k(flat_d, k, select_min=True, input_indices=flat_i)
    if metric == "euclidean":
        v = jnp.sqrt(jnp.maximum(v, 0.0))
    return v, i


@traced("ball_cover.knn_query")
def knn_query(
    index: BallCoverIndex,
    queries: jax.Array,
    k: int,
    *,
    n_probes: int = 0,
    res: Optional[Resources] = None,
) -> Tuple[jax.Array, jax.Array]:
    """kNN via ball probing (ref: ball_cover.cuh knn_query; n_probes=L ⇒
    exact, smaller ⇒ the reference's approximate/perf mode)."""
    res = ensure(res)
    queries = jnp.asarray(queries, jnp.float32)
    L = index.n_landmarks
    p = min(n_probes or max(1, int(np.sqrt(L)) * 4), L)
    return _query_jit(
        index.landmarks, index.list_vecs, index.list_index, queries,
        int(k), int(p), index.metric,
    )


@traced("ball_cover.all_knn_query")
def all_knn_query(
    index: BallCoverIndex, k: int, *, n_probes: int = 0,
    res: Optional[Resources] = None,
) -> Tuple[jax.Array, jax.Array]:
    """kNN of every indexed point (ref: ball_cover.cuh all_knn_query)."""
    # reconstruct dataset order from the padded lists
    ids = np.asarray(index.list_index)
    vecs = np.asarray(index.list_vecs)
    live = ids >= 0
    order = np.argsort(ids[live])
    data = vecs[live][order]
    return knn_query(index, jnp.asarray(data), k, n_probes=n_probes, res=res)


@traced("ball_cover.eps_nn")
def eps_nn(
    index: BallCoverIndex,
    queries: jax.Array,
    eps: float,
    *,
    res: Optional[Resources] = None,
) -> Tuple[jax.Array, jax.Array]:
    """ε-ball adjacency via landmark pruning: balls with
    dist(q, landmark) − radius > ε cannot contain matches
    (ref: ball_cover.cuh eps_nn — the triangle-inequality filter)."""
    res = ensure(res)
    queries = jnp.asarray(queries, jnp.float32)
    base = "haversine" if index.metric == "haversine" else "sqeuclidean"
    # eps is expressed in the *index metric*: squared-L2 for sqeuclidean,
    # plain L2 for euclidean, radians for haversine; internal distances are
    # squared for the L2 family, so normalize eps to the internal space
    if index.metric == "euclidean":
        eps_int = float(eps) ** 2
    else:
        eps_int = float(eps)
    ql = _dist(queries, index.landmarks, base)             # [q, L]
    if base == "sqeuclidean":
        # prune in the metric's own space: √dq − √r ≤ √eps_int
        cant = jnp.sqrt(ql) - jnp.sqrt(index.radii)[None, :] > np.sqrt(eps_int)
    else:
        cant = ql - index.radii[None, :] > eps_int
    n = int((np.asarray(index.list_index) >= 0).sum())
    q = queries.shape[0]
    adj = np.zeros((q, n), bool)
    # scan only the balls that survive pruning (host loop over landmarks —
    # ball count is √n; each scan is one batched distance)
    cant = np.asarray(cant)
    for l in range(index.n_landmarks):
        need = ~cant[:, l]
        if not need.any():
            continue
        ids = np.asarray(index.list_index[l])
        live = ids >= 0
        vecs = index.list_vecs[l][jnp.asarray(live)]
        d = np.asarray(_dist(queries, vecs, base))
        hit = d <= eps_int
        adj[:, ids[live]] |= hit & need[:, None]
    return jnp.asarray(adj), jnp.asarray(adj.sum(1).astype(np.int32))
