"""Public IVF list helpers — codepacker parity.

Reference: ``neighbors/ivf_flat_helpers.cuh``, ``neighbors/ivf_pq_helpers.cuh``
and ``neighbors/ivf_flat_codepacker.hpp`` expose raw-list access and code
pack/unpack so downstream libraries can manage list storage directly
(SURVEY §2.8 row "ivf_list / helpers / codepacker").
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.neighbors import ivf_flat as _ivf_flat
from raft_tpu.neighbors import ivf_pq as _ivf_pq


# ---- ivf_flat helpers (ref: ivf_flat_helpers.cuh) -------------------------


def ivf_flat_unpack_list(index: "_ivf_flat.Index", list_id: int):
    """(vectors [size, dim], source ids [size]) of one list."""
    size = int(index.list_sizes[list_id])
    return (
        np.asarray(index.list_data[list_id])[:size],
        np.asarray(index.list_index[list_id])[:size],
    )


# ---- ivf_pq helpers (ref: ivf_pq_helpers.cuh) -----------------------------


def ivf_pq_unpack_list(index: "_ivf_pq.Index", list_id: int):
    """(codes [size, pq_dim] uint8, source ids [size]) of one list — the
    codepacker 'unpack' direction (ref: ivf_flat_codepacker.hpp unpack)."""
    size = int(index.list_sizes[list_id])
    return (
        np.asarray(index.list_codes[list_id])[:size],
        np.asarray(index.list_index[list_id])[:size],
    )


def ivf_pq_pack_codes(codes: np.ndarray, pq_bits: int) -> np.ndarray:
    """Dense bitstream from per-byte codes — the codepacker 'pack'
    direction (ref: ivf_flat_codepacker.hpp pack; serialization layout)."""
    return _ivf_pq._pack_bits(np.asarray(codes, np.uint8), pq_bits)


def ivf_pq_unpack_codes(packed: np.ndarray, pq_dim: int, pq_bits: int) -> np.ndarray:
    return _ivf_pq._unpack_bits(np.asarray(packed, np.uint8), pq_dim, pq_bits)


def ivf_pq_reconstruct_list(
    index: "_ivf_pq.Index", list_id: int
) -> Tuple[jax.Array, np.ndarray]:
    """Approximate original-space vectors of one list
    (ref: ivf_pq_helpers.cuh reconstruct_list_data): decoded rotated
    reconstructions mapped back through the orthonormal rotation."""
    size = int(index.list_sizes[list_id])
    y_rot = index.list_data[list_id, :size].astype(jnp.float32)  # [size, rot]
    if index.list_data.dtype == jnp.int8:
        y_rot = y_rot * index.scan_scale  # dequantize the memory-lean cache
    vecs = jnp.matmul(y_rot, index.rotation)  # R^T maps rotated → original
    ids = np.asarray(index.list_index[list_id])[:size]
    return vecs, ids


def index_memory_footprint(index) -> dict:
    """Per-component byte accounting of an index (HBM capacity planning —
    the analog of the reference's index size reporting in ann-bench,
    cpp/bench/ann/src/common/benchmark.hpp index-size counter).

    Works on any index type here (brute_force/ivf_flat/ivf_pq/cagra):
    every array-valued attribute is counted; returns
    {attr: bytes, ..., "total": bytes}.
    """
    out = {}
    total = 0
    for name, val in vars(index).items():
        nbytes = None
        if isinstance(val, np.ndarray):
            nbytes = int(val.nbytes)
        elif isinstance(val, jax.Array):
            nbytes = int(np.dtype(val.dtype).itemsize * val.size)
        if nbytes is not None:
            out[name] = nbytes
            total += nbytes
    out["total"] = total
    return out
