"""Smaller neighbor primitives: ε-neighborhood, masked 1-NN, incremental
batch queries.

Reference: ``neighbors/epsilon_neighborhood.cuh:101`` (epsUnexpL2SqNeighborhood),
``distance/masked_nn.cuh`` (masked_l2_nn over a bigraph adjacency),
``neighbors/detail/knn_brute_force_batch_k_query.cuh`` (batch_k_query).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core.resources import Resources, ensure
from raft_tpu.core.trace import traced
from raft_tpu.distance.pairwise import _PREC, pairwise_distance
from raft_tpu.neighbors import brute_force
from raft_tpu.ops.matrix import select_k


@traced("extras.epsilon_neighborhood")
def epsilon_neighborhood(
    x: jax.Array,
    y: jax.Array,
    eps_sq: float,
    *,
    res: Optional[Resources] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Boolean adjacency adj[i,j] = ‖x_i − y_j‖² ≤ eps² plus per-row degree
    (ref: epsilon_neighborhood.cuh eps_neighbors_l2sq — same dense-bool
    output + vertex degree array)."""
    res = ensure(res)
    x = jnp.asarray(x, jnp.float32)
    y = jnp.asarray(y, jnp.float32)
    m, n = x.shape[0], y.shape[0]
    tile = max(1, min(m, res.workspace_rows(4 * n + n, cap=8192)))
    adjs, degs = [], []
    for s in range(0, m, tile):
        d = pairwise_distance(x[s : s + tile], y, metric="sqeuclidean", res=res)
        a = d <= eps_sq
        adjs.append(a)
        degs.append(jnp.sum(a, axis=1).astype(jnp.int32))
    return jnp.concatenate(adjs, axis=0), jnp.concatenate(degs)


@functools.partial(jax.jit, static_argnames=("sqrt",))
@traced("extras.masked_l2_nn")
def masked_l2_nn(
    x: jax.Array,
    y: jax.Array,
    adj: jax.Array,
    group_idxs: jax.Array,
    *,
    sqrt: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Masked fused L2 1-NN (ref: distance/masked_nn.cuh masked_l2_nn).

    ``adj`` [m, num_groups] marks which y-groups each x row may match;
    ``group_idxs`` [num_groups] are *exclusive end offsets* of contiguous
    y groups (the reference's group layout). Returns (min_dist [m],
    argmin [m]); rows with no admissible y get (inf, −1)."""
    m, k = adj.shape
    n = y.shape[0]
    # group id of each y row from the end-offsets
    gid = jnp.searchsorted(group_idxs, jnp.arange(n), side="right")
    allowed = adj[:, jnp.clip(gid, 0, k - 1)]          # [m, n]
    x2 = jnp.sum(x * x, axis=1)
    y2 = jnp.sum(y * y, axis=1)
    d = x2[:, None] + y2[None, :] - 2.0 * jnp.matmul(x, y.T, precision=_PREC)
    d = jnp.where(allowed, jnp.maximum(d, 0.0), jnp.inf)
    j = jnp.argmin(d, axis=1).astype(jnp.int32)
    v = jnp.take_along_axis(d, j[:, None], axis=1)[:, 0]
    j = jnp.where(jnp.isfinite(v), j, -1)
    if sqrt:
        v = jnp.sqrt(jnp.maximum(v, 0.0))
    return v, j


class BatchKQuery:
    """Incremental-k query: iterate over successive batches of neighbors
    (ref: brute_force batch_k_query — amortizes one big select across
    consumers that want k in pages).

    The TPU realization computes top-(batch_size · n_batches_consumed)
    lazily: each ``next()`` re-selects only when the cached horizon is
    exceeded, doubling the horizon to amortize (capacity-doubling like the
    reference's conservative re-query)."""

    def __init__(self, dataset, queries, batch_size: int, *,
                 metric: str = "sqeuclidean", res: Optional[Resources] = None):
        self.res = ensure(res)
        self.dataset = jnp.asarray(dataset, jnp.float32)
        self.queries = jnp.asarray(queries, jnp.float32)
        self.batch_size = int(batch_size)
        self.metric = metric
        self._pos = 0
        self._vals = None
        self._ids = None

    def _ensure(self, upto: int):
        have = 0 if self._vals is None else self._vals.shape[1]
        if upto <= have:
            return
        horizon = min(self.dataset.shape[0], max(upto, 2 * max(have, self.batch_size)))
        self._vals, self._ids = brute_force.knn(
            self.dataset, self.queries, horizon, metric=self.metric, res=self.res
        )

    def __iter__(self):
        self._pos = 0
        return self

    def __next__(self):
        if self._pos >= self.dataset.shape[0]:
            raise StopIteration
        end = min(self._pos + self.batch_size, self.dataset.shape[0])
        self._ensure(end)
        v = self._vals[:, self._pos : end]
        i = self._ids[:, self._pos : end]
        self._pos = end
        return v, i
