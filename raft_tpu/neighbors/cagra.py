"""CAGRA: graph-based ANN index (build + batched beam search).

Reference surface: ``neighbors/cagra.cuh`` / ``cagra_types.hpp:57-142`` —
build = kNN graph via IVF-PQ-search+refine or NN-descent
(``graph_build_algo`` cagra_types.hpp:50-63), then ``sort_knn_graph`` +
2-hop detour-counting ``optimize``/prune (detail/cagra/graph_core.cuh:
130-235,322); search = beam search with a visited filter and per-query
persistent CTA kernels (detail/cagra/search_single_cta_kernel-inl.cuh:55-592,
search_multi_kernel.cuh; plan/tuning search_plan.cuh:81-164 — ``itopk_size``,
``search_width``, hashmap sizing; Python ref: pylibraft neighbors/cagra).

TPU re-design
-------------
* **Build** is batched dense ops end to end: the kNN graph comes from
  IVF-PQ search + exact refine (cagra_build.cuh:47-201), NN-descent
  (our static-shape formulation, nn_descent.py), or exact brute force for
  small sets. ``optimize`` — the detour-count prune — is a per-row
  [K, K, K] membership tensor contraction, tiled with ``lax.scan``; the
  reverse-edge pass is one sort-based scatter. No irregularity anywhere.
* **Search** replaces the per-query persistent CTA + hash-set with a
  *query-batched* beam search: state is a static [tile, itopk] candidate
  buffer with explored flags; one iteration = select_k unexplored parents
  (search_width), one gather of graph rows, one MXU distance batch, and a
  broadcast-membership dedup merge back into the buffer (plays the role
  of the reference's visited hashmap, detail/cagra/hashmap.hpp — no sorts
  in the hot loop). The whole search is one ``lax.while_loop`` inside jit
  — SURVEY §7 strategy (a).
* **Low-precision datasets** halve the search's HBM gather traffic (its
  dominant cost): pass ``dataset.astype(jnp.bfloat16)`` to ``build`` —
  the index keeps the input dtype and ``_gather_rows`` casts only the
  gathered tile to f32 (the reference's half/int8 dataset templates,
  cagra_types.hpp:142) — or ``compress()`` to VPQ for 8–16×.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, replace as dc_replace
from typing import ClassVar, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from raft_tpu.core import serialize as ser
from raft_tpu.core.bitset import Bitset
from raft_tpu.core.resources import Resources, ensure
from raft_tpu.distance.pairwise import DISTANCE_TYPES, _PREC
from raft_tpu.neighbors import brute_force, ivf_pq, nn_descent
from raft_tpu.neighbors._common import sorted_id_dedup
from raft_tpu.neighbors.refine import refine
from raft_tpu.ops.matrix import select_k
from raft_tpu.core.trace import traced
from raft_tpu.core.logger import logger as _log

_SERIALIZATION_VERSION = 1


@dataclass
class IndexParams:
    """(ref: cagra_types.hpp:57-121 index_params)

    ``entry_points`` — size of the coarse entry-point table (a TPU-first
    addition, not in the reference's CAGRA): a small kmeans codebook whose
    nearest dataset row per centroid seeds the beam search, replacing most
    of the random-restart iterations with one MXU matmul. The walk starts
    next to the answer instead of navigating to it, which is what makes
    the query-batched formulation competitive — random-seeded beams spend
    the bulk of their iterations crossing clusters (measured round 4:
    2-3× the iterations for the same recall). ``None`` → auto
    (≈4·√n, power of two, clamped to [64, 4096]); ``0`` disables."""

    metric: str = "sqeuclidean"
    intermediate_graph_degree: int = 128
    graph_degree: int = 64
    #: auto | ivf_pq | nn_descent | nn_descent_batch | brute_force
    #: (nn_descent_batch = out-of-core clustered graph build,
    #: ref nn_descent_batch.cuh)
    build_algo: str = "auto"
    nn_descent_niter: int = 20
    seed: int = 0
    entry_points: Optional[int] = None


@dataclass
class SearchParams:
    """(ref: cagra_types.hpp search_params / search_plan.cuh:81-164)

    ``num_entry_centers`` — how many coarse entry points seed each query's
    beam when the index carries an entry-point table (see
    IndexParams.entry_points); 0 falls back to pure random seeding.

    ``search_width`` defaults to 1 (the reference's default is 4): on the
    batched-TPU formulation every extra parent multiplies the
    per-iteration gather/score work across the whole query tile, and the
    round-4 sweeps measured width 1 strictly pareto-better at equal
    recall on both 20k and 100k workloads (wider beams pay off only on
    weakly-connected graphs — raise it together with
    num_random_samplings there)."""

    max_queries: int = 0          # 0 → auto query tile
    itopk_size: int = 64
    max_iterations: int = 0       # 0 → auto
    search_width: int = 1
    min_iterations: int = 0
    rand_xor_mask: int = 0x128394  # seed for random init candidates
    num_random_samplings: int = 1
    num_entry_centers: int = 16


@dataclass(frozen=True)
class EffortSpec:
    """Typed search-effort knobs for CAGRA (see ivf_flat.EffortSpec for
    the contract): beam size ``itopk_size`` and parent count
    ``search_width``.  The degrade ladder moves only ``itopk_size`` —
    width 1 measured pareto-better at equal recall on this formulation
    (see SearchParams.search_width), so the ladder never widens and the
    warmed variant set stays one executable per (bucket, level)."""

    itopk_size: int = 64
    search_width: int = 1

    backend: ClassVar[str] = "cagra"

    @classmethod
    def from_params(cls, params: Optional[SearchParams] = None,
                    **extra) -> "EffortSpec":
        base = params if params is not None else SearchParams()
        return cls(itopk_size=int(base.itopk_size),
                   search_width=int(base.search_width))

    def apply(self, params: Optional[SearchParams] = None) -> SearchParams:
        base = params if params is not None else SearchParams()
        return dc_replace(base, itopk_size=int(self.itopk_size),
                          search_width=int(self.search_width))

    def degraded(self, level: int) -> "EffortSpec":
        if level <= 0:
            return self
        return EffortSpec(
            itopk_size=max(32, int(self.itopk_size) >> int(level)),
            search_width=int(self.search_width),
        )

    def knobs(self):
        return {"itopk_size": int(self.itopk_size),
                "search_width": int(self.search_width)}


class Index:
    """CAGRA index: dataset + fixed-degree directed graph
    (ref: cagra_types.hpp:142 index{dataset, graph}). ``dataset`` is either
    a dense [n, d] array or a ``vpq_dataset.VpqDataset`` (the reference's
    compressed-dataset option, dataset.hpp:37-259)."""

    def __init__(self, metric: str, dataset, graph: jax.Array,
                 entry_centers: Optional[jax.Array] = None,
                 entry_ids: Optional[jax.Array] = None):
        self.metric = metric
        self.dataset = dataset
        self.graph = graph
        #: optional coarse entry-point table: [c, d] centroids + [c] id of
        #: the dataset row nearest each centroid (beam-search seeds)
        self.entry_centers = entry_centers
        self.entry_ids = entry_ids

    @property
    def size(self) -> int:
        return self.dataset.shape[0]

    @property
    def dim(self) -> int:
        return self.dataset.shape[1]

    @property
    def graph_degree(self) -> int:
        return self.graph.shape[1]


@traced("cagra.compress")
def compress(index: Index, params=None, *, res: Optional[Resources] = None) -> Index:
    """Replace the dense dataset with a VPQ-compressed one; search then
    decodes candidates on the fly and distances become approximate
    (ref: cagra index_params.compression + compute_distance_vpq.cuh)."""
    from raft_tpu.neighbors import vpq_dataset

    if not isinstance(index.dataset, jax.Array):
        raise ValueError("index dataset is already compressed")
    params = params or vpq_dataset.VpqParams()
    ds = vpq_dataset.build(params, index.dataset, res=res)
    return Index(index.metric, ds, index.graph,
                 index.entry_centers, index.entry_ids)


# --------------------------------------------------------------------------
# graph optimization (ref: detail/cagra/graph_core.cuh)
# --------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("out_degree", "tile"))
def _prune_detourable(graph: jax.Array, out_degree: int, tile: int) -> jax.Array:
    """Detour-count prune (ref: graph_core.cuh kern_prune:130-187).

    Edge (u → v=g[u,j]) is detourable through w=g[u,i] (i<j, so w is closer
    to u than v) when v also appears in w's neighbor list. Edges are ranked
    by (detour_count, original rank) and the best ``out_degree`` kept.
    """
    n, K = graph.shape

    def body(_, row0):
        rows = jnp.clip(row0 + jnp.arange(tile), 0, n - 1)
        g = graph[rows]                                   # [t, K]
        safe = jnp.clip(g, 0, n - 1)
        hop2 = graph[safe]                                # [t, K(i), K(l)]
        # match[t,i,j] = g[t,j] ∈ hop2[t,i,:]
        match = jnp.any(
            hop2[:, :, :, None] == g[:, None, None, :], axis=2
        )                                                 # [t, i, j]
        lower = jnp.tril(jnp.ones((K, K), bool), k=-1)    # i < j mask (i rows)
        detour = jnp.sum(match & lower.T[None], axis=1)   # [t, j]
        detour = jnp.where(g < 0, K + 1, detour)
        # lexicographic (detour, rank): stable sort by detour keeps rank order
        order = jnp.argsort(detour, axis=1, stable=True)
        kept = jnp.take_along_axis(g, order[:, :out_degree], axis=1)
        return _, (rows, kept)

    n_tiles = (n + tile - 1) // tile
    starts = jnp.arange(n_tiles) * tile
    _, (rows, kept) = lax.scan(body, None, starts)
    out = jnp.zeros((n, out_degree), jnp.int32)
    return out.at[rows.reshape(-1)].set(kept.reshape(-1, out_degree))


@functools.partial(jax.jit, static_argnames=("rev_cap",))
def _reverse_graph(graph: jax.Array, rev_cap: int) -> jax.Array:
    """Reverse-edge lists via one sort-based scatter
    (ref: graph_core.cuh optimize reverse pass :322)."""
    n, D = graph.shape
    src = jnp.broadcast_to(jnp.arange(n, dtype=jnp.int32)[:, None], (n, D)).ravel()
    tgt = graph.ravel()
    order = jnp.argsort(tgt, stable=True)
    tgt_s, src_s = tgt[order], src[order]
    # position within each target group = index − first index of the group
    first = jnp.searchsorted(tgt_s, tgt_s, side="left")
    pos = jnp.arange(n * D) - first
    valid = (tgt_s >= 0) & (pos < rev_cap)
    rev = jnp.full((n, rev_cap), -1, jnp.int32)
    rev = rev.at[jnp.where(valid, tgt_s, n), jnp.where(valid, pos, 0)].set(
        jnp.where(valid, src_s, -1), mode="drop"
    )
    return rev


@jax.jit
def _merge_forward_reverse(forward: jax.Array, reverse: jax.Array) -> jax.Array:
    """Final edge list: protect the best forward half, then prefer reverse
    edges over weak forward edges, order-preserving dedupe
    (ref: graph_core.cuh optimize merge, num_protected_edges = degree/2)."""
    n, D = forward.shape
    prot = (D + 1) // 2
    cand = jnp.concatenate([forward[:, :prot], reverse, forward[:, prot:]], axis=1)
    m = cand.shape[1]
    # first-occurrence flags, mapped back to the original (unsorted) layout
    order, dup_s = sorted_id_dedup(cand)
    dup = jnp.zeros((n, m), bool).at[
        jnp.arange(n)[:, None], order
    ].set(dup_s)
    bad = dup | (cand < 0)
    # stable order with dups pushed past the end
    prio = jnp.where(bad, m + jnp.arange(m)[None, :], jnp.arange(m)[None, :])
    keep = jnp.argsort(prio, axis=1, stable=True)[:, :D]
    out = jnp.take_along_axis(cand, keep, axis=1)
    # rows with < D unique candidates: backfill from forward (always unique)
    out = jnp.where(out < 0, forward, out)
    return out


@traced("cagra.optimize")
def optimize(
    knn_graph: jax.Array,
    out_degree: int,
    *,
    res: Optional[Resources] = None,
) -> jax.Array:
    """Prune an intermediate kNN graph (rows sorted by distance) to a
    fixed-degree CAGRA search graph (ref: graph_core.cuh optimize)."""
    res = ensure(res)
    knn_graph = jnp.asarray(knn_graph, jnp.int32)
    n, K = knn_graph.shape
    if out_degree > K:
        raise ValueError(f"out_degree {out_degree} > input degree {K}")
    # [t, K, K, K] bool membership tensor bounds the tile
    tile = max(1, min(n, res.workspace_rows(K * K * K, cap=256)))
    pruned = _prune_detourable(knn_graph, out_degree, tile)
    rev = _reverse_graph(pruned, out_degree)
    return _merge_forward_reverse(pruned, rev)


# --------------------------------------------------------------------------
# build (ref: detail/cagra/cagra_build.cuh)
# --------------------------------------------------------------------------

def _build_entry_points(dataset, n_entries: int, metric: str, seed: int, res):
    """Coarse entry-point table: a small balanced-kmeans codebook plus the
    id of the dataset row nearest each centroid (the beam-search seeds).
    One trainset-subsample kmeans + one brute-force 1-NN pass — O(n·c)
    MXU work at build time that removes the random-restart navigation
    iterations from every future query."""
    from raft_tpu.cluster import kmeans_balanced
    from raft_tpu.neighbors._common import subsample_trainset

    n = dataset.shape[0]
    kb_metric = (
        "inner_product" if metric == "inner_product" else "sqeuclidean"
    )
    n_train = min(n, max(n_entries * 8, 8192))
    train = (
        subsample_trainset(dataset, n_train, seed)
        if n_train < n else jnp.asarray(dataset)
    ).astype(jnp.float32)
    kb = kmeans_balanced.KMeansBalancedParams(
        n_iters=10, metric=kb_metric, seed=seed
    )
    centers = kmeans_balanced.fit(kb, train, n_entries, res=res)
    _, ids = brute_force.knn(dataset, centers, 1, metric=metric, res=res)
    return centers, ids[:, 0].astype(jnp.int32)


def _auto_entry_points(n: int) -> int:
    """≈ 4·√n rounded up to a power of two, clamped to [64, 4096]."""
    raw = max(2.0, 4.0 * float(np.sqrt(max(n, 1))))
    return int(np.clip(1 << int(np.ceil(np.log2(raw))), 64, 4096))

def _graph_build_ivf_pq_params(params: IndexParams, n: int, d: int):
    """The internal IVF-PQ config for the knn-graph source.

    Mirrors the reference's shape (`ivf_pq::index_params::from_dataset`
    ivf_pq_types.hpp:123-136: n_lists=sqrt(n), trainset 0.1;
    cagra_build.cuh:92: n_probes=min(2*d, n_lists)) but re-tuned for the
    decoded-cache scan: our per-row scan cost is full-dimension cache
    bytes, not a 4x-compressed LUT walk, so the scanned *fraction*
    (n_probes/n_lists) is the build-time knob.  sqrt-law lists keep that
    fraction shrinking as n grows while each probe still sees enough rows
    to feed gpu_top_k candidates."""
    inter = min(params.intermediate_graph_degree, n - 1)
    n_lists = 4 if n < 10_000 else max(32, int(n**0.5))
    ip = ivf_pq.IndexParams(
        n_lists=n_lists,
        metric=params.metric,
        kmeans_trainset_fraction=1.0 if n < 10_000 else max(
            0.1, min(1.0, 128.0 * n_lists / n)),
        seed=params.seed,
    )
    # scanned fraction ~n_probes/n_lists: 32/316 at 100k (10%), 32/1000 at
    # 1M (3.2%) — graph recall is rescued by the generous candidate pool +
    # exact refine, and the n<10k brute-force path never reaches here
    sp = ivf_pq.SearchParams(n_probes=max(8, min(n_lists, 32)))
    gpu_top_k = min(n, 2 * (inter + 1))
    return ip, sp, gpu_top_k


def _graph_build_qtile(res, n: int, d: int) -> int:
    """Row-query tile for the search-all-rows graph stage (bounded by the
    per-query candidate workspace)."""
    return max(1, res.workspace_rows(4 * n // 64 + 4 * d, cap=8192))


@traced("cagra.build")
def build(
    params: IndexParams,
    dataset: jax.Array,
    *,
    res: Optional[Resources] = None,
) -> Index:
    """(ref: cagra_build.cuh build: build_knn_graph → sort → optimize)"""
    res = ensure(res)
    # keep the dataset in its input dtype (f32/bf16/int8/uint8 — ref CAGRA
    # dtype templates cagra_types.hpp:142); search casts gathered rows
    # only. A host numpy dataset stays host-side until after the graph
    # build so the out-of-core path (nn_descent_batch) never uploads it
    # wholesale; the final index upload happens once, below.
    if not isinstance(dataset, np.ndarray):
        dataset = jnp.asarray(dataset)
    n, d = dataset.shape
    metric = DISTANCE_TYPES[params.metric]
    if metric not in ("sqeuclidean", "euclidean", "inner_product"):
        raise ValueError(f"cagra supports L2/IP metrics, got {params.metric}")
    inter = min(params.intermediate_graph_degree, n - 1)
    degree = min(params.graph_degree, inter)

    algo = params.build_algo
    if algo == "auto":
        # TPU-first threshold (round-5 CAGRA build-time work, VERDICT r4
        # next #4): an exact tiled kNN graph at n=100k, d=96 is ~2 TFLOP
        # of pure MXU work — cheaper than the ivf_pq build+search+refine
        # pipeline it replaces (measured 80% of the 196 s on-chip build)
        # and yields an exact graph.  On host backends the crossover
        # stays at 8k (a single-core 100k brute scan is minutes).
        brute_cap = 131_072 if jax.default_backend() == "tpu" else 8192
        algo = "brute_force" if n <= brute_cap else "ivf_pq"

    if algo == "brute_force":
        g = nn_descent.build_exact(dataset, inter, metric=params.metric, res=res)
        knn_graph = g.graph
    elif algo in ("nn_descent", "nn_descent_batch"):
        nnd = nn_descent.IndexParams(
            graph_degree=inter,
            intermediate_graph_degree=min(n - 1, max(inter + inter // 2, inter + 8)),
            max_iterations=params.nn_descent_niter,
            metric=params.metric,
            seed=params.seed,
        )
        if algo == "nn_descent_batch":
            # out-of-core graph build: clustered per-batch GNND + merge
            # (ref: nn_descent_batch.cuh — datasets beyond device memory)
            knn_graph = nn_descent.build_batch(
                nnd, np.asarray(dataset), res=res
            ).graph
        else:
            knn_graph = nn_descent.build(nnd, dataset, res=res).graph
    elif algo == "ivf_pq":
        # ref cagra_build.cuh:47-201: ivf_pq build → per-row search with
        # gpu_top_k = degree * refine_rate → exact refine → drop self
        ip, sp, gpu_top_k = _graph_build_ivf_pq_params(params, n, d)
        idx = ivf_pq.build(ip, dataset, res=res)
        cand_parts = []
        qtile = _graph_build_qtile(res, n, d)
        for s in range(0, n, qtile):
            _, ids = ivf_pq.search(sp, idx, dataset[s : s + qtile], gpu_top_k, res=res)
            cand_parts.append(ids)
        cands = jnp.concatenate(cand_parts)
        _, knn_graph = refine(
            dataset, dataset, cands, inter + 1, metric=params.metric, res=res
        )
        # drop the self column wherever it landed
        self_col = knn_graph == jnp.arange(n, dtype=knn_graph.dtype)[:, None]
        order = jnp.argsort(self_col, axis=1, stable=True)
        knn_graph = jnp.take_along_axis(knn_graph, order, axis=1)[:, :inter]
    else:
        raise ValueError(f"unknown build_algo {params.build_algo}")

    return finalize_index(params, dataset, knn_graph, res=res)


def finalize_index(params: IndexParams, dataset, knn_graph,
                   *, res: Optional[Resources] = None) -> Index:
    """Shared index finalization (single-device ``build`` AND the MNMG
    ``comms.distributed.sharded_cagra_build``): optimize the kNN graph to
    the output degree, upload the dataset ONCE in its input dtype, build
    the coarse entry-point table."""
    res = ensure(res)
    n = dataset.shape[0]
    metric = DISTANCE_TYPES[params.metric]
    inter = min(params.intermediate_graph_degree, n - 1)
    degree = min(params.graph_degree, inter)
    graph = optimize(jnp.asarray(knn_graph, jnp.int32), degree, res=res)
    # the index itself is device-resident (search gathers from it); a
    # host build input uploads exactly once, here
    dataset = jnp.asarray(dataset)
    n_entries = params.entry_points
    if n_entries is None:
        n_entries = _auto_entry_points(n)
    n_entries = min(n_entries, n)
    entry_centers = entry_ids = None
    if n_entries:
        entry_centers, entry_ids = _build_entry_points(
            dataset, n_entries, metric, params.seed, res
        )
    _log.debug(
        "cagra.finalize: n=%d degree=%d dtype=%s entries=%d",
        n, graph.shape[1], dataset.dtype, n_entries,
    )
    return Index(params.metric, dataset, graph, entry_centers, entry_ids)


def from_graph(metric: str, dataset: jax.Array, graph: jax.Array,
               entry_centers: Optional[jax.Array] = None,
               entry_ids: Optional[jax.Array] = None) -> Index:
    """Construct an index from a prebuilt graph (ref: cagra index ctor from
    existing dataset+graph mdspans, cagra_types.hpp:142)."""
    return Index(
        metric, jnp.asarray(dataset), jnp.asarray(graph, jnp.int32),
        None if entry_centers is None else jnp.asarray(entry_centers),
        None if entry_ids is None else jnp.asarray(entry_ids, jnp.int32),
    )


# --------------------------------------------------------------------------
# search (ref: detail/cagra/search_single_cta_kernel-inl.cuh, TPU-batched)
# --------------------------------------------------------------------------

def make_seed_ids(params: SearchParams, index: Index, queries: jax.Array,
                  k: int, itopk: Optional[int] = None) -> jax.Array:
    """Init candidates for a query batch ([q, s] dataset row ids): the
    coarse entry points (when the index carries them) + a random top-up
    (the rescue knob for weakly-connected graphs, scaled by
    num_random_samplings). Factored out of :func:`search` so the sharded
    search can seed the FULL batch once and split it with the queries —
    per-query results then don't depend on how the batch was sharded.

    This function OWNS the base itopk formula (``itopk`` overrides it for
    callers that widen the buffer, e.g. filtered search) — one owner, so
    the sharded and single-device seed pools cannot drift."""
    if itopk is None:
        itopk = min(max(params.itopk_size, k), index.size)
    n = index.size
    metric = DISTANCE_TYPES[index.metric]
    q = queries.shape[0]
    use_entries = (
        index.entry_centers is not None and params.num_entry_centers > 0
    )
    if use_entries:
        s = int(min(params.num_entry_centers, index.entry_centers.shape[0]))
        entry = _entry_seeds(
            jnp.asarray(queries, jnp.float32),
            index.entry_centers.astype(jnp.float32),
            index.entry_ids, s, metric,
        )
        n_rand = min(
            n, max(itopk, 32) * max(1, params.num_random_samplings)
        )
    else:
        entry = None
        n_rand = min(n, max(2 * itopk, 128) * max(1, params.num_random_samplings))
    key = jax.random.PRNGKey(params.rand_xor_mask & 0x7FFFFFFF)
    seed_ids = jax.random.randint(key, (q, n_rand), 0, n, jnp.int32)
    if entry is not None:
        seed_ids = jnp.concatenate([entry, seed_ids], axis=1)
    return seed_ids


@functools.partial(jax.jit, static_argnames=("s", "metric"))
def _entry_seeds(queries, centers, entry_ids, s: int, metric: str):
    """Top-``s`` coarse entry points per query — one MXU matmul + select_k
    (the IVF coarse-selection shape). Returns seed ids [q, s]."""
    if metric == "inner_product":
        sc = -jnp.matmul(queries, centers.T, precision=_PREC)
    else:
        c2 = jnp.sum(centers * centers, axis=1)
        sc = c2[None, :] - 2.0 * jnp.matmul(queries, centers.T, precision=_PREC)
    _, top = select_k(sc, s, select_min=True)
    return entry_ids[top]


def _query_distance(qs: jax.Array, vecs: jax.Array, metric: str) -> jax.Array:
    """dist(qs[i], vecs[i, j]) — [t, d] vs [t, c, d]."""
    ip = jnp.einsum("td,tcd->tc", qs, vecs, precision=_PREC)
    if metric == "inner_product":
        return -ip
    v2 = jnp.sum(vecs * vecs, axis=2)
    q2 = jnp.sum(qs * qs, axis=1)
    return jnp.maximum(q2[:, None] + v2 - 2.0 * ip, 0.0)


def _gather_rows(dataset, ids):
    """Candidate-row gather: dense take or VPQ decode-on-gather
    (ref: compute_distance_vpq.cuh decodes codes inside the kernel).
    Returns f32 — the cast runs on the gathered tile only, so a
    low-precision dataset is never copied whole to fp32."""
    if isinstance(dataset, jax.Array):
        return dataset[jnp.clip(ids, 0, dataset.shape[0] - 1)].astype(jnp.float32)
    return dataset.decode(ids)


@functools.partial(
    jax.jit,
    static_argnames=(
        "k", "itopk", "width", "max_iter", "min_iter", "metric", "tile",
        "fused",
    ),
)
def _search_jit(
    dataset, graph, queries, filter_words, seed_ids,
    k: int, itopk: int, width: int, max_iter: int, min_iter: int,
    metric: str, tile: int, fused: bool = False,
):
    n, d = dataset.shape
    deg = graph.shape[1]
    q = queries.shape[0]
    n_tiles = (q + tile - 1) // tile
    pad = n_tiles * tile - q
    qt = jnp.pad(queries, ((0, pad), (0, 0))).reshape(n_tiles, tile, d)
    st = jnp.pad(seed_ids, ((0, pad), (0, 0))).reshape(n_tiles, tile, -1)
    # per-row filters (ragged batches) tile alongside the queries; ndim is
    # static in trace so the branch costs nothing at runtime
    per_row = filter_words is not None and filter_words.ndim == 2
    if per_row:
        ft = jnp.pad(filter_words, ((0, pad), (0, 0))).reshape(
            n_tiles, tile, -1
        )
    else:
        ft = jnp.zeros((n_tiles, 1, 1), jnp.uint32)  # unused carrier

    def one_tile(args):
        qs, seeds, fw_t = args                            # [t, d], [t, s]

        def filt_inf(ids, dists):
            if filter_words is None:
                return dists
            safe = jnp.clip(ids, 0, None)
            if per_row:
                word = jnp.take_along_axis(fw_t, safe // 32, axis=1)
            else:
                word = filter_words[safe // 32]
            bit = (word >> (safe % 32).astype(jnp.uint32)) & 1
            return jnp.where(bit == 0, jnp.inf, dists)

        # ---- random init (ref: random_samplings init of itopk candidates)
        vecs = _gather_rows(dataset, seeds)
        dists = _query_distance(qs, vecs, metric)
        dists = jnp.where(seeds < 0, jnp.inf, dists)
        # dedupe seeds, take itopk best
        order, dup = sorted_id_dedup(seeds)
        s_ids = jnp.take_along_axis(seeds, order, axis=1)
        s_d = jnp.where(dup, jnp.inf, jnp.take_along_axis(dists, order, axis=1))
        buf_d, buf_i = select_k(s_d, itopk, select_min=True, input_indices=s_ids)
        # inf slots must not retain a real id: it would shadow (dedup-demote)
        # a later finite copy of the same node forever
        buf_i = jnp.where(jnp.isfinite(buf_d), buf_i, -1)
        explored = jnp.zeros((tile, itopk), bool)
        # result buffer: best-k *filter-passing* candidates seen so far.
        # Traversal itself stays unfiltered — filtered-out nodes still route
        # the walk (ref: CAGRA filtering excludes hits from the result list,
        # not from graph navigation).
        res_d, res_i = select_k(
            filt_inf(buf_i, buf_d), k, select_min=True, input_indices=buf_i
        )
        res_i = jnp.where(jnp.isfinite(res_d), res_i, -1)

        def cond(state):
            it, buf_i, buf_d, explored, res_i, res_d = state
            frontier = ~explored & jnp.isfinite(buf_d)
            return (it < min_iter) | ((it < max_iter) & jnp.any(frontier))

        # strict-upper-triangular mask: earlier[i, j] ⇔ i < j (used to
        # demote later copies of an id within one candidate batch)
        c_w = width * deg
        earlier = jnp.triu(jnp.ones((c_w, c_w), bool), k=1)

        def body(state):
            it, buf_i, buf_d, explored, res_i, res_d = state
            # ---- pick search_width best unexplored parents
            # (ref: pickup_next_parents search_single_cta_kernel-inl.cuh:55)
            front_d = jnp.where(explored | ~jnp.isfinite(buf_d), jnp.inf, buf_d)
            _, ppos = select_k(front_d, width, select_min=True)
            parent_ok = jnp.take_along_axis(front_d, ppos, axis=1) < jnp.inf
            parents = jnp.take_along_axis(buf_i, ppos, axis=1)    # [t, w]
            explored = explored.at[
                jnp.arange(tile)[:, None], ppos
            ].set(True)
            if fused:
                # ---- fused hop: expand + score + dedup + merge ride one
                # Pallas kernel (kernels/cagra_traverse.py). Only the tiny
                # [t, w] neighbor-id gather stays in XLA (it doubles as the
                # kernel's scalar-prefetch operand); the [t, w·deg, d] row
                # gather, the O(c²) dedup, and the itopk merge sort never
                # materialize in HBM. The gate in search() keeps filtered
                # traffic on the XLA body (res-buffer side-merge below).
                from raft_tpu.kernels import interpret_mode
                from raft_tpu.kernels.cagra_traverse import cagra_fused_hop

                parents_m = jnp.where(parent_ok, parents, -1)
                buf_d, buf_i, explored = cagra_fused_hop(
                    dataset, graph, qs, parents_m, buf_d, buf_i, explored,
                    metric=metric, interpret=interpret_mode(),
                )
                return it + 1, buf_i, buf_d, explored, res_i, res_d
            # ---- expand: gather graph rows (the data-dependent gather)
            nbrs = graph[jnp.clip(parents, 0, n - 1)]             # [t, w, deg]
            nbrs = jnp.where(parent_ok[:, :, None], nbrs, -1)
            cand = nbrs.reshape(tile, width * deg)
            vecs = _gather_rows(dataset, cand)                    # [t, w*deg, d]
            cd = _query_distance(qs, vecs, metric)
            cd = jnp.where(cand < 0, jnp.inf, cd)
            # ---- dedup by broadcast membership instead of sort: the hot
            # loop's visited-hashmap role (detail/cagra/hashmap.hpp) is two
            # O(c²)/O(c·itopk) VPU compares — cheap, fused, and free of the
            # multi-pass bitonic sorts the sorted-id dedup cost per
            # iteration. A candidate is demoted to inf if (a) an earlier
            # slot in this batch carries the same id, or (b) the id already
            # sits in the buffer (whose copy keeps its explored flag).
            dup_in_batch = jnp.any(
                (cand[:, :, None] == cand[:, None, :]) & earlier[None], axis=1
            )                                                     # [t, c]
            in_buf = jnp.any(cand[:, :, None] == buf_i[:, None, :], axis=2)
            cd = jnp.where(dup_in_batch | in_buf, jnp.inf, cd)
            # ---- fold filter-passing candidates into the result buffer.
            # Any node already in buf was offered to the result buffer when
            # first encountered, so the mask above cannot lose hits.
            if filter_words is not None:
                # res can hold ids long evicted from buf → its own
                # membership mask keeps the result buffer duplicate-free
                in_res = jnp.any(
                    cand[:, :, None] == res_i[:, None, :], axis=2
                )
                m_i = jnp.concatenate([res_i, cand], axis=1)
                m_d = jnp.concatenate(
                    [res_d, jnp.where(in_res, jnp.inf, filt_inf(cand, cd))],
                    axis=1,
                )
                res_d, res_i = select_k(
                    m_d, k, select_min=True, input_indices=m_i
                )
                res_i = jnp.where(jnp.isfinite(res_d), res_i, -1)
            # ---- merge into the candidate buffer (ids are now unique)
            all_i = jnp.concatenate([buf_i, cand], axis=1)
            all_d = jnp.concatenate([buf_d, cd], axis=1)
            all_e = jnp.concatenate(
                [explored, jnp.zeros((tile, width * deg), bool)], axis=1
            )
            buf_d, pos = select_k(all_d, itopk, select_min=True)
            buf_i = jnp.take_along_axis(all_i, pos, axis=1)
            buf_i = jnp.where(jnp.isfinite(buf_d), buf_i, -1)
            explored = jnp.take_along_axis(all_e, pos, axis=1)
            explored = explored | ~jnp.isfinite(buf_d)
            return it + 1, buf_i, buf_d, explored, res_i, res_d

        _, buf_i, buf_d, _, res_i, res_d = lax.while_loop(
            cond, body,
            (jnp.zeros((), jnp.int32), buf_i, buf_d, explored, res_i, res_d),
        )
        if filter_words is None:
            v, i = select_k(buf_d, k, select_min=True, input_indices=buf_i)
        else:
            # result buffer may hold duplicate ids past the frontier (see
            # body); one final dedup pass cleans them
            order, dup = sorted_id_dedup(res_i)
            s_i = jnp.take_along_axis(res_i, order, axis=1)
            s_d = jnp.where(dup, jnp.inf, jnp.take_along_axis(res_d, order, axis=1))
            v, i = select_k(s_d, k, select_min=True, input_indices=s_i)
        i = jnp.where(jnp.isfinite(v), i, -1)
        if metric == "inner_product":
            v = -v
        elif metric == "euclidean":
            v = jnp.sqrt(jnp.maximum(v, 0.0))
        return v, i

    vals, idx = lax.map(one_tile, (qt, st, ft))
    return vals.reshape(-1, k)[:q], idx.reshape(-1, k)[:q]


# --------------------------------------------------------------------------
# fixed-step traversal pieces (raft_tpu.serve.graph_shard: sharded graph
# mode drives the hop loop itself, pausing every SYNC_STEPS hops for a
# cross-shard frontier exchange)
# --------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("itopk", "metric"))
def traverse_init(dataset, queries, seed_ids, itopk: int, metric: str):
    """Candidate-buffer init from seed ids — the seed half of
    ``_search_jit``, factored out for callers that own the hop loop.
    Returns ``(buf_d, buf_i, explored)`` holding the buffer invariant
    (``buf_i == -1`` wherever ``buf_d == +inf``; nothing explored)."""
    vecs = _gather_rows(dataset, seed_ids)
    dists = _query_distance(queries, vecs, metric)
    dists = jnp.where(seed_ids < 0, jnp.inf, dists)
    order, dup = sorted_id_dedup(seed_ids)
    s_ids = jnp.take_along_axis(seed_ids, order, axis=1)
    s_d = jnp.where(dup, jnp.inf, jnp.take_along_axis(dists, order, axis=1))
    buf_d, buf_i = select_k(s_d, itopk, select_min=True, input_indices=s_ids)
    buf_i = jnp.where(jnp.isfinite(buf_d), buf_i, -1)
    explored = jnp.zeros(buf_d.shape, bool)
    return buf_d, buf_i, explored


@functools.partial(
    jax.jit, static_argnames=("steps", "width", "metric", "fused")
)
def traverse_steps(dataset, graph, queries, buf_d, buf_i, explored,
                   steps: int, width: int, metric: str, fused: bool = False):
    """``steps`` unfiltered beam-search hops — ``_search_jit``'s loop body
    as a standalone fixed-trip loop over ``(buf_d, buf_i, explored)``.

    An exhausted frontier makes remaining hops no-ops (every parent slot
    reads +inf, parents mask to −1, candidate scores stay +inf), so the
    fixed trip count is always safe; that is what keeps the sharded graph
    traversal's per-query collective count static and recompile-free.
    ``graph`` may contain −1 entries (missing halo neighbors): both the
    XLA body and the fused Pallas hop mask negative candidate ids.
    ``fused`` must only be set when the caller verified
    ``traverse_supported(dataset, itopk)`` — same gate as :func:`search`.
    """
    n = dataset.shape[0]
    deg = graph.shape[1]
    tile, itopk = buf_d.shape
    c_w = width * deg
    earlier = jnp.triu(jnp.ones((c_w, c_w), bool), k=1)

    def body(_, state):
        buf_i, buf_d, explored = state
        front_d = jnp.where(explored | ~jnp.isfinite(buf_d), jnp.inf, buf_d)
        _, ppos = select_k(front_d, width, select_min=True)
        parent_ok = jnp.take_along_axis(front_d, ppos, axis=1) < jnp.inf
        parents = jnp.take_along_axis(buf_i, ppos, axis=1)
        explored = explored.at[jnp.arange(tile)[:, None], ppos].set(True)
        if fused:
            from raft_tpu.kernels import interpret_mode
            from raft_tpu.kernels.cagra_traverse import cagra_fused_hop

            parents_m = jnp.where(parent_ok, parents, -1)
            buf_d, buf_i, explored = cagra_fused_hop(
                dataset, graph, queries, parents_m, buf_d, buf_i, explored,
                metric=metric, interpret=interpret_mode(),
            )
            return buf_i, buf_d, explored
        nbrs = graph[jnp.clip(parents, 0, n - 1)]
        nbrs = jnp.where(parent_ok[:, :, None], nbrs, -1)
        cand = nbrs.reshape(tile, c_w)
        vecs = _gather_rows(dataset, cand)
        cd = _query_distance(queries, vecs, metric)
        cd = jnp.where(cand < 0, jnp.inf, cd)
        dup_in_batch = jnp.any(
            (cand[:, :, None] == cand[:, None, :]) & earlier[None], axis=1
        )
        in_buf = jnp.any(cand[:, :, None] == buf_i[:, None, :], axis=2)
        cd = jnp.where(dup_in_batch | in_buf, jnp.inf, cd)
        all_i = jnp.concatenate([buf_i, cand], axis=1)
        all_d = jnp.concatenate([buf_d, cd], axis=1)
        all_e = jnp.concatenate(
            [explored, jnp.zeros((tile, c_w), bool)], axis=1
        )
        buf_d, pos = select_k(all_d, itopk, select_min=True)
        buf_i = jnp.take_along_axis(all_i, pos, axis=1)
        buf_i = jnp.where(jnp.isfinite(buf_d), buf_i, -1)
        explored = jnp.take_along_axis(all_e, pos, axis=1)
        explored = explored | ~jnp.isfinite(buf_d)
        return buf_i, buf_d, explored

    buf_i, buf_d, explored = lax.fori_loop(
        0, steps, body, (buf_i, buf_d, explored)
    )
    return buf_d, buf_i, explored


@traced("cagra.search")
def search(
    params: SearchParams,
    index: Index,
    queries: jax.Array,
    k: int,
    *,
    sample_filter: Optional[Bitset] = None,
    deleted_mask: Optional[Bitset] = None,
    res: Optional[Resources] = None,
    seed_ids: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Batched beam search (ref: cagra_search.cuh → single-CTA kernel,
    re-expressed as query-batched iterations). Returns
    (distances [q, k], indices [q, k]).

    ``seed_ids`` overrides init-candidate generation ([q, s] dataset row
    ids) — the seam the sharded search uses so per-query results are
    bit-identical regardless of how the query batch is split.

    ``deleted_mask`` excludes set bits (tombstones, raft_tpu.serve) and
    composes with ``sample_filter`` (pass-bits kept)."""
    res = ensure(res)
    from raft_tpu.neighbors._common import resolve_pass_filter

    sample_filter = resolve_pass_filter(sample_filter, deleted_mask)
    queries = jnp.asarray(queries, jnp.float32)
    if queries.ndim != 2 or queries.shape[1] != index.dim:
        raise ValueError(f"queries shape {queries.shape} vs index dim {index.dim}")
    n = index.size
    metric = DISTANCE_TYPES[index.metric]
    itopk = min(max(params.itopk_size, k), n)
    if sample_filter is not None:
        # widen the internal buffer by the filter's inverse pass rate so the
        # beam still meets ~itopk allowed nodes (the reference ecosystem's
        # filtered search similarly grows its workload; heavy filters
        # otherwise starve the result list). Rounded to a power of two to
        # bound recompilation to O(log n) shape buckets.
        passing = max(1, int(sample_filter.count()))
        scale = min(32.0, max(1.0, n / passing))
        widened = min(n, int(itopk * scale))
        itopk = 1 << (widened - 1).bit_length()
        itopk = min(itopk, n)
    width = params.search_width
    deg = index.graph_degree
    q = queries.shape[0]
    use_entries = (
        index.entry_centers is not None and params.num_entry_centers > 0
    )
    # ref search_plan.cuh: auto max_iterations scales with itopk/width.
    # Entry-seeded walks start next to the answer and need roughly half
    # the navigation budget of random-restart walks (round-4 sweep).
    if params.max_iterations:
        max_iter = params.max_iterations
    elif use_entries:
        max_iter = max(8, (itopk + width - 1) // width)
    else:
        max_iter = max(16, (itopk + width - 1) // width * 2)
    min_iter = min(params.min_iterations, max_iter)

    # init candidates: coarse entry points when the index carries them
    # (one MXU matmul replaces most of the random-restart navigation),
    # topped up with random seeds for graphs/queries the coarse table
    # mis-covers (ref rand_xor_mask seeds + num_random_samplings).
    if seed_ids is None:
        seed_ids = make_seed_ids(params, index, queries, k, itopk=itopk)
    else:
        seed_ids = jnp.asarray(seed_ids, jnp.int32)

    per_q = 4 * (width * deg) * (index.dim + 4) + 16 * itopk
    tile = params.max_queries or max(1, min(max(q, 1), res.workspace_rows(per_q, cap=512)))
    fw = sample_filter.words if sample_filter is not None else None
    if fw is not None and fw.ndim == 2 and fw.shape[0] != q:
        raise ValueError(
            f"row filter has {fw.shape[0]} rows for {q} queries"
        )
    # fused-hop gate: filtered traffic keeps the XLA body (the res-buffer
    # side-merge has no kernel leg), as do compressed datasets and
    # out-of-envelope itopk.  RAFT_TPU_PALLAS_CAGRA=0 reverts just this
    # kernel without losing the rest of the Pallas fleet.
    from raft_tpu import kernels as _kernels
    from raft_tpu.kernels.cagra_traverse import traverse_supported

    # paged index: beam-search gathers are graph-hop-dependent, so no
    # probe-keyed prefetch exists — the whole dataset must sit in the hot
    # pool (identity-pinned once; BudgetExceeded from pin_identity
    # otherwise — raise the budget, or serve over-HBM payloads from the
    # IVF backends whose working set is probe-bounded)
    paged = getattr(index, "paged", None)
    if paged is not None:
        from raft_tpu.store.paged import PagedRows

        paged.pin_identity()
        pool, page_slot = paged.view()
        dataset = PagedRows(pool, page_slot, index.size)
    else:
        dataset = index.dataset

    fused = (
        fw is None
        and _kernels.use_pallas()
        and _kernels.cagra_fused_enabled()
        and traverse_supported(dataset, itopk)
    )
    _kernels.stamp_kernel_path("pallas" if fused else "xla")
    return _search_jit(
        dataset, index.graph, queries, fw, seed_ids,
        int(k), int(itopk), int(width), int(max_iter), int(min_iter),
        metric, int(tile), fused=fused,
    )


# --------------------------------------------------------------------------
# serialization (ref: detail/cagra/cagra_serialize.cuh)
# --------------------------------------------------------------------------

@traced("cagra.save")
def save(filename: str, index: Index, *, include_dataset: bool = True) -> None:
    from raft_tpu.neighbors.vpq_dataset import VpqDataset

    arrays = {"graph": index.graph}
    if index.entry_centers is not None:
        arrays["entry_centers"] = index.entry_centers
        arrays["entry_ids"] = index.entry_ids
    kind = "none"
    if include_dataset:
        if isinstance(index.dataset, VpqDataset):
            kind = "vpq"
            arrays.update(
                vq_centers=index.dataset.vq_centers,
                pq_codebook=index.dataset.pq_codebook,
                vq_codes=index.dataset.vq_codes,
                pq_codes=index.dataset.pq_codes,
            )
        else:
            kind = "dense"
            arrays["dataset"] = index.dataset
    ser.save_tree(
        filename, "cagra", _SERIALIZATION_VERSION,
        {
            "metric": index.metric,
            "dataset_kind": kind,
            "dim": int(index.dim),
            # kept for format compatibility with earlier files
            "include_dataset": int(include_dataset),
        },
        arrays,
    )


@traced("cagra.load")
def load(filename: str, *, dataset: Optional[jax.Array] = None) -> Index:
    from raft_tpu.neighbors.vpq_dataset import VpqDataset

    scalars, arrays = ser.load_tree(filename, "cagra", _SERIALIZATION_VERSION)
    kind = scalars.get("dataset_kind", "dense" if scalars["include_dataset"] else "none")
    if kind == "dense":
        ds = jnp.asarray(arrays["dataset"])
    elif kind == "vpq":
        ds = VpqDataset(
            jnp.asarray(arrays["vq_centers"]),
            jnp.asarray(arrays["pq_codebook"]),
            jnp.asarray(arrays["vq_codes"]),
            jnp.asarray(arrays["pq_codes"]),
            int(scalars["dim"]),
        )
    elif dataset is not None:
        ds = jnp.asarray(dataset, jnp.float32)
    else:
        raise ValueError("index was saved without dataset; pass dataset=")
    ec = arrays.get("entry_centers")
    ei = arrays.get("entry_ids")
    return Index(
        scalars["metric"], ds, jnp.asarray(arrays["graph"]),
        None if ec is None else jnp.asarray(ec),
        None if ei is None else jnp.asarray(ei, jnp.int32),
    )
