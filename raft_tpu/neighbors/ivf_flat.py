"""IVF-Flat: inverted-file index with uncompressed vectors.

Reference: balanced-kmeans coarse quantizer + per-list vector storage,
build/extend/search/serialize (ref: cpp/include/raft/neighbors/ivf_flat_types.hpp:47-284
— params ``n_lists=1024``, ``kmeans_n_iters=20``, ``kmeans_trainset_fraction``,
``adaptive_centers``; build pipeline neighbors/detail/ivf_flat_build.cuh:344;
search = coarse select then fused interleaved scan then select_k,
neighbors/detail/ivf_flat_search-inl.cuh:40-271; Python ref:
pylibraft.neighbors.ivf_flat).

TPU re-design of the storage layout: the reference interleaves each list in
groups of 32 vectors × veclen for warp-coalesced scans
(ivf_flat_build.cuh:88-154). On TPU the equivalent is a *dense padded tensor*
``list_data [n_lists, list_cap, dim]`` — every list padded to one static
capacity so the probe scan is a single gather + batched contraction with a
validity mask, fully static-shaped for XLA. Balanced kmeans keeps
``list_cap`` within a small factor of the mean list size, bounding the
padding waste; capacity rounds up to the TPU sublane multiple (8).

Search: (1) coarse: queries×centersᵀ matmul + top-n_probes (pure MXU);
(2) gather probed lists and compute per-candidate distances with the same
Gram decomposition used everywhere (‖y‖² precomputed per stored vector);
(3) masked select_k over [n_probes × list_cap] candidates.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, replace as dc_replace
from typing import ClassVar, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from raft_tpu.cluster import kmeans_balanced
from raft_tpu.core import serialize as ser
from raft_tpu.core import validation
from raft_tpu.core.bitset import Bitset
from raft_tpu.core.resources import Resources, ensure
from raft_tpu.distance.pairwise import DISTANCE_TYPES, _PREC
from raft_tpu.neighbors._common import (
    allocate_append_slots,
    centroid_group_inverse,
    compute_list_layout,
    subsample_trainset,
    coarse_select,
    invalid_mask,
    invalid_mask_rows,
    default_max_cap,
    merge_split_lists,
    pallas_scan_enabled,
    run_probe_major,
    run_query_tiled,
    select_scan_strategy,
    unpack_lists,
)
from raft_tpu.kernels import stamp_kernel_path as _stamp_kernel_path
from raft_tpu.ops.matrix import select_k
from raft_tpu.store.paged import gather_lists as _gather_lists
from raft_tpu.core.trace import traced
from raft_tpu.core.logger import logger as _log

_SERIALIZATION_VERSION = 1


@dataclass
class IndexParams:
    """(ref: ivf_flat_types.hpp:47 index_params)"""

    n_lists: int = 1024
    metric: str = "sqeuclidean"
    kmeans_n_iters: int = 20
    kmeans_trainset_fraction: float = 0.5
    adaptive_centers: bool = False
    add_data_on_build: bool = True
    conservative_memory_allocation: bool = False  # ref ivf_flat_types.hpp
    seed: int = 0


@dataclass
class SearchParams:
    """(ref: ivf_flat_types.hpp search_params — n_probes). ``strategy``
    selects the scan schedule — see ivf_pq.SearchParams.strategy (shared
    probe-major machinery, _common.invert_probes)."""

    n_probes: int = 20
    strategy: str = "auto"  # auto | query_major | probe_major


@dataclass(frozen=True)
class EffortSpec:
    """Typed search-effort knobs for IVF-Flat — the values an actuator
    (overload ladder, SLO autotuner) may move at serve time.

    Every knob is a host Python value that selects among *already
    compiled* executables: the serving warmup ladder precompiles one
    variant per (bucket, effort level), so stepping effort re-dispatches
    a warmed executable and never appears as a new static jit argument
    (the RECOMPILE rule enforces this).  ``refine_ratio`` is an offline
    sweep knob — the bench harness searches ``k × ratio`` candidates and
    exact-refines; online actuation maps only the SearchParams fields.
    """

    n_probes: int = 20
    refine_ratio: int = 1

    backend: ClassVar[str] = "ivf_flat"

    @classmethod
    def from_params(cls, params: Optional[SearchParams] = None,
                    **extra) -> "EffortSpec":
        base = params if params is not None else SearchParams()
        return cls(n_probes=int(base.n_probes),
                   refine_ratio=int(extra.get("refine_ratio", 1)))

    def apply(self, params: Optional[SearchParams] = None) -> SearchParams:
        """SearchParams carrying this spec's online knobs (non-effort
        fields inherited from ``params``)."""
        base = params if params is not None else SearchParams()
        return dc_replace(base, n_probes=int(self.n_probes))

    def degraded(self, level: int) -> "EffortSpec":
        """This spec stepped down ``level`` notches of the serving effort
        ladder: halve ``n_probes`` per level (floor 1), drop refine."""
        if level <= 0:
            return self
        return EffortSpec(
            n_probes=max(1, int(self.n_probes) >> int(level)),
            refine_ratio=1,
        )

    def knobs(self):
        return {"n_probes": int(self.n_probes),
                "refine_ratio": int(self.refine_ratio)}


class Index:
    """Padded-list IVF-Flat index.

    Fields (all jnp arrays, jit-traversable):
      centers     [n_lists, dim]     — coarse centroids
      list_data   [n_lists, cap, dim]— padded vectors (zeros past size)
      list_index  [n_lists, cap]     — source ids (-1 past size)
      list_sizes  [n_lists]
      list_norms  [n_lists, cap]     — ‖vector‖² (inf past size, so padded
                                       slots lose every select_min)
    """

    def __init__(self, metric, centers, list_data, list_index, list_sizes,
                 list_norms, headroom: bool = True):
        self.metric = metric
        self.centers = centers
        self.list_data = list_data
        self.list_index = list_index
        self.list_sizes = list_sizes
        self.list_norms = list_norms
        # list growth headroom policy (False under
        # conservative_memory_allocation; serialized like the reference's
        # conservative_memory_allocation flag, ivf_flat_serialize.cuh:66)
        self.headroom = headroom
        # cached centroid→group map for repeated fast appends (derived)
        self._group_inverse = None

    @property
    def n_lists(self) -> int:
        return self.centers.shape[0]

    @property
    def dim(self) -> int:
        return self.centers.shape[1]

    @property
    def size(self) -> int:
        return int(jnp.sum(self.list_sizes))

    @property
    def list_cap(self) -> int:
        return self.list_data.shape[1]


def _pack_lists(
    dataset: np.ndarray, ids: np.ndarray, labels: np.ndarray, n_lists: int,
    metric: str, headroom: bool = True, max_cap="default",
):
    """Streamed pack into the padded [n_lists', cap, dim] device layout +
    per-slot norms: (list, slot) metadata host-side
    (_common.compute_list_layout, no padded host payload copies), then
    row chunks scatter into donated device buffers — same 10⁸-row-safe
    scheme as ivf_pq._assemble_lists (ref: the reference's batched
    device-side list fill, ivf_flat_build.cuh:163).

    Oversized lists are split with duplicated centroids (skew-bounded cap;
    see _common.split_oversized_lists) — returns center_map so the caller
    expands its centroid rows."""
    n = dataset.shape[0]
    d = dataset.shape[1]
    # max_cap=None disables skew splitting — the sharded build's
    # shard-major relabel needs list ids to stay stable (serve.build)
    lst, slot, sizes, center_map, cap = compute_list_layout(
        labels, n_lists,
        max_cap=default_max_cap(n, n_lists) if max_cap == "default" else max_cap,
        headroom=headroom,
    )
    L = len(center_map)
    itemsize = np.dtype(dataset.dtype).itemsize
    chunk = int(np.clip((256 << 20) // max(d * (itemsize + 8), 1), 8, max(n, 8)))

    l_data = jnp.zeros((L, cap, d), dataset.dtype)
    l_index = jnp.full((L, cap), -1, jnp.int32)
    l_norms = jnp.full((L, cap), jnp.inf, jnp.float32)
    ids = np.asarray(ids, np.int32)
    lst32 = np.asarray(lst, np.int32)
    slot32 = np.asarray(slot, np.int32)
    for s in range(0, n, chunk):
        e = min(s + chunk, n)
        pad = chunk - (e - s)
        rows = dataset[s:e]
        i_c, l_c, s_c = ids[s:e], lst32[s:e], slot32[s:e]
        if pad:
            rows = np.concatenate(
                [np.asarray(rows), np.zeros((pad, d), dataset.dtype)]
            )
            i_c = np.concatenate([i_c, np.zeros(pad, np.int32)])
            l_c = np.concatenate([l_c, np.full(pad, L, np.int32)])  # drop
            s_c = np.concatenate([s_c, np.zeros(pad, np.int32)])
        l_data, l_index, l_norms = _scatter_rows_chunk(
            l_data, l_index, l_norms,
            jnp.asarray(rows), jnp.asarray(i_c), jnp.asarray(l_c),
            jnp.asarray(s_c),
        )
    return l_data, l_index, jnp.asarray(sizes), l_norms, center_map


@functools.partial(jax.jit, donate_argnums=(0, 1, 2))
def _scatter_rows_chunk(l_data, l_index, l_norms, rows, ids, lst, slot):
    """Donated chunk scatter for the streamed pack (padding rows carry
    lst == n_lists → mode="drop")."""
    rows32 = rows.astype(jnp.float32)
    return (
        l_data.at[lst, slot].set(rows, mode="drop"),
        l_index.at[lst, slot].set(ids, mode="drop"),
        l_norms.at[lst, slot].set(jnp.sum(rows32 * rows32, axis=-1), mode="drop"),
    )


@traced("ivf_flat.build")
def build(
    params: IndexParams,
    dataset: jax.Array,
    *,
    res: Optional[Resources] = None,
) -> Index:
    """(ref: ivf_flat build pipeline, detail/ivf_flat_build.cuh:344 —
    subsample trainset → kmeans_balanced::fit → predict → pack lists)

    Examples
    --------
    >>> import numpy as np
    >>> from raft_tpu.neighbors import ivf_flat
    >>> x = np.random.default_rng(0).random((2000, 16), dtype=np.float32)
    >>> idx = ivf_flat.build(
    ...     ivf_flat.IndexParams(n_lists=8, kmeans_n_iters=3), x
    ... )
    >>> d, i = ivf_flat.search(ivf_flat.SearchParams(n_probes=8), idx, x[:4], 3)
    >>> bool((np.asarray(i)[:, 0] == np.arange(4)).all())  # exact: self is 1-NN
    True
    """
    res = ensure(res)
    # host numpy/memmap datasets stay host-resident — the trainset gather
    # and extend's per-tile stream are the only uploads (see ivf_pq.build)
    if not isinstance(dataset, np.ndarray):
        dataset = jnp.asarray(dataset)
    n, d = dataset.shape
    canonical = DISTANCE_TYPES[params.metric]
    if canonical not in ("sqeuclidean", "euclidean", "inner_product", "cosine"):
        raise ValueError(f"ivf_flat supports L2/IP/cosine metrics, got {params.metric}")

    # train the coarse quantizer under the index metric so list membership
    # agrees with the probe ranking at search time (ref: ivf_flat build uses
    # index.metric for kmeans_balanced — detail/ivf_flat_build.cuh:360)
    kb_metric = canonical if canonical in ("cosine", "inner_product") else "sqeuclidean"
    kb = kmeans_balanced.KMeansBalancedParams(
        n_iters=params.kmeans_n_iters, metric=kb_metric, seed=params.seed
    )
    n_train = max(params.n_lists, int(n * params.kmeans_trainset_fraction))
    trainset = (
        subsample_trainset(dataset, n_train, params.seed)
        if n_train < n
        else jnp.asarray(dataset)
    )
    centers = kmeans_balanced.fit(kb, trainset.astype(jnp.float32), params.n_lists, res=res)

    index = Index(
        params.metric,
        centers,
        jnp.zeros((params.n_lists, 8, d), dataset.dtype),
        jnp.full((params.n_lists, 8), -1, jnp.int32),
        jnp.zeros((params.n_lists,), jnp.int32),
        jnp.full((params.n_lists, 8), jnp.inf, jnp.float32),
        headroom=not params.conservative_memory_allocation,
    )
    if params.add_data_on_build:
        index = extend(index, dataset, jnp.arange(n, dtype=jnp.int32), res=res)
    _log.debug(
        "ivf_flat.build: n=%d dim=%d n_lists=%d (requested %d) cap=%d dtype=%s",
        n, d, index.n_lists, params.n_lists, index.list_cap,
        index.list_data.dtype,
    )
    return index


@traced("ivf_flat.extend")
def extend(
    index: Index,
    new_vectors: jax.Array,
    new_indices: Optional[jax.Array] = None,
    *,
    res: Optional[Resources] = None,
) -> Index:
    """Add vectors (ref: ivf_flat extend, detail/ivf_flat_build.cuh:163).

    Capacity changes re-pack the padded layout host-side; search recompiles
    only when ``list_cap`` crosses its next padded tier — the explicit
    recompile-tier strategy for XLA static shapes (SURVEY §7 hard part 4).
    """
    res = ensure(res)
    if getattr(index, "paged", None) is not None:
        raise ValueError(
            "extend() on a paged index is unsupported — paged serving "
            "routes growth through MutableIndex side buffers and "
            "re-paginates at compaction (see docs/paged_storage.md)"
        )
    x = (
        new_vectors
        if isinstance(new_vectors, np.ndarray)
        else jnp.asarray(new_vectors, index.list_data.dtype)
    )
    canonical = DISTANCE_TYPES[index.metric]
    kb_metric = (
        canonical if canonical in ("cosine", "inner_product") else "sqeuclidean"
    )
    n_new = x.shape[0]
    if isinstance(x, np.ndarray):
        # tiled predict: a host numpy/memmap input stays host-resident and
        # only tiles cross to the device (the ivf_pq.extend scheme)
        tile = max(1, res.workspace_rows(8 * x.shape[1], cap=1 << 18))
        label_parts = []
        for s in range(0, n_new, tile):
            xt = jnp.asarray(x[s : s + tile]).astype(jnp.float32)
            label_parts.append(
                np.asarray(kmeans_balanced.predict(index.centers, xt, metric=kb_metric, res=res))
            )
        labels = (
            np.concatenate(label_parts) if label_parts else np.zeros(0, np.int64)
        )
    else:
        # device input: one fused predict, one device→host transfer (no
        # per-tile round trips through the dispatch tunnel)
        labels = np.asarray(
            kmeans_balanced.predict(
                index.centers, x.astype(jnp.float32), metric=kb_metric, res=res
            )
        )
    new_vectors = x
    old_n = index.size
    if new_indices is None:
        new_indices = jnp.arange(old_n, old_n + n_new, dtype=jnp.int32)

    # fast path: append into spare capacity with device scatters, no repack
    # (the TPU answer to the reference's device-side list growth,
    # detail/ivf_flat_build.cuh:163; shard-aware — see allocate_append_slots)
    if new_vectors.shape[0] and old_n:
        if index._group_inverse is None:
            index._group_inverse = centroid_group_inverse(index.centers)
        alloc = allocate_append_slots(
            index.centers, index.list_sizes, index.list_cap,
            np.asarray(labels), group_inverse=index._group_inverse,
        )
        if alloc is not None:
            slab, slots, counts_new = alloc
            lj, sj = jnp.asarray(slab), jnp.asarray(slots)
            rows_dev = jnp.asarray(new_vectors, index.list_data.dtype)
            rows32 = rows_dev.astype(jnp.float32)
            new = Index(
                index.metric,
                index.centers,
                index.list_data.at[lj, sj].set(rows_dev),
                index.list_index.at[lj, sj].set(
                    jnp.asarray(new_indices, jnp.int32)
                ),
                index.list_sizes + jnp.asarray(counts_new, jnp.int32),
                index.list_norms.at[lj, sj].set(
                    jnp.sum(rows32 * rows32, axis=-1)
                ),
                headroom=index.headroom,
            )
            new._group_inverse = index._group_inverse
            return new

    # merge with existing content host-side, then re-pack; split shards from
    # a previous pack are first merged back to their parent list so repeated
    # extend() calls cannot inflate n_lists
    old_rows, old_ids, old_labels = unpack_lists(
        np.asarray(index.list_data), np.asarray(index.list_index)
    )
    if old_rows.shape[0] == 0:
        # initial fill (build): skip the concatenate so the host never
        # holds a second copy of a huge dataset
        all_rows = np.asarray(new_vectors).astype(old_rows.dtype, copy=False)
        all_ids = np.asarray(new_indices, np.int32)
        all_labels = np.asarray(labels)
    else:
        all_rows = np.concatenate(
            [old_rows, np.asarray(new_vectors).astype(old_rows.dtype, copy=False)]
        )
        all_ids = np.concatenate([old_ids, np.asarray(new_indices, np.int32)])
        all_labels = np.concatenate([old_labels, np.asarray(labels)])
    uniq, all_labels = merge_split_lists(np.asarray(index.centers), all_labels)
    base_centers = index.centers[jnp.asarray(uniq)]
    list_data, list_index, list_sizes, list_norms, center_map = _pack_lists(
        all_rows, all_ids, all_labels, len(uniq), index.metric,
        headroom=index.headroom,
    )
    centers = base_centers[jnp.asarray(center_map)]
    return Index(
        index.metric, centers, list_data, list_index, list_sizes, list_norms,
        headroom=index.headroom,
    )


@functools.partial(jax.jit, static_argnames=("n_probes", "k", "metric", "query_tile"))
def _search_jit(
    queries,      # [q, d] f32
    centers,      # [L, d] f32
    list_data,    # [L, cap, d]
    list_index,   # [L, cap] int32
    list_norms,   # [L, cap] f32 (inf at padding)
    filter_words, # [W] uint32 or None-like all-ones
    n_probes: int,
    k: int,
    metric: str,
    query_tile: int,
):
    q, d = queries.shape
    cap = list_data.shape[1]
    select_min = metric != "inner_product"

    # ---- coarse: select n_probes lists (ref: ivf_flat_search-inl.cuh:40)
    probes = coarse_select(queries, centers, metric, n_probes)  # [q, p]

    n_tiles = (q + query_tile - 1) // query_tile
    pad_q = n_tiles * query_tile - q
    qt = jnp.pad(queries, ((0, pad_q), (0, 0))).reshape(n_tiles, query_tile, d)
    pt = jnp.pad(probes, ((0, pad_q), (0, 0))).reshape(n_tiles, query_tile, n_probes)
    # per-row filters (ragged batches) tile alongside the queries; ndim is
    # static in trace so the branch costs nothing at runtime
    per_row = filter_words is not None and filter_words.ndim == 2
    if per_row:
        ft = jnp.pad(filter_words, ((0, pad_q), (0, 0))).reshape(
            n_tiles, query_tile, -1
        )
    else:
        ft = jnp.zeros((n_tiles, 1, 1), jnp.uint32)  # unused carrier

    def tile(args):
        qq, pp, fw_t = args  # [t, d], [t, p], [t, W]
        # [t, p, cap, d] gather (page-table indirected when paged)
        data = _gather_lists(list_data, pp).astype(jnp.float32)
        ids = list_index[pp]                          # [t, p, cap]
        norms = list_norms[pp]                        # [t, p, cap]
        # distance epilogue per metric
        ip = jnp.einsum("td,tpcd->tpc", qq, data, precision=_PREC)
        if metric == "inner_product":
            dist = -ip
        elif metric == "cosine":
            qn = jnp.maximum(jnp.linalg.norm(qq, axis=1), 1e-12)  # [t]
            vn = jnp.sqrt(jnp.maximum(norms, 1e-24))
            dist = 1.0 - ip / (qn[:, None, None] * vn)
        else:  # sqeuclidean/euclidean: ‖y‖² − 2x·y (+‖x‖² later, rank-stable)
            dist = norms - 2.0 * ip
        if per_row:
            invalid = invalid_mask_rows(ids, fw_t)
        else:
            invalid = invalid_mask(ids, filter_words)
        dist = jnp.where(invalid, jnp.inf, dist)
        # filtered-out candidates must surface as id −1, never their real id
        ids = jnp.where(invalid, -1, ids)
        flat_d = dist.reshape(query_tile, n_probes * cap)
        flat_i = ids.reshape(query_tile, n_probes * cap)
        v, i = select_k(flat_d, k, select_min=True, input_indices=flat_i)
        if metric == "inner_product":
            v = -v
        elif metric == "euclidean":
            qq2 = jnp.sum(qq * qq, axis=1)
            v = jnp.sqrt(jnp.maximum(v + qq2[:, None], 0.0))
        elif metric == "sqeuclidean":
            qq2 = jnp.sum(qq * qq, axis=1)
            v = v + qq2[:, None]
        return v, i

    vals, idx = lax.map(tile, (qt, pt, ft))
    return (
        vals.reshape(n_tiles * query_tile, k)[:q],
        idx.reshape(n_tiles * query_tile, k)[:q],
    )


@functools.partial(
    jax.jit, static_argnames=("n_probes", "k", "metric", "bucket", "bb")
)
def _search_probe_major_jit(
    queries,      # [q, d] f32
    centers,      # [L, d] f32
    list_data,    # [L, cap, d]
    list_index,   # [L, cap] int32
    list_norms,   # [L, cap] f32 (inf at padding)
    filter_words,
    n_probes: int,
    k: int,
    metric: str,
    bucket: int,
    bb: int,
):
    """Probe-major scan schedule (shared machinery with ivf_pq —
    _common.invert_probes / merge_probe_major_partials): each list's rows
    stream from HBM once per bucket instead of once per probing query
    (the TPU answer to the reference's per-list interleaved_scan
    scheduling, ivf_flat_interleaved_scan-inl.cuh)."""
    q, d = queries.shape
    L, cap, _ = list_data.shape
    G = bucket
    kk = min(k, cap)

    probes = coarse_select(queries, centers, metric, n_probes)
    q2 = jnp.sum(queries * queries, axis=1)
    qn = jnp.maximum(jnp.sqrt(q2), 1e-12)

    def score_fn(bl, bq):
        data = _gather_lists(list_data, bl).astype(jnp.float32)    # [bb, cap, d]
        ids = list_index[bl]
        norms = list_norms[bl]
        qq = queries[jnp.clip(bq, 0)]                              # [bb, G, d]
        # precision must match the query-major einsum (_PREC = HIGHEST):
        # default precision runs f32 matmuls as bf16 passes on TPU and the
        # two schedules would disagree on close-neighbor ranks
        ip = lax.dot_general(
            qq, data, (((2,), (2,)), ((0,), (0,))),
            precision=_PREC,
            preferred_element_type=jnp.float32,
        )                                                          # [bb, G, cap]
        if metric == "inner_product":
            dist = -ip
        elif metric == "cosine":
            vn = jnp.sqrt(jnp.maximum(norms, 1e-24))
            dist = 1.0 - ip / (qn[jnp.clip(bq, 0)][:, :, None] * vn[:, None, :])
        else:  # (sq)euclidean: ‖y‖² − 2x·y (+‖x‖² later, rank-stable)
            dist = norms[:, None, :] - 2.0 * ip
        invalid = invalid_mask(ids, filter_words)                  # [bb, cap]
        dist = jnp.where(invalid[:, None, :], jnp.inf, dist)
        dist = jnp.where(bq[:, :, None] < 0, jnp.inf, dist)
        ids_m = jnp.where(invalid, -1, ids)
        return select_k(
            dist.reshape(bb * G, cap), kk, select_min=True,
            input_indices=jnp.broadcast_to(
                ids_m[:, None, :], (bb, G, cap)
            ).reshape(bb * G, cap),
        )

    v, i = run_probe_major(probes, L, G, bb, kk, k, score_fn)
    if metric == "inner_product":
        v = -v
    elif metric == "euclidean":
        v = jnp.sqrt(jnp.maximum(v + q2[:, None], 0.0))
    elif metric == "sqeuclidean":
        v = v + q2[:, None]
    return v, i


@functools.partial(
    jax.jit,
    static_argnames=("n_probes", "k", "metric", "bucket", "interpret"),
)
def _search_probe_major_pallas(
    queries, centers, list_data, list_index, list_norms, list_filter,
    n_probes: int, k: int, metric: str, bucket: int, interpret: bool,
):
    """Probe-major schedule with the fused Pallas scan (kernels/
    ivf_scan.py — payload-agnostic: here y² = the stored row norms and
    queries are unrotated; inner product rides the kernel's −ip leg and
    ``list_filter`` is the pre-packed per-list word table, packed once in
    :func:`search`). Scores + per-query top-k stay in VMEM."""
    from raft_tpu.kernels.ivf_scan import ivf_scan_probe_major
    from raft_tpu.neighbors._common import (
        invert_probes as _invert,
        merge_probe_major_partials as _merge,
    )

    q, d = queries.shape
    L, cap, _ = list_data.shape
    G = bucket
    kk = min(k, cap)
    probes = coarse_select(queries, centers, metric, n_probes)
    q2 = jnp.sum(queries * queries, axis=1)
    bucket_list, bucket_query, bucket_pair, B = _invert(probes, L, G)
    qg = queries[jnp.clip(bucket_query, 0)]                  # [B, G, d]
    q2g = jnp.where(bucket_query >= 0, q2[jnp.clip(bucket_query, 0)], jnp.inf)
    # padding slots carry inf norms; the kernel masks by ids < 0, so zero
    # them to keep inf out of the MXU product path
    norms = jnp.where(list_index >= 0, list_norms, 0.0)
    vals, ids = ivf_scan_probe_major(
        bucket_list, qg, q2g, list_data, norms, list_index, kk,
        metric=metric, list_filter=list_filter, interpret=interpret,
    )
    v, i = _merge(
        vals.reshape(B * G, kk), ids.reshape(B * G, kk),
        bucket_pair, q, n_probes, kk, k,
    )
    if metric == "inner_product":
        v = -v
    elif metric == "euclidean":
        v = jnp.sqrt(jnp.maximum(v, 0.0))
    return v, i


@functools.partial(
    jax.jit, static_argnames=("n_probes", "k", "metric", "interpret")
)
def _search_query_major_pallas(
    queries, centers, list_data, list_index, list_norms, list_filter,
    n_probes: int, k: int, metric: str, interpret: bool, query_fid=None,
):
    """Query-major schedule with the fused Pallas scan (payload-agnostic
    kernels/ivf_scan.ivf_scan_query_major — here y² = stored row norms
    and queries ride unrotated): probed lists stream straight into VMEM;
    the XLA leg's [t, p, cap, d] gather copy and score tensor never
    exist. Queries pad to the kernel group width with q2=+inf rows.

    ``query_fid`` (ragged descriptor leg) selects each query's filter row
    from a pre-packed [n_filters, L, cap_w] ``list_filter`` table; padding
    rows ride fid 0 — their q2=+inf already voids the result."""
    from raft_tpu.kernels.ivf_scan import _QM_GROUP, ivf_scan_query_major

    q, d = queries.shape
    probes = coarse_select(queries, centers, metric, n_probes)
    q2 = jnp.sum(queries * queries, axis=1)
    # padding slots carry inf norms; the kernel masks by ids < 0, so zero
    # them to keep inf out of the MXU product path
    norms = jnp.where(list_index >= 0, list_norms, 0.0)
    pad = (-q) % _QM_GROUP
    if pad:
        probes = jnp.pad(probes, ((0, pad), (0, 0)))
        queries = jnp.pad(queries, ((0, pad), (0, 0)))
        q2 = jnp.pad(q2, (0, pad), constant_values=jnp.inf)
        if query_fid is not None:
            query_fid = jnp.pad(query_fid, (0, pad))
    v, i = ivf_scan_query_major(
        probes, queries, q2, list_data, norms, list_index, int(k),
        metric=metric, scan_dtype="highest", list_filter=list_filter,
        query_fid=query_fid, interpret=interpret,
    )
    v, i = v[:q], i[:q]
    if metric == "inner_product":
        v = -v
    elif metric == "euclidean":
        # kernel folds +‖q‖² into the L2 score, so only the root remains
        v = jnp.sqrt(jnp.maximum(v, 0.0))
    return v, i


@traced("ivf_flat.search")
def search(
    params: SearchParams,
    index: Index,
    queries: jax.Array,
    k: int,
    *,
    sample_filter: Optional[Bitset] = None,
    deleted_mask: Optional[Bitset] = None,
    res: Optional[Resources] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Returns (distances [q, k], indices [q, k]); indices −1 never appear
    unless a list underfills k (then distance is +inf).

    ``deleted_mask`` excludes set bits (tombstones, raft_tpu.serve) and
    composes with ``sample_filter`` (pass-bits kept)."""
    res = ensure(res)
    from raft_tpu.neighbors._common import resolve_pass_filter

    sample_filter = resolve_pass_filter(sample_filter, deleted_mask)
    queries = jnp.asarray(queries, jnp.float32)
    if queries.ndim != 2 or queries.shape[1] != index.dim:
        raise ValueError(f"queries shape {queries.shape} vs index dim {index.dim}")
    n_probes = min(params.n_probes, index.n_lists)
    if k > n_probes * index.list_cap:
        raise ValueError(
            f"k={k} exceeds the candidate pool n_probes*list_cap="
            f"{n_probes}*{index.list_cap}; raise n_probes"
        )
    canonical = DISTANCE_TYPES[index.metric]
    fw = sample_filter.words if sample_filter is not None else None
    validation.check_in(
        params.strategy, ("auto", "query_major", "probe_major"), "strategy"
    )
    per_row = fw is not None and fw.ndim == 2
    req_strategy = params.strategy
    if per_row:
        validation.expects(
            fw.shape[0] == queries.shape[0],
            f"row filter has {fw.shape[0]} rows for "
            f"{queries.shape[0]} queries",
        )
        # probe-major tiles score whole lists against query *buckets*; a
        # per-query filter has no per-list formulation there, so ragged
        # batches always take the query-major schedule
        req_strategy = "query_major"
    strategy, bucket, bb, q_tile = select_scan_strategy(
        req_strategy, queries.shape[0], n_probes, index.n_lists,
        index.list_cap, index.dim, res.workspace_limit_bytes, k=int(k),
    )
    # paged storage: run the coarse pass up front, admit the probed
    # lists' pages, then scan through the page-table device view — the
    # search executables below are the ones the monolithic arm compiles
    paged = getattr(index, "paged", None)
    if paged is not None:
        from raft_tpu.neighbors._common import paged_lists_for_search

        list_data = paged_lists_for_search(index, queries, canonical, n_probes)
    else:
        list_data = index.list_data
    if strategy == "probe_major":
        use_pallas = pallas_scan_enabled(canonical, list_data.dtype)
        if paged is not None and use_pallas:
            from raft_tpu.kernels.ivf_scan import paged_scan_supported

            use_pallas = paged_scan_supported(
                list_data, min(int(k), index.list_cap), fw is not None
            )
        if use_pallas:
            from raft_tpu.kernels import interpret_mode
            from raft_tpu.kernels.ivf_scan import pack_list_filter

            # pack the filter ONCE per call (query-independent)
            lf = (
                None if fw is None
                else pack_list_filter(index.list_index, fw)
            )
            _stamp_kernel_path("pallas")

            def run_pm(qt):
                return _search_probe_major_pallas(
                    qt, index.centers, list_data, index.list_index,
                    index.list_norms, lf, n_probes, int(k), canonical,
                    bucket, interpret_mode(),
                )
        else:
            _stamp_kernel_path("xla")

            def run_pm(qt):
                return _search_probe_major_jit(
                    qt,
                    index.centers,
                    list_data,
                    index.list_index,
                    index.list_norms,
                    fw,
                    n_probes,
                    int(k),
                    canonical,
                    bucket,
                    bb,
                )

        # host-level query batching bounds the merge buffers (see
        # select_scan_strategy)
        return run_query_tiled(run_pm, queries, q_tile)
    from raft_tpu.kernels import ivf_scan as _scan_mod

    has_descriptor = per_row and getattr(sample_filter, "table", None) is not None
    if (
        paged is None  # query-major kernel streams whole monolithic lists
        and pallas_scan_enabled(canonical, list_data.dtype)
        and (not per_row or has_descriptor)
        and _scan_mod.qm_scratch_bytes(n_probes, index.list_cap)
        <= _scan_mod.QM_VMEM_BUDGET
    ):
        from raft_tpu.kernels import interpret_mode

        if has_descriptor:
            # ragged descriptor leg: pack every registered filter's per-list
            # word table once; each query's fid prefetches its own block
            lf = _scan_mod.pack_list_filter_table(
                index.list_index, sample_filter.table
            )
            fid = jnp.asarray(sample_filter.fid, jnp.int32)
            _stamp_kernel_path("pallas")

            def run_qm(qt, ft):
                return _search_query_major_pallas(
                    qt, index.centers, index.list_data, index.list_index,
                    index.list_norms, lf, n_probes, int(k), canonical,
                    interpret_mode(), query_fid=ft,
                )

            return run_query_tiled(
                run_qm, queries, _scan_mod.qm_query_tile(n_probes),
                extras=(fid,),
            )

        lf = (
            None if fw is None
            else _scan_mod.pack_list_filter(index.list_index, fw)
        )
        _stamp_kernel_path("pallas")

        def run_qm(qt):
            return _search_query_major_pallas(
                qt, index.centers, index.list_data, index.list_index,
                index.list_norms, lf, n_probes, int(k), canonical,
                interpret_mode(),
            )

        return run_query_tiled(
            run_qm, queries, _scan_mod.qm_query_tile(n_probes)
        )
    # tile queries so the [t, p, cap, d] gather respects the workspace budget
    per_q = 4 * n_probes * index.list_cap * (index.dim + 2)
    query_tile = int(min(max(queries.shape[0], 1), max(1, res.workspace_rows(per_q, cap=256))))
    # per-row filters land here only when the fused descriptor leg was
    # unavailable — stamp the fallback distinctly for the perf ledger A/B
    _stamp_kernel_path("xla_filter_fallback" if per_row else "xla")
    return _search_jit(
        queries,
        index.centers,
        list_data,
        index.list_index,
        index.list_norms,
        fw,
        n_probes,
        int(k),
        canonical,
        query_tile,
    )


@traced("ivf_flat.save")
def save(filename: str, index: Index) -> None:
    ser.save_tree(
        filename,
        "ivf_flat",
        _SERIALIZATION_VERSION,
        # ref serializes conservative_memory_allocation
        # (ivf_flat_serialize.cuh:66); headroom == not conservative
        {"metric": index.metric, "headroom": int(index.headroom)},
        {
            "centers": index.centers,
            "list_data": index.list_data,
            "list_index": index.list_index,
            "list_sizes": index.list_sizes,
            "list_norms": index.list_norms,
        },
    )


@traced("ivf_flat.load")
def load(filename: str) -> Index:
    scalars, arrays = ser.load_tree(filename, "ivf_flat", _SERIALIZATION_VERSION)
    return Index(
        scalars["metric"],
        jnp.asarray(arrays["centers"]),
        jnp.asarray(arrays["list_data"]),
        jnp.asarray(arrays["list_index"]),
        jnp.asarray(arrays["list_sizes"]),
        jnp.asarray(arrays["list_norms"]),
        headroom=bool(scalars.get("headroom", 1)),
    )
