"""IVF-PQ: inverted-file index with product-quantized residual vectors.

Reference surface: build/extend/search/serialize with hierarchical balanced
k-means coarse quantizer, optional random rotation, per-subspace or
per-cluster PQ codebooks (ref: cpp/include/raft/neighbors/ivf_pq_types.hpp:47-172
— ``pq_bits`` 4..8, ``pq_dim``, ``codebook_gen`` :42, ``n_probes``,
``lut_dtype``; build pipeline neighbors/detail/ivf_pq_build.cuh:1681-1836:
trainset subsample → kmeans_balanced::fit → predict → make_rotation_matrix:122
→ set_centers:317 → train_per_subset:395 / train_per_cluster:473 →
extend:1501; search pipeline neighbors/detail/ivf_pq_search.cuh:588-718:
select_clusters = GEMM + select_k, then per-probe LUT build +
compute_similarity scan + select_k; Python ref: pylibraft ivf_pq.pyx:312-748).

TPU re-design
-------------
* **Storage**: the reference packs pq_bits-wide codes into interleaved bit
  fields scanned warp-style (ivf_pq_build.cuh process_and_fill_codes:1323).
  On TPU the natural unit is the int8 VPU lane: codes live *unpacked* one
  byte per sub-quantizer in a dense padded tensor
  ``list_codes [n_lists, cap, pq_dim] uint8`` — every probe scan is then a
  static-shape gather + vectorized LUT lookup, no bit twiddling on the
  critical path. (pq_bits still bounds the codebook size 2**pq_bits, and a
  packed serialization keeps files small for pq_bits<8.)
* **LUT scoring**: LUT[q,p,j,k] = metric contribution of codebook entry k in
  subspace j for (query, probe) — built with one einsum on the MXU; the
  scan is one ``take_along_axis`` gather over the k axis followed by a sum
  over subspaces, batched over [tile, probes, cap]. This mirrors
  compute_similarity's shmem LUT (ivf_pq_compute_similarity-inl.cuh) with
  VMEM-resident LUTs.
* **Rotation**: random orthonormal (QR of gaussian), padding dim up to
  rot_dim = pq_dim*pq_len like make_rotation_matrix (ivf_pq_build.cuh:122).
* **Codebook training**: per-subspace Lloyd iterations vmapped over all
  pq_dim subspaces at once — one compiled kernel trains every codebook
  (reference loops subspaces on separate streams, train_per_subset:395).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from raft_tpu.cluster import kmeans_balanced
from raft_tpu.core import serialize as ser
from raft_tpu.core.bitset import Bitset
from raft_tpu.core.resources import Resources, ensure
from raft_tpu.distance.pairwise import DISTANCE_TYPES, _PREC
from raft_tpu.neighbors._common import (
    coarse_select,
    invalid_mask,
    pack_padded_lists,
    unpack_lists,
)
from raft_tpu.ops.matrix import select_k
from raft_tpu.core.trace import traced

_SERIALIZATION_VERSION = 1

CODEBOOK_PER_SUBSPACE = "per_subspace"
CODEBOOK_PER_CLUSTER = "per_cluster"


@dataclass
class IndexParams:
    """(ref: ivf_pq_types.hpp:47-139 index_params)"""

    n_lists: int = 1024
    metric: str = "sqeuclidean"
    kmeans_n_iters: int = 20
    kmeans_trainset_fraction: float = 0.5
    pq_bits: int = 8          # 4..8 (ref :55)
    pq_dim: int = 0           # 0 → auto: dim/4 rounded up to 8 (ref :64)
    codebook_kind: str = CODEBOOK_PER_SUBSPACE  # ref codebook_gen :42
    force_random_rotation: bool = False
    add_data_on_build: bool = True
    conservative_memory_allocation: bool = False
    seed: int = 0


@dataclass
class SearchParams:
    """(ref: ivf_pq_types.hpp:139-172 search_params)"""

    n_probes: int = 20
    lut_dtype: str = "float32"                 # float32 | bfloat16 (ref fp8/half analog)
    internal_distance_dtype: str = "float32"   # float32 | bfloat16


def _auto_pq_dim(dim: int) -> int:
    # ref ivf_pq_types.hpp:123 from_dataset: dim/4 rounded, here rounded up to
    # a multiple of 8 so rot_dim tiles the VPU sublane.
    v = max(1, dim // 4)
    return (v + 7) // 8 * 8 if v > 8 else v


class Index:
    """IVF-PQ index with padded per-list code storage.

    Fields:
      centers      [L, dim]  f32        — coarse centroids (original space)
      centers_rot  [L, rot_dim] f32     — rotated centroids
      rotation     [rot_dim, dim] f32   — orthonormal rows
      codebook     per_subspace: [pq_dim, 2**pq_bits, pq_len] f32
                   per_cluster:  [L, 2**pq_bits, pq_len] f32
      list_codes   [L, cap, pq_dim] uint8
      list_index   [L, cap] int32 (-1 past size)
      list_sizes   [L] int32
    """

    def __init__(
        self, metric, codebook_kind, pq_bits, centers, centers_rot, rotation,
        codebook, list_codes, list_index, list_sizes,
    ):
        self.metric = metric
        self.codebook_kind = codebook_kind
        self.pq_bits = pq_bits
        self.centers = centers
        self.centers_rot = centers_rot
        self.rotation = rotation
        self.codebook = codebook
        self.list_codes = list_codes
        self.list_index = list_index
        self.list_sizes = list_sizes

    @property
    def n_lists(self) -> int:
        return self.centers.shape[0]

    @property
    def dim(self) -> int:
        return self.centers.shape[1]

    @property
    def rot_dim(self) -> int:
        return self.rotation.shape[0]

    @property
    def pq_dim(self) -> int:
        return self.list_codes.shape[2]

    @property
    def pq_len(self) -> int:
        return self.rot_dim // self.pq_dim

    @property
    def pq_n_centers(self) -> int:
        return 1 << self.pq_bits

    @property
    def list_cap(self) -> int:
        return self.list_codes.shape[1]

    @property
    def size(self) -> int:
        return int(jnp.sum(self.list_sizes))


def make_rotation_matrix(
    key: jax.Array, rot_dim: int, dim: int, force_random: bool
) -> jax.Array:
    """Orthonormal [rot_dim, dim]: random QR when forced or when padding is
    needed, else identity (ref: ivf_pq_build.cuh make_rotation_matrix:122)."""
    if not force_random and rot_dim == dim:
        return jnp.eye(dim, dtype=jnp.float32)
    if not force_random:
        # norm-preserving zero-padded identity
        return jnp.eye(rot_dim, dim, dtype=jnp.float32)
    if rot_dim <= dim:
        g = jax.random.normal(key, (dim, rot_dim), jnp.float32)
        q, _ = jnp.linalg.qr(g)  # orthonormal columns
        return q.T
    # rot_dim > dim: orthonormal columns of [rot_dim, dim]
    g = jax.random.normal(key, (rot_dim, dim), jnp.float32)
    q, _ = jnp.linalg.qr(g)
    return q


@functools.partial(jax.jit, static_argnames=("n_centers", "n_iters"))
def _train_codebooks_lloyd(key, subvecs, n_centers: int, n_iters: int,
                           weights=None):
    """Batched Lloyd over S independent subspace problems.

    subvecs: [S, n, pq_len], weights: optional [S, n] (0 ⇒ row is padding and
    contributes nothing). Returns [S, n_centers, pq_len]. vmapped so all
    pq_dim (or n_lists) codebooks train in one XLA program
    (ref: train_per_subset ivf_pq_build.cuh:395 / train_per_cluster :473,
    which run a kmeans per subspace on residual slices)."""
    S, n, L = subvecs.shape
    if weights is None:
        weights = jnp.ones((S, n), subvecs.dtype)

    def one(key, x, w):
        # weight-proportional seed draw keeps padding rows out of the init
        idx = jax.random.choice(
            key, n, shape=(n_centers,), replace=n < n_centers,
            p=w / jnp.maximum(jnp.sum(w), 1e-12),
        )
        centers0 = x[idx]

        def body(centers, _):
            d2 = (
                jnp.sum(centers * centers, 1)[None, :]
                - 2.0 * jnp.matmul(x, centers.T, precision=_PREC)
            )
            labels = jnp.argmin(d2, axis=1)
            sums = jax.ops.segment_sum(x * w[:, None], labels, num_segments=n_centers)
            counts = jax.ops.segment_sum(w, labels, n_centers)
            new = jnp.where(counts[:, None] > 0, sums / jnp.maximum(counts[:, None], 1e-12), centers)
            return new, None

        centers, _ = lax.scan(body, centers0, None, length=n_iters)
        return centers

    keys = jax.random.split(key, S)
    return jax.vmap(one)(keys, subvecs, weights)


@functools.partial(jax.jit, static_argnames=("codebook_kind",))
def _encode(rotation, centers, centers_rot, codebook, x, labels, codebook_kind):
    """Residual-encode rows → uint8 codes [n, pq_dim]
    (ref: process_and_fill_codes ivf_pq_build.cuh:1323)."""
    rot_dim = rotation.shape[0]
    res = x - centers[labels]                       # [n, dim]
    res_rot = jnp.matmul(res, rotation.T, precision=_PREC)  # [n, rot_dim]
    if codebook_kind == CODEBOOK_PER_SUBSPACE:
        pq_dim, k, pq_len = codebook.shape
        sub = res_rot.reshape(-1, pq_dim, pq_len)   # [n, j, l]
        # ||sub - cb||² argmin over k: −2·ip + ||cb||²  (‖sub‖² is rank-neutral)
        ip = jnp.einsum("njl,jkl->njk", sub, codebook, precision=_PREC)
        cb2 = jnp.sum(codebook * codebook, axis=2)  # [j, k]
        codes = jnp.argmin(cb2[None] - 2.0 * ip, axis=2)
    else:
        n_lists, k, pq_len = codebook.shape
        pq_dim = rot_dim // pq_len
        sub = res_rot.reshape(-1, pq_dim, pq_len)
        cb = codebook[labels]                       # [n, k, l]
        ip = jnp.einsum("njl,nkl->njk", sub, cb, precision=_PREC)
        cb2 = jnp.sum(cb * cb, axis=2)              # [n, k]
        codes = jnp.argmin(cb2[:, None, :] - 2.0 * ip, axis=2)
    return codes.astype(jnp.uint8)


def _pack_code_lists(codes: np.ndarray, ids: np.ndarray, labels: np.ndarray, n_lists: int):
    """Scatter encoded rows into the padded [n_lists, cap, pq_dim] layout."""
    list_codes, list_index, sizes = pack_padded_lists(codes, ids, labels, n_lists)
    return jnp.asarray(list_codes), jnp.asarray(list_index), jnp.asarray(sizes)


@traced("ivf_pq.build")
def build(
    params: IndexParams,
    dataset: jax.Array,
    *,
    res: Optional[Resources] = None,
) -> Index:
    """(ref: build pipeline detail/ivf_pq_build.cuh:1681-1836)"""
    res = ensure(res)
    dataset = jnp.asarray(dataset)
    n, dim = dataset.shape
    canonical = DISTANCE_TYPES[params.metric]
    if canonical not in ("sqeuclidean", "euclidean", "inner_product"):
        raise ValueError(f"ivf_pq supports L2/IP metrics, got {params.metric}")
    if not (4 <= params.pq_bits <= 8):
        raise ValueError(f"pq_bits must be in [4, 8], got {params.pq_bits}")

    pq_dim = params.pq_dim or _auto_pq_dim(dim)
    pq_len = max(1, (dim + pq_dim - 1) // pq_dim)
    rot_dim = pq_dim * pq_len

    key = jax.random.PRNGKey(params.seed)
    k_train, k_rot, k_cb = jax.random.split(key, 3)

    # --- trainset subsample (ref :1706-1766)
    n_train = min(n, max(params.n_lists * 2, int(n * params.kmeans_trainset_fraction)))
    if n_train < n:
        train_idx = jax.random.choice(k_train, n, shape=(n_train,), replace=False)
        trainset = dataset[train_idx].astype(jnp.float32)
    else:
        trainset = dataset.astype(jnp.float32)

    # --- coarse quantizer (ref :1776-1781 → kmeans_balanced hierarchical
    # fit, trained under the index metric so list membership matches the
    # probe ranking at search time — ref ivf_pq_build.cuh:1780 passes
    # index.metric into kmeans_balanced)
    kb_metric = "inner_product" if canonical == "inner_product" else "sqeuclidean"
    kb = kmeans_balanced.KMeansBalancedParams(
        n_iters=params.kmeans_n_iters, metric=kb_metric, seed=params.seed
    )
    centers = kmeans_balanced.fit(kb, trainset, params.n_lists, res=res)
    labels = kmeans_balanced.predict(centers, trainset, metric=kb_metric, res=res)

    # --- rotation + rotated centers (ref make_rotation_matrix:122, set_centers:317)
    rotation = make_rotation_matrix(k_rot, rot_dim, dim, params.force_random_rotation)
    centers_rot = jnp.matmul(centers, rotation.T, precision=_PREC)

    # --- PQ codebooks on rotated residuals (ref train_per_subset:395 / :473)
    resid = jnp.matmul(trainset - centers[labels], rotation.T, precision=_PREC)
    k_pq = 1 << params.pq_bits
    if params.codebook_kind == CODEBOOK_PER_SUBSPACE:
        subvecs = jnp.transpose(resid.reshape(-1, pq_dim, pq_len), (1, 0, 2))
        codebook = _train_codebooks_lloyd(k_cb, subvecs, k_pq, 25)
    elif params.codebook_kind == CODEBOOK_PER_CLUSTER:
        # pool every subspace slice of a cluster's residuals into one training
        # set per cluster, padded to uniform count with weight-0 rows so the
        # padding cannot bias the centroids
        sub = np.asarray(resid).reshape(-1, pq_dim, pq_len)
        lab = np.asarray(labels)
        per = [sub[lab == c].reshape(-1, pq_len) for c in range(params.n_lists)]
        cap = max(max((p.shape[0] for p in per), default=1), k_pq)
        pooled = np.zeros((params.n_lists, cap, pq_len), np.float32)
        wts = np.zeros((params.n_lists, cap), np.float32)
        for c, p in enumerate(per):
            if p.shape[0]:
                pooled[c, : p.shape[0]] = p
                wts[c, : p.shape[0]] = 1.0
        codebook = _train_codebooks_lloyd(
            k_cb, jnp.asarray(pooled), k_pq, 25, jnp.asarray(wts)
        )
    else:
        raise ValueError(f"unknown codebook_kind {params.codebook_kind}")

    index = Index(
        params.metric,
        params.codebook_kind,
        params.pq_bits,
        centers,
        centers_rot,
        rotation,
        codebook,
        jnp.zeros((params.n_lists, 8, pq_dim), jnp.uint8),
        jnp.full((params.n_lists, 8), -1, jnp.int32),
        jnp.zeros((params.n_lists,), jnp.int32),
    )
    if params.add_data_on_build:
        index = extend(index, dataset, jnp.arange(n, dtype=jnp.int32), res=res)
    return index


@traced("ivf_pq.extend")
def extend(
    index: Index,
    new_vectors: jax.Array,
    new_indices: Optional[jax.Array] = None,
    *,
    res: Optional[Resources] = None,
) -> Index:
    """Encode + append rows (ref: extend detail/ivf_pq_build.cuh:1501)."""
    res = ensure(res)
    x = jnp.asarray(new_vectors, jnp.float32)
    canonical = DISTANCE_TYPES[index.metric]
    labels = kmeans_balanced.predict(
        index.centers, x,
        metric="inner_product" if canonical == "inner_product" else "sqeuclidean",
        res=res,
    )
    # batch the encode to bound the [n, rot_dim]+einsum workspace
    n = x.shape[0]
    tile = max(1, res.workspace_rows(4 * (index.rot_dim * 3 + index.pq_dim * index.pq_n_centers), cap=1 << 18))
    codes_parts = []
    for s in range(0, n, tile):
        codes_parts.append(
            np.asarray(
                _encode(
                    index.rotation, index.centers, index.centers_rot, index.codebook,
                    x[s : s + tile], labels[s : s + tile], index.codebook_kind,
                )
            )
        )
    codes = np.concatenate(codes_parts) if codes_parts else np.zeros((0, index.pq_dim), np.uint8)

    old_n = index.size
    if new_indices is None:
        new_indices = jnp.arange(old_n, old_n + n, dtype=jnp.int32)

    old_codes, old_ids, old_labels = unpack_lists(
        np.asarray(index.list_codes), np.asarray(index.list_index)
    )
    all_codes = np.concatenate([old_codes, codes])
    all_ids = np.concatenate([old_ids, np.asarray(new_indices, np.int32)])
    all_labels = np.concatenate([old_labels, np.asarray(labels)])
    list_codes, list_index, list_sizes = _pack_code_lists(
        all_codes, all_ids, all_labels, index.n_lists
    )
    return Index(
        index.metric, index.codebook_kind, index.pq_bits,
        index.centers, index.centers_rot, index.rotation, index.codebook,
        list_codes, list_index, list_sizes,
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "n_probes", "k", "metric", "codebook_kind", "query_tile", "lut_dtype", "acc_dtype",
    ),
)
def _search_jit(
    queries,      # [q, dim] f32
    centers,      # [L, dim]
    centers_rot,  # [L, rot_dim]
    rotation,     # [rot_dim, dim]
    codebook,
    list_codes,   # [L, cap, pq_dim] uint8
    list_index,   # [L, cap] int32
    filter_words,
    n_probes: int,
    k: int,
    metric: str,
    codebook_kind: str,
    query_tile: int,
    lut_dtype,
    acc_dtype,
):
    q, dim = queries.shape
    rot_dim = centers_rot.shape[1]
    cap = list_codes.shape[1]
    pq_dim = list_codes.shape[2]
    pq_len = rot_dim // pq_dim

    # ---- coarse cluster selection (ref select_clusters ivf_pq_search.cuh:67)
    probes = coarse_select(queries, centers, metric, n_probes)  # [q, p]

    q_rot = jnp.matmul(queries, rotation.T, precision=_PREC)  # [q, rot_dim]

    n_tiles = (q + query_tile - 1) // query_tile
    pad_q = n_tiles * query_tile - q
    qt = jnp.pad(q_rot, ((0, pad_q), (0, 0))).reshape(n_tiles, query_tile, rot_dim)
    qo = jnp.pad(queries, ((0, pad_q), (0, 0))).reshape(n_tiles, query_tile, dim)
    pt = jnp.pad(probes, ((0, pad_q), (0, 0))).reshape(n_tiles, query_tile, n_probes)

    def tile(args):
        qr, qorig, pp = args  # [t, rot_dim], [t, dim], [t, p]
        # ---- LUT (ref: compute_similarity shmem LUT; here one MXU einsum)
        if metric == "inner_product" and codebook_kind == CODEBOOK_PER_SUBSPACE:
            # probe-independent: one einsum per query, broadcast over probes
            qsub = qr.reshape(query_tile, 1, pq_dim, pq_len)
            ipq = jnp.einsum("tjl,jkl->tjk", qsub[:, 0], codebook, precision=_PREC)
            lut = jnp.broadcast_to(
                -ipq[:, None], (query_tile, n_probes, pq_dim, ipq.shape[-1])
            )
        else:
            c_rot = centers_rot[pp]                      # [t, p, rot_dim]
            # residual queries in rotated space, split into subspaces
            res = (
                (qr[:, None, :] - c_rot)
                if metric != "inner_product"
                else jnp.broadcast_to(qr[:, None, :], c_rot.shape)
            )
            res = res.reshape(query_tile, n_probes, pq_dim, pq_len)
            if codebook_kind == CODEBOOK_PER_SUBSPACE:
                # cb: [j, k, l]
                ip = jnp.einsum("tpjl,jkl->tpjk", res, codebook, precision=_PREC)
                cb2 = jnp.sum(codebook * codebook, axis=2)[None, None]  # [1,1,j,k]
            else:
                cb = codebook[pp]                        # [t, p, k, l]
                ip = jnp.einsum("tpjl,tpkl->tpjk", res, cb, precision=_PREC)
                cb2 = jnp.sum(cb * cb, axis=3)[:, :, None, :]  # [t,p,1,k]
            if metric == "inner_product":
                lut = -ip                                # score_j = −(q_j·cb_k)
            else:
                lut = cb2 - 2.0 * ip                     # ‖res_j−cb_k‖² − ‖res_j‖²
        lut = lut.astype(lut_dtype)

        # ---- scan codes: score[t,p,c] = Σ_j LUT[t,p,j,codes[p,c,j]]
        codes = list_codes[pp]                           # [t, p, cap, j] uint8
        ids = list_index[pp]                             # [t, p, cap]
        codes_t = jnp.transpose(codes, (0, 1, 3, 2)).astype(jnp.int32)  # [t,p,j,c]
        gathered = jnp.take_along_axis(lut, codes_t, axis=3)            # [t,p,j,c]
        # ref internal_distance_dtype: the score accumulator precision
        scores = jnp.sum(gathered.astype(acc_dtype), axis=2).astype(jnp.float32)

        if metric == "inner_product":
            # q·y = q·center + q_rot·decode(residual);  lut already = −q_rot·cb
            qc = jnp.einsum("td,tpd->tp", qorig, centers[pp], precision=_PREC)
            scores = scores - qc[:, :, None]
        else:
            # ‖q−y‖² ≈ ‖res_q − decode‖² = Σ_j (‖res_j−cb‖²) ; lut dropped the
            # constant ‖res_j‖² per subspace → add ‖res_q‖² back
            rq2 = jnp.sum(res * res, axis=(2, 3))        # [t, p]
            scores = scores + rq2[:, :, None]

        invalid = invalid_mask(ids, filter_words)
        scores = jnp.where(invalid, jnp.inf, scores)
        # filtered-out candidates must surface as id −1, never their real id
        ids = jnp.where(invalid, -1, ids)
        flat_s = scores.reshape(query_tile, n_probes * cap)
        flat_i = ids.reshape(query_tile, n_probes * cap)
        v, i = select_k(flat_s, k, select_min=True, input_indices=flat_i)
        # ---- postprocess (ref ivf_pq_search.cuh:453-467)
        if metric == "inner_product":
            v = -v
        elif metric == "euclidean":
            v = jnp.sqrt(jnp.maximum(v, 0.0))
        return v, i

    vals, idx = lax.map(tile, (qt, qo, pt))
    return (
        vals.reshape(n_tiles * query_tile, k)[:q],
        idx.reshape(n_tiles * query_tile, k)[:q],
    )


@traced("ivf_pq.search")
def search(
    params: SearchParams,
    index: Index,
    queries: jax.Array,
    k: int,
    *,
    sample_filter: Optional[Bitset] = None,
    res: Optional[Resources] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Returns (distances [q, k], indices [q, k]). Distances are PQ
    approximations — pipe through ``neighbors.refine`` for exact re-ranking
    (ref: ivf_pq search + refine pattern, cagra_build.cuh:146-196)."""
    res = ensure(res)
    queries = jnp.asarray(queries, jnp.float32)
    if queries.ndim != 2 or queries.shape[1] != index.dim:
        raise ValueError(f"queries shape {queries.shape} vs index dim {index.dim}")
    n_probes = min(params.n_probes, index.n_lists)
    if k > n_probes * index.list_cap:
        raise ValueError(
            f"k={k} exceeds candidate pool n_probes*list_cap="
            f"{n_probes}*{index.list_cap}; raise n_probes"
        )
    canonical = DISTANCE_TYPES[index.metric]
    lut_dtype = jnp.bfloat16 if params.lut_dtype == "bfloat16" else jnp.float32
    acc_dtype = (
        jnp.bfloat16 if params.internal_distance_dtype == "bfloat16" else jnp.float32
    )
    # per-query workspace: probe gather of codes + LUT + scores
    per_q = n_probes * (
        index.list_cap * index.pq_dim                # codes uint8
        + 4 * index.pq_dim * index.pq_n_centers      # LUT f32
        + 8 * index.list_cap                         # scores + ids
    )
    query_tile = int(min(max(queries.shape[0], 1), max(1, res.workspace_rows(per_q, cap=256))))
    fw = sample_filter.words if sample_filter is not None else None
    return _search_jit(
        queries,
        index.centers,
        index.centers_rot,
        index.rotation,
        index.codebook,
        index.list_codes,
        index.list_index,
        fw,
        n_probes,
        int(k),
        canonical,
        index.codebook_kind,
        query_tile,
        lut_dtype,
        acc_dtype,
    )


def _pack_bits(codes: np.ndarray, pq_bits: int) -> np.ndarray:
    """Pack uint8 codes (< 2**pq_bits) into a dense bitstream per row for
    serialization parity with the reference's compressed storage."""
    bits = np.unpackbits(codes[..., None], axis=-1, count=8, bitorder="little")
    bits = bits[..., :pq_bits].reshape(codes.shape[0], -1)
    return np.packbits(bits, axis=-1, bitorder="little")


def _unpack_bits(packed: np.ndarray, pq_dim: int, pq_bits: int) -> np.ndarray:
    bits = np.unpackbits(packed, axis=-1, bitorder="little")[:, : pq_dim * pq_bits]
    bits = bits.reshape(packed.shape[0], pq_dim, pq_bits)
    full = np.zeros((packed.shape[0], pq_dim, 8), np.uint8)
    full[..., :pq_bits] = bits
    return np.packbits(full, axis=-1, bitorder="little")[..., 0]


def save(filename: str, index: Index) -> None:
    lc = np.asarray(index.list_codes)
    L, cap, pq_dim = lc.shape
    packed = _pack_bits(lc.reshape(L * cap, pq_dim), index.pq_bits)
    ser.save_tree(
        filename,
        "ivf_pq",
        _SERIALIZATION_VERSION,
        {
            "metric": index.metric,
            "codebook_kind": index.codebook_kind,
            "pq_bits": index.pq_bits,
            "pq_dim": pq_dim,
            "list_cap": cap,
        },
        {
            "centers": index.centers,
            "centers_rot": index.centers_rot,
            "rotation": index.rotation,
            "codebook": index.codebook,
            "list_codes_packed": packed,
            "list_index": index.list_index,
            "list_sizes": index.list_sizes,
        },
    )


def load(filename: str) -> Index:
    scalars, arrays = ser.load_tree(filename, "ivf_pq", _SERIALIZATION_VERSION)
    L = arrays["centers"].shape[0]
    cap, pq_dim = scalars["list_cap"], scalars["pq_dim"]
    codes = _unpack_bits(arrays["list_codes_packed"], pq_dim, scalars["pq_bits"])
    return Index(
        scalars["metric"],
        scalars["codebook_kind"],
        scalars["pq_bits"],
        jnp.asarray(arrays["centers"]),
        jnp.asarray(arrays["centers_rot"]),
        jnp.asarray(arrays["rotation"]),
        jnp.asarray(arrays["codebook"]),
        jnp.asarray(codes.reshape(L, cap, pq_dim)),
        jnp.asarray(arrays["list_index"]),
        jnp.asarray(arrays["list_sizes"]),
    )
