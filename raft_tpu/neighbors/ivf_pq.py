"""IVF-PQ: inverted-file index with product-quantized residual vectors.

Reference surface: build/extend/search/serialize with hierarchical balanced
k-means coarse quantizer, optional random rotation, per-subspace or
per-cluster PQ codebooks (ref: cpp/include/raft/neighbors/ivf_pq_types.hpp:47-172
— ``pq_bits`` 4..8, ``pq_dim``, ``codebook_gen`` :42, ``n_probes``,
``lut_dtype``; build pipeline neighbors/detail/ivf_pq_build.cuh:1681-1836:
trainset subsample → kmeans_balanced::fit → predict → make_rotation_matrix:122
→ set_centers:317 → train_per_subset:395 / train_per_cluster:473 →
extend:1501; search pipeline neighbors/detail/ivf_pq_search.cuh:588-718:
select_clusters = GEMM + select_k, then per-probe LUT build +
compute_similarity scan + select_k; Python ref: pylibraft ivf_pq.pyx:312-748).

TPU re-design
-------------
* **Storage**: the reference packs pq_bits-wide codes into interleaved bit
  fields scanned warp-style (ivf_pq_build.cuh process_and_fill_codes:1323).
  On TPU the natural unit is the int8 VPU lane: codes live *unpacked* one
  byte per sub-quantizer in a dense padded tensor
  ``list_codes [n_lists, cap, pq_dim] uint8`` — every probe scan is then a
  static-shape gather + vectorized LUT lookup, no bit twiddling on the
  critical path. (pq_bits still bounds the codebook size 2**pq_bits, and a
  packed serialization keeps files small for pq_bits<8.)
* **Decoded-reconstruction scoring**: the reference's per-(query,probe) LUT
  gather (compute_similarity's shmem scan,
  ivf_pq_compute_similarity-inl.cuh) is a scalar-gather pattern the TPU
  cannot vectorize — measured 12.4 s of a 12.7 s search on a v5e chip for
  1k queries. Instead the index stores, next to the codes, the *decoded*
  reconstruction of every vector in rotated space
  (``list_data [L, cap, rot_dim]``, bf16 by default):
  ``y = center_rot + concat_j codebook[j, code_j]``. Scoring is then
  ``‖q_rot − y‖² = ‖y‖² − 2·q_rot·y + ‖q_rot‖²`` — one MXU matmul per
  query tile over gathered probe rows, identical scores to the LUT
  formulation (Σ_j ‖res_j − cb_j‖² telescopes to ‖res − dec‖²). Memory:
  2·rot_dim bytes/vector (bf16) vs the reference's fp16 LUT path — the
  same accuracy class, with codes kept packed for serialization parity.
* **Rotation**: random orthonormal (QR of gaussian), padding dim up to
  rot_dim = pq_dim*pq_len like make_rotation_matrix (ivf_pq_build.cuh:122).
* **Codebook training**: per-subspace Lloyd iterations vmapped over all
  pq_dim subspaces at once — one compiled kernel trains every codebook
  (reference loops subspaces on separate streams, train_per_subset:395).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, replace as dc_replace
from typing import ClassVar, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from raft_tpu.cluster import kmeans_balanced
from raft_tpu.core import serialize as ser
from raft_tpu.core import validation
from raft_tpu.core.bitset import Bitset
from raft_tpu.core.resources import Resources, ensure
from raft_tpu.distance.pairwise import DISTANCE_TYPES, _PREC
from raft_tpu.neighbors._common import (
    allocate_append_slots,
    centroid_group_inverse,
    compute_list_layout,
    subsample_trainset,
    coarse_select,
    default_max_cap,
    invalid_mask,
    invalid_mask_rows,
    merge_split_lists,
    pallas_scan_enabled,
    run_probe_major,
    run_query_tiled,
    select_scan_strategy,
    unpack_lists,
)
from raft_tpu.kernels import stamp_kernel_path as _stamp_kernel_path
from raft_tpu.kernels.toolkit import int8_scored_ip, quantize_queries_i8
from raft_tpu.ops.matrix import select_k
from raft_tpu.store.paged import gather_lists as _gather_lists
from raft_tpu.core.trace import traced
from raft_tpu.core.logger import logger as _log

_SERIALIZATION_VERSION = 1

CODEBOOK_PER_SUBSPACE = "per_subspace"
CODEBOOK_PER_CLUSTER = "per_cluster"

#: scan-cache storage dtypes (the lut_dtype accuracy ladder analog,
#: ref ivf_pq_types.hpp:139-172): bf16 = HBM-halving default, f32 = exact
#: decode, int8 = memory-lean quantized cache (rot_dim bytes/vector).
_DECODED_DTYPES = {
    "bfloat16": jnp.bfloat16,
    "float32": jnp.float32,
    "int8": jnp.int8,
}

#: fraction of device memory the scan cache may claim before "auto"
#: downgrades bf16 → int8 (leaves room for queries, probe gathers, and the
#: decode chunk)
_AUTO_HBM_FRACTION = 0.55



def _device_memory_budget() -> tuple[int, bool]:
    """Bytes of accelerator memory to plan against, and whether that number
    is a *real* reported limit (TPU/GPU ``memory_stats`` or the
    ``RAFT_TPU_HBM_BYTES`` override) as opposed to the 16 GiB (one v5e
    chip) assumption used when the backend reports nothing (e.g. CPU)."""
    from raft_tpu.core import env as _env

    hbm = _env.env_int("RAFT_TPU_HBM_BYTES")
    if hbm is not None:
        return hbm, True
    try:
        stats = jax.local_devices()[0].memory_stats()
        if stats and stats.get("bytes_limit"):
            return int(stats["bytes_limit"]), True
    except Exception:
        pass
    return 16 << 30, False

#: HBM budget for the f32 intermediates of one decode chunk (the decode is
#: chunked over lists so huge indexes — the int8 mode's reason to exist —
#: never materialize a full f32 copy of themselves).
_DECODE_CHUNK_BYTES = 256 << 20


@dataclass
class IndexParams:
    """(ref: ivf_pq_types.hpp:47-139 index_params)"""

    n_lists: int = 1024
    metric: str = "sqeuclidean"
    kmeans_n_iters: int = 20
    kmeans_trainset_fraction: float = 0.5
    pq_bits: int = 8          # 4..8 (ref :55)
    pq_dim: int = 0           # 0 → auto: dim/4 rounded up to 8 (ref :64)
    codebook_kind: str = CODEBOOK_PER_SUBSPACE  # ref codebook_gen :42
    force_random_rotation: bool = False
    add_data_on_build: bool = True
    conservative_memory_allocation: bool = False
    seed: int = 0
    # dtype of the decoded scan cache (the fp16-LUT accuracy-class analog,
    # ref search_params::lut_dtype ivf_pq_types.hpp:139-172): "bfloat16"
    # halves scan HBM traffic; "float32" is exact decode; "int8" is the
    # memory-lean quantized cache (rot_dim B/vector). "auto" (default)
    # picks bf16 unless the projected index footprint exceeds the device
    # memory budget (_device_memory_budget), then drops to int8 — so
    # DEEP-100M-shape builds fit a 16 GB chip without manual tuning.
    decoded_dtype: str = "auto"


@dataclass
class SearchParams:
    """(ref: ivf_pq_types.hpp:139-172 search_params)

    ``strategy`` selects the scan schedule (the analog of the reference's
    compute_similarity kernel-variant choice):

    - ``query_major`` — per query-tile, gather the rows of its probed
      lists and score them (one batched MXU contraction). HBM reads each
      list once per *probing query*.
    - ``probe_major`` — invert the (query, probe) relation: sort pairs by
      list, bucket each list's probing queries, and scan list-by-list, so
      each list's rows stream from HBM once per *bucket* (~once per
      batch) instead of once per query — the SURVEY §7 "probe-major
      batching" answer to data-dependent gathers. Per-list top-k partials
      are scattered back and merged per query.
    - ``auto`` — probe_major when the batch reuses lists heavily
      (q·n_probes ≫ n_lists and q is large), else query_major.
    """

    n_probes: int = 20
    lut_dtype: str = "float32"                 # float32 | bfloat16 (ref fp8/half analog)
    internal_distance_dtype: str = "float32"   # float32 | bfloat16
    strategy: str = "auto"                     # auto | query_major | probe_major


@dataclass(frozen=True)
class EffortSpec:
    """Typed search-effort knobs for IVF-PQ (see ivf_flat.EffortSpec for
    the contract): ``n_probes`` + ``lut_dtype`` actuate online through
    SearchParams; ``refine_ratio`` is the offline sweep's exact-refine
    multiplier.  Knob values select among warmed executables — they never
    ride as static jit arguments."""

    n_probes: int = 20
    refine_ratio: int = 1
    lut_dtype: str = "float32"

    backend: ClassVar[str] = "ivf_pq"

    @classmethod
    def from_params(cls, params: Optional[SearchParams] = None,
                    **extra) -> "EffortSpec":
        base = params if params is not None else SearchParams()
        return cls(n_probes=int(base.n_probes),
                   refine_ratio=int(extra.get("refine_ratio", 1)),
                   lut_dtype=str(base.lut_dtype))

    def apply(self, params: Optional[SearchParams] = None) -> SearchParams:
        base = params if params is not None else SearchParams()
        return dc_replace(base, n_probes=int(self.n_probes),
                          lut_dtype=str(self.lut_dtype))

    def degraded(self, level: int) -> "EffortSpec":
        """Step down ``level`` notches: halve ``n_probes`` per level
        (floor 1), drop the LUT to bf16 at level ≥ 2 (the cheapest-scan
        analog of disabling refine), drop refine."""
        if level <= 0:
            return self
        return EffortSpec(
            n_probes=max(1, int(self.n_probes) >> int(level)),
            refine_ratio=1,
            lut_dtype="bfloat16" if level >= 2 else str(self.lut_dtype),
        )

    def knobs(self):
        return {"n_probes": int(self.n_probes),
                "refine_ratio": int(self.refine_ratio),
                "lut_dtype": str(self.lut_dtype)}


def _auto_pq_dim(dim: int) -> int:
    # ref ivf_pq_types.hpp:123 from_dataset: dim/4 rounded, here rounded up to
    # a multiple of 8 so rot_dim tiles the VPU sublane.
    v = max(1, dim // 4)
    return (v + 7) // 8 * 8 if v > 8 else v


class Index:
    """IVF-PQ index with padded per-list code storage + decoded scan cache.

    Fields:
      centers      [L, dim]  f32        — coarse centroids (original space)
      centers_rot  [L, rot_dim] f32     — rotated centroids
      rotation     [rot_dim, dim] f32   — orthonormal rows
      codebook     per_subspace: [pq_dim, 2**pq_bits, pq_len] f32
                   per_cluster:  [L, 2**pq_bits, pq_len] f32
      list_codes   [L, cap, pq_dim] uint8 — device-resident (streamed
                   assemble + O(appended) fast-extend scatters); not on
                   the scan path but counted in the HBM budget (the
                   "+ pq_dim" term of the auto-dtype projection)
      list_data    [L, cap, rot_dim] bf16/f32 — decoded reconstructions
                   (center_rot + codebook decode), the search scan target
      list_y2      [L, cap] f32 — ‖reconstruction‖² (from the stored dtype)
      list_index   [L, cap] int32 (-1 past size)
      list_sizes   [L] int32
    """

    def __init__(
        self, metric, codebook_kind, pq_bits, centers, centers_rot, rotation,
        codebook, list_codes, list_index, list_sizes, list_data, list_y2,
        scan_scale: float = 1.0,
        headroom: bool = True,
    ):
        self.metric = metric
        self.codebook_kind = codebook_kind
        self.pq_bits = pq_bits
        self.centers = centers
        self.centers_rot = centers_rot
        self.rotation = rotation
        self.codebook = codebook
        self.list_codes = list_codes
        self.list_index = list_index
        self.list_sizes = list_sizes
        self.list_data = list_data
        self.list_y2 = list_y2
        # dequantization scale of an int8 scan cache (1.0 for float caches)
        self.scan_scale = scan_scale
        # list growth headroom policy (False under
        # conservative_memory_allocation; serialized like the reference's
        # conservative_memory_allocation flag, ivf_pq_serialize.cuh:64)
        self.headroom = headroom
        # cached centroid→group map for repeated fast appends (derived)
        self._group_inverse = None

    @property
    def n_lists(self) -> int:
        return self.centers.shape[0]

    @property
    def dim(self) -> int:
        return self.centers.shape[1]

    @property
    def rot_dim(self) -> int:
        return self.rotation.shape[0]

    @property
    def pq_dim(self) -> int:
        return self.list_codes.shape[2]

    @property
    def pq_len(self) -> int:
        return self.rot_dim // self.pq_dim

    @property
    def pq_n_centers(self) -> int:
        return 1 << self.pq_bits

    @property
    def list_cap(self) -> int:
        return self.list_codes.shape[1]

    @property
    def size(self) -> int:
        return int(jnp.sum(self.list_sizes))


def make_rotation_matrix(
    key: jax.Array, rot_dim: int, dim: int, force_random: bool
) -> jax.Array:
    """Orthonormal [rot_dim, dim]: random QR when forced or when padding is
    needed, else identity (ref: ivf_pq_build.cuh make_rotation_matrix:122)."""
    if not force_random and rot_dim == dim:
        return jnp.eye(dim, dtype=jnp.float32)
    if not force_random:
        # norm-preserving zero-padded identity
        return jnp.eye(rot_dim, dim, dtype=jnp.float32)
    if rot_dim <= dim:
        g = jax.random.normal(key, (dim, rot_dim), jnp.float32)
        q, _ = jnp.linalg.qr(g)  # orthonormal columns
        return q.T
    # rot_dim > dim: orthonormal columns of [rot_dim, dim]
    g = jax.random.normal(key, (rot_dim, dim), jnp.float32)
    q, _ = jnp.linalg.qr(g)
    return q


#: row-chunk budget for one Lloyd distance block across all S subspace
#: problems: [S, chunk, n_centers] f32 stays ≤ this many bytes. Without the
#: chunking the vmapped iteration materializes [S, n, 256] f32 — 24 GB at
#: the 1M build's 500k trainset (measured, benchmarks/rss_trace.py) and
#: ~98 GB at the 10M build's 2M trainset, past any HBM.
_LLOYD_BLOCK_BYTES = 512 * 1024 * 1024


@functools.partial(jax.jit, static_argnames=("n_centers", "n_iters"))
def _train_codebooks_lloyd(key, subvecs, n_centers: int, n_iters: int,
                           weights=None):
    """Batched Lloyd over S independent subspace problems.

    subvecs: [S, n, pq_len], weights: optional [S, n] (0 ⇒ row is padding and
    contributes nothing). Returns [S, n_centers, pq_len]. vmapped so all
    pq_dim (or n_lists) codebooks train in one XLA program
    (ref: train_per_subset ivf_pq_build.cuh:395 / train_per_cluster :473,
    which run a kmeans per subspace on residual slices).

    The assignment step is chunked over trainset rows (lax.scan over
    [chunk]-row blocks accumulating weighted sums/counts), bounding the
    distance block at ``_LLOYD_BLOCK_BYTES`` regardless of trainset size —
    DEEP-scale builds train their codebooks without an O(S·n·k) tensor."""
    S, n, L = subvecs.shape
    if weights is None:
        weights = jnp.ones((S, n), subvecs.dtype)

    # weight-proportional seed draw, over the UNPADDED rows so the result
    # is bit-invariant to the chunk size chosen below
    def draw(key, x, w):
        idx = jax.random.choice(
            key, n, shape=(n_centers,), replace=n < n_centers,
            p=w / jnp.maximum(jnp.sum(w), 1e-12),
        )
        return x[idx]

    keys = jax.random.split(key, S)
    centers_init = jax.vmap(draw)(keys, subvecs, weights)

    # pad rows to a chunk multiple with weight-0 rows (weightless rows
    # cannot influence sums/counts)
    chunk = int(np.clip(_LLOYD_BLOCK_BYTES // (4 * S * n_centers), 256, n))
    n_pad = (-n) % chunk
    if n_pad:
        subvecs = jnp.pad(subvecs, ((0, 0), (0, n_pad), (0, 0)))
        weights = jnp.pad(weights, ((0, 0), (0, n_pad)))
    n_chunks = (n + n_pad) // chunk

    def one(centers0, x, w):
        xc = x.reshape(n_chunks, chunk, L)
        wc = w.reshape(n_chunks, chunk)

        def body(centers, _):
            c2 = jnp.sum(centers * centers, 1)[None, :]

            def block(carry, xw):
                sums, counts = carry
                xb, wb = xw
                d2 = c2 - 2.0 * jnp.matmul(xb, centers.T, precision=_PREC)
                labels = jnp.argmin(d2, axis=1)
                sums = sums + jax.ops.segment_sum(
                    xb * wb[:, None], labels, num_segments=n_centers
                )
                counts = counts + jax.ops.segment_sum(wb, labels, n_centers)
                return (sums, counts), None

            (sums, counts), _ = lax.scan(
                block,
                (jnp.zeros((n_centers, L), x.dtype), jnp.zeros((n_centers,), x.dtype)),
                (xc, wc),
            )
            new = jnp.where(counts[:, None] > 0, sums / jnp.maximum(counts[:, None], 1e-12), centers)
            return new, None

        centers, _ = lax.scan(body, centers0, None, length=n_iters)
        return centers

    return jax.vmap(one)(centers_init, subvecs, weights)


@functools.partial(jax.jit, static_argnames=("codebook_kind",))
def _encode(rotation, centers, centers_rot, codebook, x, labels, codebook_kind):
    """Residual-encode rows → uint8 codes [n, pq_dim]
    (ref: process_and_fill_codes ivf_pq_build.cuh:1323)."""
    rot_dim = rotation.shape[0]
    res = x - centers[labels]                       # [n, dim]
    res_rot = jnp.matmul(res, rotation.T, precision=_PREC)  # [n, rot_dim]
    if codebook_kind == CODEBOOK_PER_SUBSPACE:
        pq_dim, k, pq_len = codebook.shape
        sub = res_rot.reshape(-1, pq_dim, pq_len)   # [n, j, l]
        # ||sub - cb||² argmin over k: −2·ip + ||cb||²  (‖sub‖² is rank-neutral)
        ip = jnp.einsum("njl,jkl->njk", sub, codebook, precision=_PREC)
        cb2 = jnp.sum(codebook * codebook, axis=2)  # [j, k]
        codes = jnp.argmin(cb2[None] - 2.0 * ip, axis=2)
    else:
        n_lists, k, pq_len = codebook.shape
        pq_dim = rot_dim // pq_len
        sub = res_rot.reshape(-1, pq_dim, pq_len)
        cb = codebook[labels]                       # [n, k, l]
        ip = jnp.einsum("njl,nkl->njk", sub, cb, precision=_PREC)
        cb2 = jnp.sum(cb * cb, axis=2)              # [n, k]
        codes = jnp.argmin(cb2[:, None, :] - 2.0 * ip, axis=2)
    return codes.astype(jnp.uint8)


def _decode_lists(
    codebook: np.ndarray,
    codebook_kind: str,
    centers_rot: np.ndarray,
    list_codes: np.ndarray,
    list_index: np.ndarray,
    dtype,
) -> Tuple[jax.Array, jax.Array, float]:
    """Host-side decode of packed lists → (list_data [L,cap,rot] dtype,
    list_y2 [L,cap] f32, scan_scale). y = center_rot + concat_j
    codebook[j, code_j]; padding slots are zeroed. y2 is computed from the
    *stored* (rounded/quantized) values so scores match what the scan kernel
    sees exactly.

    ``dtype == int8`` selects the memory-lean scan cache (the TPU analog of
    the reference's fp8 LUT accuracy class, ivf_pq_types.hpp lut_dtype):
    reconstructions are symmetrically quantized with one global scale
    (returned; 1.0 for float dtypes) and the scan runs on the MXU's native
    int8 path — rot_dim bytes/vector, so DEEP-100M-shape datasets fit HBM.

    The decode runs on device: only the codes (pq_dim bytes/vector) and the
    small codebook/centroid tables cross host→device; the full decoded
    cache (rot_dim·itemsize bytes/vector) is produced where it lives. It is
    jitted and chunked over the list axis so the f32 decode intermediates
    never exceed a fixed HBM budget — the int8 mode exists precisely for
    indexes whose full f32 decode would not fit."""
    L, cap, pq_dim = list_codes.shape
    codes = jnp.asarray(list_codes)
    cb = jnp.asarray(codebook)
    cr = jnp.asarray(centers_rot)
    valid = jnp.asarray(np.asarray(list_index) >= 0)
    rot_dim = cr.shape[1]
    per_list = max(1, cap * rot_dim * 4)
    chunk = int(np.clip(_DECODE_CHUNK_BYTES // per_list, 1, max(L, 1)))

    per_cluster = codebook_kind == CODEBOOK_PER_CLUSTER

    def chunks(extra=None):
        for s in range(0, L, chunk):
            cb_c = cb[s : s + chunk] if per_cluster else cb
            yield (
                cb_c, cr[s : s + chunk], codes[s : s + chunk],
                valid[s : s + chunk],
            ) + (() if extra is None else (extra,))

    def assemble(part_iter, out_dtype):
        """Write decoded chunks into preallocated (donated) buffers so peak
        HBM is one final cache + one chunk, never 2× (the concatenate of a
        parts list doubles residency exactly on the just-fits indexes the
        int8 mode exists for)."""
        data = jnp.zeros((L, cap, rot_dim), out_dtype)
        y2 = jnp.zeros((L, cap), jnp.float32)
        s = 0
        for part_d, part_y2 in part_iter:
            data = _write_rows(data, part_d, s)
            y2 = _write_rows(y2, part_y2, s)
            s += part_d.shape[0]
        return data, y2

    if dtype == jnp.int8:
        m = 0.0
        for args in chunks():
            m = max(m, float(_decode_chunk_absmax(*args, per_cluster)))
        scale = max(m, 1e-12) / 127.0
        data, y2 = assemble(
            (_decode_chunk_int8(*args, per_cluster) for args in chunks(scale)),
            jnp.int8,
        )
        return data, y2, scale
    name = "bfloat16" if dtype == jnp.bfloat16 else "float32"
    data, y2 = assemble(
        (_decode_chunk_float(*args, per_cluster, name) for args in chunks()),
        dtype,
    )
    return data, y2, 1.0


@functools.partial(jax.jit, donate_argnums=(0,))
def _write_rows(buf, part, start):
    """Donated in-place row-block write (start is traced → one compiled
    program regardless of chunk count)."""
    return lax.dynamic_update_slice_in_dim(buf, part, start, axis=0)


def _decode_y(cb, cr, codes, valid, per_cluster: bool):
    """Decoded f32 reconstructions of one list chunk (traced helper)."""
    idx = codes.astype(jnp.int32)[..., None, None]
    if per_cluster:
        dec = jnp.take_along_axis(cb[:, None, None], idx, axis=3)[..., 0, :]
    else:
        dec = jnp.take_along_axis(cb[None, None], idx, axis=3)[..., 0, :]
    y = dec.reshape(codes.shape[0], codes.shape[1], -1) + cr[:, None, :]
    return jnp.where(valid[..., None], y, 0.0)


@functools.partial(jax.jit, static_argnames=("per_cluster",))
def _decode_chunk_absmax(cb, cr, codes, valid, per_cluster: bool):
    return jnp.max(jnp.abs(_decode_y(cb, cr, codes, valid, per_cluster)))


@functools.partial(jax.jit, static_argnames=("per_cluster",))
def _decode_chunk_int8(cb, cr, codes, valid, scale, per_cluster: bool):
    y = _decode_y(cb, cr, codes, valid, per_cluster)
    y_int = jnp.clip(jnp.round(y / scale), -127, 127).astype(jnp.int8)
    y_f32 = y_int.astype(jnp.float32) * scale
    return y_int, jnp.sum(y_f32 * y_f32, axis=-1)


@functools.partial(jax.jit, static_argnames=("per_cluster", "dtype_name"))
def _decode_chunk_float(cb, cr, codes, valid, per_cluster: bool, dtype_name: str):
    y = _decode_y(cb, cr, codes, valid, per_cluster)
    y_stored = y.astype(_DECODED_DTYPES[dtype_name])
    y_f32 = y_stored.astype(jnp.float32)
    return y_stored, jnp.sum(y_f32 * y_f32, axis=-1)


def _rows_y(cb, cr, codes, labels, per_cluster: bool):
    """f32 reconstructions of a row chunk: y = cr[label] + decode(codes).
    Shared by the streamed assemble, the fast-append decode, and absmax
    scans (traced helper; OOB labels clamp-gather — callers mask/drop)."""
    codes_i = codes.astype(jnp.int32)
    if per_cluster:
        b = cb[labels]  # [n, K, l]
        dec = jnp.take_along_axis(b, codes_i[:, :, None], axis=1)
    else:
        dec = jnp.take_along_axis(
            cb[None], codes_i[:, :, None, None], axis=2
        )[:, :, 0, :]
    return dec.reshape(codes.shape[0], -1) + cr[labels]


@functools.partial(jax.jit, static_argnames=("per_cluster",))
def _rows_absmax(cb, cr, codes, labels, valid, per_cluster: bool):
    y = _rows_y(cb, cr, codes, labels, per_cluster)
    return jnp.max(jnp.where(valid[:, None], jnp.abs(y), 0.0))


@functools.partial(
    jax.jit,
    donate_argnums=(0, 1, 2, 3),
    static_argnames=("per_cluster",),
)
def _scatter_chunk(
    l_codes, l_index, l_data, l_y2,  # donated [L, cap, ...] buffers
    cb, cr, codes, ids, lst, slot, scale,
    per_cluster: bool,
):
    """Decode one row chunk and scatter it into the padded device buffers.

    Padding rows in the (fixed-size) last chunk carry lst == n_lists —
    out of bounds, so ``mode="drop"`` discards them; gather clamping on the
    decode side is harmless for dropped rows. Donation keeps peak HBM at
    one index + one chunk (the streamed analog of the reference's batched
    device-side extend, ivf_pq_build.cuh:1374-1460)."""
    y = _rows_y(cb, cr, codes, lst, per_cluster)
    if l_data.dtype == jnp.int8:
        stored = jnp.clip(jnp.round(y / scale), -127, 127).astype(jnp.int8)
        y_f32 = stored.astype(jnp.float32) * scale
    else:
        stored = y.astype(l_data.dtype)
        y_f32 = stored.astype(jnp.float32)
    y2 = jnp.sum(y_f32 * y_f32, axis=-1)
    return (
        l_codes.at[lst, slot].set(codes, mode="drop"),
        l_index.at[lst, slot].set(ids, mode="drop"),
        l_data.at[lst, slot].set(stored, mode="drop"),
        l_y2.at[lst, slot].set(y2, mode="drop"),
    )


def _assemble_lists(
    codes: np.ndarray,
    ids: np.ndarray,
    labels: np.ndarray,
    n_lists: int,
    codebook: np.ndarray,
    codebook_kind: str,
    centers_rot: np.ndarray,
    dtype,
    headroom: bool = True,
    max_cap="default",
):
    """Streamed device-side list assembly: compute the (list, slot) layout
    host-side (metadata only — O(n) ints, no padded payload copies), then
    decode + scatter row chunks into preallocated, donated device buffers.

    Host residency is bounded by the compressed stream (codes pq_dim B/row
    + labels/ids 8 B/row); device residency by the final index + one
    decode chunk. This replaces the old pack-then-decode path whose padded
    host arrays and full-index transfers could not survive 10⁸ rows
    (ref: batched extend ivf_pq_build.cuh:1374-1501). Oversized lists are
    split with duplicated centroids (skew-bounded cap;
    _common.split_oversized_lists); returns center_map for the caller to
    expand centers/codebooks."""
    n, pq_dim = codes.shape
    # max_cap=None disables skew splitting — the sharded build's
    # shard-major relabel needs list ids to stay stable (serve.build)
    lst, slot, sizes, center_map, cap = compute_list_layout(
        labels, n_lists,
        max_cap=default_max_cap(n, n_lists) if max_cap == "default" else max_cap,
        headroom=headroom,
    )
    L = len(center_map)
    centers_rot = np.asarray(centers_rot)[center_map]
    if codebook_kind == CODEBOOK_PER_CLUSTER:
        codebook = np.asarray(codebook)[center_map]
    per_cluster = codebook_kind == CODEBOOK_PER_CLUSTER
    rot_dim = centers_rot.shape[1]
    cb = jnp.asarray(codebook)
    cr = jnp.asarray(centers_rot)

    # fixed chunk size → every chunk reuses one compiled scatter program;
    # bound the f32 decode intermediates (y, dec, stored) + the per-cluster
    # codebook gather to the decode HBM budget
    per_row = rot_dim * 4 * 4
    if per_cluster:
        per_row += codebook.shape[1] * codebook.shape[2] * 4
    chunk = int(np.clip(_DECODE_CHUNK_BYTES // max(per_row, 1), 8, max(n, 8)))

    codes = np.ascontiguousarray(np.asarray(codes, np.uint8))
    ids = np.asarray(ids, np.int32)
    lst32 = np.asarray(lst, np.int32)
    slot32 = np.asarray(slot, np.int32)

    def chunk_codes(s):
        e = min(s + chunk, n)
        pad = chunk - (e - s)
        c = codes[s:e]
        l = lst32[s:e]
        if pad:
            c = np.concatenate([c, np.zeros((pad, pq_dim), np.uint8)])
            # padding rows point past the last list → scatter mode="drop"
            l = np.concatenate([l, np.full(pad, L, np.int32)])
        return jnp.asarray(c), jnp.asarray(l)

    def chunk_meta(s):
        e = min(s + chunk, n)
        pad = chunk - (e - s)
        i = ids[s:e]
        sl = slot32[s:e]
        if pad:
            i = np.concatenate([i, np.zeros(pad, np.int32)])
            sl = np.concatenate([sl, np.zeros(pad, np.int32)])
        return jnp.asarray(i), jnp.asarray(sl)

    scale = 1.0
    if dtype == jnp.int8:
        # scale pre-pass streams only codes+list ids (ids/slots are not
        # consumed until the scatter pass — keep them off the wire here)
        m = 0.0
        for s in range(0, max(n, 1), chunk):
            c, l = chunk_codes(s)
            m = max(m, float(_rows_absmax(cb, cr, c, l, l < L, per_cluster)))
        scale = max(m, 1e-12) / 127.0

    l_codes = jnp.zeros((L, cap, pq_dim), jnp.uint8)
    l_index = jnp.full((L, cap), -1, jnp.int32)
    l_data = jnp.zeros((L, cap, rot_dim), dtype)
    l_y2 = jnp.zeros((L, cap), jnp.float32)
    for s in range(0, n, chunk):
        c, l = chunk_codes(s)
        i, sl = chunk_meta(s)
        l_codes, l_index, l_data, l_y2 = _scatter_chunk(
            l_codes, l_index, l_data, l_y2,
            cb, cr, c, i, l, sl, jnp.float32(scale), per_cluster,
        )
    return (
        l_codes,
        l_index,
        jnp.asarray(sizes),
        l_data,
        l_y2,
        center_map,
        scale,
    )


@traced("ivf_pq.build")
def build(
    params: IndexParams,
    dataset: jax.Array,
    *,
    res: Optional[Resources] = None,
) -> Index:
    """(ref: build pipeline detail/ivf_pq_build.cuh:1681-1836)

    Examples
    --------
    >>> import numpy as np
    >>> from raft_tpu.neighbors import ivf_pq
    >>> x = np.random.default_rng(0).random((2000, 32), dtype=np.float32)
    >>> idx = ivf_pq.build(
    ...     ivf_pq.IndexParams(n_lists=16, pq_dim=8, kmeans_n_iters=3), x
    ... )
    >>> d, i = ivf_pq.search(ivf_pq.SearchParams(n_probes=16), idx, x[:4], 5)
    >>> i.shape
    (4, 5)
    >>> bool((np.asarray(i) >= 0).all())
    True

    ``dataset`` may be a host numpy array (including a memmap): it is never
    uploaded wholesale — the trainset subsample and the per-tile
    predict+encode stream are the only device transfers, so datasets far
    larger than HBM build on one chip (the out-of-core intent of the
    reference's deep-100M/wiki-all configs, docs/source/wiki_all_dataset.md)."""
    res = ensure(res)
    if not isinstance(dataset, np.ndarray):
        dataset = jnp.asarray(dataset)
    n, dim = dataset.shape
    canonical = DISTANCE_TYPES[params.metric]
    if canonical not in ("sqeuclidean", "euclidean", "inner_product"):
        raise ValueError(f"ivf_pq supports L2/IP metrics, got {params.metric}")
    if not (4 <= params.pq_bits <= 8):
        raise ValueError(f"pq_bits must be in [4, 8], got {params.pq_bits}")

    pq_dim = params.pq_dim or _auto_pq_dim(dim)
    pq_len = max(1, (dim + pq_dim - 1) // pq_dim)
    rot_dim = pq_dim * pq_len

    key = jax.random.PRNGKey(params.seed)
    _, k_rot, k_cb = jax.random.split(key, 3)

    # --- trainset subsample (ref :1706-1766; host-side index draw — see
    # _common.subsample_trainset for the compile-cost rationale)
    n_train = min(n, max(params.n_lists * 2, int(n * params.kmeans_trainset_fraction)))
    if n_train < n:
        trainset = subsample_trainset(dataset, n_train, params.seed).astype(jnp.float32)
    else:
        trainset = dataset.astype(jnp.float32)

    # --- coarse quantizer (ref :1776-1781 → kmeans_balanced hierarchical
    # fit, trained under the index metric so list membership matches the
    # probe ranking at search time — ref ivf_pq_build.cuh:1780 passes
    # index.metric into kmeans_balanced)
    kb_metric = "inner_product" if canonical == "inner_product" else "sqeuclidean"
    kb = kmeans_balanced.KMeansBalancedParams(
        n_iters=params.kmeans_n_iters, metric=kb_metric, seed=params.seed
    )
    centers = kmeans_balanced.fit(kb, trainset, params.n_lists, res=res)
    labels = kmeans_balanced.predict(centers, trainset, metric=kb_metric, res=res)

    # --- rotation + rotated centers (ref make_rotation_matrix:122, set_centers:317)
    rotation = make_rotation_matrix(k_rot, rot_dim, dim, params.force_random_rotation)
    centers_rot = jnp.matmul(centers, rotation.T, precision=_PREC)

    # --- PQ codebooks on rotated residuals (ref train_per_subset:395 / :473)
    resid = jnp.matmul(trainset - centers[labels], rotation.T, precision=_PREC)
    k_pq = 1 << params.pq_bits
    if params.codebook_kind == CODEBOOK_PER_SUBSPACE:
        subvecs = jnp.transpose(resid.reshape(-1, pq_dim, pq_len), (1, 0, 2))
        codebook = _train_codebooks_lloyd(k_cb, subvecs, k_pq, 25)
    elif params.codebook_kind == CODEBOOK_PER_CLUSTER:
        # pool every subspace slice of a cluster's residuals into one training
        # set per cluster, padded to uniform count with weight-0 rows so the
        # padding cannot bias the centroids (one counting-sort scatter, not a
        # python loop over n_lists). The pooled cap is bounded: the [L, cap,
        # pq_len] allocation scales with the most skewed cluster, and a
        # k_pq-center Lloyd gains nothing past a few thousand samples — rows
        # beyond the cap are dropped (uniform within-cluster subsample via
        # the trainset's row order, itself a random draw).
        flat = np.asarray(resid).reshape(-1, pq_len)
        lab2 = np.repeat(np.asarray(labels), pq_dim)
        counts = np.bincount(lab2, minlength=params.n_lists)
        cap = max(int(counts.max()) if counts.size else 1, k_pq)
        cap = min(cap, max(8 * k_pq, 2048))
        order = np.argsort(lab2, kind="stable")
        starts = np.cumsum(counts) - counts
        within = np.arange(len(lab2)) - starts[lab2[order]]
        keep = within < cap
        pooled = np.zeros((params.n_lists, cap, pq_len), np.float32)
        wts = np.zeros((params.n_lists, cap), np.float32)
        pooled[lab2[order][keep], within[keep]] = flat[order][keep]
        wts[lab2[order][keep], within[keep]] = 1.0
        codebook = _train_codebooks_lloyd(
            k_cb, jnp.asarray(pooled), k_pq, 25, jnp.asarray(wts)
        )
    else:
        raise ValueError(f"unknown codebook_kind {params.codebook_kind}")

    decoded_dtype = params.decoded_dtype
    if decoded_dtype == "auto":
        # projected footprint at bf16: padded rows × (scan cache + codes +
        # y2 + ids); 1.35 ≈ split/headroom padding allowance
        est_rows = int(n * 1.35) + 8 * params.n_lists
        bf16_bytes = est_rows * (rot_dim * 2 + pq_dim + 8)
        total, limit_is_real = _device_memory_budget()
        budget = int(_AUTO_HBM_FRACTION * total)
        # int8 is an accuracy-class change: only auto-select it against a
        # REAL reported device limit — the 16 GiB assumption on backends
        # with no bytes_limit (CPU) must not silently degrade recall.
        decoded_dtype = (
            "int8" if bf16_bytes > budget and limit_is_real else "bfloat16"
        )
        if decoded_dtype == "int8":
            _log.warning(
                "ivf_pq.build: projected bf16 cache %.1f GB exceeds %.1f GB "
                "budget — auto-selecting int8 scan cache (accuracy-class "
                "change; pass decoded_dtype explicitly to override)",
                bf16_bytes / 2**30, budget / 2**30,
            )
        elif bf16_bytes > budget:
            _log.warning(
                "ivf_pq.build: projected bf16 cache %.1f GB exceeds the "
                "assumed %.1f GB budget but the backend reports no memory "
                "limit — keeping bfloat16 (set decoded_dtype='int8' or "
                "RAFT_TPU_HBM_BYTES to opt into the quantized cache)",
                bf16_bytes / 2**30, budget / 2**30,
            )
    validation.check_in(decoded_dtype, _DECODED_DTYPES, "decoded_dtype")
    dec_dtype = _DECODED_DTYPES[decoded_dtype]
    index = Index(
        params.metric,
        params.codebook_kind,
        params.pq_bits,
        centers,
        centers_rot,
        rotation,
        codebook,
        np.zeros((params.n_lists, 8, pq_dim), np.uint8),
        jnp.full((params.n_lists, 8), -1, jnp.int32),
        jnp.zeros((params.n_lists,), jnp.int32),
        jnp.zeros((params.n_lists, 8, rot_dim), dec_dtype),
        jnp.zeros((params.n_lists, 8), jnp.float32),
        headroom=not params.conservative_memory_allocation,
    )
    if params.add_data_on_build:
        index = extend(index, dataset, jnp.arange(n, dtype=jnp.int32), res=res)
    _log.debug(
        "ivf_pq.build: n=%d dim=%d n_lists=%d (requested %d) pq_dim=%d "
        "pq_bits=%d cap=%d",
        n, dim, index.n_lists, params.n_lists, pq_dim, params.pq_bits,
        index.list_cap,
    )
    return index


def _decode_rows(index: Index, codes: jax.Array, labels: jax.Array):
    """Decode encoded rows → (stored-dtype rows [n, rot_dim], y2 [n],
    absmax scalar f32) using the index's scan-cache dtype (+frozen int8
    scale). Device-side; the per-row analog of the host _decode_lists pass.
    ``absmax`` is the pre-quantization |y| peak — callers appending into an
    int8 cache must compare it against 127·scan_scale and take the
    repack/rescale path instead when quantizing would clip."""
    y = _rows_y(
        index.codebook, index.centers_rot, codes, labels,
        index.codebook_kind == CODEBOOK_PER_CLUSTER,
    )
    absmax = jnp.max(jnp.abs(y)) if codes.shape[0] else jnp.float32(0.0)
    if index.list_data.dtype == jnp.int8:
        y_int = jnp.clip(
            jnp.round(y / index.scan_scale), -127, 127
        ).astype(jnp.int8)
        y_f32 = y_int.astype(jnp.float32) * index.scan_scale
        return y_int, jnp.sum(y_f32 * y_f32, axis=-1), absmax
    y_stored = y.astype(index.list_data.dtype)
    y_f32 = y_stored.astype(jnp.float32)
    return y_stored, jnp.sum(y_f32 * y_f32, axis=-1), absmax


def _extend_fast(index: Index, codes_np, labels_np, new_ids):
    """In-place append when the target lists still have spare capacity:
    scatter the new rows' codes/ids/decoded-values into the existing padded
    layout (device .at[] scatters for the scan cache — HBM-bandwidth cost,
    not a host re-decode of the whole index; the TPU answer to the
    reference's device-side list growth, ivf_pq_build.cuh:1501).

    Split shards of a skewed list share one centroid; rows whose predicted
    shard is full overflow into a sibling shard with space (they score
    identically at probe selection, see _common.split_oversized_lists).
    Returns None when a centroid group is out of capacity altogether, or
    when an int8 scan cache would clip the new rows at the frozen
    build-time scan_scale (caller falls back to the repack path, which
    recomputes the scale — keeps fast- and slow-path recall identical)."""
    if index._group_inverse is None:
        index._group_inverse = centroid_group_inverse(index.centers)
    alloc = allocate_append_slots(
        index.centers, index.list_sizes, index.list_cap, labels_np,
        group_inverse=index._group_inverse,
    )
    if alloc is None:
        return None
    slab, slots, counts_new = alloc

    lj = jnp.asarray(slab)
    sj = jnp.asarray(slots)
    ids_j = jnp.asarray(np.asarray(new_ids, np.int32))

    dec_rows, y2_rows, absmax = _decode_rows(index, jnp.asarray(codes_np), lj)
    if index.list_data.dtype == jnp.int8 and float(absmax) > 127.0 * float(
        index.scan_scale
    ):
        return None  # would clip at the frozen scale → repack rescales

    # codes stay a device array: the append is an O(appended) .at[] scatter
    # (uint8, same shape discipline as list_data), not a host copy+reupload
    # of the whole code tensor.
    new = Index(
        index.metric, index.codebook_kind, index.pq_bits,
        index.centers, index.centers_rot, index.rotation, index.codebook,
        jnp.asarray(index.list_codes).at[lj, sj].set(jnp.asarray(codes_np)),
        index.list_index.at[lj, sj].set(ids_j),
        index.list_sizes + jnp.asarray(counts_new, jnp.int32),
        index.list_data.at[lj, sj].set(dec_rows),
        index.list_y2.at[lj, sj].set(y2_rows),
        index.scan_scale,
        headroom=index.headroom,
    )
    new._group_inverse = index._group_inverse
    return new


@traced("ivf_pq.extend")
def extend(
    index: Index,
    new_vectors: jax.Array,
    new_indices: Optional[jax.Array] = None,
    *,
    res: Optional[Resources] = None,
) -> Index:
    """Encode + append rows (ref: extend detail/ivf_pq_build.cuh:1501).

    ``new_vectors`` may be any supported dtype (f32/bf16/int8/uint8 — ref
    ivf_pq_build.cuh:1690 dtype templates); rows are cast to f32 one tile
    at a time inside the predict+encode loop, so no full-precision copy of
    the input is ever materialized. A host numpy input (incl. memmap) stays
    host-resident: each tile is uploaded as it is encoded, and only the
    compressed stream (codes pq_dim B/row + labels) is retained — bounded
    host residency for 10⁸-row builds."""
    if getattr(index, "paged", None) is not None:
        raise ValueError(
            "extend() on a paged index is unsupported: paged serving routes "
            "growth through MutableIndex side buffers and re-paginates at "
            "compaction"
        )
    res = ensure(res)
    x = new_vectors if isinstance(new_vectors, np.ndarray) else jnp.asarray(new_vectors)
    canonical = DISTANCE_TYPES[index.metric]
    kb_metric = "inner_product" if canonical == "inner_product" else "sqeuclidean"
    # tile the predict+encode to bound the [tile, rot_dim]+einsum workspace
    n = x.shape[0]
    tile = max(1, res.workspace_rows(4 * (index.rot_dim * 3 + index.pq_dim * index.pq_n_centers), cap=1 << 18))
    codes_parts, label_parts = [], []
    for s in range(0, n, tile):
        xt = jnp.asarray(x[s : s + tile]).astype(jnp.float32)
        lt = kmeans_balanced.predict(index.centers, xt, metric=kb_metric, res=res)
        codes_parts.append(
            np.asarray(
                _encode(
                    index.rotation, index.centers, index.centers_rot, index.codebook,
                    xt, lt, index.codebook_kind,
                )
            )
        )
        label_parts.append(np.asarray(lt))
    codes = np.concatenate(codes_parts) if codes_parts else np.zeros((0, index.pq_dim), np.uint8)
    labels = (
        np.concatenate(label_parts) if label_parts else np.zeros((0,), np.int32)
    )
    return _extend_encoded(index, codes, labels, new_indices)


def _extend_encoded(
    index: Index,
    codes: np.ndarray,
    labels: np.ndarray,
    new_indices: Optional[jax.Array] = None,
) -> Index:
    """Append already-encoded rows (codes [n, pq_dim] uint8 + coarse
    labels [n]) — the assembly half of :func:`extend`. The seam the
    distributed build uses: shards encode their own rows in parallel, the
    compressed streams meet here (pq_dim B/row is all that travels)."""
    n = codes.shape[0]
    old_n = index.size
    if new_indices is None:
        new_indices = jnp.arange(old_n, old_n + n, dtype=jnp.int32)

    # fast path: append into spare capacity without touching existing rows
    if n and old_n:
        fast = _extend_fast(index, codes, labels, np.asarray(new_indices))
        if fast is not None:
            return fast

    old_codes, old_ids, old_labels = unpack_lists(
        np.asarray(index.list_codes), np.asarray(index.list_index)
    )
    if old_codes.shape[0] == 0:
        # initial fill (build): no concatenate — one copy of the code
        # stream on the host, never two
        all_codes, all_ids, all_labels = (
            codes, np.asarray(new_indices, np.int32), np.asarray(labels)
        )
    else:
        all_codes = np.concatenate([old_codes, codes])
        all_ids = np.concatenate([old_ids, np.asarray(new_indices, np.int32)])
        all_labels = np.concatenate([old_labels, np.asarray(labels)])
    # merge split shards back to their parent before re-packing (see
    # _common.merge_split_lists — keeps n_lists stable across extends)
    uniq, all_labels = merge_split_lists(np.asarray(index.centers), all_labels)
    uniq_j = jnp.asarray(uniq)
    base_centers = index.centers[uniq_j]
    base_centers_rot = index.centers_rot[uniq_j]
    base_codebook = (
        index.codebook[uniq_j]
        if index.codebook_kind == CODEBOOK_PER_CLUSTER
        else index.codebook
    )
    (
        list_codes, list_index, list_sizes, list_data, list_y2, cmap,
        scan_scale,
    ) = _assemble_lists(
        all_codes, all_ids, all_labels, len(uniq),
        np.asarray(base_codebook), index.codebook_kind,
        np.asarray(base_centers_rot), index.list_data.dtype,
        headroom=index.headroom,
    )
    cmap_j = jnp.asarray(cmap)
    codebook = (
        base_codebook[cmap_j]
        if index.codebook_kind == CODEBOOK_PER_CLUSTER
        else index.codebook
    )
    return Index(
        index.metric, index.codebook_kind, index.pq_bits,
        base_centers[cmap_j], base_centers_rot[cmap_j], index.rotation,
        codebook, list_codes, list_index, list_sizes, list_data, list_y2,
        scan_scale,
        headroom=index.headroom,
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "n_probes", "k", "metric", "query_tile", "scan_dtype", "acc_dtype",
    ),
)
def _search_jit(
    queries,      # [q, dim] f32
    centers,      # [L, dim]
    rotation,     # [rot_dim, dim]
    list_data,    # [L, cap, rot_dim] bf16/f32 — decoded reconstructions
    list_y2,      # [L, cap] f32
    list_index,   # [L, cap] int32
    filter_words,
    scan_scale,   # scalar f32 — int8-cache dequant scale (1.0 otherwise)
    n_probes: int,
    k: int,
    metric: str,
    query_tile: int,
    scan_dtype,
    acc_dtype,
):
    """Probe-gather + MXU-matmul scan over decoded reconstructions.

    The per-code LUT gather of the reference's compute_similarity kernel
    (ivf_pq_compute_similarity-inl.cuh) is replaced by
    ``‖q_rot − y‖² = y² − 2·q_rot·y + q²`` over the decoded rows — the scan
    is one batched dot_general that streams the probed lists through the
    MXU (measured ~1000× faster than take_along_axis on v5e)."""
    q, dim = queries.shape
    rot_dim = rotation.shape[0]
    cap = list_data.shape[1]

    # ---- coarse cluster selection (ref select_clusters ivf_pq_search.cuh:67)
    probes = coarse_select(queries, centers, metric, n_probes)  # [q, p]

    q_rot = jnp.matmul(queries, rotation.T, precision=_PREC)  # [q, rot_dim]

    n_tiles = (q + query_tile - 1) // query_tile
    pad_q = n_tiles * query_tile - q
    qt = jnp.pad(q_rot, ((0, pad_q), (0, 0))).reshape(n_tiles, query_tile, rot_dim)
    pt = jnp.pad(probes, ((0, pad_q), (0, 0))).reshape(n_tiles, query_tile, n_probes)
    # per-row filters (ragged batches) tile alongside the queries; ndim is
    # static in trace so the branch costs nothing at runtime
    per_row = filter_words is not None and filter_words.ndim == 2
    if per_row:
        ft = jnp.pad(filter_words, ((0, pad_q), (0, 0))).reshape(
            n_tiles, query_tile, -1
        )
    else:
        ft = jnp.zeros((n_tiles, 1, 1), jnp.uint32)  # unused carrier

    def tile(args):
        qr, pp, fw_t = args  # [t, rot_dim], [t, p], [t, W]
        dec = _gather_lists(list_data, pp)               # [t, p, cap, rot]
        ids = list_index[pp]                             # [t, p, cap]
        y2 = list_y2[pp]                                 # [t, p, cap]
        # ip[t,p,c] = q_rot[t]·y[t,p,c] — batched over t, contracting rot
        # acc_dtype = the reference's internal_distance_dtype knob: the
        # score accumulator precision (ivf_pq_types.hpp:139-172)
        if list_data.dtype == jnp.int8:
            # memory-lean mode: rows are int8 × global scan_scale; quantize
            # the query per-row and ride the MXU's native int8 path, then
            # rescale the int32 accumulator (the fp8-LUT accuracy analog)
            ip = int8_scored_ip(
                qr, dec, (((1,), (3,)), ((0,), (0,))), scan_scale
            )                                            # [t, p, cap]
        else:
            ip = lax.dot_general(
                qr.astype(scan_dtype),
                dec.astype(scan_dtype),
                (((1,), (3,)), ((0,), (0,))),            # contract rot; batch t
                preferred_element_type=acc_dtype,
            )                                            # [t, p, cap]
        if metric == "inner_product":
            scores = (-ip).astype(jnp.float32)           # q·y == q_rot·y_rot
        else:
            q2 = jnp.sum(qr * qr, axis=1).astype(acc_dtype)  # [t]
            scores = (
                y2.astype(acc_dtype) - 2.0 * ip + q2[:, None, None]
            ).astype(jnp.float32)

        if per_row:
            invalid = invalid_mask_rows(ids, fw_t)
        else:
            invalid = invalid_mask(ids, filter_words)
        scores = jnp.where(invalid, jnp.inf, scores)
        # filtered-out candidates must surface as id −1, never their real id
        ids = jnp.where(invalid, -1, ids)
        flat_s = scores.reshape(query_tile, n_probes * cap)
        flat_i = ids.reshape(query_tile, n_probes * cap)
        v, i = select_k(flat_s, k, select_min=True, input_indices=flat_i)
        # ---- postprocess (ref ivf_pq_search.cuh:453-467)
        if metric == "inner_product":
            v = -v
        elif metric == "euclidean":
            v = jnp.sqrt(jnp.maximum(v, 0.0))
        return v, i

    vals, idx = lax.map(tile, (qt, pt, ft))
    return (
        vals.reshape(n_tiles * query_tile, k)[:q],
        idx.reshape(n_tiles * query_tile, k)[:q],
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "n_probes", "k", "metric", "bucket", "bb", "scan_dtype", "acc_dtype",
    ),
)
def _search_probe_major_jit(
    queries,      # [q, dim] f32
    centers,      # [L, dim]
    rotation,     # [rot_dim, dim]
    list_data,    # [L, cap, rot_dim] bf16/f32/int8
    list_y2,      # [L, cap] f32
    list_index,   # [L, cap] int32
    filter_words,
    scan_scale,
    n_probes: int,
    k: int,
    metric: str,
    bucket: int,  # queries per list-bucket (G)
    bb: int,      # buckets per scan step
    scan_dtype,
    acc_dtype,
):
    """Probe-major scan schedule: sort the (query, probe) pairs by list,
    bucket each list's probing queries, and stream list-by-list so every
    list's rows leave HBM ~once per batch instead of once per probing
    query (SURVEY §7 hard-part-2 "probe-major batching"; plays the role of
    the reference's per-list persistent compute_similarity scheduling,
    ivf_pq_compute_similarity-inl.cuh). Per-(pair) top-k partials are
    scattered back to (query, probe) order and merged with one select_k.
    """
    q, dim = queries.shape
    L, cap, rot_dim = list_data.shape
    G = bucket
    kk = min(k, cap)

    probes = coarse_select(queries, centers, metric, n_probes)  # [q, p]
    q_rot = jnp.matmul(queries, rotation.T, precision=_PREC)    # [q, rot]
    q2 = jnp.sum(q_rot * q_rot, axis=1)                         # [q]

    def score_fn(bl, bq):
        dec = _gather_lists(list_data, bl)                         # [bb, cap, rot]
        ids = list_index[bl]                                       # [bb, cap]
        y2 = list_y2[bl]
        qr = q_rot[jnp.clip(bq, 0)]                                # [bb, G, rot]
        if list_data.dtype == jnp.int8:
            ip = int8_scored_ip(
                qr, dec, (((2,), (2,)), ((0,), (0,))), scan_scale
            )                                                      # [bb, G, cap]
        else:
            ip = lax.dot_general(
                qr.astype(scan_dtype), dec.astype(scan_dtype),
                (((2,), (2,)), ((0,), (0,))),
                preferred_element_type=acc_dtype,
            )
        if metric == "inner_product":
            scores = (-ip).astype(jnp.float32)
        else:
            qq = q2[jnp.clip(bq, 0)].astype(acc_dtype)             # [bb, G]
            scores = (
                y2[:, None, :].astype(acc_dtype) - 2.0 * ip + qq[:, :, None]
            ).astype(jnp.float32)
        invalid = invalid_mask(ids, filter_words)                  # [bb, cap]
        scores = jnp.where(invalid[:, None, :], jnp.inf, scores)
        scores = jnp.where(bq[:, :, None] < 0, jnp.inf, scores)
        ids_m = jnp.where(invalid, -1, ids)
        v, i = select_k(
            scores.reshape(bb * G, cap), kk, select_min=True,
            input_indices=jnp.broadcast_to(
                ids_m[:, None, :], (bb, G, cap)
            ).reshape(bb * G, cap),
        )
        return v, i                                                # [bb*G, kk]

    v, i = run_probe_major(probes, L, G, bb, kk, k, score_fn)
    if metric == "inner_product":
        v = -v
    elif metric == "euclidean":
        v = jnp.sqrt(jnp.maximum(v, 0.0))
    return v, i


@functools.partial(
    jax.jit,
    static_argnames=(
        "n_probes", "k", "metric", "bucket", "scan_dtype", "interpret"
    ),
)
def _search_probe_major_pallas(
    queries, centers, rotation, list_data, list_y2, list_index,
    list_filter, scan_scale, n_probes: int, k: int, metric: str,
    bucket: int, scan_dtype: str, interpret: bool,
):
    """Probe-major schedule with the fused Pallas scan
    (kernels/ivf_scan.py): per-bucket list rows DMA into VMEM via the
    scalar-prefetched bucket table, scores + per-query top-k stay in VMEM —
    the [B, G, cap] score tensor never reaches HBM (the XLA formulation's
    remaining traffic). L2 + inner-product, float or int8 caches (the
    kernel's quantized-query leg handles int8 × scan_scale);
    ``list_filter`` is the pre-packed per-list word table (packed ONCE in
    :func:`search` — it's query-independent, so packing here would redo
    the O(n) pass per query tile)."""
    from raft_tpu.kernels.ivf_scan import ivf_scan_probe_major
    from raft_tpu.neighbors._common import (
        invert_probes as _invert,
        merge_probe_major_partials as _merge,
    )

    q, _ = queries.shape
    L, cap, rot_dim = list_data.shape
    G = bucket
    kk = min(k, cap)
    probes = coarse_select(queries, centers, metric, n_probes)
    q_rot = jnp.matmul(queries, rotation.T, precision=_PREC)
    q2 = jnp.sum(q_rot * q_rot, axis=1)
    bucket_list, bucket_query, bucket_pair, B = _invert(probes, L, G)
    qg = q_rot[jnp.clip(bucket_query, 0)]                   # [B, G, rot]
    q2g = jnp.where(bucket_query >= 0, q2[jnp.clip(bucket_query, 0)], jnp.inf)
    vals, ids = ivf_scan_probe_major(
        bucket_list, qg, q2g, list_data, list_y2, list_index, kk,
        metric=metric, scan_dtype=scan_dtype, list_filter=list_filter,
        scan_scale=scan_scale, interpret=interpret,
    )
    v, i = _merge(
        vals.reshape(B * G, kk), ids.reshape(B * G, kk),
        bucket_pair, q, n_probes, kk, k,
    )
    if metric == "inner_product":
        v = -v
    elif metric == "euclidean":
        v = jnp.sqrt(jnp.maximum(v, 0.0))
    return v, i


@functools.partial(
    jax.jit,
    static_argnames=(
        "n_probes", "k", "metric", "scan_dtype", "interpret"
    ),
)
def _search_query_major_pallas(
    queries, centers, rotation, list_data, list_y2, list_index,
    list_filter, scan_scale, n_probes: int, k: int, metric: str,
    scan_dtype: str, interpret: bool, query_fid=None,
):
    """Query-major schedule with the fused Pallas scan
    (kernels/ivf_scan.ivf_scan_query_major): probed lists stream from
    the index straight into VMEM — the XLA leg's materialized
    [t, p, cap, rot] gather copy and [t, p, cap] score tensor (2× the
    whole scanned volume in extra HBM traffic) never exist.  Queries pad
    to the kernel's group width with q2=+inf rows (outputs -1, sliced
    off).

    ``query_fid`` (ragged descriptor leg) selects each query's filter
    row from a pre-packed [n_filters, L, cap_w] ``list_filter`` table;
    padding rows ride fid 0 — their q2=+inf already voids the result."""
    from raft_tpu.kernels.ivf_scan import _QM_GROUP, ivf_scan_query_major

    q, _ = queries.shape
    probes = coarse_select(queries, centers, metric, n_probes)
    q_rot = jnp.matmul(queries, rotation.T, precision=_PREC)
    q2 = jnp.sum(q_rot * q_rot, axis=1)
    pad = (-q) % _QM_GROUP
    if pad:
        probes = jnp.pad(probes, ((0, pad), (0, 0)))
        q_rot = jnp.pad(q_rot, ((0, pad), (0, 0)))
        q2 = jnp.pad(q2, (0, pad), constant_values=jnp.inf)
        if query_fid is not None:
            query_fid = jnp.pad(query_fid, (0, pad))
    v, i = ivf_scan_query_major(
        probes, q_rot, q2, list_data, list_y2, list_index, int(k),
        metric=metric, scan_dtype=scan_dtype, list_filter=list_filter,
        scan_scale=scan_scale, query_fid=query_fid, interpret=interpret,
    )
    v, i = v[:q], i[:q]
    if metric == "inner_product":
        v = -v
    elif metric == "euclidean":
        v = jnp.sqrt(jnp.maximum(v, 0.0))
    return v, i


@traced("ivf_pq.search")
def search(
    params: SearchParams,
    index: Index,
    queries: jax.Array,
    k: int,
    *,
    sample_filter: Optional[Bitset] = None,
    deleted_mask: Optional[Bitset] = None,
    res: Optional[Resources] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Returns (distances [q, k], indices [q, k]). Distances are PQ
    approximations — pipe through ``neighbors.refine`` for exact re-ranking
    (ref: ivf_pq search + refine pattern, cagra_build.cuh:146-196).

    ``deleted_mask`` excludes set bits (tombstones, raft_tpu.serve) and
    composes with ``sample_filter`` (pass-bits kept)."""
    res = ensure(res)
    from raft_tpu.neighbors._common import resolve_pass_filter

    sample_filter = resolve_pass_filter(sample_filter, deleted_mask)
    queries = jnp.asarray(queries, jnp.float32)
    if queries.ndim != 2 or queries.shape[1] != index.dim:
        raise ValueError(f"queries shape {queries.shape} vs index dim {index.dim}")
    n_probes = min(params.n_probes, index.n_lists)
    if k > n_probes * index.list_cap:
        raise ValueError(
            f"k={k} exceeds candidate pool n_probes*list_cap="
            f"{n_probes}*{index.list_cap}; raise n_probes"
        )
    canonical = DISTANCE_TYPES[index.metric]
    # scan compute dtype: bf16 halves the HBM stream and uses the MXU's
    # native path; float32 upcasts the stored rows (ref lut_dtype knob)
    scan_dtype = jnp.bfloat16 if params.lut_dtype == "bfloat16" else jnp.float32
    acc_dtype = (
        jnp.bfloat16 if params.internal_distance_dtype == "bfloat16" else jnp.float32
    )
    fw = sample_filter.words if sample_filter is not None else None
    validation.check_in(
        params.strategy, ("auto", "query_major", "probe_major"), "strategy"
    )
    per_row = fw is not None and fw.ndim == 2
    req_strategy = params.strategy
    if per_row:
        validation.expects(
            fw.shape[0] == queries.shape[0],
            f"row filter has {fw.shape[0]} rows for "
            f"{queries.shape[0]} queries",
        )
        # probe-major tiles score whole lists against query *buckets*; a
        # per-query filter has no per-list formulation there, so ragged
        # batches always take the query-major schedule
        req_strategy = "query_major"
    strategy, bucket, bb, q_tile = select_scan_strategy(
        req_strategy, queries.shape[0], n_probes, index.n_lists,
        index.list_cap, index.rot_dim, res.workspace_limit_bytes, k=int(k),
    )
    # paged index: prefetch + pin the probed lists' pages before the scan
    # executables dispatch; ``list_data`` becomes the PagedLists view and
    # the schedules below gather through the page table transparently
    paged = getattr(index, "paged", None)
    if paged is not None:
        from raft_tpu.neighbors._common import paged_lists_for_search

        list_data = paged_lists_for_search(index, queries, canonical, n_probes)
    else:
        list_data = index.list_data
    if strategy == "probe_major":
        use_pallas = pallas_scan_enabled(
            canonical, list_data.dtype, allow_int8=True
        ) and params.internal_distance_dtype == "float32"
        if paged is not None and use_pallas:
            from raft_tpu.kernels.ivf_scan import paged_scan_supported

            use_pallas = paged_scan_supported(
                list_data, min(int(k), index.list_cap), fw is not None
            )
        if use_pallas:
            # the kernel accumulates f32 only; a bf16 internal-distance
            # request must keep the XLA leg (preferred_element_type=
            # acc_dtype) or the two legs rank near-ties differently
            from raft_tpu.kernels import interpret_mode
            from raft_tpu.kernels.ivf_scan import pack_list_filter

            # pack the filter ONCE per call (query-independent)
            lf = (
                None if fw is None
                else pack_list_filter(index.list_index, fw)
            )
            _stamp_kernel_path("pallas")

            def run_pm(qt):
                return _search_probe_major_pallas(
                    qt, index.centers, index.rotation, list_data,
                    index.list_y2, index.list_index, lf,
                    float(index.scan_scale), n_probes, int(k),
                    canonical, bucket, params.lut_dtype, interpret_mode(),
                )
        else:
            _stamp_kernel_path("xla")

            def run_pm(qt):
                return _search_probe_major_jit(
                    qt,
                    index.centers,
                    index.rotation,
                    list_data,
                    index.list_y2,
                    index.list_index,
                    fw,
                    float(index.scan_scale),
                    n_probes,
                    int(k),
                    canonical,
                    bucket,
                    bb,
                    scan_dtype,
                    acc_dtype,
                )

        # host-level query batching bounds the merge buffers (pair
        # partials are O(q·p·k); see select_scan_strategy)
        return run_query_tiled(run_pm, queries, q_tile)
    from raft_tpu.kernels import ivf_scan as _scan_mod

    has_descriptor = per_row and getattr(sample_filter, "table", None) is not None
    if (
        # the fused query-major kernel has no paged leg (dense [L, cap]
        # block specs); paged searches ride the XLA gather below
        paged is None
        and pallas_scan_enabled(canonical, list_data.dtype, allow_int8=True)
        and params.internal_distance_dtype == "float32"
        # per-row filters stay fused when they carry the packed
        # descriptor (RowFilter.from_table); ad-hoc [q, w] word planes
        # still ride the XLA fallback below
        and (not per_row or has_descriptor)
        # the fused kernel's per-block score scratch must fit VMEM
        # comfortably; past that the XLA leg tiles better
        and _scan_mod.qm_scratch_bytes(n_probes, index.list_cap)
        <= _scan_mod.QM_VMEM_BUDGET
    ):
        from raft_tpu.kernels import interpret_mode

        if has_descriptor:
            # ragged descriptor leg: pack every registered filter's
            # per-list word table once; each query's fid prefetches its
            # own block (same leg ivf_flat rides — the rotation only
            # changes the query operand, not the filter plumbing)
            lf = _scan_mod.pack_list_filter_table(
                index.list_index, sample_filter.table
            )
            fid = jnp.asarray(sample_filter.fid, jnp.int32)
            _stamp_kernel_path("pallas")

            def run_qm(qt, ft):
                return _search_query_major_pallas(
                    qt, index.centers, index.rotation, index.list_data,
                    index.list_y2, index.list_index, lf,
                    float(index.scan_scale), n_probes, int(k), canonical,
                    params.lut_dtype, interpret_mode(), query_fid=ft,
                )

            return run_query_tiled(
                run_qm, queries, _scan_mod.qm_query_tile(n_probes),
                extras=(fid,),
            )

        lf = (
            None if fw is None
            else _scan_mod.pack_list_filter(index.list_index, fw)
        )
        _stamp_kernel_path("pallas")

        def run_qm(qt):
            return _search_query_major_pallas(
                qt, index.centers, index.rotation, index.list_data,
                index.list_y2, index.list_index, lf,
                float(index.scan_scale), n_probes, int(k), canonical,
                params.lut_dtype, interpret_mode(),
            )

        return run_query_tiled(
            run_qm, queries, _scan_mod.qm_query_tile(n_probes)
        )
    # per-query workspace: probe gather of decoded rows + scores + ids
    if list_data.dtype == jnp.int8:
        itemsize = 1
    else:
        itemsize = 2 if scan_dtype == jnp.bfloat16 else 4
    per_q = n_probes * index.list_cap * (index.rot_dim * itemsize + 12)
    query_tile = int(min(max(queries.shape[0], 1), max(1, res.workspace_rows(per_q, cap=1024))))
    # per-row filters land here only when the fused descriptor leg was
    # unavailable — stamp the fallback distinctly for the perf ledger A/B
    _stamp_kernel_path("xla_filter_fallback" if per_row else "xla")
    return _search_jit(
        queries,
        index.centers,
        index.rotation,
        list_data,
        index.list_y2,
        index.list_index,
        fw,
        float(index.scan_scale),
        n_probes,
        int(k),
        canonical,
        query_tile,
        scan_dtype,
        acc_dtype,
    )


def _pack_bits(codes: np.ndarray, pq_bits: int) -> np.ndarray:
    """Pack uint8 codes (< 2**pq_bits) into a dense bitstream per row for
    serialization parity with the reference's compressed storage."""
    bits = np.unpackbits(codes[..., None], axis=-1, count=8, bitorder="little")
    bits = bits[..., :pq_bits].reshape(codes.shape[0], -1)
    return np.packbits(bits, axis=-1, bitorder="little")


def _unpack_bits(packed: np.ndarray, pq_dim: int, pq_bits: int) -> np.ndarray:
    bits = np.unpackbits(packed, axis=-1, bitorder="little")[:, : pq_dim * pq_bits]
    bits = bits.reshape(packed.shape[0], pq_dim, pq_bits)
    full = np.zeros((packed.shape[0], pq_dim, 8), np.uint8)
    full[..., :pq_bits] = bits
    return np.packbits(full, axis=-1, bitorder="little")[..., 0]


@traced("ivf_pq.save")
def save(filename: str, index: Index) -> None:
    lc = np.asarray(index.list_codes)
    L, cap, pq_dim = lc.shape
    packed = _pack_bits(lc.reshape(L * cap, pq_dim), index.pq_bits)
    ser.save_tree(
        filename,
        "ivf_pq",
        _SERIALIZATION_VERSION,
        {
            "metric": index.metric,
            "codebook_kind": index.codebook_kind,
            "pq_bits": index.pq_bits,
            "pq_dim": pq_dim,
            "list_cap": cap,
            "decoded_dtype": str(np.dtype(index.list_data.dtype).name)
            if index.list_data.dtype != jnp.bfloat16
            else "bfloat16",
            # ref serializes conservative_memory_allocation
            # (ivf_pq_serialize.cuh:64); headroom == not conservative
            "headroom": int(index.headroom),
        },
        {
            "centers": index.centers,
            "centers_rot": index.centers_rot,
            "rotation": index.rotation,
            "codebook": index.codebook,
            "list_codes_packed": packed,
            "list_index": index.list_index,
            "list_sizes": index.list_sizes,
        },
    )


@traced("ivf_pq.load")
def load(filename: str) -> Index:
    scalars, arrays = ser.load_tree(filename, "ivf_pq", _SERIALIZATION_VERSION)
    L = arrays["centers"].shape[0]
    cap, pq_dim = scalars["list_cap"], scalars["pq_dim"]
    codes = _unpack_bits(arrays["list_codes_packed"], pq_dim, scalars["pq_bits"])
    codes = codes.reshape(L, cap, pq_dim)
    stored_dtype = scalars.get("decoded_dtype", "bfloat16")
    validation.check_in(stored_dtype, _DECODED_DTYPES, "decoded_dtype")
    dec_dtype = _DECODED_DTYPES[stored_dtype]
    list_index = arrays["list_index"]
    # the decoded scan cache (and its int8 scale) is derived state: rebuild
    # it from the codes
    list_data, list_y2, scan_scale = _decode_lists(
        arrays["codebook"], scalars["codebook_kind"], arrays["centers_rot"],
        codes, list_index, dec_dtype,
    )
    return Index(
        scalars["metric"],
        scalars["codebook_kind"],
        scalars["pq_bits"],
        jnp.asarray(arrays["centers"]),
        jnp.asarray(arrays["centers_rot"]),
        jnp.asarray(arrays["rotation"]),
        jnp.asarray(arrays["codebook"]),
        codes,
        jnp.asarray(list_index),
        jnp.asarray(arrays["list_sizes"]),
        list_data,
        list_y2,
        scan_scale,
        headroom=bool(scalars.get("headroom", 1)),
    )
