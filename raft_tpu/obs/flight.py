"""Always-on flight recorder: the last N batches, dumpable post-hoc.

When ``healthz()`` flips to UNHEALTHY or the recall alarm fires at 3am,
aggregates answer *that* something went wrong; the flight recorder
answers *which requests were in flight* and where their milliseconds
went.  Like the post-hoc per-operation trace artifacts of the Ragged
Paged Attention tooling (arxiv 2604.15464), no live profiler session is
required: the batcher feeds every completed (or failed) batch — member
request ids, per-request timelines reconstructed from the stage timers
it already keeps — into a bounded ring, and :func:`dump` writes both a
JSON snapshot and a Chrome-trace-event file loadable straight into
https://ui.perfetto.dev.

Triggers arrive over the :mod:`raft_tpu.obs.events` bus — the recorder
is just one subscriber (:func:`install_bus_subscriber`, wired
automatically when the default bus is created):

- health transition to UNHEALTHY (:mod:`raft_tpu.obs.health`);
- quality-alarm edge (:mod:`raft_tpu.obs.quality`);
- a hot-path recompile after warmup (the batcher);
- a batch exception on either dispatch path (the batcher);
- a compaction recall-gate abort, an SLO burn-rate alert.

Dump suppression is two-layered: the bus subscription debounces **per
reason** (``RAFT_TPU_FLIGHT_DEBOUNCE_S`` — a ``quality_alarm`` dump no
longer suppresses a later unrelated ``hot_recompile``), and a short
cross-reason correlation guard (``RAFT_TPU_INCIDENT_WINDOW_S``) keeps
one *incident* producing one artifact even when it trips several
symptoms back-to-back (the quality alarm fires, then the next
``healthz()`` goes UNHEALTHY).  :func:`auto_dump` keeps the old single
global window and survives only as a deprecated direct path.

Env knobs: ``RAFT_TPU_FLIGHT_CAP`` (ring size, batch records, default
256), ``RAFT_TPU_FLIGHT_DIR`` (auto-dump directory, default the system
temp dir), ``RAFT_TPU_FLIGHT_DEBOUNCE_S`` (minimum seconds between
auto-dumps, default 60).  ``RAFT_TPU_OBS_DISABLED`` / ``set_enabled``
turn recording off entirely (the bench's A/B leg measures the delta).

Recording cost: one dict build + deque append per *batch* (not per
request), on the completion path — after futures are already resolved.
"""

from __future__ import annotations

import itertools
import json
import os
import tempfile
import threading
import time
from collections import deque
from typing import Dict, List, Optional

import raft_tpu.obs.spans as _spans
from raft_tpu.core import env as _env
from raft_tpu.obs.registry import default_registry

#: default ring capacity (batch records)
DEFAULT_CAP = 256

#: default minimum seconds between auto-dumps
DEFAULT_DEBOUNCE_S = 60.0

# process-wide monotonically increasing request ids, assigned at
# MicroBatcher.submit (itertools.count.__next__ is atomic in CPython)
_req_ids = itertools.count(1)


def next_request_id() -> int:
    """The next request id — assigned once per submitted request."""
    return next(_req_ids)


def _env_cap() -> int:
    try:
        return max(1, _env.env_int("RAFT_TPU_FLIGHT_CAP", DEFAULT_CAP))
    except ValueError:
        return DEFAULT_CAP


def _env_debounce_s() -> float:
    try:
        return max(0.0, _env.env_float(
            "RAFT_TPU_FLIGHT_DEBOUNCE_S", DEFAULT_DEBOUNCE_S
        ))
    except ValueError:
        return DEFAULT_DEBOUNCE_S


def _env_dir() -> str:
    return _env.env_str("RAFT_TPU_FLIGHT_DIR") or tempfile.gettempdir()


class FlightRecorder:
    """Bounded ring of recent batch/event records + dump machinery.

    One instance normally lives for the whole process (module-level
    :func:`default_recorder`); tests build private ones.  All methods are
    thread-safe; :meth:`record_batch` is the only one on a serving path
    and costs a lock + deque append.
    """

    def __init__(self, cap: Optional[int] = None,
                 debounce_s: Optional[float] = None):
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=cap if cap is not None else _env_cap())
        self._recorded = 0          # total records ever (ring overwrites)
        self._dump_seq = 0
        self._last_dump: Optional[Dict[str, object]] = None
        self._last_auto = float("-inf")   # monotonic stamp of last auto-dump
        self._debounce_s = (
            debounce_s if debounce_s is not None else _env_debounce_s()
        )

    # -- recording -----------------------------------------------------------
    def record_batch(self, record: Dict[str, object]) -> None:
        """Append one batch record (built by the batcher's completion
        path).  No-op when obs is disabled, so ``RAFT_TPU_OBS_DISABLED``
        really does zero the recorder's footprint."""
        if not _spans.enabled():
            return
        with self._lock:
            self._ring.append(record)
            self._recorded += 1

    def record_event(self, kind: str, **fields: object) -> None:
        """Append one point-in-time event (e.g. a replicated-searcher
        rebuild) so incident dumps carry it next to the affected batches."""
        if not _spans.enabled():
            return
        rec = {"kind": kind, "t": time.perf_counter(), **fields}
        with self._lock:
            self._ring.append(rec)
            self._recorded += 1

    # -- reading -------------------------------------------------------------
    def records(self) -> List[Dict[str, object]]:
        """Ring contents, oldest first."""
        with self._lock:
            return list(self._ring)

    def last_dump(self) -> Optional[Dict[str, object]]:
        """``{"path", "trace_path", "reason", "unix_time"}`` of the most
        recent dump, or None — surfaced by ``SearchService.healthz()``."""
        with self._lock:
            return dict(self._last_dump) if self._last_dump else None

    def snapshot(self) -> Dict[str, object]:
        """Provider section for registry snapshots."""
        with self._lock:
            return {
                "cap": self._ring.maxlen,
                "records": len(self._ring),
                "recorded_total": self._recorded,
                "last_dump": dict(self._last_dump) if self._last_dump else None,
            }

    # -- dumping -------------------------------------------------------------
    def dump(self, directory: Optional[str] = None,
             reason: str = "manual") -> str:
        """Write the ring as ``flight_<seq>_<reason>.json`` plus a Chrome
        trace-event file (``.trace.json``) into ``directory`` (default
        ``RAFT_TPU_FLIGHT_DIR``, else the system temp dir).  Returns the
        JSON snapshot path."""
        directory = directory or _env_dir()
        os.makedirs(directory, exist_ok=True)
        with self._lock:
            records = list(self._ring)
            self._dump_seq += 1
            seq = self._dump_seq
        now = time.time()
        stem = f"flight_{seq:04d}_{reason}"
        path = os.path.join(directory, stem + ".json")
        trace_path = os.path.join(directory, stem + ".trace.json")
        snapshot = {
            "schema": "raft_tpu.flight",
            "reason": reason,
            "unix_time": now,
            "records": records,
        }
        with open(path, "w") as f:
            json.dump(snapshot, f, indent=2, default=str)
        with open(trace_path, "w") as f:
            json.dump({"traceEvents": trace_events(records)}, f, default=str)
        info = {
            "path": path,
            "trace_path": trace_path,
            "reason": reason,
            "unix_time": now,
        }
        with self._lock:
            self._last_dump = info
        default_registry().counter(
            "raft_tpu_flight_dumps_total",
            help="flight-recorder dumps written",
        ).inc(reason=reason)
        return path

    def auto_dump(self, reason: str) -> Optional[str]:
        """Deprecated direct trigger path: :meth:`dump` behind one
        *global* debounce window shared across all reasons.  In-tree
        producers now publish :mod:`raft_tpu.obs.events` events instead
        and the bus subscriber debounces per reason; this survives for
        out-of-tree callers that wired incidents before the bus existed.
        Never raises — these calls sit on health/alarm/error paths that
        must not gain failure modes.
        """
        if not _spans.enabled():
            return None
        with self._lock:
            now = time.monotonic()
            if now - self._last_auto < self._debounce_s:
                default_registry().counter(
                    "raft_tpu_flight_dumps_suppressed_total",
                    help="auto-dumps suppressed by the debounce window",
                ).inc(reason=reason)
                return None
            self._last_auto = now
        try:
            return self.dump(reason=reason)
        except Exception:  # noqa: BLE001 — incident paths must not fail
            return None

    def reset(self) -> None:
        """Clear the ring, debounce state and last-dump pointer; re-read
        the env knobs (tests / long-lived REPLs)."""
        with self._lock:
            self._ring = deque(maxlen=_env_cap())
            self._recorded = 0
            self._last_dump = None
            self._last_auto = float("-inf")
            self._debounce_s = _env_debounce_s()


def trace_events(records: List[Dict[str, object]]) -> List[Dict[str, object]]:
    """Flatten batch records into Chrome trace events (Perfetto-loadable).

    Track layout: tid 1 carries one complete ("X") slice per batch with
    the stage sub-slices laid end to end from the batch pickup stamp
    (reconstructed from the recorded durations — the recorder adds no
    clocks of its own); tid 2 carries one slice per member request
    spanning submit → resolve.  Point events (``record_event``) become
    instant ("i") events.  Timestamps are ``time.perf_counter`` seconds
    scaled to microseconds — relative, which is all Perfetto needs.
    """
    events: List[Dict[str, object]] = [
        {"ph": "M", "pid": 1, "tid": 1, "name": "thread_name",
         "args": {"name": "batches"}},
        {"ph": "M", "pid": 1, "tid": 2, "name": "thread_name",
         "args": {"name": "requests"}},
        {"ph": "M", "pid": 1, "name": "process_name",
         "args": {"name": "raft_tpu.serve"}},
    ]
    for rec in records:
        if "t_pickup" not in rec:  # a record_event point, not a batch
            events.append({
                "ph": "i", "pid": 1, "tid": 1, "s": "p",
                "name": str(rec.get("kind", "event")),
                "ts": float(rec.get("t", 0.0)) * 1e6,
                "args": {k: v for k, v in rec.items() if k != "t"},
            })
            continue
        t_pickup = float(rec.get("t_pickup", 0.0))
        t_done = float(rec.get("t_done", t_pickup))
        label = f"batch seq={rec.get('seq')} b{rec.get('bucket')}"
        if rec.get("error"):
            label += " ERROR"
        events.append({
            "ph": "X", "pid": 1, "tid": 1, "name": label,
            "ts": t_pickup * 1e6,
            "dur": max(0.0, t_done - t_pickup) * 1e6,
            "args": {
                "index": rec.get("index"),
                "request_ids": rec.get("request_ids"),
                "rows": rec.get("rows"),
                "compiles": rec.get("compiles"),
                "error": rec.get("error"),
            },
        })
        offset = t_pickup
        for stage, dur in (rec.get("stages_s") or {}).items():
            dur = float(dur)
            events.append({
                "ph": "X", "pid": 1, "tid": 1, "name": stage,
                "ts": offset * 1e6, "dur": max(0.0, dur) * 1e6,
            })
            offset += max(0.0, dur)
        for req in rec.get("requests") or ():
            t_submit = float(req.get("submit", t_pickup))
            t_resolve = float(req.get("resolve", t_done))
            events.append({
                "ph": "X", "pid": 1, "tid": 2,
                "name": f"req {req.get('id')}",
                "ts": t_submit * 1e6,
                "dur": max(0.0, t_resolve - t_submit) * 1e6,
                "args": {k: v for k, v in req.items()
                         if k not in ("submit", "resolve")},
            })
    return events


# ---------------------------------------------------------------------------
# the process-wide default recorder + module-level conveniences

_default = FlightRecorder()


def default_recorder() -> FlightRecorder:
    return _default


def record_batch(record: Dict[str, object]) -> None:
    _default.record_batch(record)


def record_event(kind: str, **fields: object) -> None:
    _default.record_event(kind, **fields)


def records() -> List[Dict[str, object]]:
    return _default.records()


def dump(directory: Optional[str] = None, reason: str = "manual") -> str:
    return _default.dump(directory, reason=reason)


def auto_dump(reason: str) -> Optional[str]:
    return _default.auto_dump(reason)


def last_dump() -> Optional[Dict[str, object]]:
    return _default.last_dump()


def flight_snapshot() -> Dict[str, object]:
    """Provider section for registry snapshots."""
    return _default.snapshot()


def reset() -> None:
    _default.reset()
    _on_bus_reset()


# ---------------------------------------------------------------------------
# event-bus subscriber: the migrated trigger path

#: default cross-reason correlation guard (seconds) — mirrors the
#: incident manager's grouping window so "one incident, one artifact"
#: survives the move to per-reason debounce
DEFAULT_CORRELATION_S = 5.0

_bus_guard = threading.Lock()
_last_bus_dump = float("-inf")   # monotonic stamp of the last bus-triggered dump


def _env_correlation_s() -> float:
    try:
        return max(0.0, _env.env_float(
            "RAFT_TPU_INCIDENT_WINDOW_S", DEFAULT_CORRELATION_S
        ))
    except ValueError:
        return DEFAULT_CORRELATION_S


def _on_bus_event(event) -> None:
    """Dump the ring for a trigger event.  The per-reason debounce
    already ran in the bus subscription; here only the short cross-reason
    correlation guard applies (several symptoms of one incident within
    ``RAFT_TPU_INCIDENT_WINDOW_S`` share the first artifact).  Never
    raises — the bus swallows subscriber errors, but a dump failure
    should not even count as one."""
    global _last_bus_dump
    if event.recovered or not _spans.enabled():
        return
    now = time.monotonic()
    with _bus_guard:
        suppressed = now - _last_bus_dump < _env_correlation_s()
        if not suppressed:
            _last_bus_dump = now
    if suppressed:
        default_registry().counter(
            "raft_tpu_flight_dumps_suppressed_total",
            help="auto-dumps suppressed by the debounce window",
        ).inc(reason=event.reason)
        return
    try:
        _default.dump(reason=event.reason)
    except Exception:  # noqa: BLE001 — incident paths must not fail
        pass


def install_bus_subscriber(bus) -> None:
    """Register the flight dumper on ``bus``: trigger kinds only,
    debounced per reason with the ``RAFT_TPU_FLIGHT_DEBOUNCE_S`` window.
    Called once per bus by :func:`raft_tpu.obs.events.default_bus`."""
    from raft_tpu.obs import events as _events

    bus.subscribe(
        _on_bus_event,
        kinds=_events.TRIGGER_KINDS,
        debounce_s=_env_debounce_s(),
        name="flight",
    )


def _on_bus_reset() -> None:
    global _last_bus_dump
    with _bus_guard:
        _last_bus_dump = float("-inf")
