"""Slow-query log: requests over a latency threshold, with their anatomy.

A p99 regression tells you *that* something is slow; the slow-query log
tells you *which* requests and *where the time went* — each entry carries
the span's stage breakdown (queue/pad/dispatch/device) and any attributed
XLA events, so "p99 doubled" resolves to "requests behind a 12 s compile"
without re-running traffic under a profiler.

Entries go two places: a bounded in-memory ring (queryable via
:func:`entries` and merged into registry snapshots) and the ``raft_tpu``
logger at WARNING (one structured line per slow request), matching the
reference's RAFT_LOG_WARN-on-degradation idiom (core/logger-inl.hpp).

Threshold: ``RAFT_TPU_SLOW_QUERY_MS`` env var, or :func:`configure`.
Default 250 ms — generous for an in-memory ANN hit, tight enough to catch
a hot-path compile.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional

from raft_tpu.core import env as _env
from raft_tpu.core.logger import child as _child_logger
from raft_tpu.obs.registry import default_registry
from raft_tpu.obs.spans import Span

_CAP = 256

_lock = threading.Lock()
_entries: deque = deque(maxlen=_CAP)
_threshold_s = _env.env_float("RAFT_TPU_SLOW_QUERY_MS", 250.0) * 1e-3


def configure(threshold_ms: Optional[float]) -> None:
    """Set the slow threshold; None disables the log entirely.

    Rejects negative thresholds: the old behaviour silently armed an
    every-query log (anything is slower than -5 ms), which reads like
    "disabled" but WARNING-spams instead.  Use ``None`` or ``0`` to log
    everything deliberately, a positive value to filter.
    """
    global _threshold_s
    if threshold_ms is not None and float(threshold_ms) < 0:
        raise ValueError(
            f"slow-query threshold must be >= 0 ms (or None to disable), "
            f"got {threshold_ms}"
        )
    _threshold_s = None if threshold_ms is None else float(threshold_ms) * 1e-3


def threshold_ms() -> Optional[float]:
    return None if _threshold_s is None else _threshold_s * 1e3


def maybe_record(span: Span, *, latency_s: Optional[float] = None,
                 detail: Optional[Dict[str, object]] = None) -> bool:
    """Log ``span`` if its latency crossed the threshold.

    ``latency_s`` overrides the span's own wall time — the batcher passes
    the worst submit→complete request latency, which includes queue wait
    the dispatch span can't see.  Returns True when recorded as slow.
    Callers sit on hot paths: the fast path is one float compare.
    """
    if latency_s is None:
        latency_s = span.duration_s
    if _threshold_s is None or latency_s is None:
        return False
    if latency_s < _threshold_s:
        return False
    entry: Dict[str, object] = {
        "unix_time": time.time(),
        "latency_ms": latency_s * 1e3,
        **span.to_dict(),
    }
    if detail:
        entry.update(detail)
    with _lock:
        _entries.append(entry)
    default_registry().counter(
        "raft_tpu_slow_queries_total",
        help="requests over the slow threshold",
    ).inc(span=span.name)
    stages = ", ".join(
        f"{k}={v:.1f}ms" for k, v in entry.get("stages_ms", {}).items()
    )
    # explain summary, when the batcher enriched the detail (the fields
    # ride the entry either way; the line is what an operator greps):
    # effort level + who set it, kernel path, bucket, page hit ratio
    summary = ", ".join(
        f"{key}={entry[key]}"
        for key in ("effort_level", "effort_source", "kernel_path",
                    "bucket", "page_hit_ratio")
        if entry.get(key) is not None
    )
    _child_logger("obs.slowlog").warning(
        "slow query: %s took %.1fms (threshold %.1fms)%s%s",
        span.name,
        latency_s * 1e3,
        _threshold_s * 1e3,
        f" [{stages}]" if stages else "",
        f" [{summary}]" if summary else "",
    )
    return True


def entries(n: int = 50) -> List[Dict[str, object]]:
    """Most recent slow entries, newest last."""
    with _lock:
        return list(_entries)[-n:]


def clear() -> None:
    with _lock:
        _entries.clear()


def slowlog_snapshot() -> Dict[str, object]:
    """Provider section for registry snapshots."""
    return {"threshold_ms": threshold_ms(), "recent": entries(20)}
