"""SLO engine: objectives, error budgets, multi-window burn-rate alerts.

The registry answers *what is happening* (latency histograms, recall
EWMAs, error counters); this module answers *is it acceptable* — the
signals-to-semantics layer operators actually page on.  Each
:class:`SloSpec` declares an objective over one served index:

- ``availability`` — fraction of requests that resolve without error,
  from ``raft_tpu_serve_requests_total`` + the per-cause
  ``raft_tpu_serve_errors_total`` counters;
- ``latency`` — fraction of requests under the target latency, from the
  ``raft_tpu_serve_request_seconds`` histogram ladder (the bucket edges
  at or below the target count as good);
- ``recall`` — the :class:`~raft_tpu.obs.quality.QualityAuditor` recall
  EWMA against the objective floor;
- ``freshness`` — mutation backlog age
  (:meth:`~raft_tpu.serve.mutation.MutableIndex.backlog_age_s`) under
  the target staleness bound.

A background thread (or explicit :meth:`SloEngine.evaluate_once` calls
— tests drive a synthetic clock instead of sleeping) samples each
source into a sliding ring and evaluates the Google-SRE multi-window
multi-burn-rate policy: the **fast** pair (5 m short / 1 h long, burn
14.4×) catches budget-torching outages in minutes, the **slow** pair
(6 h short / 3 d long, burn 1×) catches slow leaks; an alert fires only
when *both* windows of a pair burn, and re-arms when the short window
recovers — the alarm-fatigue fix a single EWMA threshold lacks.  All
windows (and the evaluation period) scale by
``RAFT_TPU_SLO_WINDOW_SCALE`` so tests and ``bench.py slo`` run the
same policy in seconds.

Alert edges publish ``slo_burn`` events on the obs bus (opening
incidents, dumping flight artifacts); budget state exports as
``raft_tpu_slo_budget_remaining{slo=}`` /
``raft_tpu_slo_burn_rate{slo=,window=}`` gauges; an exhausted budget
turns ``SearchService.healthz()`` DEGRADED — serving keeps working,
but the operator contract is broken and releases should freeze.

``slo_burn`` edges are also *actuated*, not just paged on: the serve
layer's :class:`~raft_tpu.serve.overload.AdmissionController`
subscribes to them and raises its shed pressure floor while any burn
alert for its index is live (the ``recovered=True`` edge releases the
latch) — the closed loop from "budget is burning" to "lowest-priority
traffic is shed" documented in ``docs/serving.md``.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from raft_tpu.core import env as _env
from raft_tpu.core.trace import traced
from raft_tpu.obs import events as _events
from raft_tpu.obs.registry import MetricsRegistry, default_registry

#: spec kinds understood by the evaluator
KINDS = ("availability", "latency", "recall", "freshness")

#: objective applied to threshold-style specs built by watch_index
#: (latency-under-target, freshness-under-bound)
THRESHOLD_OBJECTIVE = 0.99

#: default evaluation period (seconds, pre-scale)
DEFAULT_EVAL_S = 10.0

#: default error-budget window (seconds, pre-scale): 30 days
DEFAULT_BUDGET_WINDOW_S = 30.0 * 86400.0

#: hard cap on retained samples per spec (memory bound; at the default
#: 10 s tick this spans ~7.6 days — a real deployment would lower the
#: budget window or raise the tick, both env knobs)
MAX_SAMPLES = 65536


@dataclass(frozen=True)
class AlertPolicy:
    """One multi-window burn-rate rule: fire when both the long and the
    short window burn faster than ``max_burn``× budget."""

    name: str
    long_s: float
    short_s: float
    max_burn: float
    severity: str


#: the Google-SRE fast/slow pairs (pre-scale seconds)
ALERT_POLICIES: Tuple[AlertPolicy, ...] = (
    AlertPolicy("fast", long_s=3600.0, short_s=300.0,
                max_burn=14.4, severity="page"),
    AlertPolicy("slow", long_s=3.0 * 86400.0, short_s=6.0 * 3600.0,
                max_burn=1.0, severity="ticket"),
)


@dataclass(frozen=True)
class SloSpec:
    """One declarative objective over one served index.

    ``objective`` is the good fraction promised (0.999 = three nines);
    ``target`` parameterizes threshold kinds (latency target in
    *seconds*, freshness bound in seconds; unused for availability /
    recall).
    """

    name: str
    index: str
    kind: str
    objective: float
    target: float = 0.0
    description: str = ""

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(
                f"unknown SLO kind {self.kind!r}; known: {KINDS}"
            )
        if not 0.0 < self.objective < 1.0:
            raise ValueError(
                f"objective must be in (0, 1), got {self.objective}"
            )


class _SpecState:
    """Per-spec evaluator state: the sample ring plus cumulative-counter
    baselines and per-policy alert latches."""

    __slots__ = ("spec", "samples", "prev_bad", "prev_total", "fired",
                 "budget_remaining", "burn", "sli", "first_t")

    def __init__(self, spec: SloSpec, maxlen: int):
        self.spec = spec
        # (t, bad, weight): weight is interval requests for counter
        # kinds, 1.0 for gauge kinds
        self.samples: deque = deque(maxlen=maxlen)
        self.prev_bad: Optional[float] = None
        self.prev_total: Optional[float] = None
        self.fired: Dict[str, bool] = {}
        self.budget_remaining = 1.0
        self.burn: Dict[str, Dict[str, float]] = {}
        self.sli: Optional[float] = None
        self.first_t: Optional[float] = None


def _env_scale() -> float:
    try:
        return max(1e-9, _env.env_float("RAFT_TPU_SLO_WINDOW_SCALE", 1.0))
    except ValueError:
        return 1.0


def _env_eval_s() -> float:
    try:
        return max(1e-4, _env.env_float("RAFT_TPU_SLO_EVAL_S",
                                        DEFAULT_EVAL_S))
    except ValueError:
        return DEFAULT_EVAL_S


def _env_budget_window_s() -> float:
    try:
        return max(1e-3, _env.env_float("RAFT_TPU_SLO_BUDGET_WINDOW_S",
                                        DEFAULT_BUDGET_WINDOW_S))
    except ValueError:
        return DEFAULT_BUDGET_WINDOW_S


def default_specs(index: str) -> List[SloSpec]:
    """The four standard objectives for one served index, parameterized
    by the ``RAFT_TPU_SLO_*`` env knobs."""
    availability = _env.env_float("RAFT_TPU_SLO_AVAILABILITY", 0.999)
    p99_ms = _env.env_float("RAFT_TPU_SLO_P99_MS", 250.0)
    recall = _env.env_float("RAFT_TPU_SLO_RECALL", 0.9)
    freshness_s = _env.env_float("RAFT_TPU_SLO_FRESHNESS_S", 300.0)
    return [
        SloSpec(f"{index}-availability", index, "availability",
                objective=availability,
                description="requests resolving without error"),
        SloSpec(f"{index}-latency", index, "latency",
                objective=THRESHOLD_OBJECTIVE, target=p99_ms / 1e3,
                description=f"requests under {p99_ms:g} ms"),
        SloSpec(f"{index}-recall", index, "recall",
                objective=recall,
                description="audited recall@k EWMA"),
        SloSpec(f"{index}-freshness", index, "freshness",
                objective=THRESHOLD_OBJECTIVE, target=freshness_s,
                description=f"mutation backlog younger than "
                            f"{freshness_s:g} s"),
    ]


class SloEngine:
    """Evaluates :class:`SloSpec` rings into budgets and alerts.

    ``service`` (a :class:`~raft_tpu.serve.SearchService`) supplies the
    recall and freshness sources; availability and latency read the
    metrics registry directly, so an engine without a service still
    covers those.  ``start()`` runs the background evaluator;
    :meth:`evaluate_once` is the deterministic entry tests and the
    bench leg drive directly.
    """

    def __init__(self, specs: Sequence[SloSpec] = (), *,
                 service=None,
                 registry: Optional[MetricsRegistry] = None,
                 scale: Optional[float] = None,
                 eval_s: Optional[float] = None,
                 budget_window_s: Optional[float] = None):
        self._registry = registry if registry is not None \
            else default_registry()
        self._scale = scale if scale is not None else _env_scale()
        self._eval_s = (
            eval_s if eval_s is not None else _env_eval_s()
        ) * self._scale
        self._budget_window_s = (
            budget_window_s if budget_window_s is not None
            else _env_budget_window_s()
        ) * self._scale
        self._service = service
        self._lock = threading.Lock()
        maxlen = int(self._budget_window_s / max(self._eval_s, 1e-9)) + 8
        self._maxlen = max(64, min(maxlen, MAX_SAMPLES))
        self._states: Dict[str, _SpecState] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        for spec in specs:
            self.add_spec(spec)
        self._registry.register_provider("slo", self.snapshot)

    # -- spec management -----------------------------------------------------
    def add_spec(self, spec: SloSpec) -> None:
        """Register ``spec`` (replacing a same-named one).  The
        cumulative-counter baseline primes immediately, so history from
        before the spec existed never counts against its budget."""
        state = _SpecState(spec, self._maxlen)
        state.prev_bad, state.prev_total = self._cumulative(spec)
        with self._lock:
            self._states[spec.name] = state

    def remove_spec(self, name: str) -> None:
        with self._lock:
            self._states.pop(name, None)
        for metric, labels in (
            ("raft_tpu_slo_budget_remaining", {"slo": name}),
            ("raft_tpu_slo_burn_rate", {"slo": name}),
            ("raft_tpu_slo_alert", {"slo": name}),
        ):
            self._registry.gauge(metric).remove_matching(**labels)

    def watch_index(self, index: str) -> None:
        """Add the four :func:`default_specs` objectives for ``index``."""
        for spec in default_specs(index):
            self.add_spec(spec)

    def unwatch_index(self, index: str) -> None:
        with self._lock:
            dead = [n for n, s in self._states.items()
                    if s.spec.index == index]
        for name in dead:
            self.remove_spec(name)

    def specs(self) -> List[SloSpec]:
        with self._lock:
            return [s.spec for s in self._states.values()]

    # -- sources -------------------------------------------------------------
    def _cumulative(self, spec: SloSpec
                    ) -> Tuple[Optional[float], Optional[float]]:
        """(cumulative bad, cumulative total) for counter-style kinds;
        (None, None) for gauge-style kinds."""
        if spec.kind == "availability":
            errors = 0.0
            for key, v in self._registry.counter(
                "raft_tpu_serve_errors_total"
            ).collect().items():
                if ("index", spec.index) in key:
                    errors += v
            requests = self._registry.counter(
                "raft_tpu_serve_requests_total"
            ).value(index=spec.index)
            return errors, requests + errors
        if spec.kind == "latency":
            hist = self._registry.histogram(
                "raft_tpu_serve_request_seconds"
            )
            good = 0.0
            total = 0.0
            # bucket_totals, not collect(): collect copies every series'
            # raw reservoir under the lock observe() contends on — at the
            # evaluator's tick rate that stalls the serving hot path
            for key, (bucket_counts, count) in hist.bucket_totals().items():
                if ("index", spec.index) not in key:
                    continue
                total += count
                for i, c in enumerate(bucket_counts):
                    if hist.bucket_edge(i) <= spec.target:
                        good += c
            return total - good, total
        return None, None

    def _gauge_bad_fraction(self, spec: SloSpec) -> Optional[float]:
        """Instantaneous bad fraction for gauge-style kinds, or None when
        the source has no data yet."""
        if spec.kind == "recall":
            auditor = getattr(self._service, "auditor", None)
            if auditor is None:
                return None
            ewma = auditor.recall_ewma(spec.index)
            if ewma is None:
                return None
            return min(1.0, max(0.0, 1.0 - float(ewma)))
        if spec.kind == "freshness":
            service = self._service
            if service is None:
                return None
            try:
                index = service.registry.get(spec.index)
            except KeyError:
                return None
            age_fn = getattr(index, "backlog_age_s", None)
            if age_fn is None:
                return 0.0  # immutable index: never stale
            return 1.0 if float(age_fn()) > spec.target else 0.0
        return None

    # -- evaluation ----------------------------------------------------------
    @traced("slo.evaluate")
    def evaluate_once(self, now: Optional[float] = None
                      ) -> Dict[str, object]:
        """One evaluation tick: sample every spec, update windows,
        budgets, gauges and alert latches; publish ``slo_burn`` edges.
        ``now`` is monotonic-clock seconds (tests pass a synthetic
        clock; production passes nothing)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            states = list(self._states.values())
        report: Dict[str, object] = {}
        for state in states:
            report[state.spec.name] = self._evaluate_spec(state, now)
        return report

    def _evaluate_spec(self, state: _SpecState, now: float
                       ) -> Dict[str, object]:
        spec = state.spec
        # -- sample
        if spec.kind in ("availability", "latency"):
            bad_c, total_c = self._cumulative(spec)
            prev_bad = state.prev_bad if state.prev_bad is not None else 0.0
            prev_total = (
                state.prev_total if state.prev_total is not None else 0.0
            )
            bad = max(0.0, bad_c - prev_bad)
            weight = max(0.0, total_c - prev_total)
            state.prev_bad, state.prev_total = bad_c, total_c
            state.samples.append((now, bad, weight))
        else:
            frac = self._gauge_bad_fraction(spec)
            if frac is not None:
                state.samples.append((now, frac, 1.0))
        if state.first_t is None and state.samples:
            state.first_t = state.samples[0][0]
        budget = max(1e-9, 1.0 - spec.objective)

        def rate(window_s: float) -> float:
            lo = now - window_s
            bad_sum = 0.0
            w_sum = 0.0
            for t, b, w in reversed(state.samples):
                if t < lo:
                    break
                bad_sum += b
                w_sum += w
            return bad_sum / w_sum if w_sum > 0.0 else 0.0

        # -- budget over the (scaled) budget window, prorated by how
        # much of it has actually been observed
        observed = 0.0 if state.first_t is None else now - state.first_t
        span_frac = min(1.0, observed / self._budget_window_s) \
            if self._budget_window_s > 0 else 1.0
        consumed = (rate(self._budget_window_s) / budget) * span_frac
        state.budget_remaining = 1.0 - consumed
        g_budget = self._registry.gauge(
            "raft_tpu_slo_budget_remaining",
            help="error budget left in the rolling window (1 = untouched, "
                 "<= 0 = exhausted)",
        )
        g_budget.set(state.budget_remaining, slo=spec.name)
        g_burn = self._registry.gauge(
            "raft_tpu_slo_burn_rate",
            help="error-budget burn rate per alert window (1.0 = exactly "
                 "on budget)",
        )
        g_alert = self._registry.gauge(
            "raft_tpu_slo_alert",
            help="1 while a burn-rate alert is firing",
        )

        # -- multi-window multi-burn-rate alerts
        burns: Dict[str, Dict[str, float]] = {}
        for policy in ALERT_POLICIES:
            burn_long = rate(policy.long_s * self._scale) / budget
            burn_short = rate(policy.short_s * self._scale) / budget
            g_burn.set(burn_long, slo=spec.name, window=policy.name)
            firing = burn_long > policy.max_burn \
                and burn_short > policy.max_burn
            was = state.fired.get(policy.name, False)
            if firing and not was:
                state.fired[policy.name] = True
                _events.publish(
                    "slo_burn", f"slo_burn_{spec.name}",
                    slo=spec.name, index=spec.index, slo_kind=spec.kind,
                    policy=policy.name, severity=policy.severity,
                    burn_long=burn_long, burn_short=burn_short,
                    threshold=policy.max_burn,
                    budget_remaining=state.budget_remaining,
                )
            elif was and burn_short <= policy.max_burn:
                # the short window recovered: re-arm (and tell the
                # incident manager the story is over)
                state.fired[policy.name] = False
                _events.publish(
                    "slo_burn", f"slo_burn_{spec.name}", recovered=True,
                    slo=spec.name, index=spec.index, policy=policy.name,
                    burn_short=burn_short,
                )
            g_alert.set(
                1.0 if state.fired.get(policy.name, False) else 0.0,
                slo=spec.name, policy=policy.name,
            )
            burns[policy.name] = {
                "long": burn_long, "short": burn_short,
                "threshold": policy.max_burn,
                "firing": state.fired.get(policy.name, False),
            }
        state.burn = burns
        if state.samples:
            _, b, w = state.samples[-1]
            state.sli = 1.0 - (b / w if w > 0 else 0.0)
        return {
            "kind": spec.kind,
            "index": spec.index,
            "objective": spec.objective,
            "sli": state.sli,
            "budget_remaining": state.budget_remaining,
            "burn": burns,
            "samples": len(state.samples),
        }

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        """Run the background evaluator (idempotent)."""
        with self._lock:
            if self._thread is not None:
                return
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="raft-tpu-slo", daemon=True
            )
            thread = self._thread
        thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self._eval_s):
            try:
                self.evaluate_once()
            except Exception:  # noqa: BLE001 — the evaluator must survive
                self._registry.counter(
                    "raft_tpu_slo_eval_errors_total",
                    help="exceptions swallowed in the SLO evaluator",
                ).inc()

    def stop(self) -> None:
        """Stop the evaluator thread and detach the snapshot provider."""
        with self._lock:
            thread, self._thread = self._thread, None
        self._stop.set()
        if thread is not None:
            thread.join(timeout=5.0)
        self._registry.unregister_provider("slo", expected=self.snapshot)

    # -- reading -------------------------------------------------------------
    def budget_remaining(self, name: str) -> Optional[float]:
        with self._lock:
            state = self._states.get(name)
            return state.budget_remaining if state is not None else None

    def health(self) -> Dict[str, List[str]]:
        """``{"exhausted": [spec names], "alerting": [spec names]}`` —
        the slice ``healthz()`` folds into its verdict."""
        with self._lock:
            exhausted = [
                n for n, s in self._states.items()
                if s.budget_remaining <= 0.0
            ]
            alerting = [
                n for n, s in self._states.items()
                if any(s.fired.values())
            ]
        return {"exhausted": exhausted, "alerting": alerting}

    def paging(self) -> List[str]:
        """Spec names with a *page-severity* burn alert currently firing.

        The slice a closed-loop controller should act on: page policies
        (short windows) re-arm as soon as the short window recovers, so
        the signal tracks the incident edge-to-edge.  Ticket-severity
        latches span the long window and would hold a controller in the
        shed state long after the cause cleared.
        """
        page = {p.name for p in ALERT_POLICIES if p.severity == "page"}
        with self._lock:
            return [
                n for n, s in self._states.items()
                if any(s.fired.get(p, False) for p in page)
            ]

    def snapshot(self) -> Dict[str, object]:
        """Provider section for registry snapshots."""
        with self._lock:
            states = list(self._states.values())
        return {
            "scale": self._scale,
            "eval_s": self._eval_s,
            "budget_window_s": self._budget_window_s,
            "specs": {
                s.spec.name: {
                    "kind": s.spec.kind,
                    "index": s.spec.index,
                    "objective": s.spec.objective,
                    "target": s.spec.target,
                    "sli": s.sli,
                    "budget_remaining": s.budget_remaining,
                    "burn": s.burn,
                    "samples": len(s.samples),
                }
                for s in states
            },
        }
