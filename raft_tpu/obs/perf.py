"""Measured perf ledger: per-executable device-time attribution.

``obs/cost.py`` is analytical — compile-time FLOP/byte counts and an
optimistic roofline bound.  This module is the measured half: every
dispatched batch lands one :meth:`PerfLedger.record` keyed by the
executable that ran it, ``(index, backend, bucket, kernel_path,
version)``, accumulating device seconds, dispatches, rows and pad-waste
so the ledger can answer the questions the analytical side cannot:

- *where does device time actually go* — :meth:`PerfLedger.top_hotspots`
  ranks keys by cumulative device seconds, with a **measured** roofline
  utilization (the warmup-registered analytical FLOPs/bytes per dispatch
  divided by measured seconds, against :func:`obs.cost.device_peaks`);
- *what did the Pallas leg actually buy* — ``kernel_path`` is stamped
  live by the routing branches (:mod:`raft_tpu.kernels` thread-local),
  so pallas/xla/filter-fallback legs of the same index separate into
  distinct ledger rows under production traffic, not just frozen bench
  records;
- *did this executable just get slower* — a per-key (hence per-bucket)
  device-time EWMA pair (fast vs slow baseline) publishes a
  ``perf_regression`` bus event when the fast EWMA exceeds
  ``RAFT_TPU_PERF_REGRESSION_X`` times the baseline, debounced per key.
  The bus wiring turns that into a flight dump, a debounced
  :mod:`raft_tpu.obs.profiler` capture, and a correlated incident — the
  evidence chain for "the p99 moved" starts itself.

The hot path gains **zero new clock calls**: the batcher already times
the device stage (and maintains the ``device_busy_s`` interval union);
``record`` only receives those numbers.  ``record`` itself is float math
plus a few registry counter bumps; the EWMA trip check is inline and
only a *tripped* key pays for :meth:`PerfLedger.evaluate` (debounce
check + event publish).

Knobs: ``RAFT_TPU_PERF_LEDGER`` (master switch, default on),
``RAFT_TPU_PERF_EWMA_ALPHA``, ``RAFT_TPU_PERF_REGRESSION_X``,
``RAFT_TPU_PERF_MIN_SAMPLES``, ``RAFT_TPU_PERF_DEBOUNCE_S``,
``RAFT_TPU_PERF_CAPTURE_S``, ``RAFT_TPU_PERF_CAPTURE_DIR``.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

from raft_tpu.core import env as _env
from raft_tpu.core.trace import traced
from raft_tpu.obs import cost as _cost
from raft_tpu.obs.registry import default_registry

#: executable key: (index, backend, bucket, kernel_path, version)
Key = Tuple[str, str, int, str, str]

#: slow-baseline EWMA weight as a fraction of the fast weight — the
#: baseline must move an order of magnitude slower than the detector or
#: a sustained regression drags the baseline up and clears itself
_SLOW_DIV = 8.0


def enabled() -> bool:
    """Master switch (``RAFT_TPU_PERF_LEDGER``).  The batcher samples it
    once at construction so a disabled ledger costs zero per dispatch."""
    return _env.env_bool("RAFT_TPU_PERF_LEDGER", True)


def _env_alpha() -> float:
    try:
        a = _env.env_float("RAFT_TPU_PERF_EWMA_ALPHA", 0.25)
    except ValueError:
        a = 0.25
    return min(max(a, 1e-3), 1.0)


def _env_regression_x() -> float:
    try:
        return max(1.0, _env.env_float("RAFT_TPU_PERF_REGRESSION_X", 1.5))
    except ValueError:
        return 1.5


def _env_min_samples() -> int:
    try:
        return max(1, _env.env_int("RAFT_TPU_PERF_MIN_SAMPLES", 32))
    except ValueError:
        return 32


def _env_debounce_s() -> float:
    try:
        return max(0.0, _env.env_float("RAFT_TPU_PERF_DEBOUNCE_S", 60.0))
    except ValueError:
        return 60.0


class _KeyStats:
    """Accumulated measurements for one executable key."""

    __slots__ = (
        "device_s", "dispatches", "rows", "padded_rows",
        "fast", "slow", "samples", "last_fire_m", "regressions",
    )

    def __init__(self) -> None:
        self.device_s = 0.0
        self.dispatches = 0
        self.rows = 0
        self.padded_rows = 0
        self.fast: Optional[float] = None   # fast device-time EWMA (s)
        self.slow: Optional[float] = None   # slow baseline EWMA (s)
        self.samples = 0
        self.last_fire_m = float("-inf")    # time.monotonic of last event
        self.regressions = 0


class PerfLedger:
    """Measured device-time accounting per executable key.

    One instance normally lives for the process (:func:`default_ledger`);
    tests build private ones.  All methods are thread-safe — the batcher
    worker records, completion threads record (pipelined path), any
    thread snapshots.
    """

    def __init__(
        self,
        *,
        alpha: Optional[float] = None,
        regression_x: Optional[float] = None,
        min_samples: Optional[int] = None,
        debounce_s: Optional[float] = None,
    ):
        self._lock = threading.Lock()
        self._keys: Dict[Key, _KeyStats] = {}
        # analytical per-dispatch cost, keyed (index, bucket): the shapes
        # (hence FLOPs/bytes) are identical across kernel_path/version
        self._costs: Dict[Tuple[str, int], Tuple[float, float]] = {}
        self._alpha = alpha if alpha is not None else _env_alpha()
        self._regression_x = (
            regression_x if regression_x is not None else _env_regression_x()
        )
        self._min_samples = (
            min_samples if min_samples is not None else _env_min_samples()
        )
        self._debounce_s = (
            debounce_s if debounce_s is not None else _env_debounce_s()
        )

    # -- recording ----------------------------------------------------------
    def register_cost(self, index: str, bucket: int, flops: float,
                      bytes_accessed: float) -> None:
        """Attach the analytical per-dispatch cost of one ``(index,
        bucket)`` executable (the batcher's warmup cost accounting calls
        this) so hotspots can report measured FLOP/s, bytes/s and
        roofline utilization."""
        with self._lock:
            self._costs[(str(index), int(bucket))] = (
                float(flops), float(bytes_accessed)
            )

    @traced("perf.record")
    def record(
        self,
        *,
        index: str,
        backend: str,
        bucket: int,
        kernel_path: str,
        version: str,
        device_s: float,
        rows: int,
        padded_rows: int,
    ) -> None:
        """Account one dispatched batch.  ``device_s`` is the batcher's
        existing device-stage measurement — no clock runs here."""
        key: Key = (
            str(index), str(backend), int(bucket), str(kernel_path),
            str(version),
        )
        device_s = float(device_s)
        tripped = False
        with self._lock:
            st = self._keys.get(key)
            if st is None:
                st = self._keys[key] = _KeyStats()
            st.device_s += device_s
            st.dispatches += 1
            st.rows += int(rows)
            st.padded_rows += int(padded_rows)
            st.samples += 1
            if st.fast is None:
                st.fast = st.slow = device_s
            else:
                a = self._alpha
                st.fast += a * (device_s - st.fast)
                # the baseline learns at the detector rate until the key
                # arms, then freezes to the slow rate: a warmup transient
                # (short pipeline-fill samples) must converge into the
                # baseline before the trip check goes live, or every
                # steady workload alarms at its arming sample
                b = a if st.samples < self._min_samples else a / _SLOW_DIV
                st.slow += b * (device_s - st.slow)
            # inline trip pre-check: pure float math, evaluate() (the
            # debounce + publish) runs only for keys that actually trip
            tripped = (
                st.samples >= self._min_samples
                and st.slow is not None
                and st.slow > 0.0
                and st.fast > self._regression_x * st.slow
            )
        reg = default_registry()
        labels = {
            "index": key[0], "backend": key[1], "bucket": str(key[2]),
            "kernel_path": key[3], "version": key[4],
        }
        reg.counter(
            "raft_tpu_perf_device_seconds_total",
            help="measured device seconds per executable key",
        ).inc(device_s, **labels)
        reg.counter(
            "raft_tpu_perf_dispatches_total",
            help="dispatched batches per executable key",
        ).inc(**labels)
        reg.counter(
            "raft_tpu_perf_rows_total",
            help="real rows served per executable key",
        ).inc(int(rows), **labels)
        if tripped:
            self.evaluate(key)

    @traced("perf.evaluate")
    def evaluate(self, key: Key) -> bool:
        """Debounce-check a tripped key and publish ``perf_regression``.

        Returns True when the event was published (once per
        ``RAFT_TPU_PERF_DEBOUNCE_S`` window per key); suppressed trips
        are counted, never silently dropped."""
        now = time.monotonic()
        with self._lock:
            st = self._keys.get(key)
            if st is None:
                return False
            if now - st.last_fire_m < self._debounce_s:
                suppressed = True
            else:
                st.last_fire_m = now
                st.regressions += 1
                suppressed = False
            fast, slow = st.fast, st.slow
        index, backend, bucket, kernel_path, version = key
        if suppressed:
            default_registry().counter(
                "raft_tpu_perf_regressions_suppressed_total",
                help="regression trips suppressed by the per-key debounce",
            ).inc(index=index, bucket=str(bucket))
            return False
        ratio = (fast / slow) if slow else float("inf")
        from raft_tpu.obs import events as _events

        _events.publish(
            "perf_regression", f"perf_regression_{index}",
            index=index, backend=backend, bucket=bucket,
            kernel_path=kernel_path, version=version,
            fast_ms=fast * 1e3, baseline_ms=slow * 1e3,
            ratio=ratio,
        )
        return True

    # -- reading ------------------------------------------------------------
    def top_hotspots(self, n: int = 8) -> List[Dict[str, object]]:
        """Keys ranked by cumulative device seconds, with measured
        throughput and roofline utilization where warmup registered the
        analytical cost.  ``wasted_frac`` is the pad-waste-derived share
        of device time spent on rows nobody asked for (padding rows run
        at the same per-row cost as real ones inside a fixed-shape
        executable)."""
        with self._lock:
            items = [(k, st) for k, st in self._keys.items()]
            costs = dict(self._costs)
        items.sort(key=lambda kv: kv[1].device_s, reverse=True)
        out: List[Dict[str, object]] = []
        for key, st in items[: max(0, int(n))]:
            index, backend, bucket, kernel_path, version = key
            entry: Dict[str, object] = {
                "index": index,
                "backend": backend,
                "bucket": bucket,
                "kernel_path": kernel_path,
                "version": version,
                "device_s": st.device_s,
                "dispatches": st.dispatches,
                "rows": st.rows,
                "padded_rows": st.padded_rows,
                "wasted_frac": (
                    1.0 - st.rows / st.padded_rows
                    if st.padded_rows else None
                ),
                "regressions": st.regressions,
            }
            cost = costs.get((index, bucket))
            if cost is not None and st.device_s > 0:
                flops, nbytes = cost
                entry["flops_per_s"] = flops * st.dispatches / st.device_s
                entry["bytes_per_s"] = nbytes * st.dispatches / st.device_s
                entry["roofline_utilization"] = _cost.roofline_utilization(
                    flops * st.dispatches, nbytes * st.dispatches,
                    st.device_s,
                )
            out.append(entry)
        return out

    def totals(self) -> Dict[str, Dict[str, float]]:
        """Per-index device-second totals (reconciliation surface for
        tests: sums over keys must match the metrics device-stage
        totals)."""
        out: Dict[str, Dict[str, float]] = {}
        with self._lock:
            for (index, _b, _bk, _kp, _v), st in self._keys.items():
                agg = out.setdefault(
                    index, {"device_s": 0.0, "dispatches": 0, "rows": 0}
                )
                agg["device_s"] += st.device_s
                agg["dispatches"] += st.dispatches
                agg["rows"] += st.rows
        return out

    def refresh_gauges(self) -> None:
        """Publish the derived per-key gauges (wasted fraction, roofline
        utilization).  Pull-path work — called from :meth:`snapshot` and
        the service scrape endpoints, never per dispatch."""
        reg = default_registry()
        for h in self.top_hotspots(n=len(self._keys)):
            labels = {
                "index": h["index"], "backend": h["backend"],
                "bucket": str(h["bucket"]),
                "kernel_path": h["kernel_path"],
                "version": h["version"],
            }
            if h["wasted_frac"] is not None:
                reg.gauge(
                    "raft_tpu_perf_wasted_frac",
                    help="fraction of device time spent on padding rows",
                ).set(float(h["wasted_frac"]), **labels)
            util = h.get("roofline_utilization")
            if util is not None:
                reg.gauge(
                    "raft_tpu_perf_roofline_utilization",
                    help="measured FLOP/s over the roofline-attainable "
                         "rate per executable key",
                ).set(float(util), **labels)

    def health_slice(self) -> Dict[str, object]:
        """The slice :func:`raft_tpu.obs.health.perf_check` folds into
        the health report: keys whose regression fired within the
        current debounce window (i.e. an un-cleared regression)."""
        now = time.monotonic()
        active = []
        with self._lock:
            for key, st in self._keys.items():
                if now - st.last_fire_m < self._debounce_s:
                    index, _backend, bucket, kernel_path, _v = key
                    active.append(f"{index}/b{bucket}/{kernel_path}")
        return {"active_regressions": sorted(active)}

    def snapshot(self) -> Dict[str, object]:
        """Provider section for ``obs.snapshot()["perf"]`` (JSON-safe)."""
        self.refresh_gauges()
        with self._lock:
            n_keys = len(self._keys)
            total_device_s = sum(st.device_s for st in self._keys.values())
            total_dispatches = sum(
                st.dispatches for st in self._keys.values()
            )
            regressions = sum(st.regressions for st in self._keys.values())
        return {
            "enabled": enabled(),
            "keys": n_keys,
            "device_s": total_device_s,
            "dispatches": total_dispatches,
            "regressions": regressions,
            "hotspots": self.top_hotspots(),
            **self.health_slice(),
        }


# ---------------------------------------------------------------------------
# the process-wide default ledger + bus wiring

_default_lock = threading.Lock()
_default: Optional[PerfLedger] = None


def default_ledger() -> PerfLedger:
    """The process-wide ledger (created against current env knobs)."""
    global _default
    with _default_lock:
        if _default is None:
            _default = PerfLedger()
        return _default


def ledger_snapshot() -> Dict[str, object]:
    """Provider section for registry snapshots."""
    return default_ledger().snapshot()


def _capture_dir() -> str:
    d = _env.env_str("RAFT_TPU_PERF_CAPTURE_DIR")
    if d:
        return d
    from raft_tpu.obs import flight as _flight

    return _flight._env_dir()


def _on_bus_event(event) -> None:
    """``perf_regression`` subscriber: kick a debounced async profiler
    capture.  Installed between the flight dumper and the incident
    manager, so by the time the incident manager handles the same event
    both the flight dump *and* the capture are fresh enough to attach."""
    if event.recovered:
        return
    try:
        capture_s = _env.env_float("RAFT_TPU_PERF_CAPTURE_S", 1.0)
    except ValueError:
        capture_s = 1.0
    if capture_s <= 0:
        return
    from raft_tpu.obs import profiler as _profiler

    _profiler.capture_async(
        _capture_dir(), duration_s=capture_s, reason=event.reason,
    )


def install_bus_subscriber(bus) -> None:
    """Wire the regression→capture hook into ``bus`` (called by
    ``events._install_default_subscribers``)."""
    bus.subscribe(
        _on_bus_event,
        kinds=frozenset({"perf_regression"}),
        name="perf_capture",
    )


def _on_bus_reset() -> None:
    """Drop the default ledger (test/REPL hygiene — the next
    :func:`default_ledger` re-reads the env knobs)."""
    global _default
    import sys

    with _default_lock:
        _default = None
    profiler = sys.modules.get("raft_tpu.obs.profiler")
    if profiler is not None:
        profiler.reset()
