"""Correlated incident timelines over the obs event bus.

A 3am page never arrives alone: the recall EWMA crosses its floor, the
next ``healthz()`` flips UNHEALTHY, the flight recorder writes a dump —
three symptoms, one cause.  This module turns that burst into **one**
:class:`Incident`: a subscriber on :mod:`raft_tpu.obs.events` groups
events that land within a correlation window
(``RAFT_TPU_INCIDENT_WINDOW_S``) into a single ordered timeline, stamped
with the operational context at open and close (registry versions,
compactor state — whatever sources the service registers) and the
flight-dump artifact the same trigger produced.

Lifecycle: a *trigger* event (``events.TRIGGER_KINDS``) with no fresh
open incident opens one (bounded table, ``RAFT_TPU_INCIDENT_MAX_OPEN``;
overflow is counted, not queued — an incident flood is itself one
incident).  Context events (``registry_swap``,
``compaction_{trigger,promote}``) only annotate an already-open
timeline.  The overload kinds split the same way: ``admission_shed``
and ``degraded_enter`` are triggers (requests were rejected / effort
was cut — each opens or joins an incident, so every shed decision is
inside a correlated timeline), while ``degraded_exit`` and
``hedge_fired`` only annotate (recovery and routine tail-trimming are
evidence, not pages).  Recovery edges (``recovered=True``) stamp the incident;
sustained quiet (``RAFT_TPU_INCIDENT_AUTOCLOSE_S`` with no correlated
event) closes it — resolution ``"recovered"`` when a recovery edge was
seen, ``"quiet"`` otherwise.

Closed incidents export ``incident_<id>_<reason>.json`` plus a
Chrome-trace-event file into ``RAFT_TPU_INCIDENT_DIR`` (default: the
flight-dump directory), so one Perfetto load shows the incident slice,
its events, and the flight recorder's batch/request timelines on the
same clock (everything is stamped with ``time.perf_counter``).
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from raft_tpu.core import env as _env
from raft_tpu.core.trace import traced
from raft_tpu.obs import flight as _flight
from raft_tpu.obs import profiler as _profiler
from raft_tpu.obs import spans as _spans
from raft_tpu.obs.events import Event, EventBus, TRIGGER_KINDS
from raft_tpu.obs.registry import default_registry

#: default correlation window (seconds) — events this close are one story
DEFAULT_WINDOW_S = 5.0

#: default sustained-quiet span (seconds) before an incident auto-closes
DEFAULT_AUTOCLOSE_S = 30.0

#: default cap on concurrently open incidents
DEFAULT_MAX_OPEN = 8

#: closed incidents retained in memory for snapshots
CLOSED_KEEP = 32


def _env_window_s() -> float:
    try:
        return max(0.0, _env.env_float(
            "RAFT_TPU_INCIDENT_WINDOW_S", DEFAULT_WINDOW_S
        ))
    except ValueError:
        return DEFAULT_WINDOW_S


def _env_autoclose_s() -> float:
    try:
        return max(0.0, _env.env_float(
            "RAFT_TPU_INCIDENT_AUTOCLOSE_S", DEFAULT_AUTOCLOSE_S
        ))
    except ValueError:
        return DEFAULT_AUTOCLOSE_S


def _env_max_open() -> int:
    try:
        return max(1, _env.env_int(
            "RAFT_TPU_INCIDENT_MAX_OPEN", DEFAULT_MAX_OPEN
        ))
    except ValueError:
        return DEFAULT_MAX_OPEN


def _env_dir() -> str:
    return _env.env_str("RAFT_TPU_INCIDENT_DIR") or _flight._env_dir()


class Incident:
    """One correlated incident: trigger, ordered timeline, bracketing
    context.  Mutated only by its owning :class:`IncidentManager`."""

    def __init__(self, iid: int, trigger: Event,
                 context: Optional[Dict[str, object]]):
        self.id = iid
        self.status = "open"
        self.trigger = trigger.to_dict()
        self.reason = trigger.reason
        self.opened_unix = trigger.unix_time
        self.opened_t = trigger.t
        self.closed_unix: Optional[float] = None
        self.closed_t: Optional[float] = None
        self.recovered_unix: Optional[float] = None
        self.resolution: Optional[str] = None
        self.timeline: List[Dict[str, object]] = [trigger.to_dict()]
        self.context_open = context
        self.context_close: Optional[Dict[str, object]] = None
        self.flight: Optional[Dict[str, object]] = None
        self.capture: Optional[Dict[str, object]] = None
        self.archive: Optional[Dict[str, object]] = None
        self.last_event_mono = time.monotonic()
        self.last_event_t = trigger.t

    def to_dict(self) -> Dict[str, object]:
        return {
            "schema": "raft_tpu.incident",
            "id": self.id,
            "status": self.status,
            "reason": self.reason,
            "trigger": self.trigger,
            "opened_unix": self.opened_unix,
            "closed_unix": self.closed_unix,
            "recovered_unix": self.recovered_unix,
            "resolution": self.resolution,
            "events": len(self.timeline),
            "timeline": list(self.timeline),
            "context_open": self.context_open,
            "context_close": self.context_close,
            "flight": self.flight,
            "capture": self.capture,
            "archive": self.archive,
        }

    def summary(self) -> Dict[str, object]:
        return {
            "id": self.id,
            "status": self.status,
            "reason": self.reason,
            "opened_unix": self.opened_unix,
            "closed_unix": self.closed_unix,
            "resolution": self.resolution,
            "events": len(self.timeline),
            "flight": (self.flight or {}).get("path"),
            "capture": (self.capture or {}).get("path"),
            "archive": (self.archive or {}).get("path"),
        }

    def trace_events(self) -> List[Dict[str, object]]:
        """Chrome trace events: one "X" slice spanning the incident on
        its own track plus an instant per timeline entry — loads next to
        the flight dump's batch/request tracks (same perf_counter
        clock)."""
        end_t = self.closed_t if self.closed_t is not None \
            else self.last_event_t
        events: List[Dict[str, object]] = [
            {"ph": "M", "pid": 1, "tid": 3, "name": "thread_name",
             "args": {"name": "incidents"}},
            {"ph": "X", "pid": 1, "tid": 3,
             "name": f"incident {self.id} {self.reason}",
             "ts": self.opened_t * 1e6,
             "dur": max(0.0, end_t - self.opened_t) * 1e6,
             "args": {"resolution": self.resolution,
                      "events": len(self.timeline)}},
        ]
        for entry in self.timeline:
            events.append({
                "ph": "i", "pid": 1, "tid": 3, "s": "p",
                "name": str(entry.get("reason", entry.get("kind"))),
                "ts": float(entry.get("t", self.opened_t)) * 1e6,
                "args": {k: v for k, v in entry.items() if k != "t"},
            })
        return events


class IncidentManager:
    """Bounded open-incident table fed by an :class:`EventBus`
    subscription.  One instance normally lives for the whole process
    (installed by ``events.default_bus()``); tests build private ones
    against private buses."""

    def __init__(self, bus: Optional[EventBus] = None, *,
                 window_s: Optional[float] = None,
                 autoclose_s: Optional[float] = None,
                 max_open: Optional[int] = None):
        self._lock = threading.Lock()
        self._window_s = window_s if window_s is not None else _env_window_s()
        self._autoclose_s = (
            autoclose_s if autoclose_s is not None else _env_autoclose_s()
        )
        self._max_open = max_open if max_open is not None else _env_max_open()
        self._open: List[Incident] = []
        self._closed: deque = deque(maxlen=CLOSED_KEEP)
        self._iid = itertools.count(1)
        self._opened_total = 0
        self._dropped = 0
        self._context_sources: Dict[str, Callable[[], Dict[str, object]]] = {}
        self._sub = None
        if bus is not None:
            self._sub = bus.subscribe(self.handle_event, name="incidents")

    # -- context sources -----------------------------------------------------
    def add_context_source(
        self, name: str, fn: Callable[[], Dict[str, object]]
    ) -> None:
        """Register a callable snapshotted into ``context_open`` /
        ``context_close`` (e.g. the service's registry versions and
        compactor state).  Sources must be cheap and must not publish."""
        with self._lock:
            self._context_sources[name] = fn

    def remove_context_source(self, name: str) -> None:
        with self._lock:
            self._context_sources.pop(name, None)

    def _capture_context(self) -> Dict[str, object]:
        # Runs WITHOUT self._lock: sources reach into service/registry/
        # compactor locks, and holding ours underneath would hand the
        # LOCKORDER checker a real cycle.
        with self._lock:
            sources = dict(self._context_sources)
        out: Dict[str, object] = {}
        for name, fn in sources.items():
            try:
                out[name] = fn()
            except Exception as exc:  # noqa: BLE001 — context is best-effort
                out[name] = {"error": repr(exc)}
        return out

    # -- ingestion -----------------------------------------------------------
    @traced("incidents.ingest")
    def handle_event(self, event: Event) -> None:
        """Bus subscriber: correlate ``event`` into an open incident or
        open a new one.  Runs on the publisher's thread; everything
        outside the lock windows is allowed to be slow-ish (context
        capture, export) because events are rare by construction."""
        now = time.monotonic()
        is_trigger = event.kind in TRIGGER_KINDS and not event.recovered
        context = self._capture_context() if is_trigger else None
        dump = _flight.last_dump()
        capture = _profiler.last_capture()
        opened = None
        dropped = False
        with self._lock:
            to_close = self._sweep_locked(now)
            target = self._match_locked(now)
            if target is not None:
                self._append_locked(target, event, dump, capture, now)
            elif is_trigger:
                if len(self._open) >= self._max_open:
                    self._dropped += 1
                    dropped = True
                else:
                    opened = Incident(next(self._iid), event, context)
                    self._attach_flight_locked(opened, event, dump)
                    self._attach_capture_locked(opened, event, capture)
                    self._open.append(opened)
                    self._opened_total += 1
            # a context/recovery event with no fresh incident: not a story
            n_open = len(self._open)
        if opened is not None:
            default_registry().counter(
                "raft_tpu_incidents_total", help="incidents opened",
            ).inc(kind=event.kind)
        if dropped:
            default_registry().counter(
                "raft_tpu_incidents_dropped_total",
                help="trigger events ignored: open-incident table full",
            ).inc()
        default_registry().gauge(
            "raft_tpu_incidents_open", help="currently open incidents",
        ).set(n_open)
        self._finalize_closed(to_close)

    def _match_locked(self, now: float) -> Optional[Incident]:
        best = None
        for inc in self._open:
            if now - inc.last_event_mono <= self._window_s:
                if best is None or inc.last_event_mono > best.last_event_mono:
                    best = inc
        return best

    def _append_locked(self, inc: Incident, event: Event,
                       dump: Optional[Dict[str, object]],
                       capture: Optional[Dict[str, object]],
                       now: float) -> None:
        inc.timeline.append(event.to_dict())
        inc.last_event_mono = now
        inc.last_event_t = event.t
        if event.recovered and inc.recovered_unix is None:
            inc.recovered_unix = event.unix_time
        if event.kind == "explain_dump" and inc.archive is None:
            # the query-archive subscriber runs after us in bus order and
            # publishes this context event right after writing the dump,
            # so the artifact is this incident's by construction
            inc.archive = {
                "path": event.fields.get("path"),
                "reason": event.reason,
                "unix_time": event.unix_time,
            }
        self._attach_flight_locked(inc, event, dump)
        self._attach_capture_locked(inc, event, capture)

    def _attach_flight_locked(self, inc: Incident, event: Event,
                              dump: Optional[Dict[str, object]]) -> None:
        # Attach only a *fresh* dump (the flight subscriber runs before
        # us in bus order, so a dump this event caused already exists);
        # a stale artifact from a past incident is not this one's.
        if dump is None:
            return
        if abs(event.unix_time - float(dump["unix_time"])) > \
                max(self._window_s, 1.0):
            return
        if inc.flight is not None and inc.flight.get("path") == dump["path"]:
            return
        inc.flight = dump
        inc.timeline.append({
            "kind": "flight_dump",
            "reason": dump.get("reason"),
            "t": event.t,
            "unix_time": dump.get("unix_time"),
            "path": dump.get("path"),
            "trace_path": dump.get("trace_path"),
        })

    def _attach_capture_locked(self, inc: Incident, event: Event,
                               capture: Optional[Dict[str, object]]) -> None:
        # Same contract as flight dumps: the perf auto-capture subscriber
        # runs before us in bus order, so a capture this event triggered
        # already started; attach only a fresh one, once.
        if capture is None:
            return
        if abs(event.unix_time - float(capture["unix_time"])) > \
                max(self._window_s, 1.0):
            return
        if inc.capture is not None and \
                inc.capture.get("path") == capture["path"]:
            return
        inc.capture = capture
        inc.timeline.append({
            "kind": "profile_capture",
            "reason": capture.get("reason"),
            "t": event.t,
            "unix_time": capture.get("unix_time"),
            "path": capture.get("path"),
            "duration_s": capture.get("duration_s"),
        })

    # -- closing -------------------------------------------------------------
    def _sweep_locked(self, now: float) -> List[Incident]:
        quiet = [
            inc for inc in self._open
            if now - inc.last_event_mono > self._autoclose_s
        ]
        for inc in quiet:
            self._open.remove(inc)
            inc.status = "closed"
            inc.closed_unix = time.time()
            inc.closed_t = time.perf_counter()
            inc.resolution = (
                "recovered" if inc.recovered_unix is not None else "quiet"
            )
            self._closed.append(inc)
        return quiet

    def poll(self, now: Optional[float] = None) -> List[Incident]:
        """Close incidents whose quiet span exceeded the auto-close
        window; returns them.  Called from ``handle_event`` and
        ``snapshot`` automatically; tests pass a synthetic ``now``
        (monotonic-clock domain) instead of sleeping."""
        now = time.monotonic() if now is None else now
        with self._lock:
            to_close = self._sweep_locked(now)
            n_open = len(self._open)
        if to_close:
            default_registry().gauge(
                "raft_tpu_incidents_open", help="currently open incidents",
            ).set(n_open)
        self._finalize_closed(to_close)
        return to_close

    def _finalize_closed(self, closed: List[Incident]) -> None:
        for inc in closed:
            inc.context_close = self._capture_context()
            self._export(inc)

    def _export(self, inc: Incident) -> None:
        """Write ``incident_<id>_<reason>.json`` + ``.trace.json``.
        Best-effort and gated like flight dumps: disabled obs writes
        nothing."""
        if not _spans.enabled():
            return
        try:
            directory = _env_dir()
            os.makedirs(directory, exist_ok=True)
            stem = f"incident_{inc.id:04d}_{inc.reason}"
            path = os.path.join(directory, stem + ".json")
            with open(path, "w") as f:
                json.dump(inc.to_dict(), f, indent=2, default=str)
            with open(os.path.join(directory, stem + ".trace.json"),
                      "w") as f:
                json.dump({"traceEvents": inc.trace_events()}, f,
                          default=str)
            default_registry().counter(
                "raft_tpu_incidents_exported_total",
                help="closed-incident artifacts written",
            ).inc()
        except Exception:  # noqa: BLE001 — incident paths must not fail
            pass

    # -- reading -------------------------------------------------------------
    def open_incidents(self) -> List[Incident]:
        self.poll()
        with self._lock:
            return list(self._open)

    def closed_incidents(self) -> List[Incident]:
        with self._lock:
            return list(self._closed)

    def snapshot(self) -> Dict[str, object]:
        """Provider section for registry snapshots."""
        self.poll()
        with self._lock:
            return {
                "open": [inc.summary() for inc in self._open],
                "recent_closed": [inc.summary() for inc in self._closed],
                "opened_total": self._opened_total,
                "dropped": self._dropped,
                "window_s": self._window_s,
                "autoclose_s": self._autoclose_s,
            }


# ---------------------------------------------------------------------------
# the process-wide default manager

_default_lock = threading.Lock()
_default: Optional[IncidentManager] = None


def install(bus: Optional[EventBus] = None) -> IncidentManager:
    """Create (once) the process-wide manager subscribed to ``bus`` and
    register its ``incidents`` snapshot provider.  Called automatically
    by ``events.default_bus()``."""
    global _default
    if bus is None:
        # resolve BEFORE taking our lock: creating the default bus runs
        # _install_default_subscribers, which re-enters this function
        # (with the bus this time) — holding _default_lock across that
        # call chain would self-deadlock
        from raft_tpu.obs import events as _events

        bus = _events.default_bus()
    with _default_lock:
        if _default is None:
            _default = IncidentManager(bus)
        mgr = _default
    default_registry().register_provider("incidents", mgr.snapshot)
    return mgr


def default_manager() -> IncidentManager:
    """The process-wide manager (creating the default bus if needed)."""
    from raft_tpu.obs import events as _events

    bus = _events.default_bus()  # first creation runs install() itself
    with _default_lock:
        if _default is not None:
            return _default
    # reset() without events.reset(): the bus survived but the manager
    # (and its subscription) didn't — re-attach to the live bus
    return install(bus)


def incidents_snapshot() -> Dict[str, object]:
    """Provider section for registry snapshots."""
    return default_manager().snapshot()


def _on_bus_reset() -> None:
    """Called by ``events.reset()``: the bus (and our subscription) is
    gone, so drop the manager; the next ``default_bus()`` rebuilds both
    against fresh env knobs."""
    global _default
    with _default_lock:
        mgr, _default = _default, None
    if mgr is not None:
        if mgr._sub is not None:
            # standalone reset(): the bus may still be live — without
            # this the old manager keeps receiving events as a zombie
            mgr._sub.unsubscribe()
        default_registry().unregister_provider(
            "incidents", expected=mgr.snapshot
        )


def reset() -> None:
    """Drop the default manager (tests)."""
    _on_bus_reset()
