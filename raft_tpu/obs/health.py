"""Aggregated health verdicts: one answer to "should this replica serve?".

The registry holds dozens of series; a load balancer needs three states.
This module folds the signals the serve stack already produces — warmup
state, hot-path recompiles, queue depth, audited recall, device memory
headroom — into per-index and overall ``OK`` / ``DEGRADED`` /
``UNHEALTHY`` verdicts, published as the ``raft_tpu_health`` gauge
(0/1/2) and returned as one JSON-safe report from
``SearchService.healthz()``.

Verdict semantics follow the k8s probe convention the names suggest:
``readyz`` (traffic gate) fails while warming or UNHEALTHY; ``healthz``
(liveness/diagnostics) always answers, carrying the per-check detail so
the *reason* for a red verdict is in the same payload as the verdict.

The thresholds are deliberately simple and documented constants — the
point is an actionable default, not a tunable anomaly detector:

- any hot-path recompile after warmup is DEGRADED; ``COMPILE_STORM`` of
  them is UNHEALTHY (the latency path is paying seconds-long compiles);
- queue depth beyond ``QUEUE_DEGRADED_FACTOR``×max_batch is DEGRADED
  (coalescing has fallen behind arrivals), beyond
  ``QUEUE_UNHEALTHY_FACTOR``× is UNHEALTHY;
- audited recall EWMA below the auditor's threshold is DEGRADED, below
  half of it UNHEALTHY;
- device memory above ``MEM_DEGRADED_FRAC`` of the limit is DEGRADED,
  above ``MEM_UNHEALTHY_FRAC`` UNHEALTHY (backends without
  ``memory_stats`` report the check as unknown → OK).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, Optional

from raft_tpu.obs import events, flight
from raft_tpu.obs.registry import MetricsRegistry, default_registry

OK = "OK"
DEGRADED = "DEGRADED"
UNHEALTHY = "UNHEALTHY"

#: gauge encoding (and severity order) of the verdicts
VERDICT_VALUES = {OK: 0, DEGRADED: 1, UNHEALTHY: 2}

COMPILE_STORM = 5            # hot-path recompiles → UNHEALTHY at this many
QUEUE_DEGRADED_FACTOR = 4    # queue depth in units of max_batch
QUEUE_UNHEALTHY_FACTOR = 16
MEM_DEGRADED_FRAC = 0.90
MEM_UNHEALTHY_FRAC = 0.98


def worst(*verdicts: str) -> str:
    return max(verdicts, key=lambda v: VERDICT_VALUES[v], default=OK)


@dataclass
class IndexProbe:
    """Raw signals for one served index, gathered by the service."""

    warm: bool
    recompiles: int
    queue_depth: int
    max_batch: int
    pipeline_depth: int = 1                 # in-flight window bound (1=serial)
    inflight: int = 0                       # device batches currently in flight
    recall_ewma: Optional[float] = None     # None: auditor off / no audits yet
    recall_threshold: Optional[float] = None
    # compaction signals (None throughout: no compactor attached)
    compaction_backlog: Optional[int] = None   # pending deletes + side rows
    compaction_trigger: Optional[int] = None   # rows at which a pass fires
    compaction_last_abort: Optional[str] = None  # unresolved abort reason
    # overload actuators (None: no admission controller / degraded manager)
    admission_level: Optional[int] = None      # current shed pressure level
    degraded_level: Optional[int] = None       # current reduced-effort level
    # closed-loop autotuner (None: no autotuner attached)
    autotune_level: Optional[int] = None       # controller's effort level
    autotune_pinned_min: bool = False          # burning with no effort left


def _check(status: str, detail: str) -> Dict[str, str]:
    return {"status": status, "detail": detail}


def index_health(probe: IndexProbe) -> Dict[str, object]:
    """Fold one index's probe into {"status", "checks": {...}}."""
    checks: Dict[str, Dict[str, str]] = {}

    checks["warmup"] = (
        _check(OK, "bucket ladder compiled")
        if probe.warm
        else _check(DEGRADED, "warmup not run; first queries will compile")
    )

    if probe.recompiles >= COMPILE_STORM:
        checks["compiles"] = _check(
            UNHEALTHY,
            f"{probe.recompiles} hot-path recompiles (compile storm)",
        )
    elif probe.recompiles > 0:
        checks["compiles"] = _check(
            DEGRADED, f"{probe.recompiles} hot-path recompiles after warmup"
        )
    else:
        checks["compiles"] = _check(OK, "0 recompiles after warmup")

    depth, cap = probe.queue_depth, max(probe.max_batch, 1)
    if depth > QUEUE_UNHEALTHY_FACTOR * cap:
        checks["queue"] = _check(
            UNHEALTHY, f"queue depth {depth} >> max_batch {cap}"
        )
    elif depth > QUEUE_DEGRADED_FACTOR * cap:
        checks["queue"] = _check(
            DEGRADED, f"queue depth {depth} > {QUEUE_DEGRADED_FACTOR}x max_batch"
        )
    else:
        checks["queue"] = _check(OK, f"queue depth {depth}")

    # the pipeline's one invariant: in-flight batches never exceed the
    # configured window.  An overrun means the semaphore bound broke —
    # live device memory is no longer bounded — which is a bug, not load.
    if probe.inflight > probe.pipeline_depth:
        checks["pipeline"] = _check(
            UNHEALTHY,
            f"{probe.inflight} batches in flight > pipeline_depth "
            f"{probe.pipeline_depth} (window invariant broken)",
        )
    else:
        checks["pipeline"] = _check(
            OK,
            f"in-flight {probe.inflight} / depth {probe.pipeline_depth}",
        )

    if probe.recall_ewma is None or probe.recall_threshold is None:
        checks["recall"] = _check(OK, "no audited recall yet")
    elif probe.recall_ewma < probe.recall_threshold * 0.5:
        checks["recall"] = _check(
            UNHEALTHY,
            f"recall ewma {probe.recall_ewma:.3f} < half of threshold "
            f"{probe.recall_threshold:.3f}",
        )
    elif probe.recall_ewma < probe.recall_threshold:
        checks["recall"] = _check(
            DEGRADED,
            f"recall ewma {probe.recall_ewma:.3f} < threshold "
            f"{probe.recall_threshold:.3f}",
        )
    else:
        checks["recall"] = _check(
            OK, f"recall ewma {probe.recall_ewma:.3f}"
        )

    # compaction: an unresolved abort means maintenance is wedged (the
    # backlog keeps growing until an operator looks), and a backlog far
    # past the trigger means the compactor cannot keep up with churn —
    # both are DEGRADED, never UNHEALTHY: serving itself still answers.
    if probe.compaction_backlog is None:
        checks["compaction"] = _check(OK, "no compactor attached")
    elif probe.compaction_last_abort:
        checks["compaction"] = _check(
            DEGRADED,
            f"last compaction aborted ({probe.compaction_last_abort}); "
            f"backlog {probe.compaction_backlog}",
        )
    elif (
        probe.compaction_trigger
        and probe.compaction_backlog
        > QUEUE_DEGRADED_FACTOR * probe.compaction_trigger
    ):
        checks["compaction"] = _check(
            DEGRADED,
            f"compaction backlog {probe.compaction_backlog} >> trigger "
            f"{probe.compaction_trigger} (compactor falling behind)",
        )
    else:
        checks["compaction"] = _check(
            OK, f"compaction backlog {probe.compaction_backlog}"
        )

    # overload: a non-zero actuator level is DEGRADED by design — the
    # service is *choosing* reduced work (shedding or cheaper search) to
    # protect p0 latency.  Never UNHEALTHY: that's what the actuators
    # exist to prevent, and an UNHEALTHY verdict would pull the replica
    # from rotation and dump its load on the others mid-overload.
    if probe.admission_level is None and probe.degraded_level is None:
        checks["overload"] = _check(OK, "no overload controller attached")
    elif (probe.admission_level or 0) or (probe.degraded_level or 0):
        checks["overload"] = _check(
            DEGRADED,
            f"shedding at level {probe.admission_level or 0}, "
            f"degraded search level {probe.degraded_level or 0}",
        )
    else:
        checks["overload"] = _check(OK, "no pressure; full-effort search")

    # autotuner: like overload, reduced effort is DEGRADED by design and
    # never UNHEALTHY — the controller is trading recall headroom for
    # latency on purpose.  Pinned at minimum effort is the alarming
    # shape: the latency budget is still burning and the ladder has
    # nothing left to shed, so only an operator (capacity) can help.
    if probe.autotune_level is None:
        checks["autotune"] = _check(OK, "no autotuner attached")
    elif probe.autotune_pinned_min:
        checks["autotune"] = _check(
            DEGRADED,
            f"pinned at minimum effort (level {probe.autotune_level}) "
            f"with the latency budget still burning",
        )
    elif probe.autotune_level > 0:
        checks["autotune"] = _check(
            DEGRADED,
            f"autotuned to effort level {probe.autotune_level} "
            f"(trading recall margin for QPS/latency)",
        )
    else:
        checks["autotune"] = _check(OK, "autotuner at full effort")

    status = worst(*(c["status"] for c in checks.values()))
    return {"status": status, "checks": checks}


def device_memory_check() -> Dict[str, str]:
    """Headroom on device 0; unknown (OK) when the backend won't say."""
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats()
    except Exception:
        stats = None
    if not stats:
        return _check(OK, "memory stats unavailable on this backend")
    used = stats.get("bytes_in_use")
    limit = stats.get("bytes_limit") or stats.get("bytes_reservable_limit")
    if not used or not limit:
        return _check(OK, "memory stats incomplete on this backend")
    frac = used / limit
    detail = f"{used / 2**20:.0f}MiB / {limit / 2**20:.0f}MiB ({frac:.0%})"
    if frac > MEM_UNHEALTHY_FRAC:
        return _check(UNHEALTHY, "device memory exhausted: " + detail)
    if frac > MEM_DEGRADED_FRAC:
        return _check(DEGRADED, "device memory pressure: " + detail)
    return _check(OK, detail)


# previous overall verdict, for edge detection: the flight recorder dumps
# on the *transition* into UNHEALTHY, not on every red healthz() poll
_transition_lock = threading.Lock()
_prev_overall: Optional[str] = None


def reset_transitions() -> None:
    """Forget the last seen overall verdict (test isolation)."""
    global _prev_overall
    with _transition_lock:
        _prev_overall = None


def slo_check(slo_health: Optional[Dict[str, object]]) -> Dict[str, object]:
    """Fold an :meth:`~raft_tpu.obs.slo.SloEngine.health` slice into a
    health check: an exhausted error budget is DEGRADED — serving still
    works, but the operator contract is broken and releases should
    freeze until the budget window rolls."""
    if not slo_health:
        return _check(OK, "no SLOs configured")
    exhausted = list(slo_health.get("exhausted") or ())
    alerting = list(slo_health.get("alerting") or ())
    if exhausted:
        return _check(
            DEGRADED,
            "error budget exhausted: " + ", ".join(sorted(exhausted)),
        )
    if alerting:
        return _check(
            OK, "burn-rate alert firing: " + ", ".join(sorted(alerting))
        )
    return _check(OK, "budgets healthy")


def perf_check(perf: Optional[Dict[str, object]]) -> Dict[str, object]:
    """Fold a :meth:`~raft_tpu.obs.perf.PerfLedger.health_slice` into a
    health check: a device-time regression still inside its debounce
    window is DEGRADED — the executable answers, but slower than its own
    baseline, and the auto-captured profile is waiting to be read."""
    if not perf:
        return _check(OK, "perf ledger off or no dispatches yet")
    active = list(perf.get("active_regressions") or ())
    if active:
        return _check(
            DEGRADED,
            "device-time regression on: " + ", ".join(sorted(active)),
        )
    return _check(OK, "no active device-time regressions")


def budget_check(snapshot: Dict[str, object]) -> Dict[str, object]:
    """Fold a :meth:`raft_tpu.store.budget.MemoryBudget.snapshot` into a
    health check: a near-fully-reserved page budget is DEGRADED — the
    next pagination or page admission will raise ``BudgetExceeded``, so
    the operator hears about the pressure *before* the loud failure."""
    limit = float(snapshot.get("limit_bytes", 0) or 0)
    reserved = float(snapshot.get("reserved_bytes", 0) or 0)
    util = reserved / limit if limit else 0.0
    status = DEGRADED if util >= 0.98 else OK
    out = _check(
        status,
        f"page budget {reserved:.0f}/{limit:.0f}B reserved "
        f"({100.0 * util:.1f}%)",
    )
    out["snapshot"] = dict(snapshot)
    return out


def build_report(
    probes: Dict[str, IndexProbe],
    registry: Optional[MetricsRegistry] = None,
    slo: Optional[Dict[str, object]] = None,
    perf: Optional[Dict[str, object]] = None,
    budget: Optional[Dict[str, object]] = None,
) -> Dict[str, object]:
    """Assemble the service-wide report and publish ``raft_tpu_health``.

    One gauge series per index plus ``index=overall`` — the overall
    verdict also folds in the device memory check (a property of the
    process, not of any one index) and, when ``slo`` (an
    ``SloEngine.health()`` slice) is passed, the error-budget check.  A
    transition *into* UNHEALTHY publishes a ``health_edge`` event on the
    obs bus (whose flight subscriber dumps the ring, debounced), the
    transition back out publishes the recovery edge, and the report's
    ``flight`` key carries the most recent dump's paths so the healthz
    payload that announces the incident also says where the evidence is.
    """
    global _prev_overall
    reg = registry if registry is not None else default_registry()
    gauge = reg.gauge(
        "raft_tpu_health",
        help="serving health verdict (0=OK, 1=DEGRADED, 2=UNHEALTHY)",
    )
    indexes: Dict[str, object] = {}
    statuses = []
    for name, probe in probes.items():
        rep = index_health(probe)
        indexes[name] = rep
        statuses.append(rep["status"])
        gauge.set(VERDICT_VALUES[rep["status"]], index=name)
    mem = device_memory_check()
    slo_c = slo_check(slo) if slo is not None else None
    if slo_c is not None:
        statuses.append(slo_c["status"])
    perf_c = perf_check(perf) if perf is not None else None
    if perf_c is not None:
        statuses.append(perf_c["status"])
    budget_c = budget_check(budget) if budget is not None else None
    if budget_c is not None:
        statuses.append(budget_c["status"])
    overall = worst(mem["status"], *statuses)
    gauge.set(VERDICT_VALUES[overall], index="overall")
    with _transition_lock:
        went_unhealthy = overall == UNHEALTHY and _prev_overall != UNHEALTHY
        recovered = _prev_overall == UNHEALTHY and overall != UNHEALTHY
        _prev_overall = overall
    if went_unhealthy:
        events.publish(
            "health_edge", "health_unhealthy",
            status=overall,
            indexes={n: r["status"] for n, r in indexes.items()},
        )
    elif recovered:
        events.publish(
            "health_edge", "health_recovered", recovered=True,
            status=overall,
        )
    report = {
        "status": overall,
        "memory": mem,
        "indexes": indexes,
        "flight": flight.last_dump(),
    }
    if slo_c is not None:
        report["slo"] = slo_c
    if perf_c is not None:
        report["perf"] = perf_c
    if budget_c is not None:
        report["budget"] = budget_c
    return report
