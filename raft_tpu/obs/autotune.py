"""Closed-loop SLO autotuner: live Pareto navigation of search effort.

Everything upstream of this module *observes* — the
:class:`~raft_tpu.obs.quality.QualityAuditor` maintains a recall EWMA,
the :class:`~raft_tpu.obs.slo.SloEngine` burns error budgets, the
:class:`~raft_tpu.obs.perf.PerfLedger` attributes device seconds — but
until now the only *actuator* was PR 11's fixed overload hysteresis
ladder.  This module closes the loop:

- :class:`FrontierModel` — the measured QPS–recall frontier a
  ``python -m raft_tpu.bench frontier`` sweep emits (effort point →
  measured QPS, recall, device-seconds/query), serialized as a
  schema-versioned document and loadable at serve time
  (``RAFT_TPU_FRONTIER_PATH``).
- :class:`Autotuner` — a background evaluator (same thread/tick
  pattern as :class:`~raft_tpu.obs.slo.SloEngine`) that walks each
  watched index along its warmed effort ladder toward
  *max QPS subject to (recall EWMA ≥ floor, p99 error budget healthy)*:

  * measured recall below the floor raises effort immediately — recall
    is the hard constraint, no hysteresis on the way up;
  * a burning/exhausted latency SLO sheds effort one notch after
    ``degrade_ticks`` consecutive bad ticks;
  * sustained health walks the level back toward the frontier optimum
    (the least-effort warmed point whose predicted recall clears the
    floor) after ``restore_ticks`` consecutive calm ticks.

All movement goes through the single-writer
:class:`~raft_tpu.serve.effort.EffortArbiter` (the overload shed level
clamps, it never writes), every step publishes an ``autotune_step``
context event (annotating the incident the motivating ``slo_burn``
opened), and every tick refreshes the
``raft_tpu_autotune_{level,recall_floor_margin,predicted_qps}`` gauges
(retired with the standard ``remove_matching`` discipline on unwatch).
Because the ladder is precompiled by the serving warmup, a step never
costs a recompile — the knob values ride as host operands into already
warmed executables.
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from raft_tpu.core import env as _env
from raft_tpu.core.trace import traced
from raft_tpu.obs import events as _events
from raft_tpu.obs.registry import MetricsRegistry, default_registry

FRONTIER_SCHEMA = "raft_tpu.frontier"
FRONTIER_SCHEMA_VERSION = 1

#: synthetic fallback model (no frontier file loaded): each ladder level
#: is assumed to trade this much recall for this QPS multiplier — shaped
#: like the measured sweeps (halving n_probes/itopk roughly halves device
#: work and costs a couple recall points), only used for *predictions*,
#: never reported as a measurement
_SYNTH_QPS_GAIN_PER_LEVEL = 1.6
_SYNTH_RECALL_DROP_PER_LEVEL = 0.02


def _scale() -> float:
    return float(_env.env_float("RAFT_TPU_SLO_WINDOW_SCALE", 1.0))


@dataclass
class FrontierPoint:
    """One measured operating point on a backend's QPS–recall frontier."""

    effort: Dict[str, object]
    qps: float
    recall: float
    device_s_per_query: Optional[float] = None

    def to_dict(self) -> Dict[str, object]:
        return {
            "effort": dict(self.effort),
            "qps": float(self.qps),
            "recall": float(self.recall),
            "device_s_per_query": self.device_s_per_query,
        }

    @classmethod
    def from_dict(cls, doc: Dict[str, object]) -> "FrontierPoint":
        return cls(
            effort=dict(doc["effort"]),
            qps=float(doc["qps"]),
            recall=float(doc["recall"]),
            device_s_per_query=doc.get("device_s_per_query"),
        )


def pareto(points: List[FrontierPoint]) -> List[FrontierPoint]:
    """Non-dominated subset (no other point has ≥ recall and > qps),
    sorted by recall ascending — the same filter the plot module applies
    to sweep results."""
    keep: List[FrontierPoint] = []
    for p in sorted(points, key=lambda p: (-p.recall, -p.qps)):
        if not keep or p.qps > keep[-1].qps:
            keep.append(p)
    return list(reversed(keep))


class FrontierModel:
    """Serialized measured frontier: backend → pareto-filtered effort
    points.  ``meta`` carries the sweep's provenance (dataset, n, k,
    platform) so a serve-time load can refuse a mismatched frontier."""

    def __init__(self, points: Optional[Dict[str, List[FrontierPoint]]] = None,
                 meta: Optional[Dict[str, object]] = None):
        self.points: Dict[str, List[FrontierPoint]] = points or {}
        self.meta: Dict[str, object] = meta or {}

    def add(self, backend: str, point: FrontierPoint) -> None:
        self.points.setdefault(backend, []).append(point)

    def backends(self) -> List[str]:
        return sorted(self.points)

    def pareto_filter(self) -> None:
        """Reduce every backend's point set to its pareto frontier."""
        for backend in list(self.points):
            self.points[backend] = pareto(self.points[backend])

    def predict(self, backend: str, effort: Dict[str, object]
                ) -> Optional[FrontierPoint]:
        """The measured point closest to an effort spec's knob values
        (exact knob match preferred; otherwise nearest by relative
        distance over shared numeric knobs).  None when the frontier
        has nothing for the backend."""
        candidates = self.points.get(backend) or []
        if not candidates:
            return None
        best, best_d = None, None
        for p in candidates:
            d = 0.0
            shared = 0
            for k, v in effort.items():
                pv = p.effort.get(k)
                if isinstance(v, (int, float)) and isinstance(pv, (int, float)):
                    lo = max(1e-9, min(abs(float(v)), abs(float(pv))))
                    d += abs(float(v) - float(pv)) / lo
                    shared += 1
                elif pv is not None and pv != v:
                    d += 1.0
            if shared == 0 and d == 0.0:
                d = float("inf") if effort else 0.0
            if best_d is None or d < best_d:
                best, best_d = p, d
        return best

    # -- serialization --------------------------------------------------

    def to_dict(self) -> Dict[str, object]:
        return {
            "schema": FRONTIER_SCHEMA,
            "schema_version": FRONTIER_SCHEMA_VERSION,
            "meta": dict(self.meta),
            "points": {
                b: [p.to_dict() for p in pts]
                for b, pts in sorted(self.points.items())
            },
        }

    @classmethod
    def from_dict(cls, doc: Dict[str, object]) -> "FrontierModel":
        if doc.get("schema") != FRONTIER_SCHEMA:
            raise ValueError(
                f"not a {FRONTIER_SCHEMA} document: {doc.get('schema')!r}"
            )
        if int(doc.get("schema_version", 0)) > FRONTIER_SCHEMA_VERSION:
            raise ValueError(
                f"frontier schema_version {doc['schema_version']} is newer "
                f"than this reader ({FRONTIER_SCHEMA_VERSION})"
            )
        model = cls(meta=dict(doc.get("meta", {})))
        for backend, pts in dict(doc.get("points", {})).items():
            model.points[backend] = [FrontierPoint.from_dict(p) for p in pts]
        return model

    def save(self, path: str) -> str:
        with open(path, "w") as fh:
            json.dump(self.to_dict(), fh, indent=1, sort_keys=True)
        return path

    @classmethod
    def load(cls, path: str) -> "FrontierModel":
        with open(path) as fh:
            return cls.from_dict(json.load(fh))


@dataclass
class _IndexState:
    arbiter: object
    backend: Optional[str]
    base_spec: Optional[object]
    floor: float
    auditor: Optional[object] = None
    slo: Optional[object] = None
    perf: Optional[object] = None
    latency_specs: Tuple[str, ...] = ()
    burn_ticks: int = 0
    calm_ticks: int = 0
    pinned_min: bool = False
    last_reason: Optional[str] = None
    steps: int = 0
    predictions: Dict[int, Tuple[Optional[float], Optional[float]]] = field(
        default_factory=dict
    )


class Autotuner:
    """Background controller stepping each watched index's effort level
    through its :class:`~raft_tpu.serve.effort.EffortArbiter`.

    Same lifecycle contract as :class:`~raft_tpu.obs.slo.SloEngine`:
    ``start()`` runs the tick thread, :meth:`evaluate_once` /
    :meth:`step` are the deterministic entries tests and the bench leg
    drive with a synthetic clock, ``stop()`` joins and unregisters the
    snapshot provider.
    """

    def __init__(self, *, eval_s: Optional[float] = None,
                 recall_floor: Optional[float] = None,
                 frontier: Optional[FrontierModel] = None,
                 frontier_path: Optional[str] = None,
                 degrade_ticks: int = 2,
                 restore_ticks: int = 3,
                 registry: Optional[MetricsRegistry] = None):
        self._registry = registry if registry is not None \
            else default_registry()
        self._eval_s = (
            eval_s if eval_s is not None
            else float(_env.env_float("RAFT_TPU_AUTOTUNE_EVAL_S", 2.0))
        ) * _scale()
        self.recall_floor = (
            recall_floor if recall_floor is not None
            else float(_env.env_float("RAFT_TPU_AUTOTUNE_RECALL_FLOOR", 0.9))
        )
        self.degrade_ticks = max(1, int(degrade_ticks))
        self.restore_ticks = max(1, int(restore_ticks))
        if frontier is None:
            path = frontier_path if frontier_path is not None \
                else _env.env_str("RAFT_TPU_FRONTIER_PATH")
            if path:
                frontier = FrontierModel.load(path)
        self.frontier = frontier
        self._lock = threading.Lock()
        self._states: Dict[str, _IndexState] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._registry.register_provider("autotune", self.snapshot)

    # -- index management ----------------------------------------------

    def watch_index(self, name: str, arbiter, *, index=None,
                    auditor=None, slo=None, perf=None,
                    floor: Optional[float] = None,
                    latency_specs: Optional[Tuple[str, ...]] = None) -> None:
        """Put ``name`` under closed-loop control.  ``arbiter`` is the
        index's single effort writer; ``auditor``/``slo``/``perf`` are
        the optional measurement taps (a missing tap just removes that
        input from the policy).  ``latency_specs`` names the SloEngine
        specs whose alert/exhaustion means the p99 budget is unhealthy
        (default: the standard ``{name}-latency`` objective)."""
        from raft_tpu.neighbors import effort as _effort  # lazy: obs stays importable alone

        backend = None
        base_spec = None
        if index is not None:
            base_spec = _effort.spec_for_index(index)
            backend = base_spec.backend if base_spec is not None else None
        state = _IndexState(
            arbiter=arbiter, backend=backend, base_spec=base_spec,
            floor=self.recall_floor if floor is None else float(floor),
            auditor=auditor, slo=slo, perf=perf,
            latency_specs=tuple(latency_specs) if latency_specs is not None
            else (f"{name}-latency",),
        )
        state.predictions = self._ladder_predictions(state)
        with self._lock:
            self._states[name] = state

    def unwatch_index(self, name: str) -> None:
        with self._lock:
            self._states.pop(name, None)
        for metric in ("raft_tpu_autotune_level",
                       "raft_tpu_autotune_recall_floor_margin",
                       "raft_tpu_autotune_predicted_qps"):
            self._registry.gauge(metric).remove_matching(index=name)

    # -- the frontier view ---------------------------------------------

    def _ladder_predictions(self, state: _IndexState
                            ) -> Dict[int, Tuple[Optional[float],
                                                 Optional[float]]]:
        """(qps, recall) prediction per warmed ladder level, from the
        loaded frontier when it covers the backend, else the synthetic
        ladder model anchored at level 0 (None, None) — predictions
        scale *relative* trades, they are never reported as measured."""
        out: Dict[int, Tuple[Optional[float], Optional[float]]] = {}
        spec = state.base_spec
        for level in state.arbiter.levels():
            point = None
            if (self.frontier is not None and spec is not None
                    and state.backend):
                point = self.frontier.predict(
                    state.backend, spec.degraded(level).knobs()
                )
            if point is not None:
                out[level] = (point.qps, point.recall)
            elif level == 0:
                out[level] = (None, None)
            else:
                qps0, recall0 = out.get(0, (None, None))
                out[level] = (
                    qps0 * _SYNTH_QPS_GAIN_PER_LEVEL ** level
                    if qps0 is not None else None,
                    recall0 - _SYNTH_RECALL_DROP_PER_LEVEL * level
                    if recall0 is not None else None,
                )
        return out

    def _target_level(self, state: _IndexState) -> int:
        """Frontier optimum: the deepest (least effort → max QPS) warmed
        level whose predicted recall still clears the floor.  Unknown
        predictions are conservative — they do not qualify — so with no
        frontier loaded the optimum is full effort (level 0)."""
        target = 0
        for level in sorted(state.predictions):
            if level == 0:
                continue
            _qps, recall = state.predictions[level]
            if recall is not None and recall >= state.floor:
                target = level
        return target

    # -- controller ----------------------------------------------------

    def evaluate_once(self, now: Optional[float] = None) -> None:
        with self._lock:
            names = list(self._states)
        for name in names:
            self.step(name, now=now)

    @traced("autotune.step")
    def step(self, name: str, now: Optional[float] = None) -> int:
        """One control tick for one index; returns the (possibly new)
        autotune level.  ``now`` is monotonic seconds — tests and the
        bench leg pass a synthetic clock."""
        now = time.monotonic() if now is None else now
        with self._lock:
            state = self._states.get(name)
        if state is None:
            return 0
        arbiter = state.arbiter
        level = arbiter.autotune_level
        ewma = None
        if state.auditor is not None:
            ewma = state.auditor.recall_ewma(name)
        burning = self._latency_burning(state)
        target = self._target_level(state)

        new, reason = level, None
        if ewma is not None and ewma < state.floor and level > 0:
            # hard constraint: measured recall under the floor buys
            # effort back immediately, no hysteresis on the way up
            new, reason = level - 1, "recall_floor"
            state.burn_ticks = state.calm_ticks = 0
        elif burning:
            state.calm_ticks = 0
            state.burn_ticks += 1
            if state.burn_ticks >= self.degrade_ticks:
                state.burn_ticks = 0
                if level < arbiter.max_level and self._recall_allows(
                        state, level + 1, ewma):
                    new, reason = level + 1, "p99_burn"
        else:
            state.burn_ticks = 0
            state.calm_ticks += 1
            if state.calm_ticks >= self.restore_ticks and level != target:
                state.calm_ticks = 0
                step = -1 if level > target else 1
                if step > 0 and not self._recall_allows(
                        state, level + 1, ewma):
                    step = 0
                if step:
                    new, reason = level + step, "frontier_optimum"

        state.pinned_min = burning and level >= arbiter.max_level
        if new != level:
            new = arbiter.set_autotune_level(new)
            state.last_reason = reason
            state.steps += 1
        self._report(name, state, new, reason, ewma)
        return new

    def _latency_burning(self, state: _IndexState) -> bool:
        # page-severity burn latches only, NOT "exhausted" and NOT
        # ticket alerts: a spent budget stays exhausted for the whole
        # rolling budget window, and a ticket latch (slow pair) holds
        # until its scaled multi-hour short window drains — neither can
        # be refunded by shedding effort.  Page latches re-arm as soon
        # as the short window recovers, so the controller tracks the
        # breach edge-to-edge and climbs back once it actually ends.
        if state.slo is None:
            return False
        paging = getattr(state.slo, "paging", None)
        bad = set(paging() if paging is not None
                  else state.slo.health().get("alerting", ()))
        return any(spec in bad for spec in state.latency_specs)

    def _recall_allows(self, state: _IndexState, level: int,
                       ewma: Optional[float]) -> bool:
        """May effort drop to ``level`` without predicted recall (or,
        absent predictions, the live EWMA margin) crossing the floor?"""
        _qps, recall = state.predictions.get(level, (None, None))
        if recall is not None:
            return recall >= state.floor
        if ewma is not None:
            return ewma >= state.floor + _SYNTH_RECALL_DROP_PER_LEVEL
        return True  # no recall signal at all: latency SLO is in charge

    def _report(self, name: str, state: _IndexState, level: int,
                reason: Optional[str], ewma: Optional[float]) -> None:
        qps, _recall = state.predictions.get(level, (None, None))
        if qps is None and state.perf is not None:
            totals = state.perf.totals().get(name)
            if totals and totals.get("device_s", 0.0) > 0 \
                    and totals.get("rows", 0) > 0:
                qps = float(totals["rows"]) / float(totals["device_s"])
        self._registry.gauge(
            "raft_tpu_autotune_level",
            help="autotuner effort level (0 = full effort)",
        ).set(float(level), index=name)
        if ewma is not None:
            self._registry.gauge(
                "raft_tpu_autotune_recall_floor_margin",
                help="recall EWMA minus the configured floor",
            ).set(float(ewma) - state.floor, index=name)
        if qps is not None:
            self._registry.gauge(
                "raft_tpu_autotune_predicted_qps",
                help="frontier-predicted (or ledger-measured) QPS at the "
                     "current effort level",
            ).set(float(qps), index=name)
        if reason is not None:
            _events.publish(
                "autotune_step", f"autotune_{name}",
                recovered=(level == 0 and reason != "p99_burn"),
                index=name, level=level, step_reason=reason,
                recall_ewma=ewma, floor=state.floor, predicted_qps=qps,
                pinned_min_effort=state.pinned_min,
            )

    # -- lifecycle ------------------------------------------------------

    def start(self) -> None:
        """Run the background controller (idempotent)."""
        with self._lock:
            if self._thread is not None:
                return
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="raft-tpu-autotune", daemon=True
            )
            thread = self._thread
        thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self._eval_s):
            try:
                self.evaluate_once()
            except Exception:  # noqa: BLE001 — the controller must survive
                self._registry.counter(
                    "raft_tpu_autotune_eval_errors_total",
                    help="exceptions swallowed in the autotune evaluator",
                ).inc()

    def stop(self) -> None:
        with self._lock:
            thread, self._thread = self._thread, None
        self._stop.set()
        if thread is not None:
            thread.join(timeout=10.0)
        self._registry.unregister_provider(
            "autotune", expected=self.snapshot
        )

    # -- reading --------------------------------------------------------

    def level(self, name: str) -> Optional[int]:
        with self._lock:
            state = self._states.get(name)
        return state.arbiter.autotune_level if state is not None else None

    def health(self) -> Dict[str, List[str]]:
        """``{"pinned_min_effort": [index names]}`` — indexes where the
        latency budget is still burning with no effort left to shed;
        ``healthz()`` folds these into a DEGRADED verdict."""
        with self._lock:
            return {
                "pinned_min_effort": [
                    n for n, s in self._states.items() if s.pinned_min
                ]
            }

    def snapshot(self) -> Dict[str, object]:
        """Provider section for registry snapshots."""
        with self._lock:
            states = dict(self._states)
        return {
            "eval_s": self._eval_s,
            "recall_floor": self.recall_floor,
            "frontier_loaded": self.frontier is not None,
            "indexes": {
                name: {
                    "backend": s.backend,
                    "level": s.arbiter.autotune_level,
                    "effective_level": s.arbiter.effective_level(),
                    "floor": s.floor,
                    "steps": s.steps,
                    "last_reason": s.last_reason,
                    "pinned_min_effort": s.pinned_min,
                }
                for name, s in states.items()
            },
        }
