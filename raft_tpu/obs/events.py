"""Typed observability event bus: the one pipe every trigger flows through.

Before this module the incident plumbing was hardwired point-to-point:
``obs/health.py`` and ``obs/quality.py`` each called ``flight.auto_dump``
directly, the batcher dumped on recompiles and exceptions, and the
compactor's abort path talked straight to ``healthz()``.  Adding a new
consumer (the incident manager, the SLO engine) would have meant editing
every producer.  Now producers publish one typed :class:`Event` and
consumers subscribe:

- ``raft_tpu.obs.flight`` — dumps the flight ring for trigger events,
  debounced **per reason** (the old direct ``auto_dump`` path shared one
  window across all reasons, so a ``quality_alarm`` suppressed a later
  unrelated ``hot_recompile``);
- ``raft_tpu.obs.incidents`` — correlates events into incident
  timelines;
- anything else via :func:`subscribe`.

Event kinds are a closed taxonomy (:data:`KINDS`) — publishing an
unknown kind raises, so the vocabulary stays greppable and the docs
stay honest.  ``TRIGGER_KINDS`` marks the subset that *starts* an
incident (and a flight dump); the rest are context that only annotates
one already open (a ``registry_swap`` during a quality incident tells
the story, but a routine hot-swap is not itself an incident).  The
overload actuators (:mod:`raft_tpu.serve.overload`) publish
``admission_shed`` and ``degraded_enter`` as triggers — shedding work
or reducing search effort is an incident-worthy decision — while
``degraded_exit`` and ``hedge_fired`` are context.

Delivery is synchronous on the publisher's thread — every current
producer sits on an error/alarm/maintenance path where the old code
already wrote a dump synchronously, and synchronous delivery is what
keeps the existing trigger tests deterministic.  The bus lock is held
only to stamp/append; subscribers run outside it and may publish
themselves (the recursion guard caps reentrant depth instead of
deadlocking).  Subscriber exceptions are swallowed and counted
(``raft_tpu_events_subscriber_errors_total``) — observability must not
add failure modes to the paths it observes.

The ring of recent events is bounded (``RAFT_TPU_EVENTS_RING``);
overwritten events are counted in ``raft_tpu_events_dropped_total`` and
the ring appears in ``obs.snapshot()`` under the ``events`` provider.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from raft_tpu.core import env as _env
from raft_tpu.obs.registry import default_registry

#: the closed event taxonomy — publish() rejects anything else
KINDS = frozenset({
    "health_edge",
    "quality_alarm",
    "hot_recompile",
    "compaction_trigger",
    "compaction_promote",
    "compaction_abort",
    "registry_swap",
    "batch_error",
    "slo_burn",
    "admission_shed",
    "degraded_enter",
    "degraded_exit",
    "hedge_fired",
    "perf_regression",
    "build_complete",
    "page_thrash",
    # closed-loop autotuner effort moves: context, not trigger — the
    # slo_burn (or degraded_enter) that motivated the move opens the
    # incident; the step annotates its timeline
    "autotune_step",
    # query-archive dump written for an incident trigger: context — the
    # trigger itself opened the incident; this links the artifact into
    # its timeline
    "explain_dump",
})

#: kinds that open incidents / trigger flight dumps; the rest are context
TRIGGER_KINDS = frozenset({
    "health_edge",
    "quality_alarm",
    "hot_recompile",
    "batch_error",
    "compaction_abort",
    "slo_burn",
    "admission_shed",
    "degraded_enter",
    "perf_regression",
    "page_thrash",
})

#: default recent-events ring capacity
DEFAULT_RING = 256

#: hard cap on publishes triggered by subscribers of a single publish
_MAX_REENTRANT_DEPTH = 4


def _env_ring() -> int:
    try:
        return max(1, _env.env_int("RAFT_TPU_EVENTS_RING", DEFAULT_RING))
    except ValueError:
        return DEFAULT_RING


@dataclass(frozen=True)
class Event:
    """One typed bus event.

    ``reason`` is the human/debounce key — it becomes the flight-dump
    reason and filename stem, so producers keep the pre-bus reason
    strings (``"health_unhealthy"``, ``"batch_exception"``, ...) and the
    artifacts existing tests and runbooks know keep their names.
    ``recovered`` marks the *clearing* edge of an alarm: recovery events
    never dump or open incidents, they close them.
    """

    kind: str
    reason: str
    seq: int
    t: float          # time.perf_counter() — aligns with span/flight stamps
    unix_time: float  # time.time() — for humans and JSON exports
    recovered: bool = False
    fields: Dict[str, object] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, object]:
        return {
            "kind": self.kind,
            "reason": self.reason,
            "seq": self.seq,
            "t": self.t,
            "unix_time": self.unix_time,
            "recovered": self.recovered,
            **{k: v for k, v in self.fields.items()},
        }


class _Subscription:
    """Handle returned by :meth:`EventBus.subscribe`.

    Carries the optional kind filter and the per-reason debounce state:
    for a subscription with ``debounce_s`` set, two events sharing a
    ``reason`` within the window deliver only the first (suppressed
    deliveries are counted per reason).  Distinct reasons never suppress
    each other — that is the whole point versus the old global window.
    """

    def __init__(self, bus: "EventBus", fn: Callable[[Event], None],
                 kinds: Optional[frozenset], debounce_s: float, name: str):
        self._bus = bus
        self._fn = fn
        self._kinds = kinds
        self._debounce_s = debounce_s
        self._name = name
        self._lock = threading.Lock()
        self._last_by_reason: Dict[str, float] = {}

    def _should_deliver(self, event: Event, now: float) -> bool:
        if self._kinds is not None and event.kind not in self._kinds:
            return False
        if self._debounce_s <= 0.0:
            return True
        with self._lock:
            last = self._last_by_reason.get(event.reason, float("-inf"))
            if now - last < self._debounce_s:
                debounced = True
            else:
                self._last_by_reason[event.reason] = now
                debounced = False
        if debounced:
            default_registry().counter(
                "raft_tpu_events_debounced_total",
                help="bus deliveries suppressed by per-reason debounce",
            ).inc(subscriber=self._name, reason=event.reason)
        return not debounced

    def unsubscribe(self) -> None:
        self._bus._remove(self)


class EventBus:
    """Bounded, thread-safe pub/sub bus over the :data:`KINDS` taxonomy.

    One instance normally lives for the whole process
    (:func:`default_bus`); tests build private ones.  ``publish`` is the
    only method on producer paths and costs one lock window plus the
    synchronous fan-out.
    """

    def __init__(self, ring: Optional[int] = None):
        self._lock = threading.Lock()
        self._ring: deque = deque(
            maxlen=ring if ring is not None else _env_ring()
        )
        self._seq = itertools.count(1)
        self._published: Dict[str, int] = {}
        self._dropped = 0
        self._subs: Tuple[_Subscription, ...] = ()
        self._depth = threading.local()

    # -- subscribing ---------------------------------------------------------
    def subscribe(self, fn: Callable[[Event], None], *,
                  kinds: Optional[frozenset] = None,
                  debounce_s: float = 0.0,
                  name: str = "anonymous") -> _Subscription:
        """Register ``fn`` for every published event (optionally filtered
        to ``kinds``, optionally debounced per reason).  Returns a handle
        with ``unsubscribe()``.  Delivery order follows subscribe order.
        """
        sub = _Subscription(self, fn, kinds, debounce_s, name)
        with self._lock:
            self._subs = self._subs + (sub,)
        return sub

    def _remove(self, sub: _Subscription) -> None:
        with self._lock:
            self._subs = tuple(s for s in self._subs if s is not sub)

    # -- publishing ----------------------------------------------------------
    def publish(self, kind: str, reason: Optional[str] = None, *,
                recovered: bool = False, **fields: object) -> Event:
        """Publish one event; returns it.  ``reason`` defaults to the
        kind.  Raises ``ValueError`` for kinds outside the taxonomy —
        producers are in-tree, so a typo should fail loudly in tests,
        not vanish into an unwatched topic.
        """
        if kind not in KINDS:
            raise ValueError(
                f"unknown event kind {kind!r}; known: {sorted(KINDS)}"
            )
        with self._lock:
            event = Event(
                kind=kind,
                reason=reason if reason is not None else kind,
                seq=next(self._seq),
                t=time.perf_counter(),
                unix_time=time.time(),
                recovered=recovered,
                fields=dict(fields),
            )
            dropped = len(self._ring) == self._ring.maxlen
            self._ring.append(event)
            if dropped:
                self._dropped += 1
            self._published[kind] = self._published.get(kind, 0) + 1
            subs = self._subs
        default_registry().counter(
            "raft_tpu_events_total", help="bus events published",
        ).inc(kind=kind)
        if dropped:
            default_registry().counter(
                "raft_tpu_events_dropped_total",
                help="events evicted from the recent-events ring",
            ).inc()
        depth = getattr(self._depth, "value", 0)
        if depth >= _MAX_REENTRANT_DEPTH:
            return event  # a subscriber publishing in a loop; stop the chain
        self._depth.value = depth + 1
        try:
            now = time.monotonic()
            for sub in subs:
                try:
                    if sub._should_deliver(event, now):
                        sub._fn(event)
                except Exception:  # noqa: BLE001 — never fail a producer
                    default_registry().counter(
                        "raft_tpu_events_subscriber_errors_total",
                        help="exceptions swallowed in bus subscribers",
                    ).inc(subscriber=sub._name)
        finally:
            self._depth.value = depth
        return event

    # -- reading -------------------------------------------------------------
    def recent(self, kind: Optional[str] = None) -> List[Event]:
        """Ring contents, oldest first (optionally one kind)."""
        with self._lock:
            events = list(self._ring)
        if kind is not None:
            events = [e for e in events if e.kind == kind]
        return events

    def snapshot(self) -> Dict[str, object]:
        """Provider section for registry snapshots."""
        with self._lock:
            events = list(self._ring)
            return {
                "ring": self._ring.maxlen,
                "published": dict(self._published),
                "dropped": self._dropped,
                "subscribers": [s._name for s in self._subs],
                "recent": [e.to_dict() for e in events[-16:]],
            }


# ---------------------------------------------------------------------------
# the process-wide default bus + module-level conveniences

_default_lock = threading.Lock()
_default: Optional[EventBus] = None


def _install_default_subscribers(bus: EventBus) -> None:
    # Deferred imports: flight/incidents import this module's registry
    # sibling, so wiring at bus-creation time (not module import time)
    # keeps the obs package cycle-free.
    from raft_tpu.obs import explain as _explain
    from raft_tpu.obs import flight as _flight
    from raft_tpu.obs import incidents as _incidents
    from raft_tpu.obs import perf as _perf

    # order matters: the flight dumper and the perf auto-capture run
    # before the incident manager so the dump AND the profiler capture
    # are fresh when the incident correlating the same event attaches
    # its evidence; the query-archive dumper runs *after* the incident
    # manager so its reentrant ``explain_dump`` context publish finds
    # the incident the trigger just opened (earlier, the nested fan-out
    # would reach the incident manager before the trigger itself and
    # the artifact link would be dropped)
    _flight.install_bus_subscriber(bus)
    _perf.install_bus_subscriber(bus)
    _incidents.install(bus)
    _explain.install_bus_subscriber(bus)
    default_registry().register_provider("events", bus.snapshot)


def default_bus() -> EventBus:
    """The process-wide bus.  First use creates it and installs the
    default subscribers (flight dumper, incident manager) plus the
    ``events`` snapshot provider."""
    global _default
    created = False
    with _default_lock:
        if _default is None:
            _default = EventBus()
            created = True
        bus = _default
    if created:
        _install_default_subscribers(bus)
    return bus


def publish(kind: str, reason: Optional[str] = None, *,
            recovered: bool = False, **fields: object) -> Event:
    return default_bus().publish(
        kind, reason, recovered=recovered, **fields
    )


def subscribe(fn: Callable[[Event], None], *,
              kinds: Optional[frozenset] = None,
              debounce_s: float = 0.0,
              name: str = "anonymous") -> _Subscription:
    return default_bus().subscribe(
        fn, kinds=kinds, debounce_s=debounce_s, name=name
    )


def recent(kind: Optional[str] = None) -> List[Event]:
    return default_bus().recent(kind)


def events_snapshot() -> Dict[str, object]:
    """Provider section for registry snapshots."""
    return default_bus().snapshot()


def reset() -> None:
    """Drop the default bus (subscriptions die with it) and reset the
    incident manager so the next :func:`default_bus` rewires everything
    against fresh env knobs.  Test/REPL hygiene, like ``flight.reset``.
    """
    global _default
    import sys

    with _default_lock:
        _default = None
    incidents = sys.modules.get("raft_tpu.obs.incidents")
    if incidents is not None:
        incidents._on_bus_reset()
    flight = sys.modules.get("raft_tpu.obs.flight")
    if flight is not None:
        flight._on_bus_reset()
    perf = sys.modules.get("raft_tpu.obs.perf")
    if perf is not None:
        perf._on_bus_reset()
    explain = sys.modules.get("raft_tpu.obs.explain")
    if explain is not None:
        explain._on_bus_reset()
