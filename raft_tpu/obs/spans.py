"""Structured spans: what NVTX ranges become when you need to *query* them.

``core.trace.trace_range`` already brackets every public entry point for
the profiler's benefit; this module makes the same brackets report into
the metrics registry so the question "where did the milliseconds go"
has an answer without a Perfetto session attached:

- every range becomes a :class:`Span` (id, parent id, wall time, named
  stage timings, attributed events) on a thread-local stack;
- finishing a span feeds ``raft_tpu_span_seconds{span=<name>}`` in the
  default registry and a bounded ring of recent spans for inspection;
- :func:`current_span` lets leaf code (the XLA monitoring listener, the
  batcher's stage timers) attach data to whatever operation is running,
  with no plumbing through call signatures — the zero call-site-churn
  property the reference gets from NVTX's implicit nesting.

Spans are intentionally *not* cross-thread: a request handed from the
submitting thread to the batcher's worker starts a fresh root span there,
and queue-wait crosses the gap as an explicit stage measurement.
"""

from __future__ import annotations

import contextlib
import itertools
import threading
import time
from collections import deque
from typing import Dict, Iterator, List, Optional

from raft_tpu.core import env as _env
from raft_tpu.obs.registry import default_registry

def _ring_cap() -> int:
    """Recent-span ring capacity: ``RAFT_TPU_SPAN_RING``, default 512."""
    try:
        return max(1, _env.env_int("RAFT_TPU_SPAN_RING", 512))
    except ValueError:
        return 512


_ids = itertools.count(1)  # itertools.count.__next__ is atomic in CPython
_tls = threading.local()
_recent_lock = threading.Lock()
#: ring of recently finished root spans (tests / debugging / slow log)
_recent: deque = deque(maxlen=_ring_cap())

_disabled = _env.env_bool("RAFT_TPU_OBS_DISABLED", False)


def set_enabled(enabled: bool) -> None:
    """Global kill-switch (also: RAFT_TPU_OBS_DISABLED=1 at import)."""
    global _disabled
    _disabled = not enabled


def enabled() -> bool:
    return not _disabled


class Span:
    """One timed operation. Mutable while open; frozen facts after close."""

    __slots__ = (
        "name", "span_id", "parent_id", "t_start", "t_end",
        "stages", "events",
    )

    def __init__(self, name: str, span_id: int, parent_id: Optional[int]):
        self.name = name
        self.span_id = span_id
        self.parent_id = parent_id
        self.t_start = time.perf_counter()
        self.t_end: Optional[float] = None
        #: named sub-timings in seconds (queue/pad/dispatch/device, ...)
        self.stages: Dict[str, float] = {}
        #: attributed event tallies (xla_compiles, xla_compile_seconds, ...)
        self.events: Dict[str, float] = {}

    @property
    def duration_s(self) -> Optional[float]:
        if self.t_end is None:
            return None
        return self.t_end - self.t_start

    def add_stage(self, name: str, seconds: float) -> None:
        self.stages[name] = self.stages.get(name, 0.0) + float(seconds)

    def add_event(self, name: str, value: float = 1.0) -> None:
        self.events[name] = self.events.get(name, 0.0) + float(value)

    def to_dict(self) -> Dict[str, object]:
        return {
            "name": self.name,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "duration_ms": (
                None if self.duration_s is None else self.duration_s * 1e3
            ),
            "stages_ms": {k: v * 1e3 for k, v in self.stages.items()},
            "events": dict(self.events),
        }

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        d = self.duration_s
        return (
            f"<Span {self.name} id={self.span_id} "
            f"{'open' if d is None else f'{d * 1e3:.3f}ms'}>"
        )


def _stack() -> List[Span]:
    s = getattr(_tls, "stack", None)
    if s is None:
        s = _tls.stack = []
    return s


def current_span() -> Optional[Span]:
    """Innermost open span on this thread, or None."""
    s = getattr(_tls, "stack", None)
    return s[-1] if s else None


@contextlib.contextmanager
def span(name: str) -> Iterator[Optional[Span]]:
    """Open a child of the current span (or a root).  Yields the Span, or
    None when observability is globally disabled."""
    if _disabled:
        yield None
        return
    stack = _stack()
    parent = stack[-1] if stack else None
    sp = Span(name, next(_ids), parent.span_id if parent else None)
    stack.append(sp)
    try:
        yield sp
    finally:
        sp.t_end = time.perf_counter()
        stack.pop()
        _record_finished(sp, parent)


def set_ring_capacity(cap: Optional[int] = None) -> int:
    """Resize the recent-span ring, keeping its newest entries.  With no
    argument, re-reads ``RAFT_TPU_SPAN_RING`` — the hook the conftest
    reset fixture and long-lived REPLs use.  Returns the new capacity."""
    global _recent
    new_cap = _ring_cap() if cap is None else max(1, int(cap))
    with _recent_lock:
        if _recent.maxlen != new_cap:
            _recent = deque(_recent, maxlen=new_cap)
    return new_cap


def clear_recent() -> None:
    """Drop the recent-span ring contents (test isolation)."""
    with _recent_lock:
        _recent.clear()


def _record_finished(sp: Span, parent: Optional[Span]) -> None:
    reg = default_registry()
    try:
        # the span id rides along as a per-bucket exemplar, so a fat p99
        # bucket in the scrape links back to a concrete recorded span
        reg.histogram(
            "raft_tpu_span_seconds",
            help="wall time per traced operation",
        ).observe(sp.duration_s, exemplar=f"span-{sp.span_id}", span=sp.name)
    except Exception:
        # span names are static strings in practice; a pathological dynamic
        # name tripping the cardinality cap must not break the traced API
        pass
    if parent is not None:
        # roll attributed events up so root spans carry the whole story
        for k, v in sp.events.items():
            parent.add_event(k, v)
    else:
        with _recent_lock:
            _recent.append(sp)


def open_span(name: str) -> Optional[Span]:
    """A *detached* root span for operations that cross threads.

    The pipelined serve dispatch opens a ``serve.batch`` span on the
    dispatch thread and closes it on the completion thread — a lifetime
    no context manager on either thread can express.  Detached spans are
    never pushed on a thread-local stack, so :func:`current_span` does
    not see them and XLA events attribute to whatever stacked span is
    open instead (after warmup the pipelined hot path emits no events,
    so nothing is lost).  Returns ``None`` when obs is disabled; close
    with :func:`finish_span`.
    """
    if _disabled:
        return None
    return Span(name, next(_ids), None)


def finish_span(sp: Optional[Span]) -> None:
    """Close a span from :func:`open_span`: stamps the end time, feeds
    ``raft_tpu_span_seconds`` and the recent-roots ring.  Idempotent and
    None-tolerant so error paths can call it unconditionally."""
    if sp is None or sp.t_end is not None:
        return
    sp.t_end = time.perf_counter()
    _record_finished(sp, None)


def recent_spans(n: int = 50) -> List[Dict[str, object]]:
    """Most recent finished root spans, newest last (JSON-safe)."""
    with _recent_lock:
        items = list(_recent)[-n:]
    return [sp.to_dict() for sp in items]


def spans_snapshot() -> Dict[str, object]:
    """Provider section for registry snapshots."""
    return {"recent": recent_spans(20)}
