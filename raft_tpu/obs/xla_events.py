"""jax.monitoring → registry bridge: compiles, cache hits, transfers.

``serve.metrics`` counts exactly one thing (backend compiles, for the
zero-recompile contract).  Production debugging needs the rest of the
story: how *long* compiles took, whether the executable came from the
persistent cache, and what host↔device transfers cost — attributed to
the operation that caused them, because "something compiled" is useless
while "``serve.batch`` compiled for 12 s at 14:03" is a pager message.

Event vocabulary (jax 0.4.x, matched by substring so newer versions'
renames degrade to the generic family instead of vanishing):

- ``/jax/core/compile/backend_compile_duration``  → compile family
- ``/jax/core/compile/jaxpr_trace_duration`` etc. → trace family
- ``/jax/compilation_cache/cache_hits|cache_misses`` → cache family
- anything containing ``transfer``                → transfer family

Listener callbacks tolerate extra positional/keyword arguments: newer jax
versions append context args to duration listeners, and a signature
mismatch there would silently disable every listener in the process.
"""

from __future__ import annotations

import threading
from typing import Optional

from raft_tpu.obs import spans as _spans
from raft_tpu.obs.registry import MetricsRegistry, default_registry

_install_lock = threading.Lock()
_installed = False

#: compile-duration histogram ladder: 10 ms .. ~160 s (seconds)
_COMPILE_BUCKETS = tuple(0.01 * (2.0 ** i) for i in range(15))


def _family(event: str) -> Optional[str]:
    if "backend_compile" in event:
        return "backend_compile"
    if "/compile/" in event or event.endswith("_compile_duration"):
        return "compile_stage"
    if "cache_hit" in event:
        return "cache_hit"
    if "cache_miss" in event:
        return "cache_miss"
    if "compilation_cache" in event:
        return "cache_other"
    if "transfer" in event:
        return "transfer"
    return None


def _attribute(reg: MetricsRegistry, family: str, seconds: Optional[float]
               ) -> None:
    """Book one event against the innermost open span (if any)."""
    sp = _spans.current_span()
    span_name = sp.name if sp is not None else "(no span)"
    if family == "backend_compile":
        reg.counter(
            "raft_tpu_xla_compiles_total",
            help="XLA backend compiles, by enclosing traced span",
        ).inc(span=span_name)
        if seconds is not None:
            reg.histogram(
                "raft_tpu_xla_compile_seconds",
                help="XLA backend compile durations",
                buckets=_COMPILE_BUCKETS,
            ).observe(seconds)
        if sp is not None:
            sp.add_event("xla_compiles")
            if seconds is not None:
                sp.add_event("xla_compile_seconds", seconds)
    elif family in ("cache_hit", "cache_miss"):
        reg.counter(
            "raft_tpu_xla_executable_cache_total",
            help="persistent compilation cache hits/misses",
        ).inc(result=("hit" if family == "cache_hit" else "miss"))
        if sp is not None:
            sp.add_event(f"xla_cache_{family.split('_')[1]}")
    elif family == "transfer":
        reg.counter(
            "raft_tpu_xla_transfer_events_total",
            help="host<->device transfer events",
        ).inc(span=span_name)
        if seconds is not None:
            reg.histogram(
                "raft_tpu_xla_transfer_seconds",
                help="host<->device transfer durations",
            ).observe(seconds)
        if sp is not None:
            sp.add_event("xla_transfers")
    elif family == "compile_stage":
        # jaxpr trace / mlir lowering durations: aggregate only
        reg.histogram(
            "raft_tpu_xla_lowering_seconds",
            help="jaxpr trace + lowering stage durations",
        ).observe(seconds if seconds is not None else 0.0)


def _on_event_duration(event: str, duration: float, *args, **kwargs) -> None:
    # *args/**kwargs: newer jax passes extra context positionally; a strict
    # 2-arg signature would raise inside jax and break all listeners
    if not _spans.enabled():
        return
    fam = _family(str(event))
    if fam is not None:
        _attribute(default_registry(), fam, float(duration))


def _on_event(event: str, *args, **kwargs) -> None:
    if not _spans.enabled():
        return
    fam = _family(str(event))
    if fam is not None:
        _attribute(default_registry(), fam, None)


def install(force: bool = False) -> bool:
    """Register the monitoring listeners (idempotent, process-wide).

    Returns True when the listeners are active after the call.  ``force``
    re-registers after a ``jax.monitoring.clear_event_listeners()`` (which
    tests use; jax offers no unregister API).
    """
    global _installed
    with _install_lock:
        if _installed and not force:
            return True
        import jax.monitoring

        jax.monitoring.register_event_duration_secs_listener(
            _on_event_duration
        )
        jax.monitoring.register_event_listener(_on_event)
        _installed = True
        return True


def installed() -> bool:
    return _installed
