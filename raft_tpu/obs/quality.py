"""Online recall auditing: is the index still telling the truth?

Latency metrics catch a slow index; nothing in the serve stack catches a
*wrong* one — an IVF index whose centroids went stale after a bad
hot-swap keeps answering fast, every dashboard stays green, and recall
quietly drops to 0.3.  The auditor closes that gap the way production
ANN deployments do: shadow-score a sample of live traffic against an
exact oracle.

Mechanics (the hot-path contract is the whole design):

- :meth:`QualityAuditor.observe` is called by the batcher after each
  dispatched batch with the *already computed* results.  It flips a
  sampling coin and, on heads, enqueues the batch onto a **bounded**
  queue with ``put_nowait`` — the hot path never computes recall, never
  touches the device, and never blocks: a full queue drops the sample
  and increments ``raft_tpu_quality_dropped_total`` instead.
- A daemon worker thread pops samples, reconstructs the exact answer by
  brute-force numpy scan over the index's live vectors (pure numpy on
  purpose: a jnp dispatch from this thread would race the serve stack's
  recompile-attribution bracket and contend for the device), and scores
  the served ids with the canonical
  :func:`raft_tpu.stats.metrics.recall_at_k` and
  :func:`~raft_tpu.stats.metrics.rank_displacement`.
- Streaming results land in the metrics registry as
  ``raft_tpu_recall{index=,version=}`` /
  ``raft_tpu_recall_ewma`` / ``raft_tpu_rank_displacement`` gauges.
- When the recall EWMA crosses ``threshold`` the degradation alarm fires
  *once per excursion* (edge-triggered): a WARNING log line plus the
  ``on_degraded(name, version, ewma)`` callback; recovery re-arms it.

The oracle dataset is cached per (name, version, generation) — a swap or
a mutation invalidates it — so steady traffic pays one
``live_vectors()`` materialization per index state, not per sample.
"""

from __future__ import annotations

import queue
import random
import threading
from typing import Callable, Dict, Optional, Tuple

import numpy as np

from raft_tpu.core.logger import child as _child_logger
from raft_tpu.obs import events
from raft_tpu.obs.registry import MetricsRegistry, default_registry
from raft_tpu.stats.metrics import rank_displacement, recall_at_k

_log = _child_logger("obs.quality")

_ORACLE_CACHE_CAP = 4


def _exact_topk(
    data: np.ndarray, data_ids: np.ndarray, queries: np.ndarray,
    k: int, metric: str,
) -> Tuple[np.ndarray, np.ndarray]:
    """Exact top-k (distances, global ids) by full numpy scan."""
    q = np.asarray(queries, dtype=np.float32)
    x = np.asarray(data, dtype=np.float32)
    if metric == "inner_product":
        scores = -(q @ x.T)                    # negate: smaller-is-better
    elif metric == "cosine":
        qn = q / np.maximum(np.linalg.norm(q, axis=1, keepdims=True), 1e-12)
        xn = x / np.maximum(np.linalg.norm(x, axis=1, keepdims=True), 1e-12)
        scores = 1.0 - qn @ xn.T
    else:                                      # sqeuclidean and friends
        scores = (
            (q * q).sum(1, keepdims=True)
            - 2.0 * (q @ x.T)
            + (x * x).sum(1)[None, :]
        )
    k = min(k, x.shape[0])
    part = np.argpartition(scores, k - 1, axis=1)[:, :k]
    part_scores = np.take_along_axis(scores, part, axis=1)
    order = np.argsort(part_scores, axis=1)
    idx = np.take_along_axis(part, order, axis=1)
    return (
        np.take_along_axis(scores, idx, axis=1),
        np.asarray(data_ids)[idx],
    )


class _Sample:
    __slots__ = ("name", "version", "index", "queries", "ids")

    def __init__(self, name, version, index, queries, ids):
        self.name = name
        self.version = version
        self.index = index
        self.queries = queries
        self.ids = ids


class QualityAuditor:
    """Asynchronous shadow-scoring of served batches against an exact oracle.

    Parameters
    ----------
    k:
        Depth of the audited recall (``recall@k``); served results are
        truncated to this many columns.
    sampling:
        Fraction of observed batches audited (1.0 = every batch).
    threshold:
        Recall EWMA below this fires the degradation alarm.
    ewma_alpha:
        Weight of the newest sample in the EWMA (higher = twitchier).
    queue_cap:
        Bound on in-flight samples; overflow drops (never blocks).
    on_degraded:
        ``callback(name, version, ewma)`` invoked from the worker thread
        once per downward threshold crossing.
    registry:
        Metrics registry to publish into (process default when omitted).
    """

    def __init__(
        self,
        *,
        k: int = 10,
        sampling: float = 0.1,
        threshold: float = 0.9,
        ewma_alpha: float = 0.3,
        queue_cap: int = 64,
        on_degraded: Optional[Callable[[str, int, float], None]] = None,
        registry: Optional[MetricsRegistry] = None,
        seed: int = 0,
    ):
        if not 0.0 <= sampling <= 1.0:
            raise ValueError(f"sampling must be in [0, 1], got {sampling}")
        self.k = int(k)
        self.sampling = float(sampling)
        self.threshold = float(threshold)
        self.ewma_alpha = float(ewma_alpha)
        self.on_degraded = on_degraded
        self._registry = registry
        self._rng = random.Random(seed)
        self._queue: "queue.Queue[Optional[_Sample]]" = queue.Queue(
            maxsize=int(queue_cap)
        )
        self._lock = threading.Lock()
        self._done = threading.Condition(self._lock)
        self._submitted = 0
        self._processed = 0
        self._dropped = 0
        self._errors = 0
        # (name) -> {"version", "ewma", "n", "alarmed", "last", "displacement"}
        self._state: Dict[str, Dict[str, object]] = {}
        self._oracle_cache: Dict[Tuple[str, int, int], Tuple[np.ndarray, np.ndarray]] = {}
        self._stopping = False
        self._thread = threading.Thread(
            target=self._worker, name="raft-tpu-quality-auditor", daemon=True
        )
        self._thread.start()
        self._reg().register_provider("quality", self.snapshot)

    def _reg(self) -> MetricsRegistry:
        return self._registry if self._registry is not None else default_registry()

    # -- hot path ------------------------------------------------------------
    def observe(self, name: str, version: int, index, queries, ids) -> bool:
        """Maybe enqueue one served batch for auditing.  O(1), non-blocking,
        exception-free — this runs inside the batcher's dispatch path."""
        try:
            if self._stopping or self._rng.random() >= self.sampling:
                return False
            sample = _Sample(
                name, version, index, np.asarray(queries), np.asarray(ids)
            )
            try:
                self._queue.put_nowait(sample)
            except queue.Full:
                with self._lock:
                    self._dropped += 1
                self._reg().counter(
                    "raft_tpu_quality_dropped_total",
                    help="audit samples dropped on a full queue",
                ).inc(index=name)
                return False
            with self._lock:
                self._submitted += 1
            return True
        except Exception:  # noqa: BLE001 — never let auditing fail a search
            return False

    # -- worker side ---------------------------------------------------------
    def _oracle(self, sample: _Sample) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        key = (
            sample.name, sample.version,
            int(getattr(sample.index, "generation", 0)),
        )
        hit = self._oracle_cache.get(key)
        if hit is not None:
            return hit
        vecs, ids = sample.index.live_vectors()
        if vecs.shape[0] == 0:
            return None
        if len(self._oracle_cache) >= _ORACLE_CACHE_CAP:
            self._oracle_cache.pop(next(iter(self._oracle_cache)))
        self._oracle_cache[key] = (vecs, ids)
        return self._oracle_cache[key]

    def _audit(self, sample: _Sample) -> None:
        oracle = self._oracle(sample)
        if oracle is None:
            return
        vecs, vec_ids = oracle
        metric = getattr(sample.index, "metric", "sqeuclidean")
        k = min(self.k, sample.ids.shape[1], vecs.shape[0])
        _, ref_ids = _exact_topk(vecs, vec_ids, sample.queries, k, metric)
        served = sample.ids[:, :k]
        recall = recall_at_k(served, ref_ids, k)
        displacement = rank_displacement(served, ref_ids, k)

        reg = self._reg()
        labels = {"index": sample.name, "version": str(sample.version)}
        reg.gauge(
            "raft_tpu_recall",
            help="recall@k of the latest audited batch vs the exact oracle",
        ).set(recall, **labels)
        reg.gauge(
            "raft_tpu_rank_displacement",
            help="mean |served rank - true rank| of the latest audited batch",
        ).set(displacement, **labels)
        reg.counter(
            "raft_tpu_quality_audited_total", help="batches shadow-scored"
        ).inc(index=sample.name)

        with self._lock:
            st = self._state.get(sample.name)
            if st is None or st["version"] != sample.version:
                st = {
                    "version": sample.version, "ewma": recall, "n": 0,
                    "alarmed": False, "last": recall,
                    "displacement": displacement,
                }
                self._state[sample.name] = st
            else:
                st["ewma"] = (
                    self.ewma_alpha * recall
                    + (1.0 - self.ewma_alpha) * float(st["ewma"])
                )
            st["n"] = int(st["n"]) + 1
            st["last"] = recall
            st["displacement"] = displacement
            ewma = float(st["ewma"])
            fire = ewma < self.threshold and not st["alarmed"]
            rearm = bool(st["alarmed"]) and ewma >= self.threshold
            if fire:
                st["alarmed"] = True
            elif rearm:
                st["alarmed"] = False
        reg.gauge(
            "raft_tpu_recall_ewma",
            help="EWMA of audited recall@k (degradation alarm input)",
        ).set(ewma, **labels)
        if fire:
            _log.warning(
                "recall degradation on %r v%d: ewma %.3f < threshold %.3f "
                "(last sample %.3f over %d audits)",
                sample.name, sample.version, ewma, self.threshold,
                recall, int(st["n"]),
            )
            # the alarm edge is an incident: the bus's flight subscriber
            # captures the in-flight batches while they are still in the
            # recorder ring (debounced, so a subsequent UNHEALTHY
            # healthz() does not double-dump) and the incident manager
            # opens the timeline
            events.publish(
                "quality_alarm",
                index=sample.name, version=sample.version,
                ewma=ewma, threshold=self.threshold, last=recall,
            )
            cb = self.on_degraded
            if cb is not None:
                try:
                    cb(sample.name, sample.version, ewma)
                except Exception:
                    _log.exception("on_degraded callback raised")
        elif rearm:
            # recovery edge: tells the incident manager the story is over
            events.publish(
                "quality_alarm", "quality_recovered", recovered=True,
                index=sample.name, version=sample.version, ewma=ewma,
            )

    def _worker(self) -> None:
        while True:
            sample = self._queue.get()
            if sample is None:
                return
            try:
                self._audit(sample)
            except Exception:
                with self._lock:
                    self._errors += 1
                _log.exception(
                    "audit failed for %r v%s", sample.name, sample.version
                )
            finally:
                with self._done:
                    self._processed += 1
                    self._done.notify_all()

    # -- introspection / lifecycle -------------------------------------------
    def flush(self, timeout: float = 10.0) -> bool:
        """Block until every enqueued sample has been audited (one audit
        flush); False on timeout.  Test/benchmark synchronization point."""
        with self._done:
            return self._done.wait_for(
                lambda: self._processed >= self._submitted, timeout=timeout
            )

    def recall_ewma(self, name: str) -> Optional[float]:
        with self._lock:
            st = self._state.get(name)
            return float(st["ewma"]) if st is not None else None

    def snapshot(self) -> Dict[str, object]:
        """Provider section for registry snapshots."""
        with self._lock:
            return {
                "sampling": self.sampling,
                "threshold": self.threshold,
                "submitted": self._submitted,
                "processed": self._processed,
                "dropped": self._dropped,
                "errors": self._errors,
                "indexes": {
                    name: {
                        "version": st["version"],
                        "recall_ewma": float(st["ewma"]),
                        "last_recall": float(st["last"]),
                        "rank_displacement": float(st["displacement"]),
                        "audits": int(st["n"]),
                        "alarmed": bool(st["alarmed"]),
                    }
                    for name, st in self._state.items()
                },
            }

    def stop(self) -> None:
        """Drain and stop the worker; detach the snapshot provider."""
        if self._stopping:
            return
        self._stopping = True
        try:
            self._queue.put(None, timeout=5.0)
        except queue.Full:
            pass  # worker wedged; the daemon thread dies with the process
        self._thread.join(timeout=10.0)
        self._reg().unregister_provider("quality", expected=self.snapshot)

    def __enter__(self) -> "QualityAuditor":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()
