"""Process-wide metrics registry: counters, gauges, labeled histograms.

The reference RAFT answers "where did the milliseconds go" with NVTX ranges
read back through Nsight; a serving deployment needs the same answer as
*queryable state* — a registry any thread can record into and any exporter
can snapshot, with no profiler session attached (ref: core/nvtx.hpp ranges;
"Memory Safe Computations with XLA Compiler" argues the instrumentation
must live in the framework, not the bench).

Design points:

- **Thread-safe**: one lock per registry guards the metric map; each series
  updates under it.  Recording is a dict lookup + float add — cheap enough
  for the serve hot path (guarded by ``tests/test_obs.py``'s overhead test).
- **Fixed bucket ladders**: histograms bucket into a ladder fixed at
  creation (default: exponential seconds ladder spanning 50 µs → 60 s), so
  the Prometheus export is a classic cumulative ``_bucket`` series.  A
  bounded reservoir of raw observations rides along for exact percentiles
  in JSON snapshots (same O(reservoir) math as ``serve.metrics``).
- **Label-cardinality cap**: every metric refuses to materialize more than
  ``max_series`` distinct label sets — a runaway label (e.g. a request id)
  raises :class:`LabelCardinalityError` instead of silently leaking memory.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

#: default per-metric cap on distinct label sets
MAX_SERIES = 256

#: default histogram ladder: exponential, 50 µs .. 60 s (seconds).  Chosen
#: to straddle both single-batch CPU dispatches (~100 µs) and cold XLA
#: compiles (~10-100 s tails land in +Inf).
DEFAULT_BUCKETS = tuple(
    5e-5 * (2.0 ** i) for i in range(21)
)  # 50us, 100us, ... ~52s

#: bounded per-series reservoir for exact percentile math
_RESERVOIR = 2048

LabelValue = Tuple[Tuple[str, str], ...]


class LabelCardinalityError(RuntimeError):
    """A metric exceeded its label-set cap (would leak memory forever)."""


def _label_key(labels: Dict[str, str]) -> LabelValue:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class _Metric:
    """Base: named metric holding labeled series under the registry lock."""

    kind = "untyped"

    def __init__(self, name: str, help: str, lock: threading.Lock,
                 max_series: int):
        self.name = name
        self.help = help
        self._lock = lock
        self._max_series = max_series
        self._series: Dict[LabelValue, object] = {}

    def _get_series(self, labels: Dict[str, str]):
        key = _label_key(labels)
        s = self._series.get(key)
        if s is None:
            if len(self._series) >= self._max_series:
                raise LabelCardinalityError(
                    f"metric {self.name!r} exceeded {self._max_series} label "
                    f"sets (offending labels: {dict(key)!r}); a label is "
                    "probably carrying an unbounded value (request id, "
                    "timestamp, ...)"
                )
            s = self._new_series()
            self._series[key] = s
        return s

    def _new_series(self):  # pragma: no cover - abstract
        raise NotImplementedError

    def series(self) -> List[LabelValue]:
        with self._lock:
            return list(self._series.keys())

    def remove(self, **labels: str) -> bool:
        """Drop one labeled series; True when it existed.

        Retiring a label set (an unregistered index, a dead registry
        version) must also retire its series, or the exporter keeps
        publishing the last value forever — a gauge that can never go
        away reads as a leak that never resolves."""
        with self._lock:
            return self._series.pop(_label_key(labels), None) is not None

    def remove_matching(self, **labels: str) -> int:
        """Drop every series whose labels include ``labels``; returns the
        count removed (``index=x`` clears all of x's versions at once)."""
        want = set(_label_key(labels))
        with self._lock:
            dead = [k for k in self._series if want.issubset(set(k))]
            for k in dead:
                del self._series[k]
            return len(dead)


class Counter(_Metric):
    """Monotonically increasing count (requests, compiles, errors)."""

    kind = "counter"

    def _new_series(self) -> List[float]:
        return [0.0]

    def inc(self, value: float = 1.0, **labels: str) -> None:
        if value < 0:
            raise ValueError(f"counter {self.name!r} cannot decrease")
        with self._lock:
            self._get_series(labels)[0] += value

    def value(self, **labels: str) -> float:
        with self._lock:
            s = self._series.get(_label_key(labels))
            return float(s[0]) if s is not None else 0.0

    def collect(self) -> Dict[LabelValue, float]:
        with self._lock:
            return {k: float(v[0]) for k, v in self._series.items()}


class Gauge(_Metric):
    """Last-write-wins instantaneous value (queue depth, index size)."""

    kind = "gauge"

    def _new_series(self) -> List[float]:
        return [0.0]

    def set(self, value: float, **labels: str) -> None:
        with self._lock:
            self._get_series(labels)[0] = float(value)

    def inc(self, value: float = 1.0, **labels: str) -> None:
        with self._lock:
            self._get_series(labels)[0] += value

    def value(self, **labels: str) -> float:
        with self._lock:
            s = self._series.get(_label_key(labels))
            return float(s[0]) if s is not None else 0.0

    def collect(self) -> Dict[LabelValue, float]:
        with self._lock:
            return {k: float(v[0]) for k, v in self._series.items()}


class _HistSeries:
    __slots__ = ("bucket_counts", "sum", "count", "reservoir", "exemplars")

    def __init__(self, n_buckets: int):
        self.bucket_counts = [0] * n_buckets  # non-cumulative, per bucket
        self.sum = 0.0
        self.count = 0
        self.reservoir: List[float] = []
        #: bucket index -> (observed value, exemplar id); last-write-wins,
        #: so storage is bounded by the ladder length, not traffic
        self.exemplars: Dict[int, Tuple[float, str]] = {}


class Histogram(_Metric):
    """Observations bucketed into a fixed ladder + bounded raw reservoir.

    Bucket semantics match Prometheus: ``bucket_counts[i]`` counts
    observations with ``value <= buckets[i]`` (exclusive of earlier
    buckets); values above the last edge land in the implicit ``+Inf``
    overflow slot (index ``len(buckets)``).
    """

    kind = "histogram"

    def __init__(self, name: str, help: str, lock: threading.Lock,
                 max_series: int, buckets: Sequence[float] = DEFAULT_BUCKETS,
                 reservoir: int = _RESERVOIR):
        super().__init__(name, help, lock, max_series)
        b = [float(x) for x in buckets]
        if not b or sorted(b) != b:
            raise ValueError(f"histogram {name!r} needs ascending buckets")
        self.buckets: Tuple[float, ...] = tuple(b)
        self._reservoir_cap = int(reservoir)

    def _new_series(self) -> _HistSeries:
        return _HistSeries(len(self.buckets) + 1)  # +1: +Inf overflow

    def observe(self, value: float, exemplar: Optional[str] = None,
                **labels: str) -> None:
        """Record ``value``.  ``exemplar`` (a request/span id) is retained
        per destination bucket, last-write-wins — the link from a fat p99
        bucket back to a concrete flight-recorder entry.  ``exemplar`` is
        a reserved keyword, not a label."""
        value = float(value)
        # bisect outside the lock — buckets are immutable
        lo, hi = 0, len(self.buckets)
        while lo < hi:
            mid = (lo + hi) // 2
            if value <= self.buckets[mid]:
                hi = mid
            else:
                lo = mid + 1
        with self._lock:
            s = self._get_series(labels)
            s.bucket_counts[lo] += 1
            s.sum += value
            s.count += 1
            if exemplar is not None:
                s.exemplars[lo] = (value, str(exemplar))
            res = s.reservoir
            if len(res) >= self._reservoir_cap:
                # ring overwrite: keep a sliding window of recent values
                res[s.count % self._reservoir_cap] = value
            else:
                res.append(value)

    def percentile(self, q: float, **labels: str) -> Optional[float]:
        """Exact percentile over the (bounded) reservoir; None when empty."""
        with self._lock:
            s = self._series.get(_label_key(labels))
            if s is None or not s.reservoir:
                return None
            arr = np.asarray(s.reservoir, dtype=np.float64)
        return float(np.percentile(arr, q))

    def collect(self) -> Dict[LabelValue, Dict[str, object]]:
        with self._lock:
            out = {}
            for k, s in self._series.items():
                out[k] = {
                    "bucket_counts": list(s.bucket_counts),
                    "sum": float(s.sum),
                    "count": int(s.count),
                    "reservoir": np.asarray(s.reservoir, dtype=np.float64),
                    "exemplars": dict(s.exemplars),
                }
        return out

    def bucket_totals(self) -> Dict[LabelValue, Tuple[List[int], int]]:
        """``{labels: (bucket_counts, count)}`` — the cheap read for
        periodic pollers (the SLO evaluator).  Unlike :meth:`collect`
        this copies no reservoirs or exemplars, so the lock — shared
        with hot-path ``observe()`` — is held for O(buckets) per series
        instead of O(reservoir)."""
        with self._lock:
            return {
                k: (list(s.bucket_counts), int(s.count))
                for k, s in self._series.items()
            }

    def bucket_edge(self, i: int) -> float:
        """Upper edge of bucket ``i`` (``inf`` for the overflow slot)."""
        return self.buckets[i] if i < len(self.buckets) else float("inf")

    def clear_exemplars(self) -> None:
        """Drop retained exemplars on every series (test isolation)."""
        with self._lock:
            for s in self._series.values():
                s.exemplars.clear()

    def snapshot_series(self, k: LabelValue, data: Dict[str, object]
                        ) -> Dict[str, object]:
        """JSON-safe view of one collected series (percentiles in ms)."""
        arr = data["reservoir"]
        out: Dict[str, object] = {
            "count": data["count"],
            "sum": data["sum"],
        }
        if getattr(arr, "size", 0):
            for q in (50, 90, 99):
                out[f"p{q}_ms"] = float(np.percentile(arr, q) * 1e3)
        exemplars = data.get("exemplars")
        if exemplars:
            out["exemplars"] = [
                {
                    # "+Inf" keeps the overflow edge strict-JSON-safe
                    "le": (e if e != float("inf") else "+Inf"),
                    "value": v,
                    "id": ex,
                }
                for i, (v, ex) in sorted(exemplars.items())
                for e in (self.bucket_edge(i),)
            ]
        return out


class MetricsRegistry:
    """Named metrics + pluggable snapshot providers, all thread-safe.

    One instance normally lives for the whole process (module-level
    :func:`raft_tpu.obs.registry`); tests build private ones.
    """

    def __init__(self, *, max_series: int = MAX_SERIES):
        self._lock = threading.Lock()          # guards metric/provider maps
        self._series_lock = threading.Lock()   # shared by all series updates
        self._metrics: Dict[str, _Metric] = {}
        self._providers: Dict[str, Callable[[], Dict[str, object]]] = {}
        self._max_series = max_series

    # -- metric constructors (get-or-create, type-checked) ------------------
    def _named(self, name: str, cls, **kwargs) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, lock=self._series_lock,
                        max_series=self._max_series, **kwargs)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}"
                )
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._named(name, Counter, help=help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._named(name, Gauge, help=help)

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._named(name, Histogram, help=help, buckets=buckets)

    def metrics(self) -> List[_Metric]:
        with self._lock:
            return list(self._metrics.values())

    # -- providers: external components merged into snapshots ---------------
    def register_provider(
        self, name: str, fn: Callable[[], Dict[str, object]]
    ) -> None:
        """Merge ``fn()`` (a JSON-safe dict) under ``name`` in snapshots.
        Re-registering a name replaces the previous provider."""
        with self._lock:
            self._providers[name] = fn

    def unregister_provider(self, name: str, expected=None) -> None:
        """Remove provider ``name``.  With ``expected``, remove only when
        the registered callable is that exact one — so tearing down a
        replaced component can't detach its successor's provider."""
        with self._lock:
            if expected is None or self._providers.get(name) == expected:
                self._providers.pop(name, None)

    # -- reading -------------------------------------------------------------
    def snapshot(self) -> Dict[str, object]:
        """One JSON-safe dict: all metrics + all provider sections."""
        out: Dict[str, object] = {
            "counters": {}, "gauges": {}, "histograms": {},
        }
        for m in self.metrics():
            if isinstance(m, Counter):
                out["counters"][m.name] = {
                    _fmt_labels(k): v for k, v in m.collect().items()
                }
            elif isinstance(m, Gauge):
                out["gauges"][m.name] = {
                    _fmt_labels(k): v for k, v in m.collect().items()
                }
            elif isinstance(m, Histogram):
                out["histograms"][m.name] = {
                    _fmt_labels(k): m.snapshot_series(k, d)
                    for k, d in m.collect().items()
                }
        with self._lock:
            providers = dict(self._providers)
        for name, fn in providers.items():
            try:
                out[name] = fn()
            except Exception as exc:  # provider bugs must not kill snapshots
                out[name] = {"error": repr(exc)}
        return out

    def clear_exemplars(self) -> None:
        """Drop retained histogram exemplars without touching counts —
        the between-tests reset (exemplars are last-write-wins state)."""
        for m in self.metrics():
            if isinstance(m, Histogram):
                m.clear_exemplars()

    def reset(self) -> None:
        """Drop all metrics and providers (tests / long-lived REPLs)."""
        with self._lock:
            self._metrics.clear()
            self._providers.clear()


def _fmt_labels(key: LabelValue) -> str:
    """Stable human/JSON key for one label set ('' for the bare series)."""
    return ",".join(f"{k}={v}" for k, v in key)


# ---------------------------------------------------------------------------
# the process-wide default registry

_default = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    return _default
