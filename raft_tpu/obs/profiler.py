"""One-line Perfetto captures: ``with obs.profile(dir): ...``.

Wraps ``jax.profiler.trace`` the way the Ragged Paged Attention tooling
wraps its Perfetto captures (arxiv 2604.15464): the capture is bracketed
in a span so registry snapshots record that (and how long) a profiling
session ran, and the ``RAFT_TPU_DISABLE_PROFILER`` escape hatch from
``core.trace`` still applies — CI boxes without a writable trace dir can
no-op the capture without touching call sites.
"""

from __future__ import annotations

import contextlib
from typing import Iterator

from raft_tpu.core import env as _env
from raft_tpu.obs import spans as _spans
from raft_tpu.obs.registry import default_registry


@contextlib.contextmanager
def profile(log_dir: str, *, host_tracer_level: int = 2) -> Iterator[None]:
    """Capture a Perfetto/XPlane trace of the enclosed block to ``log_dir``.

    View with ``xprof`` / TensorBoard's profile plugin, or load the
    ``.trace.json.gz`` into https://ui.perfetto.dev.  Every
    ``trace_range``-wrapped call inside shows as a named host range;
    device ops carry the matching ``jax.named_scope`` labels.
    """
    if _env.env_bool("RAFT_TPU_DISABLE_PROFILER"):
        yield
        return
    import jax

    default_registry().counter(
        "raft_tpu_profile_captures_total",
        help="jax.profiler trace sessions started via obs.profile",
    ).inc()
    with _spans.span("obs.profile"):
        with jax.profiler.trace(log_dir):
            yield
