"""One-line Perfetto captures: ``with obs.profile(dir): ...``.

Wraps ``jax.profiler.trace`` the way the Ragged Paged Attention tooling
wraps its Perfetto captures (arxiv 2604.15464): the capture is bracketed
in a span so registry snapshots record that (and how long) a profiling
session ran, and the ``RAFT_TPU_DISABLE_PROFILER`` escape hatch from
``core.trace`` still applies — CI boxes without a writable trace dir can
no-op the capture without touching call sites.

:func:`capture_async` is the unattended variant the perf ledger's
``perf_regression`` subscriber fires: ``jax.profiler.start_trace`` plus
a timer-driven stop, so a regression detected on the serving path gets
a bounded profile of the *next* few dispatches without blocking the
publisher.  One capture runs at a time (the jax profiler is a process
singleton); overlapping requests are counted and skipped.
:func:`last_capture` exposes the newest capture's info the same way
``flight.last_dump()`` does, which is what lets the incident manager
attach captures into timelines exactly like flight dumps.
"""

from __future__ import annotations

import contextlib
import os
import re
import threading
import time
from typing import Dict, Iterator, Optional

from raft_tpu.core import env as _env
from raft_tpu.obs import spans as _spans
from raft_tpu.obs.registry import default_registry


@contextlib.contextmanager
def profile(log_dir: str, *, host_tracer_level: int = 2) -> Iterator[None]:
    """Capture a Perfetto/XPlane trace of the enclosed block to ``log_dir``.

    View with ``xprof`` / TensorBoard's profile plugin, or load the
    ``.trace.json.gz`` into https://ui.perfetto.dev.  Every
    ``trace_range``-wrapped call inside shows as a named host range;
    device ops carry the matching ``jax.named_scope`` labels.
    """
    if _env.env_bool("RAFT_TPU_DISABLE_PROFILER"):
        yield
        return
    import jax

    default_registry().counter(
        "raft_tpu_profile_captures_total",
        help="jax.profiler trace sessions started via obs.profile",
    ).inc()
    with _spans.span("obs.profile"):
        with jax.profiler.trace(log_dir):
            yield


# ---------------------------------------------------------------------------
# unattended captures (perf-regression auto-profile)

_state_lock = threading.Lock()
_active = False
_last_capture: Optional[Dict[str, object]] = None


def last_capture() -> Optional[Dict[str, object]]:
    """``{"path", "reason", "duration_s", "t", "unix_time"}`` of the most
    recent :func:`capture_async`, or None.  Recorded at capture *start*
    so the incident correlating the triggering event can attach the
    capture immediately (the trace file lands ``duration_s`` later)."""
    with _state_lock:
        return dict(_last_capture) if _last_capture is not None else None


def capture_async(
    log_dir: str, *, duration_s: float, reason: str = "manual",
) -> Optional[Dict[str, object]]:
    """Start a bounded profiler capture without blocking the caller.

    Returns the capture info dict (also exposed by :func:`last_capture`)
    or None when profiling is disabled, a capture is already running, or
    the profiler refuses to start.  The stop runs on a daemon timer
    thread after ``duration_s``.
    """
    global _active, _last_capture
    if _env.env_bool("RAFT_TPU_DISABLE_PROFILER") or duration_s <= 0:
        return None
    import jax

    with _state_lock:
        if _active:
            default_registry().counter(
                "raft_tpu_profile_captures_skipped_total",
                help="async capture requests skipped because one was "
                     "already running",
            ).inc()
            return None
        _active = True
    stem = re.sub(r"[^A-Za-z0-9_.-]", "_", reason)
    path = os.path.join(log_dir, f"profile_{stem}_{os.getpid()}")
    try:
        jax.profiler.start_trace(path)
    except Exception:  # already tracing elsewhere — never fail the caller
        with _state_lock:
            _active = False
        return None
    info = {
        "path": path,
        "reason": reason,
        "duration_s": float(duration_s),
        "t": time.perf_counter(),
        "unix_time": time.time(),
    }
    with _state_lock:
        _last_capture = dict(info)
    default_registry().counter(
        "raft_tpu_profile_captures_total",
        help="jax.profiler trace sessions started via obs.profile",
    ).inc()
    timer = threading.Timer(duration_s, _finish_capture)
    timer.daemon = True
    timer.start()
    return info


def _finish_capture() -> None:
    global _active
    with _state_lock:
        if not _active:
            return
        _active = False
    try:
        import jax

        jax.profiler.stop_trace()
    except Exception:  # stop raced a reset — the capture is gone anyway
        pass


def reset() -> None:
    """Stop any active capture and forget the last one (test hygiene,
    reached through ``events.reset`` → ``perf._on_bus_reset``)."""
    global _last_capture
    _finish_capture()
    with _state_lock:
        _last_capture = None
