"""Per-query EXPLAIN plans + the tail-sampled query archive.

The rest of the obs stack explains *aggregate* behaviour (PerfLedger
hotspots, SLO burn, incident timelines); this module explains a *single
request*: which admission decision it hit, what effort level was in
force and who set it, which capacity bucket and kernel path served it,
which coarse lists were probed, how the page cache treated its lists,
which shards contributed, and where its milliseconds went.  Two modes:

- **On-demand deep explain** — :meth:`raft_tpu.serve.service.
  SearchService.explain` runs one real request through the normal
  batched path and assembles an :class:`ExplainPlan` from instruments
  that already exist.  Nothing is re-simulated: the plan is a join over
  the enriched flight-recorder batch record (keyed by the existing
  request id) plus a few host-side, off-hot-path probes (coarse probe
  replay, shard ownership of the returned ids, the recall-audit EWMA).
- **Always-on tail sampling** — a bounded :class:`QueryArchive` ring
  retains full plans only for the interesting tail: slowest-per-window,
  shed / deadline-expired, errored, and recall-alarm-correlated
  requests, plus a deterministic 1-in-N baseline population.  The
  archive dumps alongside flight records on incident triggers and the
  resulting ``explain_dump`` context event links the artifact into the
  open incident's timeline.

Collection discipline matches the flight recorder: **zero new hot-path
clock calls** (the :class:`TailSampler` clocks itself off the batch
record's existing ``t_done`` stamp), zero host syncs, and decisions are
recorded host-side where they are already made — the batcher enriches
the one dict it already builds per completed batch.  Everything is
gated by ``RAFT_TPU_EXPLAIN`` (deep explains temporarily force the gate
open for their own request only) and by the master obs switch.

Env knobs: ``RAFT_TPU_EXPLAIN`` (enable tail sampling),
``RAFT_TPU_EXPLAIN_ARCHIVE_CAP`` (archive ring size, default 128),
``RAFT_TPU_EXPLAIN_TAIL_PER_WINDOW`` (slowest-N kept per one-second
window, default 4).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Dict, List, Optional

import raft_tpu.obs.spans as _spans
from raft_tpu.core import env as _env
from raft_tpu.core.trace import traced
from raft_tpu.obs import flight as _flight
from raft_tpu.obs.registry import default_registry

#: default archive ring capacity (plans)
DEFAULT_CAP = 128

#: default slowest-N retained per sampling window
DEFAULT_TAIL_PER_WINDOW = 4

#: tail-sampler window length (seconds of record time, not wall clocks)
WINDOW_S = 1.0

#: deterministic baseline population: every Nth observed request
BASELINE_STRIDE = 64

#: how long after a quality alarm requests count as alarm-correlated
ALARM_WINDOW_S = 2.0


def _env_cap() -> int:
    try:
        return max(1, _env.env_int(
            "RAFT_TPU_EXPLAIN_ARCHIVE_CAP", DEFAULT_CAP
        ))
    except ValueError:
        return DEFAULT_CAP


def _env_tail_per_window() -> int:
    try:
        return max(1, _env.env_int(
            "RAFT_TPU_EXPLAIN_TAIL_PER_WINDOW", DEFAULT_TAIL_PER_WINDOW
        ))
    except ValueError:
        return DEFAULT_TAIL_PER_WINDOW


# ---------------------------------------------------------------------------
# enablement: env gate + deep-explain scope

_deep_lock = threading.Lock()
_deep_active = 0


@contextmanager
def deep_scope():
    """Force the explain gate open for the duration (deep explains work
    without ``RAFT_TPU_EXPLAIN`` set; the batch carrying the explained
    request is observed exactly like a sampled one)."""
    global _deep_active
    with _deep_lock:
        _deep_active += 1
    try:
        yield
    finally:
        with _deep_lock:
            _deep_active -= 1


def enabled() -> bool:
    """Whether explain collection is on: ``RAFT_TPU_EXPLAIN`` or an
    active :func:`deep_scope`.  Checked once per batch (and once per
    paged-lists resolve), never per request."""
    if _deep_active > 0:
        return True
    return _env.env_bool("RAFT_TPU_EXPLAIN")


# ---------------------------------------------------------------------------
# thread-local stamps: decisions recorded where they are already made.
# The dispatch thread stamps (ragged dispatch params, page-cache deltas)
# and the batcher consumes on the same thread right after the call —
# mirroring kernels.stamp_kernel_path/consume_kernel_path.

_tls = threading.local()


def stamp_page_stats(stats: Dict[str, object]) -> None:
    """Record this dispatch's page-cache interaction (set by
    ``neighbors._common.paged_lists_for_search`` on the dispatch
    thread)."""
    _tls.page = stats


def consume_page_stats(default: Optional[Dict[str, object]] = None):
    """Pop the page stamp (batcher ``_invoke``, same thread)."""
    stats = getattr(_tls, "page", None)
    _tls.page = None
    return stats if stats is not None else default


def stamp_dispatch(info: Dict[str, object]) -> None:
    """Record dispatch-level parameters (effective search params, k_max)
    — set by ``serve.ragged.RaggedSearcher`` on the dispatch thread."""
    _tls.dispatch = info


def consume_dispatch(default: Optional[Dict[str, object]] = None):
    """Pop the dispatch stamp (batcher ``_invoke``, same thread)."""
    info = getattr(_tls, "dispatch", None)
    _tls.dispatch = None
    return info if info is not None else default


# ---------------------------------------------------------------------------
# the plan

class ExplainPlan:
    """One request's assembled EXPLAIN-ANALYZE plan.

    A thin, JSON-able wrapper over named sections (``request``,
    ``outcome``, ``admission``, ``effort``, ``bucket``, ``kernel_path``,
    ``probe``, ``page``, ``shards``, ``stages``, ...).  Sections a given
    backend cannot attribute carry ``{"available": False}`` rather than
    disappearing, so consumers need no per-backend branching.
    """

    def __init__(self, sections: Dict[str, object]):
        self.sections = sections

    def __getitem__(self, key: str):
        return self.sections[key]

    def get(self, key: str, default=None):
        return self.sections.get(key, default)

    def to_dict(self) -> Dict[str, object]:
        return {"schema": "raft_tpu.explain", **self.sections}

    def to_json(self, indent: Optional[int] = 2) -> str:
        return json.dumps(self.to_dict(), indent=indent, default=str)

    def to_text(self) -> str:
        """Human-readable plan, one section per block."""
        s = self.sections
        req = s.get("request", {}) or {}
        out = s.get("outcome", {}) or {}
        lines = [
            f"EXPLAIN request {req.get('id')} "
            f"index={s.get('bucket', {}).get('index')} "
            f"outcome={out.get('outcome')}",
        ]
        for key in ("request", "outcome", "admission", "effort", "bucket",
                    "kernel_path", "probe", "page", "shards", "stages",
                    "audit", "sampling", "results"):
            if key not in s:
                continue
            val = s[key]
            if isinstance(val, dict):
                body = ", ".join(f"{k}={v}" for k, v in val.items())
            else:
                body = str(val)
            lines.append(f"  {key:<12} {body}")
        return "\n".join(lines)


def summary_line(record: Dict[str, object]) -> Dict[str, object]:
    """The compact explain summary the slow-query log appends to its
    entries: effort level, kernel path, bucket, page hit ratio — enough
    to act on a slow line without a separate archive lookup."""
    effort = record.get("effort") or {}
    page = record.get("page") or {}
    hits = page.get("hits")
    misses = page.get("misses")
    ratio = None
    if hits is not None and misses is not None and (hits + misses) > 0:
        ratio = round(hits / float(hits + misses), 4)
    return {
        "effort_level": effort.get("effective_level"),
        "effort_source": effort.get("source"),
        "kernel_path": record.get("kernel_path"),
        "page_hit_ratio": ratio,
    }


def build_plan(record: Dict[str, object], member: Dict[str, object],
               reason: str) -> ExplainPlan:
    """Join one member request against its enriched batch record.

    Pure dict shuffling over stamps already taken — no clocks, no device
    access.  ``record`` is the flight-recorder batch dict (enriched by
    the batcher with ``admission_level`` / ``effort`` / ``kernel_path``
    / ``page`` / ``dispatch`` when explain is enabled); ``member`` is
    the per-request entry inside it.
    """
    error = record.get("error")
    dispatch = record.get("dispatch") or {}
    probe = dict(record.get("probe") or {"available": False})
    if dispatch:
        # dispatch-level params (effective n_probes etc.) annotate the
        # probe section even before a deep explain fills in list ids
        probe.setdefault("params", dispatch)
    sections: Dict[str, object] = {
        "request": {
            "id": member.get("id"),
            "rows": member.get("rows"),
            "k": member.get("k"),
            "fid": member.get("fid"),
            "priority": member.get("priority"),
            "queue_ms": member.get("queue_ms"),
            "latency_ms": member.get("latency_ms"),
        },
        "outcome": {
            "outcome": "error" if error else "ok",
            "error": error,
            "sampled_reason": reason,
        },
        "admission": {
            "admitted": True,
            "pressure_level": record.get("admission_level", 0),
        },
        "effort": record.get("effort") or {"available": False},
        "bucket": {
            "index": record.get("index"),
            "bucket": record.get("bucket"),
            "batch_rows": record.get("rows"),
            "seq": record.get("seq"),
            "compiles": record.get("compiles"),
            "hedged": record.get("hedged", False),
        },
        "kernel_path": record.get("kernel_path") or "unknown",
        "probe": probe,
        "page": record.get("page") or {"available": False},
        "shards": {"available": False},
        "stages": {
            "batch_stages_s": record.get("stages_s"),
            "batch_waits_s": record.get("waits_s"),
            "queue_ms": member.get("queue_ms"),
            "latency_ms": member.get("latency_ms"),
            "request_stages_ms": member.get("stages_ms"),
        },
    }
    return ExplainPlan(sections)


def shed_plan(req, index: str, outcome: str, level: int) -> ExplainPlan:
    """Minimal plan for a request that never reached a dispatch: shed by
    admission control or expired at its deadline.  Uses only stamps the
    request already carries (``t_submit``) — no new clocks."""
    try:
        # deferred: obs must not import serve at module time
        from raft_tpu.serve.overload import priority_name
        pname = priority_name(getattr(req, "priority", None))
    except Exception:  # noqa: BLE001 — labeling is best-effort
        pname = "unknown"
    sections: Dict[str, object] = {
        "request": {
            "id": getattr(req, "req_id", None),
            "rows": int(getattr(req, "rows", None).shape[0])
            if getattr(req, "rows", None) is not None else None,
            "k": getattr(req, "k", None),
            "fid": getattr(req, "fid", None),
            "priority": getattr(req, "priority", None),
            "priority_name": pname,
            "submit": getattr(req, "t_submit", None),
        },
        "outcome": {"outcome": outcome, "error": None,
                    "sampled_reason": outcome},
        "admission": {"admitted": False, "pressure_level": level},
        "effort": {"available": False},
        "bucket": {"index": index},
        "kernel_path": "none",
        "probe": {"available": False},
        "page": {"available": False},
        "shards": {"available": False},
        "stages": {"available": False},
    }
    return ExplainPlan(sections)


# ---------------------------------------------------------------------------
# tail sampling

class TailSampler:
    """Deterministic tail selection, clocked by the records themselves.

    "Now" is always the observed batch record's existing ``t_done``
    stamp — the sampler takes **zero clock calls of its own**, which
    also makes selection reproducible on a synthetic clock in tests.
    Selection reasons, in priority order:

    - ``recall_alarm`` — the request completed within
      :data:`ALARM_WINDOW_S` after a quality-alarm edge;
    - ``slow_window`` — among the slowest N (greedy top-N: a request is
      kept when fewer than N were kept this window or it is slower than
      the slowest already kept) in its aligned :data:`WINDOW_S` window;
    - ``baseline`` — every :data:`BASELINE_STRIDE`-th observed request
      (deterministic stride, not RNG).
    """

    def __init__(self, per_window: Optional[int] = None,
                 window_s: float = WINDOW_S,
                 baseline_stride: int = BASELINE_STRIDE,
                 alarm_window_s: float = ALARM_WINDOW_S):
        self._per_window = (
            per_window if per_window is not None else _env_tail_per_window()
        )
        self._window_s = float(window_s)
        self._stride = max(1, int(baseline_stride))
        self._alarm_window_s = float(alarm_window_s)
        self._lock = threading.Lock()
        self._win: Optional[int] = None
        self._kept: List[float] = []     # latencies kept this window
        self._count = 0
        self._alarm_t = float("-inf")

    def note_alarm(self, t: float) -> None:
        """Stamp a quality-alarm edge (bus-subscriber thread; ``t`` is
        the event's existing perf_counter stamp)."""
        with self._lock:
            self._alarm_t = max(self._alarm_t, float(t))

    def reasons(self, *, latency_s: float, now: float) -> List[str]:
        """Selection reasons for one observed request (empty = not
        sampled).  ``now`` is the batch record's ``t_done``."""
        out: List[str] = []
        with self._lock:
            self._count += 1
            if now - self._alarm_t <= self._alarm_window_s:
                out.append("recall_alarm")
            win = int(now // self._window_s) if self._window_s > 0 else 0
            if win != self._win:
                self._win = win
                self._kept = []
            if len(self._kept) < self._per_window:
                self._kept.append(latency_s)
                out.append("slow_window")
            elif latency_s > min(self._kept):
                self._kept.remove(min(self._kept))
                self._kept.append(latency_s)
                out.append("slow_window")
            if self._count % self._stride == 0:
                out.append("baseline")
        return out

    def reset(self) -> None:
        with self._lock:
            self._win = None
            self._kept = []
            self._count = 0
            self._alarm_t = float("-inf")
            self._per_window = _env_tail_per_window()


# ---------------------------------------------------------------------------
# the archive

class QueryArchive:
    """Bounded ring of archived ExplainPlans + dump machinery.

    One instance normally lives for the whole process (module-level
    :func:`default_archive`); tests build private ones.  All methods are
    thread-safe.  :meth:`observe_batch` is the only one near a serving
    path and runs once per completed batch, after futures are resolved,
    only when :func:`enabled` — it scans the record's member list and
    archives the selected tail.
    """

    def __init__(self, cap: Optional[int] = None,
                 sampler: Optional[TailSampler] = None):
        self._lock = threading.Lock()
        self._cap = cap if cap is not None else _env_cap()
        self._ring: deque = deque()
        self._depth: Dict[str, int] = {}
        self._archived = 0
        self._dump_seq = 0
        self._last_dump: Optional[Dict[str, object]] = None
        self._watch: set = set()
        self.sampler = sampler if sampler is not None else TailSampler()

    # -- deep-explain coordination ------------------------------------------
    def watch(self, request_id: int) -> None:
        """Mark one in-flight request for unconditional archiving
        (``SearchService.explain`` retrieves its plan by id)."""
        with self._lock:
            self._watch.add(request_id)

    def unwatch(self, request_id: int) -> None:
        with self._lock:
            self._watch.discard(request_id)

    def find(self, request_id: int) -> Optional[Dict[str, object]]:
        """Most recent archive entry for ``request_id``, or None."""
        with self._lock:
            for entry in reversed(self._ring):
                if entry.get("request_id") == request_id:
                    return entry
        return None

    # -- observation ---------------------------------------------------------
    def observe_batch(self, record: Dict[str, object]) -> None:
        """Scan one enriched batch record (the same dict the flight
        recorder keeps) and archive the interesting tail.  No clocks:
        the sampler runs on the record's ``t_done``."""
        if not _spans.enabled():
            return
        error = record.get("error")
        now = float(record.get("t_done", 0.0))
        with self._lock:
            watching = bool(self._watch)
            watch = set(self._watch) if watching else ()
        for member in record.get("requests") or ():
            reasons: List[str] = []
            if error:
                reasons.append("error")
            latency_s = float(member.get("latency_ms") or 0.0) / 1e3
            reasons.extend(
                self.sampler.reasons(latency_s=latency_s, now=now)
            )
            deep = watching and member.get("id") in watch
            if deep:
                reasons.insert(0, "deep")
            if not reasons:
                continue
            plan = build_plan(record, member, reasons[0])
            plan.sections["sampling"] = {"reasons": reasons}
            self.record(plan, reason=reasons[0])

    def observe_admission(self, index: str, *, shed=(), expired=(),
                          level: int = 0) -> None:
        """Archive requests that never reached a dispatch (shed /
        deadline-expired) — always part of the interesting tail."""
        if not _spans.enabled():
            return
        for req, outcome in (
            [(r, "shed") for r in shed]
            + [(r, "deadline_expired") for r in expired]
        ):
            plan = shed_plan(req, index, outcome, level)
            self.record(plan, reason=outcome)

    @traced("explain.record")
    def record(self, plan: ExplainPlan, *, reason: str) -> None:
        """Append one plan to the ring; evicts oldest-first past the cap
        with per-index depth bookkeeping (the depth gauge must fall when
        an index's plans age out)."""
        if not _spans.enabled():
            return
        sections = plan.sections
        index = str(
            (sections.get("bucket") or {}).get("index") or "default"
        )
        entry = {
            "request_id": (sections.get("request") or {}).get("id"),
            "index": index,
            "reason": reason,
            "plan": sections,
        }
        gauge = default_registry().gauge(
            "raft_tpu_explain_archive_depth",
            help="archived explain plans currently retained, per index",
        )
        with self._lock:
            self._ring.append(entry)
            self._archived += 1
            self._depth[index] = self._depth.get(index, 0) + 1
            evicted: List[Dict[str, object]] = []
            while len(self._ring) > self._cap:
                evicted.append(self._ring.popleft())
            for old in evicted:
                old_index = old["index"]
                n = self._depth.get(old_index, 1) - 1
                if n <= 0:
                    self._depth.pop(old_index, None)
                else:
                    self._depth[old_index] = n
            depths = dict(self._depth)
        default_registry().counter(
            "raft_tpu_explain_sampled_total",
            help="explain plans archived, by index and selection reason",
        ).inc(index=index, reason=reason)
        for name, depth in depths.items():
            gauge.set(depth, index=name)
        for old in evicted:
            if old["index"] not in depths:
                gauge.remove_matching(index=old["index"])

    # -- reading -------------------------------------------------------------
    def plans(self, *, index: Optional[str] = None) -> List[Dict[str, object]]:
        """Archive contents, oldest first (optionally one index)."""
        with self._lock:
            entries = list(self._ring)
        if index is not None:
            entries = [e for e in entries if e["index"] == index]
        return entries

    def last_dump(self) -> Optional[Dict[str, object]]:
        with self._lock:
            return dict(self._last_dump) if self._last_dump else None

    def snapshot(self) -> Dict[str, object]:
        """Provider section for registry snapshots."""
        with self._lock:
            return {
                "cap": self._cap,
                "archived": len(self._ring),
                "archived_total": self._archived,
                "depth": dict(self._depth),
                "last_dump": (
                    dict(self._last_dump) if self._last_dump else None
                ),
            }

    # -- dumping -------------------------------------------------------------
    @traced("explain.dump")
    def dump(self, directory: Optional[str] = None,
             reason: str = "manual") -> str:
        """Write the archive as ``archive_<seq>_<reason>.json`` next to
        the flight dumps (``RAFT_TPU_FLIGHT_DIR``).  Returns the path."""
        directory = directory or _flight._env_dir()
        os.makedirs(directory, exist_ok=True)
        with self._lock:
            entries = list(self._ring)
            self._dump_seq += 1
            seq = self._dump_seq
        now = time.time()
        path = os.path.join(directory, f"archive_{seq:04d}_{reason}.json")
        snapshot = {
            "schema": "raft_tpu.explain_archive",
            "reason": reason,
            "unix_time": now,
            "entries": entries,
        }
        with open(path, "w") as f:
            json.dump(snapshot, f, indent=2, default=str)
        info = {"path": path, "reason": reason, "unix_time": now}
        with self._lock:
            self._last_dump = info
        default_registry().counter(
            "raft_tpu_explain_dumps_total",
            help="query-archive dumps written",
        ).inc(reason=reason)
        return path

    # -- retirement / hygiene ------------------------------------------------
    def unwatch_index(self, name: str) -> None:
        """Retire one index's archive state and metric series (the PR 16
        stale-series pattern: ``SearchService.remove_index`` is the
        hook)."""
        with self._lock:
            self._ring = deque(
                e for e in self._ring if e["index"] != name
            )
            self._depth.pop(name, None)
        default_registry().counter(
            "raft_tpu_explain_sampled_total",
            help="explain plans archived, by index and selection reason",
        ).remove_matching(index=name)
        default_registry().gauge(
            "raft_tpu_explain_archive_depth",
            help="archived explain plans currently retained, per index",
        ).remove_matching(index=name)

    def reset(self) -> None:
        """Clear the ring, watches and dump state; re-read env knobs."""
        with self._lock:
            self._cap = _env_cap()
            self._ring = deque()
            self._depth = {}
            self._archived = 0
            self._last_dump = None
            self._watch = set()
        self.sampler.reset()


# ---------------------------------------------------------------------------
# the process-wide default archive + module-level conveniences

_default = QueryArchive()


def default_archive() -> QueryArchive:
    return _default


def observe_batch(record: Dict[str, object]) -> None:
    """Batcher hook: never raises — observability must not add failure
    modes to the completion path it observes."""
    try:
        _default.observe_batch(record)
    except Exception:  # noqa: BLE001 — serving paths must not fail
        pass


def observe_admission(index: str, *, shed=(), expired=(),
                      level: int = 0) -> None:
    """Admission hook: never raises (sits on the shed path)."""
    try:
        _default.observe_admission(
            index, shed=shed, expired=expired, level=level
        )
    except Exception:  # noqa: BLE001 — serving paths must not fail
        pass


def plans(*, index: Optional[str] = None) -> List[Dict[str, object]]:
    return _default.plans(index=index)


def dump(directory: Optional[str] = None, reason: str = "manual") -> str:
    return _default.dump(directory, reason=reason)


def explain_snapshot() -> Dict[str, object]:
    """Provider section for registry snapshots."""
    return _default.snapshot()


def reset() -> None:
    _default.reset()
    _on_bus_reset()


# ---------------------------------------------------------------------------
# event-bus subscriber: alarm correlation + incident-time archive dumps

_bus_guard = threading.Lock()
_last_bus_dump = float("-inf")   # monotonic stamp of the last bus-triggered dump


def _on_bus_event(event) -> None:
    """Trigger-kind handler.  Quality alarms stamp the sampler (so the
    requests completing just after an alarm edge join the tail); every
    non-recovered trigger dumps the archive next to the flight dump —
    behind the same cross-reason correlation guard — and publishes an
    ``explain_dump`` context event that the incident manager links into
    the open incident's timeline.  Installed *after* the incident
    manager so the reentrant publish finds the incident already open.
    Never raises."""
    global _last_bus_dump
    if event.kind == "quality_alarm" and not event.recovered:
        try:
            _default.sampler.note_alarm(event.t)
        except Exception:  # noqa: BLE001 — alarm paths must not fail
            pass
    if event.recovered or not _spans.enabled():
        return
    now = time.monotonic()
    with _bus_guard:
        suppressed = now - _last_bus_dump < _flight._env_correlation_s()
        if not suppressed:
            _last_bus_dump = now
    if suppressed:
        return
    with _default._lock:
        empty = not _default._ring
    if empty:
        return
    try:
        path = _default.dump(reason=event.reason)
    except Exception:  # noqa: BLE001 — incident paths must not fail
        return
    try:
        from raft_tpu.obs import events as _events

        _events.publish(
            "explain_dump", reason=event.reason, path=path,
            trigger_kind=event.kind,
        )
    except Exception:  # noqa: BLE001 — incident paths must not fail
        pass


def install_bus_subscriber(bus) -> None:
    """Register the archive dumper on ``bus``: trigger kinds only,
    debounced per reason with the flight window.  Called once per bus by
    :func:`raft_tpu.obs.events.default_bus` — after the incident
    manager, so the ``explain_dump`` context event correlates into the
    incident the same trigger just opened."""
    from raft_tpu.obs import events as _events

    bus.subscribe(
        _on_bus_event,
        kinds=_events.TRIGGER_KINDS,
        debounce_s=_flight._env_debounce_s(),
        name="explain",
    )


def _on_bus_reset() -> None:
    global _last_bus_dump
    with _bus_guard:
        _last_bus_dump = float("-inf")
