"""XLA capacity accounting: what each compiled executable costs the chip.

"Memory Safe Computations with XLA Compiler" (PAPERS.md) makes the case
that memory/compute figures must come from the compiler, not from a
guess: XLA already knows the FLOPs, the bytes each HLO touches, and the
buffer sizes it allocated — this module surfaces those numbers as
queryable gauges, per serving executable, so "are we near the roofline"
and "did the old index version's arrays actually get freed" stop being
profiler questions.

Three layers:

- :func:`analyze_compiled` — tolerant extraction from a ``jax`` AOT
  ``Compiled`` object.  ``cost_analysis()`` returns a list of dicts on
  some backends, a dict on others, and ``None`` (or raises) on the rest;
  ``memory_analysis()`` may lack a peak-memory field entirely (the CPU
  client derives nothing).  Whatever is absent stays absent — no gauge is
  ever published from a made-up number.
- :func:`analyze_callable` + :func:`record_cost` — AOT-compile a callable
  at given arg shapes, time one execution of the already-compiled
  executable, and publish ``raft_tpu_xla_*`` gauges with a roofline
  utilization estimate against configurable device peaks
  (``RAFT_TPU_PEAK_FLOPS`` / ``RAFT_TPU_PEAK_BW`` env vars, else
  per-platform defaults).
- :func:`refresh_live_buffer_gauges` — walks an
  :class:`~raft_tpu.serve.registry.IndexRegistry`'s weakly-referenced
  version history and publishes ``raft_tpu_index_live_bytes`` per
  (name, version) still alive on the host; versions the GC has collected
  get their series *removed*, so a stale series IS the leak report.

Everything here runs at warmup or snapshot time — never on the serving
hot path — and every extraction is exception-tolerant: a backend that
cannot answer degrades to absent gauges, not to a crashed warmup.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

from raft_tpu.core import env as _env
from raft_tpu.core.logger import child as _child_logger
from raft_tpu.obs.registry import MetricsRegistry, default_registry

_log = _child_logger("obs.cost")

#: (peak FLOP/s, peak memory bandwidth bytes/s) per platform family.
#: TPU figures track a v5e-class part (bf16 matmul peak, HBM2e bw); the
#: CPU default is a deliberately round server-class estimate.  Override
#: with RAFT_TPU_PEAK_FLOPS / RAFT_TPU_PEAK_BW for the actual part.
DEFAULT_PEAKS: Dict[str, Tuple[float, float]] = {
    "tpu": (197e12, 819e9),
    "gpu": (312e12, 2039e9),
    "cpu": (1e11, 5e10),
}


def device_peaks(platform: Optional[str] = None) -> Tuple[float, float]:
    """(peak_flops_per_s, peak_bytes_per_s) for the active platform."""
    if platform is None:
        try:
            import jax

            platform = jax.devices()[0].platform
        except Exception:  # no backend at all — fall through to cpu row
            platform = "cpu"
    flops, bw = DEFAULT_PEAKS.get(platform, DEFAULT_PEAKS["cpu"])
    flops = _env.env_float("RAFT_TPU_PEAK_FLOPS", flops)
    bw = _env.env_float("RAFT_TPU_PEAK_BW", bw)
    return flops, bw


@dataclass
class CostReport:
    """Everything extractable from one compiled executable (None = the
    backend would not say)."""

    flops: Optional[float] = None
    bytes_accessed: Optional[float] = None
    peak_memory_bytes: Optional[float] = None
    argument_memory_bytes: Optional[float] = None
    output_memory_bytes: Optional[float] = None
    temp_memory_bytes: Optional[float] = None
    generated_code_bytes: Optional[float] = None
    seconds: Optional[float] = None          # one timed post-compile run
    utilization: Optional[float] = None      # achieved / roofline-attainable
    labels: Dict[str, str] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, object]:
        return {k: v for k, v in vars(self).items() if v is not None}


def _cost_props(compiled) -> Dict[str, float]:
    """Flatten cost_analysis() across its per-backend shapes."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return {}
    if ca is None:
        return {}
    if isinstance(ca, dict):
        ca = [ca]
    out: Dict[str, float] = {}
    try:
        for entry in ca:
            for key, val in dict(entry).items():
                if isinstance(val, (int, float)):
                    out[key] = out.get(key, 0.0) + float(val)
    except Exception:
        return {}
    return out


def analyze_compiled(compiled) -> CostReport:
    """Extract a :class:`CostReport` from a jax AOT ``Compiled`` object.

    Never raises: fields the backend cannot report stay ``None``.
    """
    rep = CostReport()
    props = _cost_props(compiled)
    if "flops" in props:
        rep.flops = props["flops"]
    if "bytes accessed" in props:
        rep.bytes_accessed = props["bytes accessed"]
    try:
        mem = compiled.memory_analysis()
    except Exception:
        mem = None
    if mem is not None:
        def _grab(*names):
            for n in names:
                v = getattr(mem, n, None)
                if isinstance(v, (int, float)) and v >= 0:
                    return float(v)
            return None

        rep.argument_memory_bytes = _grab("argument_size_in_bytes")
        rep.output_memory_bytes = _grab("output_size_in_bytes")
        rep.temp_memory_bytes = _grab("temp_size_in_bytes")
        rep.generated_code_bytes = _grab("generated_code_size_in_bytes")
        # TPU clients report peak directly; the CPU client doesn't — the
        # arg+output+temp sum is the working-set lower bound XLA admits to
        rep.peak_memory_bytes = _grab("peak_memory_in_bytes")
        if rep.peak_memory_bytes is None:
            parts = [
                p for p in (
                    rep.argument_memory_bytes,
                    rep.output_memory_bytes,
                    rep.temp_memory_bytes,
                )
                if p is not None
            ]
            if parts:
                rep.peak_memory_bytes = float(sum(parts))
    return rep


def roofline_utilization(
    flops: Optional[float],
    bytes_accessed: Optional[float],
    seconds: Optional[float],
    platform: Optional[str] = None,
) -> Optional[float]:
    """Achieved FLOP/s as a fraction of the roofline-attainable rate.

    Attainable = ``min(peak_flops, intensity * peak_bw)`` — the classic
    roofline ceiling at the program's arithmetic intensity.  1.0 means
    the executable runs as fast as this hardware can run *this* program;
    low values point at launch overhead or a mis-scheduled kernel rather
    than "needs a bigger chip".  None when any input is unknown.
    """
    if not flops or not seconds or seconds <= 0:
        return None
    peak_flops, peak_bw = device_peaks(platform)
    attainable = peak_flops
    if bytes_accessed and bytes_accessed > 0:
        attainable = min(peak_flops, (flops / bytes_accessed) * peak_bw)
    if attainable <= 0:
        return None
    return float((flops / seconds) / attainable)


def analyze_callable(fn, *args, time_run: bool = True) -> Optional[CostReport]:
    """AOT-compile ``fn`` at ``args``'s shapes and report its cost.

    With ``time_run`` the *compiled* executable is executed once and
    timed, yielding the roofline utilization estimate.  Returns ``None``
    when lowering/compilation itself fails (e.g. a backend without AOT
    support) — callers treat that as "no gauges", not an error.

    Note the compile here is a real XLA compile: callers must only do
    this at warmup (the serve stack does), never per request.
    """
    import jax

    from raft_tpu.ops import cost as ops_cost

    try:
        with ops_cost.capture() as notes:
            compiled = jax.jit(fn).lower(*args).compile()
    except Exception as exc:
        _log.debug("cost analysis unavailable: %r", exc)
        return None
    rep = analyze_compiled(compiled)
    # Mosaic custom-calls are opaque to XLA's cost model on TPU, so a
    # kernel-dominated executable can report no flops/bytes at all.  The
    # Pallas wrappers note their analytic CostEstimates at trace time;
    # use their total ONLY where XLA reported nothing (in interpret mode
    # XLA sees the lowered kernel body — supplementing there would
    # double count).
    noted = ops_cost.noted_total(notes)
    if noted is not None:
        if rep.flops is None and noted.flops:
            rep.flops = float(noted.flops)
        if rep.bytes_accessed is None and noted.bytes_accessed:
            rep.bytes_accessed = float(noted.bytes_accessed)
    if time_run:
        try:
            t0 = time.perf_counter()
            jax.block_until_ready(compiled(*args))
            rep.seconds = time.perf_counter() - t0
        except Exception:
            rep.seconds = None
    rep.utilization = roofline_utilization(
        rep.flops, rep.bytes_accessed, rep.seconds
    )
    return rep


#: gauge name → CostReport attribute published by record_cost
_GAUGES = (
    ("raft_tpu_xla_flops", "flops",
     "FLOPs per execution of a compiled serving executable"),
    ("raft_tpu_xla_bytes_accessed", "bytes_accessed",
     "bytes each execution moves (XLA cost model)"),
    ("raft_tpu_peak_memory_bytes", "peak_memory_bytes",
     "peak (or derived arg+out+temp) device memory of one executable"),
    ("raft_tpu_xla_argument_memory_bytes", "argument_memory_bytes",
     "argument buffer bytes of one executable"),
    ("raft_tpu_xla_output_memory_bytes", "output_memory_bytes",
     "output buffer bytes of one executable"),
    ("raft_tpu_xla_roofline_utilization", "utilization",
     "achieved FLOP/s over the roofline-attainable rate (0..1)"),
)


def record_cost(
    report: Optional[CostReport],
    registry: Optional[MetricsRegistry] = None,
    **labels: str,
) -> None:
    """Publish a report's known fields as gauges; absent fields publish
    nothing (the acceptance contract for backends that return None)."""
    if report is None:
        return
    reg = registry if registry is not None else default_registry()
    report.labels = {str(k): str(v) for k, v in labels.items()}
    for gauge_name, attr, help_ in _GAUGES:
        val = getattr(report, attr)
        if val is not None:
            reg.gauge(gauge_name, help=help_).set(float(val), **labels)


# ---------------------------------------------------------------------------
# live-buffer accounting per IndexRegistry version

def refresh_live_buffer_gauges(
    index_registry, registry: Optional[MetricsRegistry] = None,
) -> Dict[str, float]:
    """Publish ``raft_tpu_index_live_bytes{index=,version=}`` for every
    index version still alive on the host.

    The serve :class:`~raft_tpu.serve.registry.IndexRegistry` keeps a
    weak reference to every version it has ever held; a hot-swapped-out
    version whose arrays are still reachable (an in-flight batch, a
    caller's stray reference, a leak) keeps its gauge — a version the GC
    collected gets its series removed.  The dashboard view is therefore
    exact: two live series under one name during a swap is normal for
    seconds, and a pathological leak is an old version's series that
    never disappears.

    Pipelined dispatch interacts here by design: at ``pipeline_depth``
    > 1 up to that many batches can each pin the version they resolved,
    so a swapped-out version legitimately stays live for up to
    ``pipeline_depth`` batch completions (bounded by the in-flight
    semaphore) rather than one.  The gauges stay truthful because they
    report reachability, not intent — the leak signal is a series that
    outlives the window, not one that exists during it.
    """
    reg = registry if registry is not None else default_registry()
    gauge = reg.gauge(
        "raft_tpu_index_live_bytes",
        help="host+device bytes held by each still-reachable index version",
    )
    live: Dict[str, float] = {}
    alive_keys = set()
    for (name, version), index in index_registry.live_versions().items():
        if getattr(getattr(index, "index", None), "paged", None) is not None:
            # paged versions report through the page-residency gauges
            # (refresh_page_gauges) — a monolithic live-bytes series for
            # them would double-count the aliased cold tier; any series a
            # version published before pagination retires below
            continue
        try:
            nbytes = float(index.device_bytes())
        except Exception:
            continue
        labels = {"index": name, "version": str(version)}
        gauge.set(nbytes, **labels)
        alive_keys.add((name, str(version)))
        live[f"{name}:v{version}"] = nbytes
    # retire series whose version object is gone
    for key in gauge.series():
        d = dict(key)
        if "index" in d and "version" in d:
            if (d["index"], d["version"]) not in alive_keys:
                gauge.remove(**d)
    return live


def refresh_page_gauges(
    index_registry, registry: Optional[MetricsRegistry] = None,
) -> Dict[str, Dict[str, float]]:
    """Publish page-residency gauges for every still-reachable *paged*
    index version: ``raft_tpu_page_resident{index=,version=}`` (pages in
    the HBM hot pool), ``raft_tpu_page_host`` (cold pages on host only),
    and ``raft_tpu_page_pool_bytes`` (device bytes the hot pool + page
    table reserve from the memory budget).

    Rides the same weak version history as
    :func:`refresh_live_buffer_gauges` and retires series whose version
    object the GC collected — the fetch/eviction *flow* counters
    (``raft_tpu_page_{hits,misses,evictions}_total``) are push-side,
    bumped by :class:`~raft_tpu.store.tiered.TieredStore` itself.
    """
    reg = registry if registry is not None else default_registry()
    g_res = reg.gauge(
        "raft_tpu_page_resident",
        help="HBM-resident pages of each still-reachable paged index version",
    )
    g_host = reg.gauge(
        "raft_tpu_page_host",
        help="host-only (cold) pages of each still-reachable paged index version",
    )
    g_bytes = reg.gauge(
        "raft_tpu_page_pool_bytes",
        help="device bytes reserved by each paged version's hot pool",
    )
    out: Dict[str, Dict[str, float]] = {}
    alive = set()
    for (name, version), index in index_registry.live_versions().items():
        tiered = getattr(getattr(index, "index", None), "paged", None)
        if tiered is None:
            continue
        try:
            st = tiered.stats()
            pool_bytes = float(tiered.nbytes)
        except Exception:
            continue
        labels = {"index": name, "version": str(version)}
        g_res.set(float(st["resident"]), **labels)
        g_host.set(float(st["host_only"]), **labels)
        g_bytes.set(pool_bytes, **labels)
        alive.add((name, str(version)))
        out[f"{name}:v{version}"] = {
            "resident": float(st["resident"]),
            "host": float(st["host_only"]),
            "pool_bytes": pool_bytes,
        }
    for gauge in (g_res, g_host, g_bytes):
        for key in gauge.series():
            d = dict(key)
            if "index" in d and "version" in d:
                if (d["index"], d["version"]) not in alive:
                    gauge.remove(**d)
    return out


def refresh_mutation_gauges(
    index_registry, registry: Optional[MetricsRegistry] = None,
) -> Dict[str, Dict[str, float]]:
    """Publish per-index mutation-pressure gauges from the registry's
    *current* entries: ``raft_tpu_index_pending_deletes``,
    ``raft_tpu_index_side_rows``, and ``raft_tpu_index_tombstone_frac``
    (tombstones over main rows, construction padding excluded).

    These are the compaction trigger inputs — the same numbers
    :class:`~raft_tpu.serve.compactor.Compactor` compares against its
    policy — so compaction pressure is visible in ``prometheus()``
    output, not only via method calls.  Entries that are not
    :class:`~raft_tpu.serve.mutation.MutableIndex` (sharded indexes,
    raw wrappers without a side buffer) are skipped; series for names
    no longer registered are removed, mirroring
    :func:`refresh_live_buffer_gauges`.
    """
    reg = registry if registry is not None else default_registry()
    g_del = reg.gauge(
        "raft_tpu_index_pending_deletes",
        help="tombstoned rows awaiting compaction (padding excluded)",
    )
    g_side = reg.gauge(
        "raft_tpu_index_side_rows",
        help="live upsert rows in the brute-force side buffer",
    )
    g_frac = reg.gauge(
        "raft_tpu_index_tombstone_frac",
        help="pending deletes over main structure rows",
    )
    out: Dict[str, Dict[str, float]] = {}
    alive = set()
    for name in index_registry.names():
        try:
            index = index_registry.get(name)
            deletes, side = index.pending_mutations()
            denom = max(
                index.main_size - getattr(index, "_n_structural", 0), 1
            )
        except (KeyError, AttributeError):
            continue
        except Exception:
            continue
        frac = float(deletes) / float(denom)
        g_del.set(deletes, index=name)
        g_side.set(side, index=name)
        g_frac.set(frac, index=name)
        alive.add(name)
        out[name] = {
            "pending_deletes": float(deletes),
            "side_rows": float(side),
            "tombstone_frac": frac,
        }
    for gauge in (g_del, g_side, g_frac):
        for key in gauge.series():
            d = dict(key)
            if d.get("index") not in alive:
                gauge.remove(**d)
    return out
