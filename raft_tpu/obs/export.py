"""Exporters: Prometheus text format + JSON snapshot helpers.

The registry's native ``snapshot()`` is the JSON answer; this module adds
the scrape answer — Prometheus text exposition format 0.0.4, the lingua
franca every metrics pipeline ingests.  Output is deterministic (metrics
and series sorted) so diffs and the regex round-trip test in
``tests/test_obs.py`` are stable.
"""

from __future__ import annotations

import json
import re
from typing import Dict, Optional

from raft_tpu.obs.registry import (
    Counter,
    Gauge,
    Histogram,
    LabelValue,
    MetricsRegistry,
    default_registry,
)

_NAME_OK = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_OK = re.compile(r"[a-zA-Z_][a-zA-Z0-9_]*$")

#: the canonical scrape content types — every HTTP surface (the
#: operational gateway, user-wired handlers, docs) must cite these two
#: constants rather than re-inlining the literals
PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
OPENMETRICS_CONTENT_TYPE = (
    "application/openmetrics-text; version=1.0.0; charset=utf-8"
)

_OPENMETRICS_MEDIA = "application/openmetrics-text"
_CLASSIC_MEDIA = ("text/plain", "text/*", "*/*", "")


def negotiate_content_type(accept: Optional[str]) -> str:
    """Pick the exposition format an ``Accept`` header asks for.

    Returns :data:`OPENMETRICS_CONTENT_TYPE` when the client lists
    ``application/openmetrics-text`` with a quality at least as high as
    any classic-text alternative (Prometheus's scraper sends exactly
    that when OpenMetrics ingestion is on), else
    :data:`PROMETHEUS_CONTENT_TYPE`.  Malformed q-values are treated as
    1.0 — a scrape endpoint should degrade to *an* answer, never to 400.
    """
    if not accept:
        return PROMETHEUS_CONTENT_TYPE
    q_open, q_classic = 0.0, 0.0
    for part in accept.split(","):
        params = part.split(";")
        media = params[0].strip().lower()
        q = 1.0
        for p in params[1:]:
            k, _, v = p.partition("=")
            if k.strip().lower() == "q":
                try:
                    q = float(v.strip())
                except ValueError:
                    q = 1.0
        if media == _OPENMETRICS_MEDIA:
            q_open = max(q_open, q)
        elif media in _CLASSIC_MEDIA:
            q_classic = max(q_classic, q)
    if q_open > 0.0 and q_open >= q_classic:
        return OPENMETRICS_CONTENT_TYPE
    return PROMETHEUS_CONTENT_TYPE


def _sanitize(name: str, label: bool = False) -> str:
    out = re.sub(r"[^a-zA-Z0-9_:]" if not label else r"[^a-zA-Z0-9_]",
                 "_", name)
    if not out or not out[0].isalpha() and out[0] != "_":
        out = "_" + out
    return out


def _escape_value(v: str) -> str:
    return v.replace("\\", r"\\").replace("\n", r"\n").replace('"', r'\"')


def _fmt_series(name: str, labels: LabelValue,
                extra: Optional[Dict[str, str]] = None) -> str:
    items = [(k, v) for k, v in labels]
    if extra:
        items += list(extra.items())
    if not items:
        return name
    body = ",".join(
        f'{_sanitize(k, label=True)}="{_escape_value(str(v))}"'
        for k, v in items
    )
    return f"{name}{{{body}}}"


def _fmt_float(x: float) -> str:
    if x == float("inf"):
        return "+Inf"
    if float(x).is_integer() and abs(x) < 1e15:
        return str(int(x))
    return repr(float(x))


def _render(reg: MetricsRegistry, openmetrics: bool) -> str:
    lines = []
    for m in sorted(reg.metrics(), key=lambda m: m.name):
        name = _sanitize(m.name)
        assert _NAME_OK.match(name)
        if m.help:
            lines.append(f"# HELP {name} {_escape_value(m.help)}")
        lines.append(f"# TYPE {name} {m.kind}")
        if isinstance(m, (Counter, Gauge)):
            data = m.collect()
            for k in sorted(data.keys()):
                lines.append(f"{_fmt_series(name, k)} {_fmt_float(data[k])}")
        elif isinstance(m, Histogram):
            data = m.collect()
            for k in sorted(data.keys()):
                d = data[k]
                cum = 0
                exemplars = d.get("exemplars") or {}
                edges = list(m.buckets) + [float("inf")]
                for i, (edge, n) in enumerate(zip(edges, d["bucket_counts"])):
                    cum += n
                    line = (
                        f"{_fmt_series(name + '_bucket', k, {'le': _fmt_float(edge)})}"
                        f" {cum}"
                    )
                    if openmetrics and i in exemplars:
                        # OpenMetrics exemplar: the bucket's retained
                        # request/span id + the observed value it came with
                        value, ex_id = exemplars[i]
                        line += (
                            f' # {{request_id="{_escape_value(str(ex_id))}"}}'
                            f" {_fmt_float(value)}"
                        )
                    lines.append(line)
                lines.append(
                    f"{_fmt_series(name + '_sum', k)} {_fmt_float(d['sum'])}"
                )
                lines.append(
                    f"{_fmt_series(name + '_count', k)} {d['count']}"
                )
    if openmetrics:
        lines.append("# EOF")
    return "\n".join(lines) + "\n" if lines else ""


def to_prometheus(registry: Optional[MetricsRegistry] = None) -> str:
    """Render ``registry`` (default: process registry) as Prometheus text.

    Classic text exposition 0.0.4 — deliberately exemplar-free, because
    plain-Prometheus scrapers reject the OpenMetrics exemplar syntax.
    Use :func:`to_openmetrics` for the exemplar-bearing document.
    """
    reg = registry if registry is not None else default_registry()
    return _render(reg, openmetrics=False)


def to_openmetrics(registry: Optional[MetricsRegistry] = None) -> str:
    """Render ``registry`` as OpenMetrics text with histogram exemplars.

    Identical to :func:`to_prometheus` except each ``_bucket`` line whose
    bucket retains an exemplar gains the OpenMetrics suffix
    ``# {request_id="req-123"} <observed value>`` — the hop from a fat
    p99 bucket to the flight recorder's record of that request — and the
    document ends with the mandatory ``# EOF`` marker.  Serve scrape
    endpoints that negotiate ``application/openmetrics-text`` should
    return this form.
    """
    reg = registry if registry is not None else default_registry()
    return _render(reg, openmetrics=True)


def snapshot_json(registry: Optional[MetricsRegistry] = None,
                  indent: Optional[int] = None) -> str:
    """The registry snapshot serialized to a JSON string."""
    reg = registry if registry is not None else default_registry()
    return json.dumps(reg.snapshot(), indent=indent, default=str)


def write_snapshot(path: str,
                   registry: Optional[MetricsRegistry] = None) -> None:
    """Dump a JSON snapshot to ``path`` (atomic-enough single write)."""
    with open(path, "w") as f:
        f.write(snapshot_json(registry, indent=2))
