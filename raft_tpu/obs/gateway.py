"""Operational HTTP gateway: the obs pull surface, reachable over TCP.

Every scrape/probe/debug answer in this package is a lock-light pull
API — ``to_prometheus()``, ``SearchService.healthz()/readyz()``,
``obs.snapshot()``, the incident/flight/explain/perf exports — but until
this module none of it was reachable from outside the process:
``docs/observability.md`` said "wire it to any HTTP handler" and
stopped.  :class:`OperationalGateway` is that handler, stdlib-only
(``http.server``), embeddable (``SearchService(gateway=True)`` owns one)
and runnable standalone (``python -m raft_tpu.obs.gateway --port N``
attaches to the process-default registries).

Read plane (GET):

- ``/metrics`` — Prometheus text 0.0.4, or OpenMetrics 1.0.0 with
  exemplars when the ``Accept`` header negotiates it
  (:func:`raft_tpu.obs.export.negotiate_content_type`);
- ``/healthz`` — the full health report; HTTP 503 only on an
  ``UNHEALTHY`` verdict (liveness keeps answering while DEGRADED);
- ``/readyz`` — the traffic gate; 503 until every served index's
  bucket ladder is warm (and always 503 with no service attached);
- ``/snapshot`` — ``SearchService.metrics()`` (or the bare registry
  snapshot standalone);
- ``/slo`` ``/autotune`` ``/perf/hotspots`` ``/incidents[/<id>]``
  ``/flight`` — the corresponding subsystem snapshots;
- ``/explain?name=<index>&q=<v0,v1,...>`` — a deep-mode EXPLAIN replay
  through the live batched path (needs an attached service).

Admin plane (POST, default off): enabled by ``RAFT_TPU_GATEWAY_ADMIN``
*and* guarded by a mandatory ``RAFT_TPU_GATEWAY_TOKEN`` bearer check —
admin-on with no token configured refuses with 403 (fail closed), and
with the plane off the routes 404 like they don't exist.
``/admin/compact?name=``, ``/admin/effort_pin?name=&level=`` (negative
level clears the pin), ``/admin/flight_dump``, ``/admin/archive_dump``.

Design constraints, in order: the server must never touch the serve hot
path (it only calls the existing pull APIs, takes no serve locks of its
own, and adds zero clock reads to any dispatch); it must be bounded (a
fixed worker pool serves requests — a scrape storm queues at accept(),
it does not spawn threads); and it must be observable itself —
``raft_tpu_gateway_requests_total{route,code}`` counts every answer by
*matched route pattern* (bounded label cardinality; a melting scraper
shows up in its own scrape).
"""

from __future__ import annotations

import hmac
import json
import socketserver
import threading
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, replace
from http.server import BaseHTTPRequestHandler, HTTPServer
from typing import Callable, Dict, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from raft_tpu.core import env as _env
from raft_tpu.core.trace import traced
from raft_tpu.obs import export as _export
from raft_tpu.obs import flight as _flight
from raft_tpu.obs import health as _health
from raft_tpu.obs import incidents as _incidents
from raft_tpu.obs import perf as _perf
from raft_tpu.obs.registry import MetricsRegistry, default_registry

JSON_CONTENT_TYPE = "application/json; charset=utf-8"

#: dispatch result: (status, content type, body, extra headers)
_Answer = Tuple[int, str, bytes, Optional[Dict[str, str]]]


@dataclass(frozen=True)
class GatewayConfig:
    """Bind/auth knobs for one :class:`OperationalGateway`.

    ``port=0`` binds an ephemeral port (read it back from
    :attr:`OperationalGateway.port`) — the test/bench default, so
    parallel processes never fight over a listener.
    """

    host: str = "127.0.0.1"
    port: int = 0
    admin: bool = False
    token: Optional[str] = None
    max_workers: int = 4

    @classmethod
    def from_env(cls) -> "GatewayConfig":
        return cls(
            port=_env.env_int("RAFT_TPU_GATEWAY_PORT", 0),
            admin=_env.env_bool("RAFT_TPU_GATEWAY_ADMIN", False),
            token=_env.env_str("RAFT_TPU_GATEWAY_TOKEN"),
        )


class _Handler(BaseHTTPRequestHandler):
    """Thin adapter: parse the request line, hand off to the gateway's
    :meth:`OperationalGateway.dispatch`, write the answer back."""

    server_version = "raft-tpu-gateway"
    # HTTP/1.0 closes per response: scrapers reconnect per scrape and a
    # drain never waits on an idle keep-alive connection
    protocol_version = "HTTP/1.0"

    def log_message(self, format, *args):  # noqa: A002 — stdlib signature
        pass  # the request counter is the access log

    def do_GET(self):  # noqa: N802 — stdlib dispatch name
        self._answer("GET")

    def do_POST(self):  # noqa: N802
        self._answer("POST")

    def _answer(self, method: str) -> None:
        gateway = self.server.gateway  # type: ignore[attr-defined]
        parsed = urlparse(self.path)
        query = {k: v[-1] for k, v in parse_qs(parsed.query).items()}
        status, ctype, body, extra = gateway.dispatch(
            method, parsed.path, query, self.headers
        )
        try:
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            for key, value in (extra or {}).items():
                self.send_header(key, value)
            self.end_headers()
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass  # client hung up mid-answer; nothing to salvage


class _GatewayServer(socketserver.ThreadingMixIn, HTTPServer):
    """HTTPServer whose connections run on a *bounded* pool.

    ``ThreadingMixIn`` is in the MRO for its shutdown bookkeeping, but
    ``process_request`` is overridden to submit to a fixed
    ``ThreadPoolExecutor`` instead of spawning a thread per connection —
    a scrape storm queues inside the executor rather than growing
    unbounded threads, and ``close()`` can drain in-flight responses
    with one ``shutdown(wait=True)``.
    """

    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, address, gateway: "OperationalGateway",
                 max_workers: int):
        super().__init__(address, _Handler)
        self.gateway = gateway
        self._pool = ThreadPoolExecutor(
            max_workers=max(1, int(max_workers)),
            thread_name_prefix="raft-tpu-gateway",
        )

    def process_request(self, request, client_address):
        try:
            self._pool.submit(self._work, request, client_address)
        except RuntimeError:
            # pool already shut down: a connection raced the close —
            # refuse it instead of serving off a dying server
            self.shutdown_request(request)

    def _work(self, request, client_address):
        try:
            self.finish_request(request, client_address)
        except Exception:  # noqa: BLE001 — stdlib handle_error contract
            self.handle_error(request, client_address)
        finally:
            self.shutdown_request(request)

    def drain(self) -> None:
        """Block until every in-flight response has been written."""
        self._pool.shutdown(wait=True)


class OperationalGateway:
    """The operational HTTP server over the obs pull surface.

    Parameters
    ----------
    service:
        The live ``SearchService`` to answer for, or ``None`` for a
        standalone gateway over the process-default registries (then
        ``/readyz`` is always 503 and ``/explain`` 404s — there is no
        serving process to gate or replay through).
    config:
        Bind/auth knobs; default :meth:`GatewayConfig.from_env`.
    registry:
        Metrics registry for the gateway's own request counter (default:
        the process registry — the one ``/metrics`` scrapes, so the
        gateway's traffic rides the same document).
    """

    def __init__(self, service=None, *,
                 config: Optional[GatewayConfig] = None,
                 registry: Optional[MetricsRegistry] = None):
        self.service = service
        self.config = config if config is not None else \
            GatewayConfig.from_env()
        reg = registry if registry is not None else default_registry()
        self._requests = reg.counter(
            "raft_tpu_gateway_requests_total",
            help="gateway HTTP requests by matched route and status code",
        )
        self._lock = threading.Lock()
        self._server: Optional[_GatewayServer] = None
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        # route table: matched pattern -> (method, handler).  The pattern
        # string is also the counter's route label — bounded cardinality
        # by construction (raw paths never become labels).
        self._routes: Dict[str, Tuple[str, Callable]] = {
            "/metrics": ("GET", self._r_metrics),
            "/healthz": ("GET", self._r_healthz),
            "/readyz": ("GET", self._r_readyz),
            "/snapshot": ("GET", self._r_snapshot),
            "/slo": ("GET", self._r_slo),
            "/perf/hotspots": ("GET", self._r_hotspots),
            "/incidents": ("GET", self._r_incidents),
            "/incidents/<id>": ("GET", self._r_incident),
            "/flight": ("GET", self._r_flight),
            "/explain": ("GET", self._r_explain),
            "/autotune": ("GET", self._r_autotune),
            "/admin/compact": ("POST", self._r_admin_compact),
            "/admin/effort_pin": ("POST", self._r_admin_effort_pin),
            "/admin/flight_dump": ("POST", self._r_admin_flight_dump),
            "/admin/archive_dump": ("POST", self._r_admin_archive_dump),
        }

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "OperationalGateway":
        """Bind and serve on a background accept thread.  Idempotent.
        Raises ``OSError`` when the configured port cannot be bound."""
        with self._lock:
            if self._server is not None or self._closed:
                return self
            cfg = self.config
            server = _GatewayServer(
                (cfg.host, cfg.port), self, cfg.max_workers
            )
            thread = threading.Thread(
                target=server.serve_forever,
                kwargs={"poll_interval": 0.05},
                name="raft-tpu-gateway-accept",
                daemon=True,
            )
            self._server, self._thread = server, thread
            thread.start()
        return self

    def close(self) -> None:
        """Stop accepting, drain in-flight responses, release the port.
        Idempotent; safe to call on a never-started gateway."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            server, thread = self._server, self._thread
            self._server = self._thread = None
        if server is None:
            return
        server.shutdown()  # stops the accept loop
        if thread is not None:
            thread.join(timeout=10.0)
        server.drain()  # waits for every submitted response to finish
        server.server_close()

    @property
    def port(self) -> Optional[int]:
        """The bound port (the real one when config.port was 0), or
        ``None`` before :meth:`start`."""
        with self._lock:
            return self._server.server_address[1] if self._server else None

    @property
    def url(self) -> Optional[str]:
        with self._lock:
            if self._server is None:
                return None
            host, port = self._server.server_address[:2]
        return f"http://{host}:{port}"

    def __enter__(self) -> "OperationalGateway":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # -- dispatch --------------------------------------------------------

    @traced("gateway.request")
    def dispatch(self, method: str, path: str, query: Dict[str, str],
                 headers) -> _Answer:
        """Route one request and count the answer.  Never raises — an
        unexpected handler error becomes a 500 with the error text, so
        the process keeps serving scrapes through its own bugs."""
        route, answer = "unknown", None
        try:
            match = self._match(path)
            if match is None:
                answer = _json_error(404, "no such route")
            else:
                route, allowed, handler, arg = match
                if method != allowed:
                    answer = _json_error(
                        405, f"method {method} not allowed",
                        extra={"Allow": allowed},
                    )
                elif route.startswith("/admin/"):
                    answer = self._authorize(headers) or \
                        handler(query, arg, headers)
                else:
                    answer = handler(query, arg, headers)
        except Exception as exc:  # noqa: BLE001 — keep the server up
            answer = _json_error(500, f"internal error: {exc!r}")
        self._requests.inc(route=route, code=str(answer[0]))
        return answer

    def _match(self, path: str):
        """Resolve ``path`` to ``(pattern, method, handler, arg)``."""
        entry = self._routes.get(path)
        if entry is not None:
            return path, entry[0], entry[1], None
        if path.startswith("/incidents/"):
            incident_id = path[len("/incidents/"):]
            if incident_id and "/" not in incident_id:
                method, handler = self._routes["/incidents/<id>"]
                return "/incidents/<id>", method, handler, incident_id
        return None

    def _authorize(self, headers) -> Optional[_Answer]:
        """Admin-plane gate: ``None`` admits, an answer refuses."""
        cfg = self.config
        if not cfg.admin:
            # plane off: indistinguishable from a route that never existed
            return _json_error(404, "no such route")
        if not cfg.token:
            return _json_error(
                403, "admin plane enabled but RAFT_TPU_GATEWAY_TOKEN is "
                     "not configured; refusing all admin requests",
            )
        supplied = (headers.get("Authorization") or "").strip()
        expected = f"Bearer {cfg.token}"
        if not hmac.compare_digest(supplied, expected):
            return _json_error(
                401, "missing or invalid bearer token",
                extra={"WWW-Authenticate": "Bearer"},
            )
        return None

    # -- read plane ------------------------------------------------------

    def _r_metrics(self, query, arg, headers) -> _Answer:
        ctype = _export.negotiate_content_type(headers.get("Accept"))
        openmetrics = ctype == _export.OPENMETRICS_CONTENT_TYPE
        if self.service is not None:
            # the service's scrape path refreshes pull gauges first
            text = (self.service.openmetrics() if openmetrics
                    else self.service.prometheus())
        else:
            text = (_export.to_openmetrics() if openmetrics
                    else _export.to_prometheus())
        return 200, ctype, text.encode("utf-8"), None

    def _r_healthz(self, query, arg, headers) -> _Answer:
        if self.service is not None:
            report = self.service.healthz()
        else:
            # standalone: no served indexes to probe, but the device
            # memory check and the overall verdict machinery still apply
            report = _health.build_report({})
        status = 503 if report.get("status") == _health.UNHEALTHY else 200
        return _json_answer(status, report)

    def _r_readyz(self, query, arg, headers) -> _Answer:
        if self.service is None:
            return _json_answer(
                503, {"ready": False, "reason": "no service attached"}
            )
        report = self.service.readyz()
        return _json_answer(200 if report.get("ready") else 503, report)

    def _r_snapshot(self, query, arg, headers) -> _Answer:
        if self.service is not None:
            return _json_answer(200, self.service.metrics())
        return _json_answer(200, default_registry().snapshot())

    def _r_slo(self, query, arg, headers) -> _Answer:
        engine = getattr(self.service, "slo_engine", None)
        if engine is None:
            return _json_error(404, "no SLO engine configured")
        return _json_answer(200, engine.snapshot())

    def _r_hotspots(self, query, arg, headers) -> _Answer:
        try:
            n = max(1, min(int(query.get("n", "8")), 64))
        except ValueError:
            return _json_error(400, "n must be an integer")
        return _json_answer(
            200, {"hotspots": _perf.default_ledger().top_hotspots(n)}
        )

    def _r_incidents(self, query, arg, headers) -> _Answer:
        return _json_answer(200, _incidents.default_manager().snapshot())

    def _r_incident(self, query, incident_id, headers) -> _Answer:
        manager = _incidents.default_manager()
        for incident in (
            list(manager.open_incidents()) + list(manager.closed_incidents())
        ):
            if incident.id == incident_id:
                return _json_answer(200, incident.to_dict())
        return _json_error(404, f"no incident {incident_id!r}")

    def _r_flight(self, query, arg, headers) -> _Answer:
        return _json_answer(200, _flight.flight_snapshot())

    def _r_explain(self, query, arg, headers) -> _Answer:
        if self.service is None:
            return _json_error(404, "explain needs an attached service")
        name, raw = query.get("name"), query.get("q")
        if not name or not raw:
            return _json_error(400, "explain needs name= and q= "
                                    "(comma-separated floats)")
        try:
            vector = [float(x) for x in raw.split(",") if x.strip()]
        except ValueError:
            return _json_error(400, "q must be comma-separated floats")
        if name not in set(self.service.names()):
            return _json_error(404, f"no index {name!r}")
        import numpy as np  # deferred: keep module import light
        try:
            plan = self.service.explain(
                name, np.asarray(vector, dtype=np.float32), timeout=30.0
            )
        except RuntimeError as exc:  # obs pipeline off
            return _json_error(503, str(exc))
        except ValueError as exc:  # wrong dimensionality etc.
            return _json_error(400, str(exc))
        return _json_answer(200, plan.to_dict())

    def _r_autotune(self, query, arg, headers) -> _Answer:
        tuner = getattr(self.service, "autotuner", None)
        if tuner is None:
            return _json_error(404, "no autotuner configured")
        body = tuner.snapshot()
        if self.service is not None:
            # fold in the live arbitrated levels — the snapshot's view is
            # the tuner's intent, the arbiter's is what dispatch uses
            efforts = {}
            for name in self.service.names():
                arbiter = self.service.effort_arbiter(name)
                if arbiter is not None:
                    efforts[name] = arbiter.snapshot()
            body["effort"] = efforts
        return _json_answer(200, body)

    # -- admin plane -----------------------------------------------------

    def _r_admin_compact(self, query, arg, headers) -> _Answer:
        if self.service is None:
            return _json_error(404, "no service attached")
        name = query.get("name")
        if not name:
            return _json_error(400, "compact needs name=")
        if name not in set(self.service.names()):
            return _json_error(404, f"no index {name!r}")
        try:
            return _json_answer(200, self.service.compact_now(name))
        except RuntimeError as exc:  # no compactor configured
            return _json_error(409, str(exc))

    def _r_admin_effort_pin(self, query, arg, headers) -> _Answer:
        if self.service is None:
            return _json_error(404, "no service attached")
        name = query.get("name")
        if not name:
            return _json_error(400, "effort_pin needs name= and level=")
        if name not in set(self.service.names()):
            return _json_error(404, f"no index {name!r}")
        arbiter = self.service.effort_arbiter(name)
        if arbiter is None:
            return _json_error(
                409, f"index {name!r} has no effort arbiter (service "
                     "runs without overload or autotune)",
            )
        try:
            level = int(query.get("level", ""))
        except ValueError:
            return _json_error(400, "level must be an integer "
                                    "(negative clears the pin)")
        pinned = arbiter.set_pin(None if level < 0 else level)
        return _json_answer(
            200, {"name": name, "pinned": pinned, **arbiter.snapshot()}
        )

    def _r_admin_flight_dump(self, query, arg, headers) -> _Answer:
        path = _flight.dump(reason="gateway_admin")
        return _json_answer(200, {"path": path})

    def _r_admin_archive_dump(self, query, arg, headers) -> _Answer:
        from raft_tpu.obs import explain as _explain
        path = _explain.dump(reason="gateway_admin")
        return _json_answer(200, {"path": path})


def _json_answer(status: int, payload) -> _Answer:
    body = json.dumps(payload, default=str).encode("utf-8")
    return status, JSON_CONTENT_TYPE, body, None


def _json_error(status: int, message: str,
                extra: Optional[Dict[str, str]] = None) -> _Answer:
    body = json.dumps({"error": message}).encode("utf-8")
    return status, JSON_CONTENT_TYPE, body, extra


def main(argv=None, *, ready=None) -> int:
    """``python -m raft_tpu.obs.gateway --port N`` — standalone gateway.

    Serves the process-default registries (useful for a sidecar-style
    debug process, or any embedder that builds indexes without a
    ``SearchService``).  Exits 1 when the port cannot be bound; SIGTERM
    and SIGINT close the listener and drain in-flight responses before
    the process exits (``ready``, test hook: called with the started
    gateway and the stop event).
    """
    import argparse
    import signal
    import sys

    parser = argparse.ArgumentParser(
        prog="python -m raft_tpu.obs.gateway",
        description="standalone raft_tpu operational HTTP gateway",
    )
    parser.add_argument("--port", type=int, default=None,
                        help="listen port (default RAFT_TPU_GATEWAY_PORT)")
    parser.add_argument("--host", default=None,
                        help="bind address (default 127.0.0.1)")
    parser.add_argument("--admin", action="store_true",
                        help="enable the POST /admin plane (still needs "
                             "RAFT_TPU_GATEWAY_TOKEN)")
    args = parser.parse_args(argv)

    config = GatewayConfig.from_env()
    if args.port is not None:
        config = replace(config, port=args.port)
    if args.host is not None:
        config = replace(config, host=args.host)
    if args.admin:
        config = replace(config, admin=True)

    gateway = OperationalGateway(config=config)
    try:
        gateway.start()
    except OSError as exc:
        print(f"raft-tpu-gateway: bind {config.host}:{config.port} "
              f"failed: {exc}", file=sys.stderr)
        return 1

    stop = threading.Event()

    def _terminate(signum, frame):
        stop.set()

    try:
        signal.signal(signal.SIGTERM, _terminate)
        signal.signal(signal.SIGINT, _terminate)
    except ValueError:
        pass  # not the main thread (embedded/test use): caller stops us

    print(f"raft-tpu-gateway: serving {gateway.url}", file=sys.stderr)
    if ready is not None:
        ready(gateway, stop)
    stop.wait()
    gateway.close()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
