"""raft_tpu.obs — unified observability: metrics, spans, XLA events.

The reference RAFT instruments every entry point with NVTX ranges and
spdlog (core/nvtx.hpp, core/logger-inl.hpp) and reads the story back
through Nsight.  A TPU serving deployment needs that story *without a
profiler attached*, so this package turns the existing instrumentation
into queryable state:

- :mod:`~raft_tpu.obs.registry` — process-wide thread-safe metrics
  (counters, gauges, labeled histograms with fixed bucket ladders and a
  label-cardinality cap that raises instead of leaking).
- :mod:`~raft_tpu.obs.spans` — structured spans (id, parent, wall time,
  stage timings) fed automatically by ``core.trace.trace_range`` /
  ``@traced``, i.e. every already-instrumented entry point in
  ``neighbors/``, ``cluster/`` and ``serve/`` reports with zero call-site
  churn.
- :mod:`~raft_tpu.obs.xla_events` — ``jax.monitoring`` listeners for
  compile durations, executable-cache hits and transfer events,
  attributed to the enclosing span.
- :mod:`~raft_tpu.obs.export` — Prometheus text format + JSON snapshot.
- :mod:`~raft_tpu.obs.slowlog` — slow-query log with stage breakdowns.
- :mod:`~raft_tpu.obs.profiler` — ``obs.profile(dir)``: one-line
  Perfetto capture.
- :mod:`~raft_tpu.obs.quality` — online recall auditor: shadow-samples
  served batches against an exact oracle off the hot path, with a
  degradation alarm on the recall EWMA.
- :mod:`~raft_tpu.obs.cost` — XLA capacity accounting: per-executable
  FLOPs / bytes / peak memory from ``cost_analysis()`` plus roofline
  utilization and live-buffer gauges per index version.
- :mod:`~raft_tpu.obs.health` — OK/DEGRADED/UNHEALTHY verdicts behind
  ``SearchService.healthz()`` / ``readyz()``.
- :mod:`~raft_tpu.obs.flight` — always-on flight recorder: a bounded
  ring of recent batches with member request ids and per-request
  timelines, auto-dumped (JSON + Perfetto-loadable Chrome trace) on
  health/quality/recompile/exception incidents.
- :mod:`~raft_tpu.obs.events` — bounded in-process pub/sub bus carrying
  every operationally interesting edge (health transitions, quality
  alarms, hot recompiles, batch errors, compaction lifecycle, registry
  swaps, SLO burns); the flight auto-dump is one subscriber.
- :mod:`~raft_tpu.obs.slo` — declarative SLOs with error budgets and
  Google-SRE multi-window multi-burn-rate alerting over availability,
  p99 latency, audited recall and mutation freshness.
- :mod:`~raft_tpu.obs.incidents` — bus subscriber correlating bursts of
  events into incident timelines with service context at open/close,
  exported as JSON + Chrome trace alongside flight dumps.
- :mod:`~raft_tpu.obs.perf` — measured perf ledger: per-executable
  device-time attribution keyed ``(index, backend, bucket, kernel_path,
  version)``, hotspot ranking with measured roofline utilization, and a
  per-bucket EWMA regression detector that auto-triggers a profiler
  capture and lands inside the correlated incident.
- :mod:`~raft_tpu.obs.autotune` — closed-loop SLO autotuner: walks each
  served index's warmed effort ladder (through the serve
  ``EffortArbiter``) toward max QPS subject to recall ≥ floor and a
  healthy p99 error budget, navigating the measured QPS–recall
  :class:`FrontierModel` a ``bench frontier`` sweep emits.
- :mod:`~raft_tpu.obs.explain` — per-query EXPLAIN plans: on-demand
  deep explains (``SearchService.explain``) joined from the existing
  instruments, plus an always-on tail-sampled :class:`QueryArchive`
  that retains full plans for the interesting tail and dumps alongside
  flight records into the correlated incident timeline.
- :mod:`~raft_tpu.obs.gateway` — stdlib-only operational HTTP server
  over this whole pull surface: ``/metrics`` (content-negotiated
  Prometheus/OpenMetrics), ``/healthz``/``/readyz`` load-balancer
  probes, snapshot/incident/flight/explain/autotune debug endpoints and
  a token-guarded admin plane; owned by ``SearchService(gateway=True)``
  or run standalone via ``python -m raft_tpu.obs.gateway``.

Quick start::

    from raft_tpu import obs
    obs.install()                      # XLA listeners + span/slowlog merge
    ... build / search / serve ...
    print(obs.snapshot())              # JSON-safe dict
    print(obs.to_prometheus())         # scrape document
    with obs.profile("/tmp/trace"):    # deep dive
        index = ivf_pq.build(params, dataset)

See ``docs/observability.md`` for the guided tour.
"""

from raft_tpu.obs.cost import (
    CostReport,
    analyze_callable,
    analyze_compiled,
    record_cost,
    refresh_live_buffer_gauges,
)
from raft_tpu.obs.export import (
    OPENMETRICS_CONTENT_TYPE,
    PROMETHEUS_CONTENT_TYPE,
    negotiate_content_type,
    snapshot_json,
    to_openmetrics,
    to_prometheus,
    write_snapshot,
)
from raft_tpu.obs.events import (
    Event,
    EventBus,
    default_bus,
    events_snapshot,
    publish,
    subscribe,
)
from raft_tpu.obs.explain import (
    ExplainPlan,
    QueryArchive,
    TailSampler,
    default_archive,
    explain_snapshot,
)
from raft_tpu.obs.autotune import Autotuner, FrontierModel, FrontierPoint
from raft_tpu.obs.flight import (
    FlightRecorder,
    default_recorder,
    flight_snapshot,
    next_request_id,
)
from raft_tpu.obs.incidents import (
    Incident,
    IncidentManager,
    incidents_snapshot,
)
from raft_tpu.obs.perf import (
    PerfLedger,
    default_ledger,
    ledger_snapshot,
)
from raft_tpu.obs.profiler import capture_async, last_capture, profile
from raft_tpu.obs.quality import QualityAuditor
from raft_tpu.obs.registry import (
    Counter,
    Gauge,
    Histogram,
    LabelCardinalityError,
    MetricsRegistry,
    default_registry,
)
from raft_tpu.obs.slo import AlertPolicy, SloEngine, SloSpec
from raft_tpu.obs.slowlog import slowlog_snapshot
from raft_tpu.obs.spans import (
    Span,
    current_span,
    finish_span,
    open_span,
    recent_spans,
    set_enabled,
    span,
    spans_snapshot,
)
from raft_tpu.obs import (
    autotune,
    cost,
    events,
    explain,
    flight,
    gateway,
    health,
    incidents,
    perf,
    profiler,
    quality,
    slo,
    slowlog,
    spans,
    xla_events,
)
from raft_tpu.obs.gateway import GatewayConfig, OperationalGateway

registry = default_registry  # `obs.registry()` reads as the obvious accessor


def install() -> None:
    """Activate the full pipeline: XLA monitoring listeners, the span and
    slow-query sections in registry snapshots, and the default event bus
    (whose creation wires the flight auto-dump subscriber and the
    incident manager).  Idempotent."""
    xla_events.install()
    reg = default_registry()
    reg.register_provider("spans", spans_snapshot)
    reg.register_provider("slow_queries", slowlog_snapshot)
    reg.register_provider("flight", flight_snapshot)
    reg.register_provider("perf", ledger_snapshot)
    reg.register_provider("explain", explain_snapshot)
    events.default_bus()


def snapshot():
    """JSON-safe snapshot of the process registry (counters, gauges,
    histograms, plus every registered provider section)."""
    return default_registry().snapshot()


__all__ = [
    "AlertPolicy",
    "Autotuner",
    "CostReport",
    "Counter",
    "Event",
    "EventBus",
    "ExplainPlan",
    "FlightRecorder",
    "FrontierModel",
    "FrontierPoint",
    "Gauge",
    "GatewayConfig",
    "Histogram",
    "Incident",
    "IncidentManager",
    "LabelCardinalityError",
    "MetricsRegistry",
    "OPENMETRICS_CONTENT_TYPE",
    "OperationalGateway",
    "PROMETHEUS_CONTENT_TYPE",
    "PerfLedger",
    "QualityAuditor",
    "QueryArchive",
    "SloEngine",
    "SloSpec",
    "Span",
    "TailSampler",
    "analyze_callable",
    "analyze_compiled",
    "autotune",
    "capture_async",
    "cost",
    "current_span",
    "default_archive",
    "default_bus",
    "default_ledger",
    "default_recorder",
    "default_registry",
    "events",
    "events_snapshot",
    "explain",
    "explain_snapshot",
    "finish_span",
    "flight",
    "gateway",
    "health",
    "incidents",
    "incidents_snapshot",
    "install",
    "last_capture",
    "ledger_snapshot",
    "negotiate_content_type",
    "next_request_id",
    "open_span",
    "perf",
    "profile",
    "profiler",
    "publish",
    "quality",
    "recent_spans",
    "record_cost",
    "refresh_live_buffer_gauges",
    "registry",
    "set_enabled",
    "slo",
    "slowlog",
    "snapshot",
    "snapshot_json",
    "span",
    "spans",
    "subscribe",
    "to_openmetrics",
    "to_prometheus",
    "write_snapshot",
    "xla_events",
]
