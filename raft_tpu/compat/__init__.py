"""pylibraft-compatible API surface (ref: python/pylibraft/ — SURVEY §2.14).

``raft_tpu.compat.pylibraft`` mirrors the reference's Python package layout
(common/distance/matrix/cluster/neighbors/random) so code written against
pylibraft ports by switching the import root. Arrays in are anything
array-like; outputs follow ``config.set_output_as`` (default: device arrays,
like pylibraft's device_ndarray default).
"""

from raft_tpu.compat import pylibraft

__all__ = ["pylibraft"]
