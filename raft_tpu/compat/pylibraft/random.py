"""(ref: pylibraft.random — rmat_rectangular_generator.pyx)"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np

from raft_tpu.compat.pylibraft.common import DeviceResources
from raft_tpu.compat.pylibraft.config import convert_output
from raft_tpu.random import datagen as _datagen


def rmat(r_scale, c_scale, n_edges, theta=None, seed=12345,
         handle: Optional[DeviceResources] = None):
    key = jax.random.PRNGKey(seed)
    out = _datagen.rmat(
        key, int(r_scale), int(c_scale), int(n_edges),
        theta=None if theta is None else np.asarray(theta),
    )
    return convert_output(out)
