"""Global output-conversion hook (ref: pylibraft config.set_output_as,
docs/source/quick_start.md:156-166 — "numpy" | "cupy" | callable; here
"numpy" | "jax" | callable)."""

from __future__ import annotations

from typing import Callable, Union

import numpy as np

_output_as = "jax"


def set_output_as(kind: Union[str, Callable]) -> None:
    global _output_as
    if not (kind in ("jax", "numpy", "device") or callable(kind)):
        raise ValueError("set_output_as expects 'jax', 'numpy', or a callable")
    _output_as = kind


def get_output_as():
    return _output_as


def convert_output(x):
    if _output_as in ("jax", "device"):
        return x
    if _output_as == "numpy":
        return np.asarray(x)
    return _output_as(x)
