"""(ref: pylibraft.neighbors — brute_force.pyx, ivf_flat/, ivf_pq/,
cagra/, hnsw.pyx, refine.pyx, rbc.pyx, eps_neighborhood.pyx)"""

from __future__ import annotations

from typing import Optional

import numpy as np

from raft_tpu.compat.pylibraft.common import DeviceResources, to_device_array
from raft_tpu.compat.pylibraft.config import convert_output
from raft_tpu.neighbors import ball_cover as _ball_cover
from raft_tpu.neighbors import brute_force as _bf
from raft_tpu.neighbors import cagra as _cagra
from raft_tpu.neighbors import extras as _extras
from raft_tpu.neighbors import hnsw as _hnsw
from raft_tpu.neighbors import ivf_flat as _ivf_flat
from raft_tpu.neighbors import ivf_pq as _ivf_pq
from raft_tpu.neighbors.refine import refine as _refine


def _res(handle):
    return handle.res if handle else None


class brute_force:
    @staticmethod
    def knn(dataset, queries, k, metric="sqeuclidean",
            handle: Optional[DeviceResources] = None):
        d, i = _bf.knn(
            to_device_array(dataset), to_device_array(queries), int(k),
            metric=metric, res=_res(handle),
        )
        return convert_output(d), convert_output(i)


class _IndexModule:
    """Shared shape of the ivf_flat / ivf_pq / cagra compat namespaces:
    IndexParams/SearchParams/build/search/extend/save/load passthroughs
    (ref: each pylibraft sub-package exposes exactly this surface)."""

    _mod = None

    @classmethod
    def build(cls, params, dataset, handle: Optional[DeviceResources] = None):
        return cls._mod.build(params, to_device_array(dataset), res=_res(handle))

    @classmethod
    def search(cls, params, index, queries, k,
               handle: Optional[DeviceResources] = None):
        d, i = cls._mod.search(
            params, index, to_device_array(queries), int(k), res=_res(handle)
        )
        return convert_output(d), convert_output(i)

    @classmethod
    def save(cls, filename, index):
        cls._mod.save(filename, index)

    @classmethod
    def load(cls, filename):
        return cls._mod.load(filename)


class ivf_flat(_IndexModule):
    _mod = _ivf_flat
    IndexParams = _ivf_flat.IndexParams
    SearchParams = _ivf_flat.SearchParams

    @classmethod
    def extend(cls, index, new_vectors, new_indices=None,
               handle: Optional[DeviceResources] = None):
        return _ivf_flat.extend(
            index, to_device_array(new_vectors),
            None if new_indices is None else to_device_array(new_indices),
            res=_res(handle),
        )


class ivf_pq(_IndexModule):
    _mod = _ivf_pq
    IndexParams = _ivf_pq.IndexParams
    SearchParams = _ivf_pq.SearchParams

    @classmethod
    def extend(cls, index, new_vectors, new_indices=None,
               handle: Optional[DeviceResources] = None):
        return _ivf_pq.extend(
            index, to_device_array(new_vectors),
            None if new_indices is None else to_device_array(new_indices),
            res=_res(handle),
        )


class cagra(_IndexModule):
    _mod = _cagra
    IndexParams = _cagra.IndexParams
    SearchParams = _cagra.SearchParams


class hnsw:
    """(ref: pylibraft.neighbors.hnsw + cagra hnswlib export)"""

    @staticmethod
    def from_cagra(index, filename):
        _hnsw.serialize_to_hnswlib(filename, index)
        return _hnsw.load(filename, dim=index.dim, metric=index.metric)

    @staticmethod
    def load(filename, dim, metric="sqeuclidean"):
        return _hnsw.load(filename, dim=dim, metric=metric)

    @staticmethod
    def search(index, queries, k, ef=64, handle: Optional[DeviceResources] = None):
        d, i = _hnsw.search(index, to_device_array(queries), int(k), ef=ef,
                            res=_res(handle))
        return convert_output(d), convert_output(i)


def refine(dataset, queries, candidates, k, metric="sqeuclidean",
           handle: Optional[DeviceResources] = None):
    d, i = _refine(
        to_device_array(dataset), to_device_array(queries),
        to_device_array(candidates), int(k), metric=metric, res=_res(handle),
    )
    return convert_output(d), convert_output(i)


class rbc:
    """(ref: pylibraft.neighbors.rbc — random ball cover)"""

    @staticmethod
    def build(dataset, metric="sqeuclidean", n_landmarks=0,
              handle: Optional[DeviceResources] = None):
        return _ball_cover.build(
            to_device_array(dataset), metric=metric, n_landmarks=n_landmarks,
            res=_res(handle),
        )

    @staticmethod
    def query(index, queries, k, handle: Optional[DeviceResources] = None):
        d, i = _ball_cover.knn_query(
            index, to_device_array(queries), int(k), res=_res(handle)
        )
        return convert_output(d), convert_output(i)

    @staticmethod
    def eps_query(index, queries, eps, handle: Optional[DeviceResources] = None):
        adj, deg = _ball_cover.eps_nn(
            index, to_device_array(queries), eps, res=_res(handle)
        )
        return convert_output(adj), convert_output(deg)


def eps_neighborhood(x, y, eps_sq, handle: Optional[DeviceResources] = None):
    adj, deg = _extras.epsilon_neighborhood(
        to_device_array(x), to_device_array(y), eps_sq, res=_res(handle)
    )
    return convert_output(adj), convert_output(deg)
