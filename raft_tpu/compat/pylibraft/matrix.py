"""(ref: pylibraft.matrix — select_k.pyx)"""

from __future__ import annotations

from typing import Optional

from raft_tpu.compat.pylibraft.common import DeviceResources, to_device_array
from raft_tpu.compat.pylibraft.config import convert_output
from raft_tpu.ops import matrix as _matrix


def select_k(dataset, k, select_min=True, handle: Optional[DeviceResources] = None):
    vals, idx = _matrix.select_k(
        to_device_array(dataset), int(k), select_min=select_min
    )
    return convert_output(vals), convert_output(idx)
