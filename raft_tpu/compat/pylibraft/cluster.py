"""(ref: pylibraft.cluster — kmeans.pyx: KMeansParams, fit, cluster_cost,
compute_new_centroids)"""

from __future__ import annotations

from typing import Optional

from raft_tpu.cluster import kmeans as _kmeans
from raft_tpu.compat.pylibraft.common import DeviceResources, to_device_array
from raft_tpu.compat.pylibraft.config import convert_output

KMeansParams = _kmeans.KMeansParams


class kmeans:
    """Namespace parity with pylibraft.cluster.kmeans."""

    KMeansParams = _kmeans.KMeansParams

    @staticmethod
    def fit(params, X, sample_weights=None, handle: Optional[DeviceResources] = None):
        res = handle.res if handle else None
        centroids, inertia, n_iter = _kmeans.fit(
            params, to_device_array(X),
            None if sample_weights is None else to_device_array(sample_weights),
            res=res,
        )
        return convert_output(centroids), float(inertia), int(n_iter)

    @staticmethod
    def cluster_cost(X, centroids, handle: Optional[DeviceResources] = None):
        return float(
            _kmeans.cluster_cost(to_device_array(X), to_device_array(centroids))
        )

    @staticmethod
    def compute_new_centroids(
        X, centroids, labels=None, sample_weights=None,
        handle: Optional[DeviceResources] = None,
    ):
        out = _kmeans.compute_new_centroids(
            to_device_array(X), to_device_array(centroids),
            None if labels is None else to_device_array(labels),
            None if sample_weights is None else to_device_array(sample_weights),
        )
        return convert_output(out)


fit = kmeans.fit
cluster_cost = kmeans.cluster_cost
compute_new_centroids = kmeans.compute_new_centroids
