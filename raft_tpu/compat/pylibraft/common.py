"""Handle + device array shims (ref: pylibraft/common/ — handle.pyx
DeviceResources, device_ndarray.py, cai_wrapper.py, auto_sync_handle).

On TPU the "handle" wraps raft_tpu.core.Resources (workspace limits, PRNG
root) and ``sync()`` maps to block_until_ready of outstanding work — the
async-dispatch analog of the reference's stream sync."""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core.resources import Resources


class DeviceResources:
    """(ref: pylibraft.common.DeviceResources / device_resources handle)"""

    def __init__(self, workspace_limit_bytes: int = 256 * 1024 * 1024):
        self.res = Resources(workspace_limit_bytes=workspace_limit_bytes)

    def sync(self) -> None:
        # XLA dispatch is async like CUDA streams; a barrier on a trivial
        # computation flushes the queue (ref handle.sync semantics)
        jax.block_until_ready(jnp.zeros(()))


# legacy alias (ref: pylibraft Handle = DeviceResources)
Handle = DeviceResources


class device_ndarray:
    """Minimal device array owner (ref: pylibraft/common/device_ndarray.py —
    there backed by rmm DeviceBuffer + __cuda_array_interface__; here a jax
    Array with numpy bridging)."""

    def __init__(self, np_arr):
        self._array = jnp.asarray(np_arr)

    @classmethod
    def empty(cls, shape, dtype=np.float32, order="C"):
        if order != "C":
            raise ValueError("row-major only on TPU")
        return cls(np.empty(shape, dtype))

    @property
    def shape(self):
        return self._array.shape

    @property
    def dtype(self):
        return np.dtype(self._array.dtype.name)

    def copy_to_host(self) -> np.ndarray:
        return np.asarray(self._array)

    def __array__(self):
        return np.asarray(self._array)

    @property
    def array(self) -> jax.Array:
        return self._array


def to_device_array(x) -> jax.Array:
    """Accept numpy / jax / device_ndarray / anything __array__-able
    (ref: cai_wrapper's __cuda_array_interface__ bridging)."""
    if isinstance(x, device_ndarray):
        return x.array
    return jnp.asarray(x)
