"""Handle + device array shims (ref: pylibraft/common/ — handle.pyx
DeviceResources, device_ndarray.py, cai_wrapper.py, auto_sync_handle).

On TPU the "handle" wraps raft_tpu.core.Resources (workspace limits, PRNG
root) and ``sync()`` maps to block_until_ready of outstanding work — the
async-dispatch analog of the reference's stream sync."""

from __future__ import annotations

import functools
import inspect
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core.resources import Resources


class DeviceResources:
    """(ref: pylibraft.common.DeviceResources / device_resources handle)"""

    def __init__(self, workspace_limit_bytes: int = 256 * 1024 * 1024):
        self.res = Resources(workspace_limit_bytes=workspace_limit_bytes)

    def sync(self) -> None:
        # XLA dispatch is async like CUDA streams; a barrier on a trivial
        # computation flushes the queue (ref handle.sync semantics)
        jax.block_until_ready(jnp.zeros(()))


# legacy alias (ref: pylibraft Handle = DeviceResources)
Handle = DeviceResources


class device_ndarray:
    """Minimal device array owner (ref: pylibraft/common/device_ndarray.py —
    there backed by rmm DeviceBuffer + __cuda_array_interface__; here a jax
    Array with numpy bridging)."""

    def __init__(self, np_arr):
        self._array = jnp.asarray(np_arr)

    @classmethod
    def empty(cls, shape, dtype=np.float32, order="C"):
        if order != "C":
            raise ValueError("row-major only on TPU")
        return cls(np.empty(shape, dtype))

    @property
    def shape(self):
        return self._array.shape

    @property
    def dtype(self):
        return np.dtype(self._array.dtype)

    def copy_to_host(self) -> np.ndarray:
        return np.asarray(self._array)

    def __array__(self):
        return np.asarray(self._array)

    @property
    def array(self) -> jax.Array:
        return self._array


def to_device_array(x) -> jax.Array:
    """Accept numpy / jax / device_ndarray / anything __array__-able
    (ref: cai_wrapper's __cuda_array_interface__ bridging)."""
    if isinstance(x, device_ndarray):
        return x.array
    return jnp.asarray(x)


class cai_wrapper:
    """Array-attribute wrapper (ref: pylibraft/common/cai_wrapper.py:21 —
    there reads __cuda_array_interface__; here any array-like via the
    device bridge, exposing the same .dtype/.shape/.c_contiguous surface)."""

    def __init__(self, x):
        self._array = to_device_array(x)

    @property
    def dtype(self):
        # ml_dtypes-aware (bf16 etc.): jax dtypes ARE numpy dtype objects
        return np.dtype(self._array.dtype)

    @property
    def shape(self):
        return tuple(self._array.shape)

    @property
    def c_contiguous(self) -> bool:
        return True  # XLA arrays are dense row-major

    @property
    def array(self) -> jax.Array:
        return self._array


# host-array twin (ref: pylibraft/common/ai_wrapper.py — __array_interface__)
ai_wrapper = cai_wrapper


def auto_sync_handle(fn):
    """Decorator: default + sync the handle around the call
    (ref: pylibraft/common/auto_sync_handle — injects a handle kwarg and
    syncs it after the wrapped call when it was auto-created). Handles
    passed positionally are honored via signature binding."""

    sig = inspect.signature(fn)

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        bound = sig.bind_partial(*args, **kwargs)
        created = bound.arguments.get("handle") is None
        if created:
            bound.arguments["handle"] = DeviceResources()
        out = fn(*bound.args, **bound.kwargs)
        if created:
            bound.arguments["handle"].sync()
        return out

    return wrapper


def auto_convert_output(fn):
    """Decorator applying config.set_output_as to array returns
    (ref: pylibraft/common/auto_convert_output). Tuple returns keep their
    type (NamedTuples included)."""

    from raft_tpu.compat.pylibraft import config

    def _conv(x):
        if isinstance(x, jax.Array):
            return config.convert_output(x)
        if isinstance(x, tuple):
            vals = [_conv(v) for v in x]
            # NamedTuple subclasses construct from positional fields
            return type(x)(*vals) if hasattr(x, "_fields") else tuple(vals)
        if isinstance(x, list):
            return [_conv(v) for v in x]
        if isinstance(x, dict):
            return {k: _conv(v) for k, v in x.items()}
        return x

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        return _conv(fn(*args, **kwargs))

    return wrapper
