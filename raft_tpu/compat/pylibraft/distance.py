"""(ref: pylibraft.distance — pairwise_distance.pyx, fused_l2_nn.pyx)"""

from __future__ import annotations

from typing import Optional

from raft_tpu.compat.pylibraft.common import DeviceResources, to_device_array
from raft_tpu.compat.pylibraft.config import convert_output
from raft_tpu.distance import fused_nn as _fused
from raft_tpu.distance import pairwise as _pairwise

DISTANCE_TYPES = sorted(_pairwise.DISTANCE_TYPES)


def pairwise_distance(X, Y, metric="euclidean", p=2.0, handle: Optional[DeviceResources] = None):
    res = handle.res if handle else None
    # preserve X-is-Y through the conversion so the core's exact-diagonal
    # rule (self-distance is 0) can apply
    out = _pairwise.pairwise_distance(
        to_device_array(X),
        None if Y is X else to_device_array(Y),
        metric=metric, p=p, res=res,
    )
    return convert_output(out)


def fused_l2_nn_argmin(X, Y, handle: Optional[DeviceResources] = None):
    res = handle.res if handle else None
    out = _fused.fused_l2_nn_argmin(to_device_array(X), to_device_array(Y), res=res)
    return convert_output(out)


def fused_distance_nn_argmin(X, Y, metric="euclidean", handle: Optional[DeviceResources] = None):
    res = handle.res if handle else None
    out = _fused.fused_distance_nn_argmin(
        to_device_array(X), to_device_array(Y), metric=metric, res=res
    )
    return convert_output(out)
