"""Drop-in pylibraft namespace (ref: python/pylibraft/pylibraft/)."""

from raft_tpu.compat.pylibraft import (
    cluster,
    common,
    config,
    distance,
    matrix,
    neighbors,
    random,
)

__all__ = [
    "cluster", "common", "config", "distance", "matrix", "neighbors", "random",
]
