"""Continuous ragged batching: one packed dispatch for heterogeneous
requests, retiring the pow2 pad ladder's executable lattice.

The classic :class:`~raft_tpu.serve.batcher.MicroBatcher` fixes *batch*
shapes with the pow2 bucket ladder, but every other request-level degree
of freedom — top-``k``, the sample-filter bitset — still leaks into the
executable universe: a service that wants per-request k and filters in
classic mode runs one batcher variant per (k, filter) pair, warms
(buckets × ks × filters) executables, and still recompiles the first
time a novel combination shows up.

Ragged mode collapses that lattice to **one executable per capacity
bucket** by making ``k`` and the filter *data* instead of shape:

- Every dispatch computes the spec's static ``k_max`` result columns.
  Each request's own ``k`` rides in a ``[cap] int32`` descriptor column;
  :func:`raft_tpu.ops.matrix.mask_row_k` applies it inside the
  executable (positions past a row's k surface as id −1 at the worst
  distance) and the future slices its ``[:k]`` columns host-side after
  copy-out.
- Filters are registered up front in a :class:`FilterRegistry`, which
  packs them as rows of one ``[F, W] uint32`` table; a request carries
  only its ``fid``.  The dispatcher gathers the batch's rows host-side
  (numpy — an eager device gather would trace a fresh executable every
  time ``F`` grows) into a :class:`~raft_tpu.core.bitset.RowFilter`
  whose shape depends on the bucket only.  fid 0 is the reserved
  all-pass row, so unfiltered and filtered requests pack together.
- Tombstones compose unchanged: the mutable search folds the deleted
  mask into the per-row pass words before the backend runs
  (:func:`raft_tpu.neighbors._common.resolve_pass_filter`).

Register filters **before** :meth:`~raft_tpu.serve.service.SearchService.
warmup`.  Registration itself never recompiles the XLA legs (the table
gather is host-side and ``W`` is fixed at construction), but two paths
key on filter-derived *Python* values: cagra widens its internal search
width from the registry's pinned minimum pass count, and the fused
Pallas ivf_flat leg packs the whole table per list (``F`` in its
operand shapes).  A post-warmup registration that changes either costs
one compile per bucket on the next dispatch — surfaced loudly as a
``hot_recompile`` obs event, never silently.

Admission becomes *continuous* with the pipeline enabled: the batcher
worker claims the in-flight window slot before cutting the batch, so
requests keep packing into the forming batch for exactly as long as the
device window is full — batch fill rises (and padding waste falls) when
the device, not arrival, is the bottleneck.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu import kernels as _kernels
from raft_tpu.core import env as _env
from raft_tpu.core.bitset import Bitset, RowFilter
from raft_tpu.core.trace import traced
from raft_tpu.distance import DISTANCE_TYPES
from raft_tpu.obs import explain as _explain
from raft_tpu.ops.matrix import mask_row_k
from raft_tpu.serve.mutation import MutableIndex


def _params_info(search_params) -> Optional[dict]:
    """Host-side summary of a SearchParams object for explain stamps —
    only the effort-relevant Python values, never the object itself."""
    if search_params is None:
        return None
    out = {}
    for attr in ("n_probes", "itopk_size", "search_width", "lut_dtype"):
        val = getattr(search_params, attr, None)
        if val is not None:
            out[attr] = str(val) if attr == "lut_dtype" else val
    return out or None


@dataclass(frozen=True)
class RaggedSpec:
    """Ragged-mode configuration for a service (or one batcher).

    ``k_max`` is the static top-k capacity every dispatch computes;
    per-request k may not exceed it.  ``filters`` controls whether the
    per-request filter-id column is wired through (off saves the
    RowFilter gather for services that never register filters).
    """

    k_max: int = 32
    filters: bool = True

    @classmethod
    def from_env(cls) -> "RaggedSpec":
        return cls(
            k_max=_env.env_int("RAFT_TPU_RAGGED_KMAX", 32),
            filters=_env.env_bool("RAFT_TPU_RAGGED_FILTERS", True),
        )


class FilterRegistry:
    """Registered sample filters for one ragged-served index.

    Filters pack as rows of one ``[F, W] uint32`` table over a fixed
    global-id space of ``n_bits`` ids; requests reference them by row
    index (fid).  fid 0 is the reserved all-pass row.  Registration is
    append-only — fids stay stable for the life of the served index.

    Semantics: a filter *allows* exactly the ids whose bit is set.  Ids
    past a registered mask's length are denied (zero-filled), but ids
    past the registry's own ``n_bits`` — e.g. side-buffer rows upserted
    after construction — pass every filter (the serve layer treats
    uncovered ids as unconstrained; see ``MutableIndex._side_passes``).
    """

    def __init__(self, n_bits: int):
        if n_bits < 1:
            raise ValueError(f"n_bits must be >= 1, got {n_bits}")
        self.n_bits = int(n_bits)
        self._n_words = (self.n_bits + 31) // 32
        self._lock = threading.Lock()
        all_pass = np.full((1, self._n_words), 0xFFFFFFFF, dtype=np.uint32)
        tail = self.n_bits % 32
        if tail:
            # mask the tail bits so pass counts (cagra's search-width
            # input) stay exact
            all_pass[0, -1] = np.uint32((1 << tail) - 1)
        self._table = all_pass
        self._pass_counts = [self.n_bits]

    def __len__(self) -> int:
        with self._lock:
            return self._table.shape[0]

    def register(self, mask) -> int:
        """Register one filter; returns its fid.

        ``mask`` is a bool array over global ids (shorter than ``n_bits``
        is zero-extended: uncovered ids are denied) or a
        :class:`~raft_tpu.core.bitset.Bitset`.
        """
        if isinstance(mask, Bitset):
            if mask.n_bits > self.n_bits:
                raise ValueError(
                    f"filter covers {mask.n_bits} ids but the registry "
                    f"was sized for {self.n_bits}"
                )
            src = np.asarray(mask.words, dtype=np.uint32)
            words = np.zeros((self._n_words,), dtype=np.uint32)
            words[: src.shape[0]] = src
            count = int(np.unpackbits(
                words.view(np.uint8), bitorder="little"
            ).sum())
        else:
            mask = np.asarray(mask, dtype=bool).reshape(-1)
            if mask.shape[0] > self.n_bits:
                raise ValueError(
                    f"filter covers {mask.shape[0]} ids but the registry "
                    f"was sized for {self.n_bits}"
                )
            padded = np.zeros((self._n_words * 32,), dtype=np.uint8)
            padded[: mask.shape[0]] = mask
            words = np.packbits(padded, bitorder="little").view(np.uint32)
            count = int(mask.sum())
        with self._lock:
            fid = self._table.shape[0]
            # replace, never mutate: snapshot() hands out the old array
            # without copying and dispatches may still hold it
            self._table = np.concatenate(
                [self._table, words[None, :]], axis=0
            )
            self._pass_counts.append(count)
        return fid

    def contains(self, fid: int) -> bool:
        with self._lock:
            return 0 <= fid < self._table.shape[0]

    def snapshot(self) -> Tuple[np.ndarray, int]:
        """(table [F, W], min pass count) — one consistent view.

        The min pass count is the registry-wide floor, pinned so cagra's
        filter-aware search widening sees the same host int on every
        batch regardless of which fids happen to be present — the value
        changes only on registration, never per dispatch.
        """
        with self._lock:
            return self._table, min(self._pass_counts)


class RaggedSearcher:
    """The batcher-facing search fn for one ragged-served index.

    ``__call__(queries [cap, d], row_k [cap], row_fid [cap])`` resolves
    the registry once per batch (the same hot-swap atomicity boundary as
    the classic path), materializes the batch's per-request
    :class:`~raft_tpu.core.bitset.RowFilter` from the filter table
    (host-side numpy gather), and runs the merged mutable search at the
    bucket's static ``k_max`` with per-row k masking inside the
    executable.  Everything shape-relevant depends only on the bucket:
    zero recompiles after a one-variant-per-bucket warmup.
    """

    def __init__(self, service, name: str, spec: RaggedSpec,
                 filters: Optional[FilterRegistry], degraded=None,
                 effort=None):
        self._service = service
        self._name = name
        self._spec = spec
        self._filters = filters
        # optional serve.overload.DegradedModeManager: under sustained
        # pressure its level prescribes reduced-effort search params
        self._degraded = degraded
        # optional serve.effort.EffortArbiter: when present it is the
        # single source of the effective effort level (overload clamp +
        # autotuner walk) and supersedes the direct degraded lookup
        self._effort = effort

    @property
    def filters(self) -> Optional[FilterRegistry]:
        return self._filters

    @traced("serve.ragged.dispatch")
    def __call__(self, queries: jax.Array, row_k: jax.Array,
                 row_fid: jax.Array):
        # resolve once per BATCH: the whole packed batch is answered
        # by one index version (hot-swap atomicity boundary)
        index, _version = self._service.registry.get_versioned(self._name)
        row_k = jnp.asarray(row_k, jnp.int32)
        sample_filter = None
        if self._filters is not None:
            table, min_pass = self._filters.snapshot()
            # HOST gather (numpy in, numpy indexing): the RowFilter's
            # words depend on the bucket size only, so the table may
            # grow without changing any traced shape
            sample_filter = RowFilter.from_table(
                table, np.asarray(row_fid, np.int32),
                self._filters.n_bits, pass_count=min_pass,
            )
        if not isinstance(index, MutableIndex):
            # ShardedIndex (and anything else duck-typed): run at k_max
            # and mask each row's k after it.  Registered filters ride as
            # a per-query global-id RowFilter — the packed table is tiny
            # and replicates to every shard (ShardedIndex.search rebases
            # it per shard; one extra cached executable per k, never a
            # per-(k × filter) lattice)
            # perf-ledger attribution: the SPMD body traces once, so the
            # routing stamp happens here on the host, not inside search
            # (graph-mode CAGRA serves filtered traffic through its exact
            # brute-refine core, so a filtered dispatch stamps "sharded")
            graph_walk = (
                getattr(index, "graph_mode", False) and sample_filter is None
            )
            _kernels.stamp_kernel_path(
                "sharded_graph" if graph_walk else "sharded"
            )
            if _explain.enabled():
                # host-side decision stamp — the batcher consumes it on
                # this same thread right after the call
                _explain.stamp_dispatch({
                    "k_max": self._spec.k_max,
                    "sharded": True,
                    "filters": sample_filter is not None,
                })
            if sample_filter is not None:
                dist, ids = index.search(
                    queries, self._spec.k_max, sample_filter=sample_filter
                )
            else:
                dist, ids = index.search(queries, self._spec.k_max)
            select_min = DISTANCE_TYPES[index.metric] != "inner_product"
            return mask_row_k(dist, ids, row_k, select_min=select_min)
        search_params = None
        if self._effort is not None:
            # arbitrated effort level (overload clamp + autotuner); every
            # (bucket, level) variant was warmed by the batcher's
            # level-pinned warmup
            search_params = self._effort.apply(index)
        elif self._degraded is not None:
            # reduced-effort params under pressure; every (bucket, level)
            # variant was warmed by the batcher's level-pinned warmup
            search_params = self._degraded.params_for(index)
        if _explain.enabled():
            # effective effort params actually handed to the backend —
            # recorded where the decision is made, zero extra derivation
            _explain.stamp_dispatch({
                "k_max": self._spec.k_max,
                "filters": sample_filter is not None,
                "effort_params": _params_info(search_params),
            })
        return index.search(
            queries, self._spec.k_max,
            sample_filter=sample_filter, row_k=row_k,
            search_params=search_params,
        )
