"""Partitioned CAGRA: true sharded graph traversal with halo frontiers.

:class:`~raft_tpu.serve.shard.ShardedIndex` serves CAGRA by
row-partitioned brute refine — exact, but O(rows/shard) device work per
query, which forfeits CAGRA's algorithmic win exactly where sharding is
supposed to deliver it.  This module restores the sublinear walk at pod
scale:

* **Cluster cut** — the graph is partitioned with the existing balanced
  k-means coarse clustering (:mod:`raft_tpu.cluster.kmeans_balanced`,
  ``C = n_shards``): each shard owns the rows of its cluster, so the cut
  follows the data's own geometry and most graph edges stay internal.
* **Halo nodes** — each shard replicates a bounded set of cross-cut
  neighbors (ranked by in-degree from owned rows, capped by
  ``RAFT_TPU_SHARD_CAGRA_HALO``) so local hops never dead-end at a
  partition boundary.  Halo rows route the walk but never appear in
  results (the per-shard pass bitset covers owned live rows only, so the
  merged id set is duplicate-free).
* **Shard-local traversal** — each shard runs the PR 13 fused Pallas hop
  (or its XLA twin off-TPU) over its *local-id* subgraph
  (:func:`raft_tpu.neighbors.cagra.traverse_steps`); the local↔global id
  translation is one gather (local→global, via the shard's ``ids`` row)
  and one binary search (global→local, via a sorted gid table).
* **Halo frontier exchange** — every ``RAFT_TPU_SHARD_CAGRA_SYNC_STEPS``
  local hops the shards exchange their current best candidates (global
  ids + traversal-space distances, optionally bf16-quantized like the
  shard merge, EQuARX-style) through the same all-gather the brute merge
  uses; each shard folds the arrivals it can resolve locally back into
  its buffer as unexplored candidates.  The cadence is fixed at trace
  time, so the number of collectives per query is static and the
  batcher's zero-recompile contract holds.

The brute-refine path stays the default (``RAFT_TPU_SHARD_CAGRA=brute``)
and the correctness control arm; ``bench.py shard_cagra`` freezes the
graph-vs-brute A/B (matched recall, modeled per-device work ratio).
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core import env as _env
from raft_tpu.core.bitset import WORD_BITS
from raft_tpu.distance.pairwise import DISTANCE_TYPES
from raft_tpu.neighbors import cagra
from raft_tpu.neighbors._common import sorted_id_dedup
from raft_tpu.ops.matrix import select_k
from raft_tpu.serve.shard import ShardedIndex, _pack_pass_words, _place

__all__ = ["GraphShardedIndex", "partition_cagra_graph"]

#: per-shard cap on replicated halo rows (unset = keep every cross-cut
#: neighbor; 0 = no halo — local hops dead-end at the cut)
HALO_ENV = "RAFT_TPU_SHARD_CAGRA_HALO"

#: local hops between cross-shard frontier exchanges (static cadence)
SYNC_STEPS_ENV = "RAFT_TPU_SHARD_CAGRA_SYNC_STEPS"

#: sorted-gid-table padding sentinel: sorts past every real int32 id
_GID_PAD = np.int32(np.iinfo(np.int32).max)


def sync_steps_from_env() -> int:
    """Resolve ``RAFT_TPU_SHARD_CAGRA_SYNC_STEPS`` (floor 1)."""
    return max(1, int(_env.env_int(SYNC_STEPS_ENV, 4)))


def halo_cap_from_env() -> Optional[int]:
    """Resolve ``RAFT_TPU_SHARD_CAGRA_HALO`` (None = unlimited)."""
    cap = _env.env_int(HALO_ENV)
    return None if cap is None else max(0, int(cap))


def cut_labels(data: np.ndarray, n_shards: int, metric: str,
               seed: int = 0) -> np.ndarray:
    """Cluster-cut shard assignment: one balanced k-means with
    ``C = n_shards`` over (a subsample of) the dataset, then a full
    predict pass.  The same coarse clustering the IVF builds and the
    CAGRA entry-point table already use — the cut follows data geometry,
    so most graph edges stay shard-internal."""
    from raft_tpu.cluster import kmeans_balanced
    from raft_tpu.neighbors._common import subsample_trainset

    canonical = DISTANCE_TYPES[metric]
    kb_metric = (
        "inner_product" if canonical == "inner_product" else "sqeuclidean"
    )
    n = data.shape[0]
    n_train = min(n, max(n_shards * 1024, 8192))
    train = (
        subsample_trainset(data, n_train, seed) if n_train < n
        else jnp.asarray(data)
    ).astype(jnp.float32)
    kb = kmeans_balanced.KMeansBalancedParams(
        n_iters=10, metric=kb_metric, seed=seed
    )
    centers = kmeans_balanced.fit(kb, train, n_shards)
    labels = kmeans_balanced.predict(
        centers, jnp.asarray(data, jnp.float32), metric=kb_metric
    )
    return np.asarray(labels, np.int64)


def partition_cagra_graph(
    data: np.ndarray,
    graph: np.ndarray,
    labels: np.ndarray,
    n_shards: int,
    *,
    halo_cap: Optional[int] = None,
    deleted: Optional[np.ndarray] = None,
    entry_ids: Optional[np.ndarray] = None,
) -> Tuple[Dict[str, np.ndarray], np.ndarray, Dict[str, list]]:
    """Materialize per-shard subgraphs with halo replicas (host numpy).

    Each shard owns the rows with its label; its halo is the distinct
    cross-cut neighbors of owned rows, ranked by in-degree from owned
    rows (high-traffic boundary nodes replicate first) and capped at
    ``halo_cap``.  The local id space is owned rows then halo rows, every
    shard padded to a uniform length (rows zero, ids −1, graph −1, pass
    bits clear — the traversal masks all of them).

    Returns ``(sharded-part stacks, halo_start [S], shard stats)``; the
    parts are ``rows``/``ids``/``pass_words``/``graph``/``sort_gid``/
    ``sort_lid`` (+ ``entry_lids`` when ``entry_ids`` is given).
    """
    n, d = data.shape
    deg = graph.shape[1]
    owned = [
        np.flatnonzero(labels == s).astype(np.int64) for s in range(n_shards)
    ]
    halos = []
    for s in range(n_shards):
        o = owned[s]
        ext = np.empty(0, np.int64)
        if o.size:
            nb = graph[o].ravel()
            nb = nb[(nb >= 0) & (nb < n)]
            ext = nb[labels[nb] != s]
        if ext.size:
            uniq, counts = np.unique(ext, return_counts=True)
            order = np.argsort(-counts, kind="stable")  # ties: gid asc
            h = uniq[order]
        else:
            h = np.empty(0, np.int64)
        if halo_cap is not None:
            h = h[:halo_cap]
        halos.append(np.sort(h))

    rl = max(1, max(len(o) + len(h) for o, h in zip(owned, halos)))
    rows = np.zeros((n_shards, rl, d), data.dtype)
    ids = np.full((n_shards, rl), -1, np.int32)
    lgraph = np.full((n_shards, rl, deg), -1, np.int32)
    words = np.zeros(
        (n_shards, (rl + WORD_BITS - 1) // WORD_BITS), np.uint32
    )
    sort_gid = np.full((n_shards, rl), _GID_PAD, np.int32)
    sort_lid = np.zeros((n_shards, rl), np.int32)
    halo_start = np.zeros((n_shards,), np.int32)
    elids = (
        None if entry_ids is None
        else np.full((n_shards, len(entry_ids)), -1, np.int32)
    )
    live_rows, halo_rows = [], []
    g2l = np.full((n,), -1, np.int32)
    for s in range(n_shards):
        loc = np.concatenate([owned[s], halos[s]])
        m = loc.size
        halo_start[s] = owned[s].size
        if m:
            rows[s, :m] = data[loc]
            ids[s, :m] = loc
            g2l[:] = -1
            g2l[loc] = np.arange(m, dtype=np.int32)
            sub = graph[loc]
            lgraph[s, :m] = np.where(
                (sub >= 0) & (sub < n), g2l[np.clip(sub, 0, n - 1)], -1
            )
            order = np.argsort(loc, kind="stable")
            sort_gid[s, :m] = loc[order]
            sort_lid[s, :m] = order
            if elids is not None:
                elids[s] = g2l[np.clip(entry_ids, 0, n - 1)]
        passes = np.zeros((rl,), bool)
        passes[: owned[s].size] = True
        if deleted is not None and owned[s].size:
            passes[: owned[s].size] &= ~np.asarray(deleted)[owned[s]]
        words[s] = _pack_pass_words(passes)
        live_rows.append(int(passes.sum()))
        halo_rows.append(int(halos[s].size))

    sharded = {
        "rows": rows, "ids": ids, "pass_words": words, "graph": lgraph,
        "sort_gid": sort_gid, "sort_lid": sort_lid,
    }
    if elids is not None:
        sharded["entry_lids"] = elids
    return sharded, halo_start, {"rows": live_rows, "halo": halo_rows}


class GraphShardedIndex(ShardedIndex):
    """Sharded CAGRA served by partitioned graph traversal.

    Construct through :meth:`ShardedIndex.from_index` with
    ``cagra_mode="graph"`` (or ``RAFT_TPU_SHARD_CAGRA=graph``), or through
    ``serve.build.build_sharded(kind="cagra", cagra_mode="graph")`` which
    emits the partitioned layout directly from the ring-kNN graph.

    Unfiltered searches run the halo-frontier traversal; filtered
    searches (and anything the walk cannot serve) ride the inherited
    exact brute-refine core over the same ``rows``/``ids``/``pass_words``
    parts — one layout, two engines.
    """

    graph_mode = True

    def __init__(self, comms, metric, dim, size, parts, specs, *,
                 search_params=None, merge_dtype=None, label="",
                 shard_stats=None, halo_start=None, sync_steps=4,
                 has_entries=False):
        self._halo_start = (
            np.zeros((comms.get_size(),), np.int32)
            if halo_start is None else np.asarray(halo_start, np.int32)
        )
        self._sync_steps = max(1, int(sync_steps))
        self._has_entries = bool(has_entries)
        if search_params is None:
            search_params = cagra.SearchParams()
        super().__init__(
            comms, "cagra", metric, dim, size, parts, specs,
            search_params=search_params, merge_dtype=merge_dtype,
            label=label, shard_stats=shard_stats,
        )

    # -- construction --------------------------------------------------------
    @classmethod
    def _shard_graph(cls, comms, inner, deleted, search_params,
                     merge_dtype, label) -> "GraphShardedIndex":
        """Partition a built :class:`raft_tpu.neighbors.cagra.Index`."""
        if getattr(inner, "paged", None) is not None:
            raise NotImplementedError(
                "graph-mode sharded CAGRA cannot serve a paged dataset: "
                "per-shard halo subgraphs re-index rows into local id "
                "spaces, and the paged per-DMA translation tables are "
                "keyed by global row id — halo rows would read wrong "
                "pages.  Serve paged CAGRA unsharded, or shard with "
                "RAFT_TPU_SHARD_CAGRA=brute (row-partitioned brute "
                "refine)."
            )
        if not isinstance(inner.dataset, (jax.Array, np.ndarray)):
            raise NotImplementedError(
                "graph-mode sharded CAGRA needs a dense dataset; "
                "VPQ-compressed indexes keep RAFT_TPU_SHARD_CAGRA=brute"
            )
        data = np.asarray(inner.dataset)
        graph = np.asarray(inner.graph, np.int32)
        n, d = data.shape
        s_count = comms.get_size()
        labels = cut_labels(data, s_count, inner.metric)
        entry_ids = (
            None if inner.entry_centers is None
            else np.asarray(inner.entry_ids, np.int64)
        )
        sharded, halo_start, stats = partition_cagra_graph(
            data, graph, labels, s_count,
            halo_cap=halo_cap_from_env(),
            deleted=None if deleted is None else np.asarray(deleted),
            entry_ids=entry_ids,
        )
        replicated = {}
        if entry_ids is not None:
            replicated["entry_centers"] = np.asarray(
                inner.entry_centers, np.float32
            )
        parts, specs = _place(comms, sharded=sharded, replicated=replicated)
        live = n if deleted is None else n - int(np.asarray(deleted).sum())
        return cls(
            comms, inner.metric, d, live, parts, specs,
            search_params=search_params, merge_dtype=merge_dtype,
            label=label, shard_stats=stats, halo_start=halo_start,
            sync_steps=sync_steps_from_env(),
            has_entries=entry_ids is not None,
        )

    # -- traversal configuration --------------------------------------------
    def _traverse_config(self, kk: int) -> Dict[str, object]:
        """Static per-searcher traversal plan: buffer width, hop budget,
        exchange cadence, and the fused-kernel gate — all resolved
        host-side once, so the SPMD body traces with a fixed collective
        count."""
        from raft_tpu import kernels as _kernels
        from raft_tpu.kernels.cagra_traverse import traverse_supported

        rl = int(self._parts["rows"].shape[1])
        params = self.search_params
        metric = DISTANCE_TYPES[self.metric]
        itopk = min(max(int(params.itopk_size), int(kk)), rl)
        width = max(1, int(params.search_width))
        if params.max_iterations:
            max_iter = int(params.max_iterations)
        elif self._has_entries:
            # entry-seeded walks start next to the answer (cagra.search's
            # auto budget)
            max_iter = max(8, (itopk + width - 1) // width)
        else:
            max_iter = max(16, (itopk + width - 1) // width * 2)
        sync = self._sync_steps
        rounds = max(1, -(-max_iter // sync))
        # same routing gate as cagra.search: the fused Pallas hop serves
        # dense f32/bf16 subgraphs at fold-friendly widths on TPU
        fused = (
            _kernels.use_pallas()
            and _kernels.cagra_fused_enabled()
            and traverse_supported(self._parts["rows"], itopk)
        )
        return {
            "itopk": itopk, "width": width, "metric": metric,
            "sync": sync, "rounds": rounds, "fused": fused,
            # frontier-exchange width per shard: enough to re-seed a
            # remote walk without bloating the collective
            "ex_w": min(itopk, 32),
        }

    def modeled_device_work(self, kk: int) -> Dict[str, int]:
        """Analytic per-query-per-shard distance-computation count for the
        traversal plan ``_traverse_config(kk)`` resolves: seed scoring at
        init plus ``width·deg`` candidate scores per hop.  The brute-refine
        control arm scores every resident row (``rows_len``), so
        ``rows_len / total`` is the modeled per-device work ratio the
        ``bench.py shard_cagra`` A/B freezes."""
        cfg = self._traverse_config(kk)
        rl = int(self._parts["rows"].shape[1])
        deg = int(self._parts["graph"].shape[2])
        params = self.search_params
        n_samplings = max(1, int(params.num_random_samplings))
        if self._has_entries:
            n_centers = int(self._parts["entry_centers"].shape[0])
            s = min(max(int(params.num_entry_centers), 0), n_centers)
            seeds = s + min(rl, max(cfg["itopk"], 32) * n_samplings)
        else:
            seeds = min(rl, max(2 * cfg["itopk"], 128) * n_samplings)
        hops = int(cfg["rounds"]) * int(cfg["sync"])
        per_hop = int(cfg["width"]) * deg
        return {
            "seeds": int(seeds),
            "hops": hops,
            "per_hop": per_hop,
            "distances": int(seeds) + hops * per_hop,
            "rows_len": rl,
        }

    # -- serving -------------------------------------------------------------
    def _make_init(self, cfg):
        """Per-shard buffer init: top entry centers mapped to local ids
        (−1 where this shard holds neither the row nor a halo copy of it)
        plus a random local top-up — same seeding discipline as
        ``cagra.make_seed_ids``, in local id space."""
        names = self._names
        params = self.search_params
        has_entries = self._has_entries
        itopk, metric = cfg["itopk"], cfg["metric"]

        def init(q, *args):
            p = dict(zip(names, args))
            rows, ids = p["rows"][0], p["ids"][0]
            rl = rows.shape[0]
            nq = q.shape[0]
            seeds = []
            if has_entries:
                centers = p["entry_centers"].astype(jnp.float32)
                s = int(min(
                    max(int(params.num_entry_centers), 0), centers.shape[0]
                ))
                if s > 0:
                    seeds.append(cagra._entry_seeds(
                        q, centers, p["entry_lids"][0], s, metric
                    ))
                n_rand = min(
                    rl,
                    max(itopk, 32) * max(1, int(params.num_random_samplings)),
                )
            else:
                n_rand = min(
                    rl,
                    max(2 * itopk, 128)
                    * max(1, int(params.num_random_samplings)),
                )
            key = jax.random.PRNGKey(int(params.rand_xor_mask) & 0x7FFFFFFF)
            # the same local ids on every shard name DIFFERENT global
            # rows, so the pooled random seeds are distinct cross-shard
            # without any coordination
            seeds.append(jax.random.randint(key, (nq, n_rand), 0, rl,
                                            jnp.int32))
            lids = (
                jnp.concatenate(seeds, axis=1) if len(seeds) > 1
                else seeds[0]
            )
            # demote padding rows (id −1) and absent entry rows before
            # they can seed the buffer
            safe = jnp.clip(lids, 0, rl - 1)
            lids = jnp.where((lids >= 0) & (ids[safe] >= 0), lids, -1)
            return cagra.traverse_init(rows, q, lids, itopk, metric)

        return init

    def _make_extract(self, cfg):
        """Frontier-exchange payload: this shard's current best ``ex_w``
        candidates as (traversal-space distance, GLOBAL id), optionally
        quantized like the final merge (EQuARX-style)."""
        ex_w = cfg["ex_w"]
        merge_dtype = self.merge_dtype

        def extract(buf_d, buf_i, ids):
            rl = ids.shape[0]
            d, lid = select_k(buf_d, ex_w, select_min=True,
                              input_indices=buf_i)
            gid = jnp.where(
                lid >= 0, ids[jnp.clip(lid, 0, rl - 1)], jnp.int32(-1)
            )
            d = jnp.where(gid >= 0, d, jnp.inf)
            if merge_dtype is not None and d.dtype != merge_dtype:
                d = d.astype(merge_dtype)
            return d, gid

        return extract

    def _make_fold(self, cfg):
        """Fold the gathered cross-shard frontier back into the local
        buffer: binary-search each global id in the sorted local gid
        table, keep the ones this shard can resolve (owned or halo),
        dedup, and merge as UNEXPLORED candidates — the next super-step's
        hops expand them.  Arrivals reuse the distance computed on their
        source shard (same row, same query, same metric)."""
        itopk = cfg["itopk"]

        def fold(buf_d, buf_i, explored, gd, gg, sort_gid, sort_lid):
            rl = sort_gid.shape[0]
            pos = jnp.clip(jnp.searchsorted(sort_gid, gg), 0, rl - 1)
            present = (sort_gid[pos] == gg) & (gg >= 0)
            lid = jnp.where(present, sort_lid[pos], jnp.int32(-1))
            d = jnp.where(lid >= 0, gd.astype(jnp.float32), jnp.inf)
            # the same row can arrive from several shards (halo copies):
            # keep one
            order, dup = sorted_id_dedup(lid)
            lid_s = jnp.take_along_axis(lid, order, axis=1)
            d_s = jnp.where(
                dup, jnp.inf, jnp.take_along_axis(d, order, axis=1)
            )
            # resident buffer entries win — they carry explored flags
            in_buf = jnp.any(
                lid_s[:, :, None] == buf_i[:, None, :], axis=2
            )
            d_s = jnp.where(in_buf, jnp.inf, d_s)
            all_d = jnp.concatenate([buf_d, d_s], axis=1)
            all_i = jnp.concatenate([buf_i, lid_s], axis=1)
            all_e = jnp.concatenate(
                [explored, jnp.zeros(d_s.shape, bool)], axis=1
            )
            buf_d, mpos = select_k(all_d, itopk, select_min=True)
            buf_i = jnp.take_along_axis(all_i, mpos, axis=1)
            buf_i = jnp.where(jnp.isfinite(buf_d), buf_i, -1)
            explored = jnp.take_along_axis(all_e, mpos, axis=1)
            explored = explored | ~jnp.isfinite(buf_d)
            return buf_d, buf_i, explored

        return fold

    def _make_finalize(self, cfg, kk: int):
        """Per-shard answer: mask the buffer to owned live rows (the pass
        bitset), select the best ``kk``, translate to global ids, and
        apply the final metric transforms — the cross-shard merge's
        tie-stable select expects final-space values."""
        metric = cfg["metric"]
        merge_dtype = self.merge_dtype

        def finalize(buf_d, buf_i, ids, pass_words):
            rl = ids.shape[0]
            safe = jnp.clip(buf_i, 0, rl - 1).astype(jnp.uint32)
            word = pass_words[safe // WORD_BITS]
            bit = (word >> (safe % WORD_BITS)) & jnp.uint32(1)
            d = jnp.where((bit == 1) & (buf_i >= 0), buf_d, jnp.inf)
            gid = jnp.where(
                buf_i >= 0, ids[jnp.clip(buf_i, 0, rl - 1)], jnp.int32(-1)
            )
            v, gi = select_k(d, kk, select_min=True, input_indices=gid)
            gi = jnp.where(jnp.isfinite(v), gi, -1)
            if metric == "inner_product":
                v = -v
            elif metric == "euclidean":
                v = jnp.sqrt(jnp.maximum(v, 0.0))
            if merge_dtype is not None and v.dtype != merge_dtype:
                v = v.astype(merge_dtype)
            return v, gi

        return finalize

    def _make_local(self, k: int, kk: int, npb: int,
                    filter_bits: Optional[int] = None):
        """Graph-mode SPMD body: init → (SYNC_STEPS local hops → frontier
        all-gather → fold) × rounds → finalize → the one cross-shard
        merge.  The round loop unrolls at trace time, so the collective
        count is static — ``2·(rounds−1)`` frontier gathers plus the two
        merge gathers, every dispatch.  Filtered traffic keeps the
        inherited exact brute-refine body (the walk has no filtered leg;
        the parts serve both)."""
        if filter_bits is not None:
            return super()._make_local(k, kk, npb, filter_bits)
        cfg = self._traverse_config(kk)
        names = self._names
        comms = self.comms
        select_min = self.select_min
        # nested jit for everything but the collectives: older jax's
        # ShardMapTracer lacks the eager operator surface (same split as
        # ShardedIndex._make_local / replica.py)
        init = jax.jit(self._make_init(cfg))
        extract = jax.jit(self._make_extract(cfg))
        fold = jax.jit(self._make_fold(cfg))
        finalize = jax.jit(self._make_finalize(cfg, kk))
        steps = functools.partial(
            cagra.traverse_steps, steps=cfg["sync"], width=cfg["width"],
            metric=cfg["metric"], fused=cfg["fused"],
        )

        def _select(vg, ig):
            from raft_tpu.ops import matrix

            return matrix.select_k_stable(
                vg.astype(jnp.float32), k,
                select_min=select_min, input_indices=ig,
            )

        sel = jax.jit(_select)
        rounds = cfg["rounds"]

        def local(q, *args):
            p = dict(zip(names, args))
            rows, graph = p["rows"][0], p["graph"][0]
            state = init(q, *args)
            for r in range(rounds):
                buf_d, buf_i, explored = state
                state = steps(rows, graph, q, buf_d, buf_i, explored)
                if r + 1 < rounds:
                    buf_d, buf_i, explored = state
                    fd, fg = extract(buf_d, buf_i, p["ids"][0])
                    fdg = comms.allgather(fd, axis=1)
                    fgg = comms.allgather(fg, axis=1)
                    state = fold(
                        buf_d, buf_i, explored, fdg, fgg,
                        p["sort_gid"][0], p["sort_lid"][0],
                    )
            buf_d, buf_i, _ = state
            v, gi = finalize(buf_d, buf_i, p["ids"][0], p["pass_words"][0])
            vg = comms.allgather(v, axis=1)
            ig = comms.allgather(gi, axis=1)
            return sel(vg, ig)

        return local

    def _make_shard_search(self, kk: int, npb: int,
                           filter_bits: Optional[int] = None):
        """Exchange-free per-shard core — the full hop budget run locally
        with no collectives, same signature as the inherited brute core.
        This is what :meth:`measure_shard_skew` and the explain probe
        dispatch per shard (a collective inside would deadlock a
        single-shard replay); filtered requests return the inherited
        exact brute-refine core."""
        if filter_bits is not None:
            return super()._make_shard_search(kk, npb, filter_bits)
        cfg = self._traverse_config(kk)
        names = self._names
        init = self._make_init(cfg)
        finalize = self._make_finalize(cfg, kk)
        total = cfg["rounds"] * cfg["sync"]

        def core(q, *args):
            p = dict(zip(names, args))
            rows, graph = p["rows"][0], p["graph"][0]
            buf_d, buf_i, explored = init(q, *args)
            buf_d, buf_i, explored = cagra.traverse_steps(
                rows, graph, q, buf_d, buf_i, explored,
                steps=total, width=cfg["width"], metric=cfg["metric"],
                fused=cfg["fused"],
            )
            return finalize(
                buf_d, buf_i, p["ids"][0], p["pass_words"][0]
            )

        return core

    # -- observability -------------------------------------------------------
    def explain_contributions(self, ids) -> Dict[str, object]:
        """Per-shard counts of merged result ids under the CLUSTER cut
        (the base class's contiguous ``id // rows_per_shard`` rule does
        not apply), plus the graph-mode layout facts."""
        try:
            flat = np.asarray(ids).reshape(-1)
            flat = flat[flat >= 0]
            owner_map = self._graph_owner()
            flat = flat[flat < owner_map.shape[0]]
            owner = owner_map[flat]
            s_count = self.n_shards
            counts = np.bincount(
                owner[(owner >= 0) & (owner < s_count)], minlength=s_count
            )
            return {
                "available": True,
                "mode": "graph",
                "n_shards": s_count,
                "per_shard": [int(c) for c in counts[:s_count]],
                "owned_rows": list(self._shard_stats.get("rows", [])),
                "halo_rows": list(self._shard_stats.get("halo", [])),
                "sync_steps": int(self._sync_steps),
            }
        except Exception as exc:  # never let explain break serving
            return {"available": False, "error": repr(exc)}

    def _graph_owner(self) -> np.ndarray:
        """Cached global-id → owning-shard map from the owned prefixes of
        each shard's id row (built once, deep-explain only)."""
        owner = getattr(self, "_owner_map", None)
        if owner is None:
            ids = np.asarray(self._parts["ids"])  # raft-tpu: ignore[HOSTSYNC] deep-explain only: one-time owner-map pull, never on the hot path
            top = int(ids.max()) + 1 if ids.size else 0
            owner = np.full(max(top, 0), -1, np.int32)
            for s in range(ids.shape[0]):
                own = ids[s, : int(self._halo_start[s])]
                own = own[own >= 0]
                owner[own] = s
            self._owner_map = owner
        return owner

    def explain_traversal(self, queries, k: int = 10) -> Dict[str, object]:
        """Deep-explain traversal probe: per-shard hop budget, frontier
        exchange rounds, and halo-hit counts for one query batch.

        Replays the exchange-free per-shard core (the same hop budget the
        SPMD dispatch runs) shard by shard and counts how many of each
        shard's surviving buffer candidates are halo rows — how hard each
        query leaned on the replicated boundary.  Off the hot path by
        construction (operator / deep-explain entry); compiles and syncs
        here never touch the serving executables."""
        queries = jnp.asarray(queries, jnp.float32)
        if queries.ndim != 2 or queries.shape[1] != self.dim:
            raise ValueError(
                f"queries shape {queries.shape} vs index dim {self.dim}"
            )
        rl = int(self._parts["rows"].shape[1])
        kk = min(max(1, int(k)), rl)
        cfg = self._traverse_config(kk)
        names = self._names
        init = jax.jit(self._make_init(cfg))
        halo_hits, buffer_live = [], []
        for s in range(self.n_shards):
            args = tuple(
                self._parts[n][s : s + 1]
                if self._specs[n] and self._specs[n][0] is not None
                else self._parts[n]
                for n in names
            )
            p = dict(zip(names, args))
            buf_d, buf_i, explored = init(queries, *args)
            buf_d, buf_i, _ = cagra.traverse_steps(
                p["rows"][0], p["graph"][0], queries, buf_d, buf_i,
                explored, steps=cfg["rounds"] * cfg["sync"],
                width=cfg["width"], metric=cfg["metric"],
                fused=cfg["fused"],
            )
            lids = np.asarray(buf_i)  # raft-tpu: ignore[HOSTSYNC] deep-explain probe pull, never on the hot path
            fin = np.isfinite(np.asarray(buf_d))  # raft-tpu: ignore[HOSTSYNC] deep-explain probe pull, never on the hot path
            hs = int(self._halo_start[s])
            halo_hits.append(int(((lids >= hs) & fin).sum()))
            buffer_live.append(int(fin.sum()))
        return {
            "available": True,
            "hops": int(cfg["rounds"] * cfg["sync"]),
            "sync_steps": int(cfg["sync"]),
            "exchange_rounds": int(cfg["rounds"] - 1),
            "itopk": int(cfg["itopk"]),
            "halo_hits": halo_hits,
            "buffer_candidates": buffer_live,
        }
