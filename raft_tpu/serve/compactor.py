"""Online compaction: background shadow rebuilds with zero serving gaps.

A :class:`~raft_tpu.serve.mutation.MutableIndex` accretes tombstones and a
brute-force side buffer forever — at production churn the side-buffer
merge becomes the hot path and dead main slots waste device memory.  The
compactor is the maintenance loop that folds both back into the main
structure *off* the serving path:

1. **Watch.**  A worker thread scans every ``MutableIndex`` registered
   with the service's :class:`~raft_tpu.serve.registry.IndexRegistry`
   against a :class:`CompactionPolicy` (side-buffer rows, tombstone
   fraction; ``RAFT_TPU_COMPACT_*`` env knobs), publishing per-index
   backlog gauges so compaction pressure is visible in ``prometheus()``.
2. **Shadow rebuild.**  A triggered pass captures the index's mutation
   state under its lock, then decodes the immutable main structure in
   bounded chunks (:meth:`MutableIndex.iter_main_rows`) and rebuilds a
   shadow: surviving + side rows re-clustered through ``extend`` into an
   empty IVF clone (trained centers/codebooks reused), re-linked CAGRA
   neighborhoods (surviving graph rows remapped, affected nodes re-kNN'd,
   reverse edges for new nodes), or a plain ``brute_force.build``.  The
   projected peak host bytes are checked against ``headroom_frac`` ×
   the live index's bytes *before* any allocation — a pass that would
   blow the budget aborts instead of OOMing a serving replica (the
   memory-safe-XLA discipline applied to maintenance).
3. **Shape stability.**  The shadow's dataset is padded to the next
   power of two (+1) with permanently-tombstoned sentinel rows and
   wrapped with a row→global-id map, so consecutive compactions keep the
   same main shapes and ids never change under the caller.  Before
   promotion the worker warms the service's whole bucket ladder against
   the shadow's shapes — including the post-swap mutation variants
   (tombstones-only, and each side-buffer capacity tier up to the
   policy's trigger threshold) — so the first query after the swap, and
   the first upsert/delete after *that*, ride already-compiled
   executables.  Hot-path recompiles stay at zero; compiles spent here
   land on the worker thread, which the batcher's per-thread compile
   bracket (``compile_count(thread=True)``) correctly ignores.
4. **Quality gate.**  Recall of the shadow on a held-back sample of live
   rows must not regress vs the serving index (both measured against an
   exact oracle over the captured rows).  A failed gate aborts the pass,
   logs, bumps the abort gauge (``healthz()`` folds it into DEGRADED),
   and re-arms after a cooldown.
5. **Promote.**  The final mutation delta (anything that landed during
   the rebuild) is folded into the shadow while holding the old index's
   lock, the registry hot-swaps atomically, and the old index is marked
   retired — writers still holding the old reference forward their
   mutations to the successor, so no write is ever lost to a swap.
"""

from __future__ import annotations

import threading
import time
import weakref
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import jax
import numpy as np

from raft_tpu import obs
from raft_tpu.core import env as _env
from raft_tpu.obs import events as obs_events
from raft_tpu.core.logger import child as _child_logger
from raft_tpu.core.trace import trace_range, traced
from raft_tpu.distance import DISTANCE_TYPES
from raft_tpu.serve.mutation import MutableIndex, _next_pow2
from raft_tpu.stats.metrics import recall_at_k
from raft_tpu.store.budget import BudgetExceeded, default_budget

_log = _child_logger("serve.compactor")

#: live compactors, for the test-suite reset hook (order independence)
_live: "weakref.WeakSet[Compactor]" = weakref.WeakSet()


def reset() -> None:
    """Stop every live compactor worker (conftest autouse hook)."""
    for c in list(_live):
        try:
            c.stop()
        except Exception:  # pragma: no cover - teardown best effort
            pass


@dataclass(frozen=True)
class CompactionPolicy:
    """When to compact, how much memory a pass may use, and the gate.

    A pass triggers when *either* pressure threshold is crossed:
    ``max_side_rows`` live side-buffer rows (the brute-force merge cost
    every query pays) or ``max_tombstone_frac`` of the main rows
    tombstoned (dead device memory).  ``headroom_frac`` bounds the
    rebuild's projected peak host bytes at that fraction of the live
    index's ``device_bytes()``; a pass that would exceed it aborts
    before allocating.  ``recall_slack`` is the quality gate's tolerance:
    shadow recall may trail serving recall by at most this much on the
    held-back sample.
    """

    max_side_rows: int = 1024
    max_tombstone_frac: float = 0.25
    interval_s: float = 2.0          # worker scan period
    cooldown_s: float = 30.0         # per-index re-arm delay after an abort
    headroom_frac: float = 4.0       # peak rebuild bytes / live index bytes
    # (the pow2-padded shadow plus the dense row gather peak near 3x
    # the live bytes for brute_force, so 2.0 would refuse normal passes)
    chunk_rows: int = 65536          # main-structure decode chunk
    gate_queries: int = 64           # held-back sample size
    gate_k: int = 10
    recall_slack: float = 0.02
    seed: int = 0x5EED

    @classmethod
    def from_env(cls) -> "CompactionPolicy":
        """Policy with every field overridable via ``RAFT_TPU_COMPACT_*``."""
        return cls(
            max_side_rows=_env.env_int("RAFT_TPU_COMPACT_MAX_SIDE_ROWS", 1024),
            max_tombstone_frac=_env.env_float(
                "RAFT_TPU_COMPACT_MAX_TOMBSTONE_FRAC", 0.25
            ),
            interval_s=_env.env_float("RAFT_TPU_COMPACT_INTERVAL_S", 2.0),
            cooldown_s=_env.env_float("RAFT_TPU_COMPACT_COOLDOWN_S", 30.0),
            headroom_frac=_env.env_float(
                "RAFT_TPU_COMPACT_HEADROOM_FRAC", 4.0
            ),
            chunk_rows=_env.env_int("RAFT_TPU_COMPACT_CHUNK_ROWS", 65536),
            gate_queries=_env.env_int("RAFT_TPU_COMPACT_GATE_QUERIES", 64),
            recall_slack=_env.env_float("RAFT_TPU_COMPACT_RECALL_SLACK", 0.02),
        )

    @staticmethod
    def disabled_by_env() -> bool:
        return _env.env_bool("RAFT_TPU_COMPACT_DISABLED", False)


@dataclass
class _Capture:
    """Mutation state of the source index at one instant (under its lock)."""

    deleted: np.ndarray        # main-row tombstone mask copy
    side_count: int            # occupied side slots at capture
    side_live: np.ndarray      # full side liveness copy (length >= side_count)
    side_ids: np.ndarray       # full side id array copy
    generation: int


def _capture_locked(mi: MutableIndex) -> _Capture:
    return _Capture(
        deleted=mi._deleted.copy(),
        side_count=mi._side_count,
        side_live=mi._side_live.copy(),
        side_ids=mi._side_ids.copy(),
        generation=mi._generation,
    )


class Compactor:
    """Background maintenance worker over a service's registered indexes.

    Owned by :class:`~raft_tpu.serve.service.SearchService` (the
    ``compaction=`` constructor knob); standalone construction takes the
    service explicitly.  ``start=True`` launches the daemon scan loop;
    :meth:`trigger_now` runs one synchronous pass regardless of
    thresholds (operator escape hatch), :meth:`pause`/:meth:`resume`
    gate the automatic loop, and :meth:`drain` blocks until no pass is
    running.
    """

    def __init__(self, service, policy: Optional[CompactionPolicy] = None,
                 *, start: bool = False):
        self.service = service
        self.policy = policy if policy is not None else CompactionPolicy.from_env()
        self._stop = threading.Event()
        self._paused = threading.Event()
        self._idle = threading.Event()
        self._idle.set()
        self._lock = threading.Lock()       # compaction state, not the pass
        self._pass_lock = threading.Lock()  # one pass at a time
        self._worker: Optional[threading.Thread] = None
        self._cooldown_until: Dict[str, float] = {}
        self._last_abort: Dict[str, Dict[str, object]] = {}
        self._compactions = 0
        self._aborts = 0
        self._last_result: Optional[Dict[str, object]] = None
        obs.default_registry().register_provider("compaction", self.snapshot)
        _live.add(self)
        if start:
            self.start()

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        with self._lock:
            if self._worker is not None and self._worker.is_alive():
                return
            self._stop.clear()
            self._worker = threading.Thread(
                target=self._run, name="raft-tpu-compactor", daemon=True
            )
            self._worker.start()

    def stop(self) -> None:
        self._stop.set()
        worker = self._worker
        if worker is not None and worker is not threading.current_thread():
            worker.join(timeout=30)
        obs.default_registry().unregister_provider(
            "compaction", expected=self.snapshot
        )

    def pause(self) -> None:
        """Suspend automatic triggering (a running pass finishes)."""
        self._paused.set()

    def resume(self) -> None:
        self._paused.clear()

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Block until no compaction pass is in flight; True on success."""
        return self._idle.wait(timeout=timeout)

    # -- worker loop ---------------------------------------------------------
    def _run(self) -> None:
        while not self._stop.wait(self.policy.interval_s):
            if self._paused.is_set():
                continue
            try:
                self.scan()
            except Exception:  # noqa: BLE001 — the loop must survive
                _log.exception("compactor scan failed")

    def scan(self) -> None:
        """One pass over registered indexes: refresh backlog gauges and
        compact whichever entry crosses its policy thresholds first."""
        registry = self.service.registry
        for name in registry.names():
            try:
                mi, _version = registry.get_versioned(name)
            except KeyError:
                continue
            if not isinstance(mi, MutableIndex):
                continue  # ShardedIndex etc. — immutable, nothing to fold
            deletes, side = mi.pending_mutations()
            self._publish_backlog(name, mi, deletes, side)
            if self._stop.is_set() or self._paused.is_set():
                return
            if not self._should_trigger(name, mi, deletes, side):
                continue
            self.compact(name)

    def _should_trigger(
        self, name: str, mi: MutableIndex, deletes: int, side: int
    ) -> bool:
        if time.monotonic() < self._cooldown_until.get(name, 0.0):
            return False
        if side >= self.policy.max_side_rows:
            return True
        live_cap = mi.main_size - mi._n_structural
        frac = deletes / live_cap if live_cap else 0.0
        return frac >= self.policy.max_tombstone_frac

    def _publish_backlog(
        self, name: str, mi: MutableIndex, deletes: int, side: int
    ) -> None:
        reg = obs.default_registry()
        reg.gauge(
            "raft_tpu_compaction_backlog",
            help="pending mutations (tombstones + live side rows) awaiting "
            "compaction",
        ).set(deletes + side, index=name)
        trigger = self.policy.max_side_rows + int(
            self.policy.max_tombstone_frac
            * max(mi.main_size - mi._n_structural, 1)
        )
        reg.gauge(
            "raft_tpu_compaction_trigger_threshold",
            help="combined backlog level that triggers a compaction pass",
        ).set(trigger, index=name)

    # -- the pass ------------------------------------------------------------
    def trigger_now(self, name: str) -> Dict[str, object]:
        """Run one synchronous pass for ``name``, ignoring thresholds and
        cooldowns (they exist to pace the automatic loop, not operators)."""
        self._cooldown_until.pop(name, None)
        return self.compact(name)

    @traced("serve.compact")
    def compact(self, name: str) -> Dict[str, object]:
        """One full compaction pass: capture → shadow rebuild (budgeted)
        → ladder warm → quality gate → delta-fold promote."""
        with self._pass_lock:
            self._idle.clear()
            try:
                result = self._compact_inner(name)
            except BudgetExceeded as exc:
                # shadow pagination blew the shared page budget — same
                # abort class as the projected-bytes gate, so operators
                # see one "budget" reason for both enforcement points
                result = self.abort(name, "budget", str(exc))
            except Exception as exc:  # noqa: BLE001 — abort, don't crash
                result = self.abort(name, "error", repr(exc))
            finally:
                self._idle.set()
            self._last_result = result
            return result

    def _compact_inner(self, name: str) -> Dict[str, object]:
        registry = self.service.registry
        mi, version = registry.get_versioned(name)
        if not isinstance(mi, MutableIndex):
            return {"name": name, "status": "noop", "reason": "not mutable"}
        deletes, side = mi.pending_mutations()
        if deletes == 0 and side == 0:
            return {"name": name, "status": "noop", "reason": "clean"}
        t0 = time.perf_counter()
        obs_events.publish(
            "compaction_trigger",
            index=name, version=version, deletes=deletes, side=side,
        )
        self._progress(name, 0.0)

        with mi._lock:
            cap = _capture_locked(mi)
        live_main = int((~cap.deleted).sum())
        side_live_n = int(cap.side_live[: cap.side_count].sum())
        m = live_main + side_live_n
        if m < 2:
            return self.abort(name, "empty", f"only {m} live rows")

        # ---- memory budget: project BEFORE allocating -------------------
        live_bytes = mi.device_bytes()
        budget = int(self.policy.headroom_frac * live_bytes)
        projected = self._project_peak_bytes(mi, m)
        obs.default_registry().gauge(
            "raft_tpu_compaction_peak_bytes",
            help="projected peak host bytes of the last rebuild pass",
        ).set(projected, index=name)
        if projected > budget:
            return self.abort(
                name, "budget",
                f"projected {projected}B > {budget}B "
                f"({self.policy.headroom_frac}x of {live_bytes}B live)",
            )
        # shared enforcement with the page-store ledger: a configured
        # RAFT_TPU_PAGE_HBM_BUDGET_MB bounds the rebuild too — the shadow
        # index's pages will reserve from the same budget at pagination
        page_budget = default_budget()
        if (
            page_budget is not None
            and getattr(mi.index, "paged", None) is not None
            and not page_budget.would_fit(projected)
        ):
            return self.abort(
                name, "budget",
                f"projected {projected}B exceeds the page-budget remainder "
                f"{page_budget.remaining()}B (shared "
                "RAFT_TPU_PAGE_HBM_BUDGET_MB ledger)",
            )

        # ---- gather live rows (chunked main decode + captured side) -----
        rows, gids = self._gather_live(mi, cap, m)
        self._progress(name, 0.4)

        # ---- shadow rebuild with pow2 padding + id map ------------------
        shadow_mi = self._build_shadow(mi, cap, rows, gids)
        self._progress(name, 0.6)

        # ---- bulk delta fold (mutations that landed during the gather) --
        cap = self._fold_delta(mi, cap, shadow_mi)

        # ---- warm the ladder + post-swap mutation variants --------------
        self._warm_shadow(name, mi, shadow_mi)
        self._progress(name, 0.8)

        # ---- quality gate ----------------------------------------------
        ok, serving_recall, shadow_recall = self._gate(mi, shadow_mi, rows, gids)
        if not ok:
            return self.abort(
                name, "gate",
                f"shadow recall {shadow_recall:.4f} < serving "
                f"{serving_recall:.4f} - {self.policy.recall_slack}",
            )

        # ---- promote ----------------------------------------------------
        new_version = self.promote(name, mi, cap, shadow_mi)
        obs_events.publish(
            "compaction_promote",
            index=name, old_version=version, version=new_version,
        )
        self._progress(name, 1.0)
        with self._lock:
            self._compactions += 1
            self._last_abort.pop(name, None)
        obs.default_registry().counter(
            "raft_tpu_compactions_total", help="promoted compaction passes"
        ).inc(index=name)
        elapsed = time.perf_counter() - t0
        result = {
            "name": name,
            "status": "promoted",
            "from_version": version,
            "version": new_version,
            "rows": int(m),
            "folded_deletes": deletes,
            "folded_side_rows": side,
            "serving_recall": serving_recall,
            "shadow_recall": shadow_recall,
            "projected_peak_bytes": projected,
            "budget_bytes": budget,
            "elapsed_s": elapsed,
        }
        _log.info(
            "compacted %r v%d -> v%d: %d rows, %d deletes + %d side rows "
            "folded, recall %.4f -> %.4f, %.2fs",
            name, version, new_version, m, deletes, side,
            serving_recall, shadow_recall, elapsed,
        )
        return result

    # -- rebuild pieces ------------------------------------------------------
    def _project_peak_bytes(self, mi: MutableIndex, m: int) -> int:
        """Peak host bytes of the rebuild, estimated before allocating:
        the dense live-rows buffer, a shadow structure scaled from the
        live one by survivor count, and one decode chunk."""
        rows_bytes = m * mi.dim * 4
        struct_bytes = 0
        for v in vars(mi.index).values():
            nb = getattr(v, "nbytes", None)
            if isinstance(nb, (int, np.integer)):
                struct_bytes += int(nb)
        padded = _next_pow2(m + 1)
        shadow_bytes = int(struct_bytes * (padded / max(mi.main_size, 1)))
        chunk_bytes = min(self.policy.chunk_rows, padded) * mi.dim * 4
        return rows_bytes + shadow_bytes + chunk_bytes

    def _gather_live(
        self, mi: MutableIndex, cap: _Capture, m: int
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Dense (rows, global ids) of every row live at capture time.

        Main rows stream through :meth:`MutableIndex.iter_main_rows` so
        the full structure is never decoded twice; the captured tombstone
        mask (not the live one) keeps the capture consistent."""
        rows = np.empty((m, mi.dim), np.float32)
        gids = np.empty((m,), np.int64)
        off = 0
        for ridx, chunk in mi.iter_main_rows(self.policy.chunk_rows):
            keep = ~cap.deleted[ridx]
            n = int(keep.sum())
            if not n:
                continue
            rows[off:off + n] = chunk[keep]
            kept_rows = ridx[keep]
            if mi._main_ids is None:
                gids[off:off + n] = kept_rows
            else:
                gids[off:off + n] = mi._main_ids[kept_rows]
            off += n
        live_slots = np.flatnonzero(cap.side_live[: cap.side_count])
        n_side = live_slots.size
        if n_side:
            with mi._lock:  # _side_data may be mid-growth; slot rows are stable
                rows[off:off + n_side] = mi._side_data[live_slots]
            gids[off:off + n_side] = cap.side_ids[live_slots]
            off += n_side
        assert off == m, (off, m)
        return rows, gids

    def _build_shadow(
        self, mi: MutableIndex, cap: _Capture,
        rows: np.ndarray, gids: np.ndarray,
    ) -> MutableIndex:
        """Rebuild the main structure from the live rows, padded to a
        power-of-two row count with permanently-tombstoned sentinels.

        Padding keeps consecutive compactions on the same array shapes
        (executables key on shapes) and guarantees the tombstone filter
        is always present, so post-swap deletes reuse the warmed
        tombstoned-search variant instead of compiling a new one."""
        m = rows.shape[0]
        padded = _next_pow2(m + 1)
        pad = padded - m
        if DISTANCE_TYPES[mi.metric] == "inner_product":
            # zero rows score 0 under inner product: never competitive
            # for the tombstone filter to matter, and never a neighbor
            pad_rows = np.zeros((pad, mi.dim), np.float32)
        else:
            # push sentinels far from the data so they are nobody's
            # graph neighbor and cluster into one cold IVF list
            pad_rows = np.full((pad, mi.dim), 1e6, np.float32)
        all_rows = np.concatenate([rows, pad_rows], axis=0)
        all_gids = np.concatenate(
            [gids, np.full((pad,), -1, np.int64)], axis=0
        )
        with trace_range("serve.compact.rebuild"):
            shadow_index = self._rebuild_structure(mi, cap, all_rows)
        src_tiered = getattr(mi.index, "paged", None)
        if src_tiered is not None:
            # a paged source promotes to a paged shadow at the same page
            # size; BudgetExceeded here surfaces as a "budget" abort
            from raft_tpu.store import paginate_index

            paginate_index(
                shadow_index,
                page_rows=int(src_tiered.store.page_rows),
                name=f"shadow:{mi.kind}",
            )
        shadow = MutableIndex(
            shadow_index,
            kind=mi.kind,
            search_params=mi.search_params,
            main_ids=all_gids,
        )
        with shadow._lock:
            shadow._deleted[m:] = True
            shadow._n_deleted = pad
            shadow._n_structural = pad
            # padding ids are -1; fresh ids continue the source's sequence
            shadow._next_id = max(shadow._next_id, mi._next_id)
            shadow._refresh_snapshot_locked()
        return shadow

    def _rebuild_structure(
        self, mi: MutableIndex, cap: _Capture, all_rows: np.ndarray
    ):
        from raft_tpu.neighbors import brute_force

        n = all_rows.shape[0]
        ids = np.arange(n, dtype=np.int32)
        if mi.kind == "brute_force":
            return brute_force.build(all_rows, metric=mi.metric)
        if mi.kind == "ivf_flat":
            from raft_tpu.neighbors import ivf_flat
            import jax.numpy as jnp

            old = mi.index
            L = old.centers.shape[0]
            # empty clone reusing the trained centers: extend takes the
            # streamed initial-fill repack (re-clusters every list)
            empty = ivf_flat.Index(
                old.metric, old.centers,
                jnp.zeros((L, 8, mi.dim), old.list_data.dtype),
                jnp.full((L, 8), -1, jnp.int32),
                jnp.zeros((L,), jnp.int32),
                jnp.full((L, 8), jnp.inf, jnp.float32),
                headroom=old.headroom,
            )
            return ivf_flat.extend(empty, all_rows, ids)
        if mi.kind == "ivf_pq":
            from raft_tpu.neighbors import ivf_pq
            import jax.numpy as jnp

            old = mi.index
            L = old.centers.shape[0]
            empty = ivf_pq.Index(
                old.metric, old.codebook_kind, old.pq_bits,
                old.centers, old.centers_rot, old.rotation, old.codebook,
                np.zeros((L, 8, old.pq_dim), np.uint8),
                jnp.full((L, 8), -1, jnp.int32),
                jnp.zeros((L,), jnp.int32),
                jnp.zeros((L, 8, old.rot_dim), old.list_data.dtype),
                jnp.zeros((L, 8), jnp.float32),
                headroom=old.headroom,
            )
            return ivf_pq.extend(empty, all_rows, ids)
        if mi.kind == "cagra":
            return self._relink_cagra(mi, cap, all_rows)
        raise ValueError(f"unsupported kind {mi.kind!r}")

    def _relink_cagra(
        self, mi: MutableIndex, cap: _Capture, all_rows: np.ndarray
    ):
        """Re-link the CAGRA graph instead of rebuilding it from scratch:
        surviving rows keep their (remapped) neighbor lists; only nodes
        touching dead neighbors, plus the new side/padding rows, get
        fresh exact neighborhoods — then reverse edges make the new rows
        reachable from the survivors."""
        from raft_tpu.neighbors import brute_force, cagra

        old = mi.index
        n_new = all_rows.shape[0]
        old_graph = np.asarray(old.graph)
        degree = min(old_graph.shape[1], n_new - 1)
        # all_rows is laid out [surviving main (row order) | side | pad],
        # matching the gather, so the captured mask names the survivors
        surv_old = np.flatnonzero(~cap.deleted)
        remap = np.full((old_graph.shape[0],), -1, np.int64)
        remap[surv_old] = np.arange(surv_old.size)
        graph = np.full((n_new, degree), -1, np.int64)
        graph[: surv_old.size] = remap[old_graph[surv_old][:, :degree]]
        # affected = survivors referencing dead neighbors + every new row
        affected = np.flatnonzero((graph == -1).any(axis=1))
        if affected.size:
            chunk = max(1, self.policy.chunk_rows // max(degree + 1, 1))
            for s in range(0, affected.size, chunk):
                idx = affected[s : s + chunk]
                _d, nb = brute_force.knn(
                    all_rows, all_rows[idx], degree + 1, metric=mi.metric
                )
                nb = np.asarray(nb, np.int64)
                # drop self-edges, keep the best `degree` others
                rows_nb = np.empty((idx.size, degree), np.int64)
                for j, node in enumerate(idx):
                    cand = nb[j][nb[j] != node][:degree]
                    if cand.size < degree:  # duplicates collapsed the list
                        cand = np.resize(cand, degree)
                    rows_nb[j] = cand
                graph[idx] = rows_nb
        # reverse edges: each brand-new node replaces the worst slot of
        # its first few neighbors, so beam searches seeded on survivors
        # can reach it
        n_surv = surv_old.size
        new_nodes = np.arange(n_surv, n_new)
        slot = {}
        for node in new_nodes:
            for v in graph[node][: max(1, degree // 4)]:
                v = int(v)
                if v == node or v < 0:
                    continue
                s = slot.get(v, 0)
                if s >= max(1, degree // 2):
                    continue
                graph[v, degree - 1 - s] = node
                slot[v] = s + 1
        return cagra.from_graph(mi.metric, all_rows, graph.astype(np.int32))

    def _fold_delta(
        self, mi: MutableIndex, cap: _Capture, shadow: MutableIndex
    ) -> _Capture:
        """Replay mutations that landed on ``mi`` after ``cap`` into the
        shadow; returns the refreshed capture so the fold is incremental
        (promote runs it once more, small, under the source's lock)."""
        with mi._lock:
            now = _capture_locked(mi)
            # side rows appended after the capture (copy under the lock —
            # the buffer may grow concurrently otherwise)
            new_slots = np.arange(cap.side_count, now.side_count)
            new_rows = mi._side_data[new_slots].copy() if new_slots.size else None
        self._apply_delta(mi, cap, now, new_slots, new_rows, shadow)
        return now

    def _fold_delta_locked(
        self, mi: MutableIndex, cap: _Capture, shadow: MutableIndex
    ) -> None:
        """Final fold, caller holds ``mi._lock`` (nothing can race)."""
        now = _capture_locked(mi)
        new_slots = np.arange(cap.side_count, now.side_count)
        new_rows = mi._side_data[new_slots] if new_slots.size else None
        self._apply_delta(mi, cap, now, new_slots, new_rows, shadow)

    def _apply_delta(self, mi, cap, now, new_slots, new_rows, shadow) -> None:
        # 1. main rows tombstoned since capture -> delete their global ids
        newly_dead = now.deleted & ~cap.deleted
        if newly_dead.any():
            dead_rows = np.flatnonzero(newly_dead)
            if mi._main_ids is None:
                dead_ids = dead_rows
            else:
                dead_ids = mi._main_ids[dead_rows]
            shadow.delete(dead_ids)
        # 2. captured-live side rows killed since capture
        was_live = cap.side_live[: cap.side_count]
        still = now.side_live[: cap.side_count]
        died = was_live & ~still
        if died.any():
            shadow.delete(cap.side_ids[: cap.side_count][died])
        # 3. side rows appended since capture, replayed in slot order so
        # repeated upserts of one id resolve to the latest row
        for i, slot in enumerate(new_slots):
            if not now.side_live[slot]:
                continue  # upserted then deleted during the rebuild
            shadow.upsert(new_rows[i][None], ids=[int(now.side_ids[slot])])

    def _warm_shadow(
        self, name: str, mi: MutableIndex, shadow: MutableIndex
    ) -> None:
        """Compile every executable the post-swap hot path can need, on
        THIS thread: the service's bucket ladder against the shadow's
        current state, the tombstones-only variant, and each side-buffer
        capacity tier up to the policy trigger — so neither the swap nor
        the next mutations cause a hot-path compile."""
        try:
            batcher = self.service._batcher(name)
            buckets = list(batcher.buckets())
        except KeyError:
            buckets = [1]
        k = self.service._ks.get(name, self.service.k)
        dummy = {
            b: np.zeros((b, shadow.dim), np.float32) for b in buckets
        }

        def ladder(target: MutableIndex) -> None:
            for b in buckets:
                jax.block_until_ready(target.search(dummy[b], k))

        with trace_range("serve.compact.warm"):
            # the exact state that will serve right after the swap
            ladder(shadow)
            # mutation variants: a throwaway wrapper around the SAME built
            # structure (no copy) walks the side-capacity tiers; compiles
            # key on shapes, so the serving shadow reuses them later
            warm = MutableIndex(
                shadow.index, kind=shadow.kind,
                search_params=shadow.search_params,
                main_ids=shadow._main_ids,
            )
            with warm._lock:
                warm._deleted[:] = shadow._deleted
                warm._n_deleted = shadow._n_deleted
                warm._refresh_snapshot_locked()
            ladder(warm)  # tombstones-only (post-swap, side folded away)
            cap_ceiling = _next_pow2(max(8, self.policy.max_side_rows))
            rng = np.random.default_rng(self.policy.seed)
            cap_now = warm._side_data.shape[0]
            while cap_now < cap_ceiling:
                grow_to = max(8, cap_now * 2)
                add = grow_to - warm._side_count
                warm.upsert(
                    rng.random((add, warm.dim)).astype(np.float32)
                )
                cap_now = warm._side_data.shape[0]
                ladder(warm)

    def _gate(
        self, mi: MutableIndex, shadow: MutableIndex,
        rows: np.ndarray, gids: np.ndarray,
    ) -> Tuple[bool, float, float]:
        """Differential recall gate on a held-back sample of live rows:
        the shadow must not trail the serving index by more than
        ``recall_slack`` against an exact oracle over the captured rows."""
        from raft_tpu.neighbors import brute_force

        pol = self.policy
        nq = min(pol.gate_queries, rows.shape[0])
        if nq == 0:
            return True, 1.0, 1.0
        rng = np.random.default_rng(pol.seed + mi.generation)
        pick = rng.choice(rows.shape[0], size=nq, replace=False)
        scale = float(np.abs(rows).mean()) or 1.0
        queries = rows[pick] + rng.standard_normal(
            (nq, rows.shape[1])
        ).astype(np.float32) * 0.01 * scale
        k = min(pol.gate_k, rows.shape[0])
        with trace_range("serve.compact.gate"):
            _d, oracle_rows = brute_force.knn(
                rows, queries, k, metric=mi.metric
            )
            oracle_ids = gids[np.asarray(oracle_rows)]
            _d, serving_ids = mi.search(queries, k)
            _d, shadow_ids = shadow.search(queries, k)
        serving = recall_at_k(np.asarray(serving_ids), oracle_ids)
        shadowr = recall_at_k(np.asarray(shadow_ids), oracle_ids)
        ok = shadowr + pol.recall_slack >= serving
        return ok, float(serving), float(shadowr)

    @traced("serve.compact.promote")
    def promote(
        self, name: str, mi: MutableIndex, cap: _Capture,
        shadow: MutableIndex,
    ) -> int:
        """Atomic cutover: final delta fold + registry hot-swap + retire
        the old index, all while holding its mutation lock — a writer
        either lands before the fold (and is folded) or after the swap
        (and is forwarded to the successor).  Readers are untouched: the
        swap is a tuple replacement, atomic at batch granularity."""
        with mi._lock:
            self._fold_delta_locked(mi, cap, shadow)
            version = self.service.registry.swap(name, shadow)
            mi._retired_to = shadow
        return version

    @traced("serve.compact.rebuild_sharded")
    def rebuild_sharded(
        self, name: str, comms=None, *, n_devices: Optional[int] = None,
        index_params=None, search_params=None,
        reduce_dtype: Optional[str] = None,
    ) -> Dict[str, object]:
        """Distributed full rebuild: retrain ``name``'s live rows into a
        fresh :class:`~raft_tpu.serve.shard.ShardedIndex` over the mesh
        (:func:`raft_tpu.serve.build.build_sharded`) and hot-swap it in.

        This is the capacity escape hatch the in-place compaction pass
        cannot offer: when the live set has outgrown a single-chip shadow
        rebuild, the training runs sharded (every Lloyd/codebook/kNN leg
        on the mesh) and the result lands already partitioned.  The
        served id space becomes dense row positions ``0..m-1`` (same
        contract as ``ShardedIndex.from_index`` after a compaction); the
        returned ``ids`` array maps new position → old global id.
        ``index_params`` defaults to the source's metric and (for IVF
        kinds) its current ``n_lists``.
        """
        from raft_tpu.serve.build import build_sharded

        mi = self.service.registry.get(name)
        if not isinstance(mi, MutableIndex):
            return {
                "name": name, "status": "noop",
                "reason": f"not mutable ({type(mi).__name__})",
            }
        with self._pass_lock:
            with mi._lock:
                cap = _capture_locked(mi)
            live_main = int((~cap.deleted).sum())
            side_live_n = int(cap.side_live[: cap.side_count].sum())
            m = live_main + side_live_n
            if m < 2:
                return self.abort(name, "empty", f"only {m} live rows")
            rows, gids = self._gather_live(mi, cap, m)
            if index_params is None:
                index_params = self._default_build_params(mi)
            if search_params is None:
                search_params = mi.search_params
            sharded = build_sharded(
                mi.kind, rows, comms, n_devices=n_devices,
                index_params=index_params, search_params=search_params,
                metric=mi.metric, reduce_dtype=reduce_dtype, label=name,
            )
            with mi._lock:
                version = self.service.registry.swap(name, sharded)
                # retire the writer: contains() keeps answering through
                # the successor, while forwarded upsert/delete hit
                # ShardedIndex's loud NotImplementedError instead of
                # silently landing on a dead index
                mi._retired_to = sharded
        obs_events.publish(
            "registry_swap", index=name, version=version,
            reason="sharded rebuild",
        )
        return {
            "name": name, "status": "promoted", "rows": m,
            "shards": sharded.n_shards, "version": version, "ids": gids,
        }

    def _default_build_params(self, mi: MutableIndex):
        """Backend IndexParams mirroring the source's metric/list count."""
        if mi.kind == "brute_force":
            return None
        if mi.kind == "ivf_flat":
            from raft_tpu.neighbors import ivf_flat

            return ivf_flat.IndexParams(
                n_lists=int(mi.index.n_lists), metric=mi.metric,
            )
        if mi.kind == "ivf_pq":
            from raft_tpu.neighbors import ivf_pq

            old = mi.index
            return ivf_pq.IndexParams(
                n_lists=int(old.n_lists), metric=mi.metric,
                pq_bits=int(old.pq_bits), pq_dim=int(old.pq_dim),
                codebook_kind=old.codebook_kind,
            )
        from raft_tpu.neighbors import cagra

        return cagra.IndexParams(metric=mi.metric)

    @traced("serve.compact.abort")
    def abort(self, name: str, reason: str, detail: str = "") -> Dict[str, object]:
        """Record a failed/refused pass: log, gauge, cooldown, re-arm."""
        entry = {
            "name": name,
            "status": "aborted",
            "reason": reason,
            "detail": detail,
            "at": time.time(),
        }
        with self._lock:
            self._aborts += 1
            self._last_abort[name] = entry
            self._cooldown_until[name] = (
                time.monotonic() + self.policy.cooldown_s
            )
        obs.default_registry().counter(
            "raft_tpu_compaction_aborts_total",
            help="compaction passes aborted (gate/budget/error)",
        ).inc(index=name, reason=reason)
        # the abort→DEGRADED wiring rides the bus too: healthz folds
        # stats()["last_abort"] into its verdict, and this event opens /
        # annotates the incident timeline alongside it
        obs_events.publish(
            "compaction_abort", f"compaction_abort_{reason}",
            index=name, cause=reason, detail=detail,
        )
        _log.warning("compaction of %r aborted (%s): %s", name, reason, detail)
        return entry

    def _progress(self, name: str, frac: float) -> None:
        obs.default_registry().gauge(
            "raft_tpu_compaction_progress",
            help="phase progress of the current/last pass (0..1)",
        ).set(frac, index=name)

    # -- introspection -------------------------------------------------------
    def stats(self, name: str) -> Dict[str, object]:
        """Per-index compaction state for healthz folding."""
        registry = self.service.registry
        backlog = None
        trigger = None
        try:
            mi, _v = registry.get_versioned(name)
            if isinstance(mi, MutableIndex):
                deletes, side = mi.pending_mutations()
                backlog = deletes + side
                trigger = self.policy.max_side_rows + int(
                    self.policy.max_tombstone_frac
                    * max(mi.main_size - mi._n_structural, 1)
                )
        except KeyError:
            pass
        with self._lock:
            last_abort = self._last_abort.get(name)
        return {
            "backlog": backlog,
            "trigger": trigger,
            "last_abort": last_abort,
        }

    def snapshot(self) -> Dict[str, object]:
        """The obs provider section (``obs.snapshot()['compaction']``)."""
        with self._lock:
            out: Dict[str, object] = {
                "compactions": self._compactions,
                "aborts": self._aborts,
                "paused": self._paused.is_set(),
                "running": not self._idle.is_set(),
                "worker_alive": (
                    self._worker is not None and self._worker.is_alive()
                ),
                "last_result": self._last_result,
                "last_aborts": dict(self._last_abort),
            }
        pol = self.policy
        out["policy"] = {
            "max_side_rows": pol.max_side_rows,
            "max_tombstone_frac": pol.max_tombstone_frac,
            "interval_s": pol.interval_s,
            "cooldown_s": pol.cooldown_s,
            "headroom_frac": pol.headroom_frac,
            "chunk_rows": pol.chunk_rows,
            "gate_queries": pol.gate_queries,
            "recall_slack": pol.recall_slack,
        }
        return out
