"""Dynamic micro-batcher: coalesce single-query requests into padded,
power-of-two-bucketed batches.

The serving problem on TPU is that ``jit`` specializes on shapes: a stream
of requests with 1, 3, 7, 2, ... queries would trigger a fresh XLA compile
per novel shape.  The batcher fixes the shape universe up front — batches
are always padded to a bucket from the ladder ``min_bucket, 2*min_bucket,
..., max_batch`` — and :meth:`MicroBatcher.warmup` runs a dummy batch
through every bucket so each executable exists *before* traffic arrives.
After warmup the hot path performs zero compiles, which
:class:`~raft_tpu.serve.metrics.ServingMetrics` verifies by bracketing
every dispatch with :func:`~raft_tpu.serve.metrics.compile_count`.

Coalescing policy: the worker thread takes whatever is queued the moment
it wakes; if the pending rows are below ``max_batch`` it waits up to
``max_delay_ms`` (measured from the oldest queued request) for stragglers,
then dispatches.  Latency recorded per request is submit→complete, i.e.
queue wait is included — that is the number a caller actually experiences.

Pipelined dispatch (``pipeline_depth`` > 1): the dispatch path splits
into three stages so the host and device overlap instead of taking
turns.  (1) The worker pads the batch into a reusable per-bucket staging
buffer and *enqueues* the warmed executable without blocking on the
result; (2) a semaphore bounds the in-flight window to ``pipeline_depth``
device batches, so live device memory stays bounded and the host stalls
(``inflight_wait`` stage) instead of overrunning the device; (3) a
completion thread blocks on the *oldest* in-flight batch, copies results
out, resolves futures in submission order, and runs the observer /
metrics / slow-log off the dispatch path.  ``pipeline_depth=1`` keeps
the original fully-serial dispatch, byte for byte.  Steady-state QPS at
depth > 1 is bounded by the *max* of the host and device stage times
rather than their sum (``bench.py serve`` measures the A/B).

Request identity: every :meth:`MicroBatcher.submit` assigns a
process-wide monotonically increasing request id (returned on the future
as ``fut.request_id``).  Both dispatch paths feed each completed or
failed batch — member request ids plus per-request timelines
reconstructed from the stage stamps above — to the always-on
:mod:`raft_tpu.obs.flight` recorder, and auto-dump it on a hot-path
recompile or batch exception.  The only hot-path additions are the
submit-time id assignment and one dict build per *batch* after futures
resolve.

Staging-buffer safety: completion is strictly FIFO and the semaphore
caps in-flight batches at ``pipeline_depth``, so by the time a bucket's
ring slot (one of ``pipeline_depth`` per bucket) comes around again its
previous occupant has fully completed — including the observer call,
which sees a *copy* of the staged rows precisely because the auditor
holds samples past the batch's lifetime.

Ragged mode (``ragged=`` a :class:`raft_tpu.serve.ragged.RaggedSpec`):
heterogeneous requests — each with its own top-``k`` and registered
filter id — pack into ONE dispatch per capacity bucket.  ``k`` and the
filter become descriptor *data* (``row_k``/``row_fid`` int32 columns
alongside the padded queries) instead of executable shapes, collapsing
the per-(bucket × k × filter) variant lattice the classic mode would
need.  With the pipeline enabled, admission also turns *continuous*:
the worker claims the in-flight window slot before cutting the batch,
so the forming batch keeps admitting submissions for exactly as long as
the device window is full (see :meth:`MicroBatcher._worker`).
"""

from __future__ import annotations

import itertools
import queue as queue_mod
import threading
import time
from collections import deque
from contextlib import nullcontext
from concurrent.futures import Future, TimeoutError as _FutureTimeout
from typing import Callable, Deque, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from raft_tpu.core import env as _env
from raft_tpu.core.trace import trace_range
from raft_tpu import kernels as _kernels
from raft_tpu.kernels.toolkit import next_pow2
from raft_tpu.obs import events as obs_events
from raft_tpu.obs import explain as obs_explain
from raft_tpu.obs import flight, slowlog, spans
from raft_tpu.obs import perf as obs_perf
from raft_tpu.serve.metrics import ServingMetrics, compile_count
from raft_tpu.serve.overload import expire_deadlines, validate_priority

# search_fn: (queries [b, dim] float32) -> (distances [b, k], ids [b, k]).
# In ragged mode the signature grows two descriptor columns:
# (queries [b, dim], row_k [b] int32, row_fid [b] int32) -> same shapes,
# always at the spec's k_max — per-request k is data, not shape.
SearchFn = Callable[[jax.Array], Tuple[jax.Array, jax.Array]]

# observer: (queries [n, dim], distances [n, k], ids [n, k]) -> None, called
# with the REAL (unpadded) rows after each dispatched batch resolves.  Must
# be non-blocking — the quality auditor's sample-and-enqueue qualifies.
Observer = Callable[[np.ndarray, np.ndarray, np.ndarray], None]


# canonical pow2 helper lives in kernels.toolkit; the old private name is
# kept because the ladder math below reads naturally with it
_next_pow2 = next_pow2


class _Request:
    __slots__ = ("rows", "future", "t_submit", "req_id", "k", "fid",
                 "priority", "deadline")

    def __init__(self, rows: np.ndarray, future: Future, t_submit: float,
                 req_id: int, k: int = 0, fid: int = 0,
                 priority: int = 1, deadline: Optional[float] = None):
        self.rows = rows
        self.future = future
        self.t_submit = t_submit
        self.req_id = req_id
        self.k = k        # ragged mode: this request's top-k (<= k_max)
        self.fid = fid    # ragged mode: registered filter id (0 = all-pass)
        self.priority = priority    # 0 interactive … 3 background
        self.deadline = deadline    # absolute perf_counter s, or None


class _InFlight:
    """One dispatched-but-not-completed batch, handed from the dispatch
    thread to the completion thread in submission order."""

    __slots__ = (
        "batch", "padded", "n", "bucket", "queue_waits", "t_pad",
        "inflight_wait", "t_dispatch", "t_enqueued", "dist", "ids",
        "compiles", "sp", "done", "seq", "t_pickup", "hedged",
        "kernel_path", "admit_level", "page", "dispatch_info",
    )

    def __init__(self, batch: List[_Request]):
        self.batch = batch
        self.done = threading.Event()
        self.hedged = False
        self.kernel_path = "unknown"
        self.admit_level = 0
        self.page = None           # explain: page-cache stats stamp
        self.dispatch_info = None  # explain: ragged dispatch params stamp


class MicroBatcher:
    """Coalesces query requests into pow2-padded batches for a search fn.

    Parameters
    ----------
    search_fn:
        Callable mapping a ``[b, dim]`` float32 query batch to
        ``(distances [b, k], ids [b, k])``.  It is resolved per *dispatch*,
        so a registry hot-swap behind the callable takes effect without
        restarting the batcher (and without recompiles, as shapes are
        unchanged).
    dim:
        Query dimensionality; padded rows are zeros of this width.
    min_bucket / max_batch:
        Bucket ladder bounds; both are rounded up to powers of two.
    max_delay_ms:
        Max time a request may wait for coalescing before dispatch.
    metrics:
        Optional shared :class:`ServingMetrics`; a private one is created
        otherwise.
    start:
        When True (default) the worker thread starts immediately.  Tests
        use ``start=False`` + :meth:`flush` for deterministic batching.
    observer:
        Optional post-dispatch hook receiving the real rows of every
        resolved batch ``(queries, distances, ids)`` — the quality
        auditor's shadow-sampling entry.  Exceptions are swallowed and
        the call sits after future resolution, so a misbehaving observer
        can delay the *next* batch but never fail or block a result.
    cost_accounting:
        When True (default; env ``RAFT_TPU_COST_ACCOUNTING=0`` disables)
        :meth:`warmup` additionally AOT-compiles each bucket's executable
        for XLA cost/memory analysis and publishes ``raft_tpu_xla_*``
        gauges.  Purely best-effort: backends that cannot answer leave
        the gauges absent.
    pipeline_depth:
        Bound on device batches in flight (default from
        ``RAFT_TPU_PIPELINE_DEPTH``, else 2).  ``1`` reproduces the
        original serial dispatch exactly: pad, enqueue, block, resolve —
        all on the dispatching thread.  At depth > 1 the host pads and
        enqueues the next batch while up to ``pipeline_depth`` earlier
        batches run on the device; a completion thread resolves futures
        in submission order.  Memory cost: ``pipeline_depth`` staging
        buffers per touched bucket plus the live device buffers of the
        in-flight batches.
    ragged:
        Optional :class:`raft_tpu.serve.ragged.RaggedSpec`.  When set,
        ``search_fn`` takes ``(queries, row_k, row_fid)`` and always
        computes ``k_max`` result columns; :meth:`submit` accepts
        per-request ``k``/``fid`` and each future is sliced to its own
        ``[:k]`` after copy-out.  One executable per capacity bucket —
        the (bucket × k × filter) variant lattice collapses.  At
        ``pipeline_depth`` > 1 admission is continuous (see the worker).
    admission / degraded / hedger:
        Optional overload actuators (:mod:`raft_tpu.serve.overload`).
        ``admission`` (an :class:`~raft_tpu.serve.overload.
        AdmissionController`) runs at every batch cut — it expires
        past-deadline requests and sheds low-priority work under
        pressure, resolving their futures with typed errors before the
        batch reaches the device; its verdict also feeds ``degraded``
        (a :class:`~raft_tpu.serve.overload.DegradedModeManager`),
        whose hysteretic effort level the search fn may consult.
        Without a controller, deadline expiry still runs at every cut.
        ``hedger`` (a :class:`~raft_tpu.serve.overload.
        HedgedDispatcher`) reroutes batches carrying priority-0 traffic
        through a raced two-member dispatch; warmup warms every member.
    perf_meta:
        Optional zero-argument callable returning ``(backend, version)``
        strings for the perf-ledger executable key — the service points
        this at its registry so every dispatch is attributed to the
        index *kind and version* actually serving it.  Standalone
        batchers default to ``("unknown", "0")``.  The ledger itself
        (:mod:`raft_tpu.obs.perf`) rides the stage stamps this class
        already takes — ``RAFT_TPU_PERF_LEDGER=0`` disables it, sampled
        once at construction so the hot path never re-reads env.
    """

    def __init__(
        self,
        search_fn: SearchFn,
        dim: int,
        *,
        min_bucket: int = 1,
        max_batch: int = 64,
        max_delay_ms: float = 2.0,
        metrics: Optional[ServingMetrics] = None,
        start: bool = True,
        observer: Optional[Observer] = None,
        cost_accounting: Optional[bool] = None,
        pipeline_depth: Optional[int] = None,
        ragged=None,
        admission=None,
        degraded=None,
        effort=None,
        hedger=None,
        perf_meta: Optional[Callable[[], Tuple[str, str]]] = None,
    ):
        if dim <= 0:
            raise ValueError(f"dim must be positive, got {dim}")
        if min_bucket <= 0 or max_batch <= 0:
            raise ValueError("min_bucket and max_batch must be positive")
        min_bucket = _next_pow2(min_bucket)
        max_batch = _next_pow2(max_batch)
        if min_bucket > max_batch:
            raise ValueError(
                f"min_bucket={min_bucket} exceeds max_batch={max_batch}"
            )
        self._search_fn = search_fn
        self.dim = int(dim)
        self.min_bucket = min_bucket
        self.max_batch = max_batch
        self.max_delay_s = float(max_delay_ms) * 1e-3
        self.metrics = metrics if metrics is not None else ServingMetrics()
        self.observer = observer
        if cost_accounting is None:
            cost_accounting = _env.env_bool("RAFT_TPU_COST_ACCOUNTING", True)
        self.cost_accounting = bool(cost_accounting)
        if pipeline_depth is None:
            pipeline_depth = _env.env_int("RAFT_TPU_PIPELINE_DEPTH", 2)
        if pipeline_depth < 1:
            raise ValueError(
                f"pipeline_depth must be >= 1, got {pipeline_depth}"
            )
        self.pipeline_depth = int(pipeline_depth)
        # ragged mode (a serve.ragged.RaggedSpec, or None for classic):
        # search_fn takes (queries, row_k, row_fid) and always computes
        # k_max columns; per-request k/fid ride as data.  Admission turns
        # continuous at depth > 1: the worker claims the in-flight window
        # slot BEFORE cutting the batch, so requests keep packing into the
        # forming batch while the device window is full.
        self.ragged = ragged
        if ragged is not None and ragged.k_max < 1:
            raise ValueError(f"ragged k_max must be >= 1, got {ragged.k_max}")
        # overload actuators (serve.overload); admission inherits this
        # batcher's metrics so shed/expired requests land in the same
        # error counters the SLO availability spec reads
        self.admission = admission
        self.degraded = degraded
        # optional serve.effort.EffortArbiter: the single effort writer
        # (overload ladder clamp + autotuner walk) — when present its
        # ladder supersedes degraded's for warmup, since the search fn
        # consults the arbiter, not the manager, for effective params
        self.effort = effort
        self.hedger = hedger
        if admission is not None and admission.metrics is None:
            admission.metrics = self.metrics
        if hedger is not None and hedger.metrics is None:
            hedger.metrics = self.metrics
        if hedger is not None and hedger.on_interval is None:
            # mirrored hedge members report their device windows here so
            # device_busy_s() merges the pair instead of double-counting
            hedger.on_interval = self._note_device_interval
        # -- measured perf ledger (obs.perf) ---------------------------------
        # enabled() is sampled ONCE: the hot path holds either a ledger
        # reference or None, never an env read
        self._perf = obs_perf.default_ledger() if obs_perf.enabled() else None
        self._perf_meta = (
            perf_meta if perf_meta is not None else (lambda: ("unknown", "0"))
        )
        # attribution fallback when the search fn did not stamp a routing
        # choice this dispatch (e.g. hedged members run on pool threads,
        # whose thread-local stamps this thread cannot see)
        self._kpath_default = "pallas" if _kernels.use_pallas() else "xla"
        self._last_kernel_path = self._kpath_default
        self._last_hedged = False
        # explain stamps consumed per dispatch (written/read under
        # _dispatch_lock, like _last_kernel_path) + the last admission
        # verdict level (written by _admit on the same thread that then
        # dispatches the batch)
        self._last_page_stats = None
        self._last_dispatch_info = None
        self._last_admit_level = 0

        self._cond = threading.Condition()
        self._queue: Deque[_Request] = deque()
        self._stopping = False
        # one dispatch *stage* at a time, shared by worker thread and
        # flush(); at depth 1 it additionally covers the device wait (the
        # original serial behavior)
        self._dispatch_lock = threading.Lock()
        self._warm = False
        self._thread: Optional[threading.Thread] = None
        # -- pipelined dispatch state (idle at pipeline_depth == 1) ----------
        self._inflight_sem = threading.Semaphore(self.pipeline_depth)
        self._inflight_q: "queue_mod.Queue[Optional[_InFlight]]" = (
            queue_mod.Queue()
        )
        self._completion_thread: Optional[threading.Thread] = None
        self._inflight_lock = threading.Lock()
        self._inflight = 0
        # per-bucket ring of pipeline_depth reusable staging buffers
        self._staging: Dict[int, List[Optional[np.ndarray]]] = {}
        self._staging_idx: Dict[int, int] = {}
        # union of [enqueue, ready] intervals: device-busy estimate for the
        # bench's idle-fraction figure (completion thread only)
        self._busy_s = 0.0
        self._busy_until = 0.0
        # flight-recorder batch sequence (per batcher; request ids are
        # process-wide, see obs.flight.next_request_id)
        self._batch_seq = itertools.count(1)
        self.metrics.record_pipeline(self.pipeline_depth, 0)
        if start:
            self.start()

    # -- bucket ladder -------------------------------------------------------
    def buckets(self) -> List[int]:
        """The full bucket ladder, ascending."""
        out, b = [], self.min_bucket
        while b < self.max_batch:
            out.append(b)
            b *= 2
        out.append(self.max_batch)
        return out

    def bucket_for(self, n_rows: int) -> int:
        """Smallest bucket holding ``n_rows`` (clamped into the ladder)."""
        return min(self.max_batch, max(self.min_bucket, _next_pow2(n_rows)))

    # -- lifecycle -----------------------------------------------------------
    def warmup(self) -> int:
        """Compile every bucket's executable up front; returns compile count.

        Runs a zero-filled batch through each bucket in the ladder and
        blocks on the result.  Compiles spent here are booked as
        ``warmup_compiles`` and the hot-path recompile counter is reset, so
        any later non-zero ``recompiles`` is a genuine shape leak.

        With ``cost_accounting`` each bucket's executable is additionally
        AOT-compiled for :mod:`raft_tpu.obs.cost` analysis — FLOPs, bytes
        accessed, peak memory and roofline utilization land as
        ``raft_tpu_xla_*`` gauges labeled ``index=<name>,bucket=<b>``.
        The extra compiles happen here, inside warmup, so the hot-path
        zero-recompile contract is untouched.
        """
        total = 0
        # degraded mode changes search params (host Python values the
        # backends trace on), so every level of the ladder gets its own
        # warmup pass — a pressure-driven level flip must never compile
        # on the hot path
        actuator = self.effort if self.effort is not None else self.degraded
        levels = (None,) if actuator is None else actuator.levels()
        with self._dispatch_lock, trace_range("serve.warmup"):
            for level in levels:
                pin = (nullcontext() if level is None
                       else actuator.pinned(level))
                with pin:
                    for b in self.buckets():
                        dummy = np.zeros((b, self.dim), dtype=np.float32)
                        c0 = compile_count(thread=True)
                        # ragged mode warms ONE variant per bucket — k and
                        # filter are data, so the dummy descriptor columns
                        # cover every later (k, fid) mix
                        dist, ids = self._invoke(dummy, [])
                        jax.block_until_ready((dist, ids))
                        if self.hedger is not None:
                            self.hedger.warm(*self._invoke_args(dummy, []))
                        total += compile_count(thread=True) - c0
                        if self.cost_accounting and not level:
                            self._account_bucket_cost(b, dummy)
        self.metrics.record_warmup(total)
        self.metrics.reset_hot_path()
        self._warm = True
        return total

    def _invoke_args(self, padded: np.ndarray, batch: List[_Request]):
        """The search fn's argument tuple for one padded bucket.

        Ragged mode attaches the per-request descriptor columns: each
        request's rows carry its ``(k, fid)``; padding rows run at
        ``k_max`` / filter 0 (all-pass), so the call is the same trace
        for every batch of this bucket.  Classic mode is the original
        single-argument form, byte for byte.
        """
        if self.ragged is None:
            return (jax.numpy.asarray(padded),)
        bucket = padded.shape[0]
        row_k = np.full((bucket,), self.ragged.k_max, np.int32)
        row_fid = np.zeros((bucket,), np.int32)
        off = 0
        for req in batch:
            m = req.rows.shape[0]
            row_k[off : off + m] = req.k
            row_fid[off : off + m] = req.fid
            off += m
        return (
            jax.numpy.asarray(padded),
            jax.numpy.asarray(row_k),
            jax.numpy.asarray(row_fid),
        )

    def _invoke(self, padded: np.ndarray, batch: List[_Request]):
        """Hand one padded bucket to the search fn (or, for batches
        carrying priority-0 traffic with a hedger installed, to the
        raced two-member dispatch).

        Side channel: records whether this dispatch was hedged and which
        ``kernel_path`` the search fn stamped (``kernels.
        stamp_kernel_path`` in the neighbors routing code) on
        ``self._last_hedged`` / ``self._last_kernel_path`` — safe as
        instance state because every call site holds ``_dispatch_lock``.
        """
        args = self._invoke_args(padded, batch)
        hedger = self.hedger
        hedged = hedger is not None and any(r.priority == 0 for r in batch)
        self._last_hedged = hedged
        _kernels.consume_kernel_path()  # drop any stale stamp first
        obs_explain.consume_page_stats()
        obs_explain.consume_dispatch()
        if hedged:
            out = hedger.dispatch(*args)
        else:
            out = self._search_fn(*args)
        self._last_kernel_path = _kernels.consume_kernel_path(
            self._kpath_default
        )
        # explain stamps ride the same thread-local side channel as the
        # kernel-path stamp; empty (None) unless explain collection is on
        self._last_page_stats = obs_explain.consume_page_stats()
        self._last_dispatch_info = obs_explain.consume_dispatch()
        return out

    def _note_device_interval(self, t_start: float, t_end: float) -> None:
        """Merge one device window ``[t_start, t_end]`` into the busy-time
        union.  This is the hedger's ``on_interval`` sink: each member of
        a mirrored hedge pair reports its own window, and the incremental
        union counts their overlap ONCE — so ``device_busy_s()`` stays an
        upper-bounded union instead of double-counting the race."""
        with self._inflight_lock:
            if t_end > self._busy_until:
                self._busy_s += t_end - max(t_start, self._busy_until)
                self._busy_until = t_end

    def _result_view(self, req: _Request, dist: np.ndarray, ids: np.ndarray,
                     off: int):
        """This request's slice of a completed batch's host arrays.

        Ragged mode also slices the column axis down to the request's own
        ``k`` — the executable computed ``k_max`` columns for everyone."""
        m = req.rows.shape[0]
        d, i = dist[off : off + m], ids[off : off + m]
        if self.ragged is not None and req.k < d.shape[1]:
            d, i = d[:, : req.k], i[:, : req.k]
        return d, i

    def _account_bucket_cost(self, bucket: int, dummy: np.ndarray) -> None:
        """Best-effort XLA cost/memory gauges for one bucket's executable."""
        try:
            from raft_tpu.obs import cost as obs_cost

            args = self._invoke_args(dummy, [])
            report = obs_cost.analyze_callable(self._search_fn, *args)
            obs_cost.record_cost(
                report,
                index=self.metrics.name or "default",
                bucket=str(bucket),
            )
            if (
                self._perf is not None
                and report.flops is not None
                and report.bytes_accessed is not None
            ):
                # analytical per-dispatch cost for the ledger's measured
                # roofline: keyed (index, bucket) — shapes are identical
                # across kernel paths and versions
                self._perf.register_cost(
                    self.metrics.name or "default", int(bucket),
                    report.flops, report.bytes_accessed,
                )
        except Exception:  # noqa: BLE001 — accounting must not fail warmup
            pass

    @property
    def warm(self) -> bool:
        """True once :meth:`warmup` has compiled the bucket ladder."""
        return self._warm

    def queue_depth(self) -> int:
        """Rows currently waiting for dispatch (health signal)."""
        with self._cond:
            return sum(r.rows.shape[0] for r in self._queue)

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        with self._cond:
            self._stopping = False
        self._thread = threading.Thread(
            target=self._worker, name="raft-tpu-serve-batcher", daemon=True
        )
        self._thread.start()

    def stop(self, drain: bool = True) -> None:
        """Stop the worker thread; with ``drain`` pending requests complete
        first, otherwise they fail with :class:`RuntimeError`.  Batches
        already in flight complete and resolve their futures either way —
        they were dispatched before the stop, and dropping device results
        on the floor would break the delivered-exactly-once contract."""
        with self._cond:
            self._stopping = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if drain:
            self.flush()
        else:
            with self._cond:
                pending, self._queue = self._queue, deque()
            for req in pending:
                req.future.set_exception(
                    RuntimeError("MicroBatcher stopped before dispatch")
                )
        self._shutdown_completion()
        self.metrics.close()

    def _shutdown_completion(self) -> None:
        """Drain the completion thread: in-flight batches finish, then the
        sentinel stops the loop.  Safe to call with nothing in flight."""
        t = self._completion_thread
        if t is not None and t.is_alive():
            self._inflight_q.put(None)
            t.join()
        self._completion_thread = None

    def __enter__(self) -> "MicroBatcher":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- submission ----------------------------------------------------------
    def submit(self, queries, *, k: Optional[int] = None,
               fid: Optional[int] = None,
               priority: Optional[int] = None,
               deadline_s: Optional[float] = None) -> Future:
        """Enqueue one request of shape ``[dim]`` or ``[m, dim]``.

        Returns a future resolving to ``(distances [m, k], ids [m, k])``
        numpy arrays (the leading axis is squeezed away for 1-D input).
        The future carries the request's process-wide monotonically
        increasing id as ``fut.request_id`` — the handle that links a
        caller's latency to its flight-recorder timeline and histogram
        exemplar.

        Ragged mode only: ``k`` picks this request's top-k (default and
        ceiling: the spec's ``k_max``) and ``fid`` a registered filter id
        (default 0, the all-pass row).  Heterogeneous ``(k, fid)`` mixes
        pack into one batch — they are descriptor data, not shapes.

        Any mode: ``priority`` is the request's class (0=interactive,
        1=standard — the default, 2=batch, 3=background) and
        ``deadline_s`` a server-side budget measured from now.  Both are
        host-side request metadata (no effect on executable shapes).  A
        request whose deadline passes before its batch is cut resolves
        with :class:`~raft_tpu.serve.overload.DeadlineExceeded` instead
        of occupying a device slot; under overload an installed
        :class:`~raft_tpu.serve.overload.AdmissionController` sheds the
        lowest priorities first with the typed
        :class:`~raft_tpu.serve.overload.Shed` error.
        """
        if self.ragged is None:
            if k is not None or fid is not None:
                raise ValueError(
                    "per-request k/fid need ragged mode — construct the "
                    "batcher (or SearchService) with ragged="
                )
            k, fid = 0, 0
        else:
            k = self.ragged.k_max if k is None else int(k)
            if not 1 <= k <= self.ragged.k_max:
                raise ValueError(
                    f"k={k} outside [1, k_max={self.ragged.k_max}]"
                )
            fid = 0 if fid is None else int(fid)
            if fid < 0:
                raise ValueError(f"fid must be >= 0, got {fid}")
        rows = np.asarray(queries, dtype=np.float32)
        squeeze = rows.ndim == 1
        if squeeze:
            rows = rows[None, :]
        if rows.ndim != 2 or rows.shape[1] != self.dim:
            raise ValueError(
                f"expected queries of dim {self.dim}, got shape {rows.shape}"
            )
        if rows.shape[0] > self.max_batch:
            raise ValueError(
                f"request of {rows.shape[0]} rows exceeds max_batch="
                f"{self.max_batch}; split it client-side"
            )
        priority = validate_priority(priority)
        if deadline_s is not None and float(deadline_s) <= 0.0:
            raise ValueError(
                f"deadline_s must be positive, got {deadline_s}"
            )
        t_submit = time.perf_counter()
        deadline = None if deadline_s is None else t_submit + float(deadline_s)
        req_id = flight.next_request_id()
        fut: Future = Future()
        fut.request_id = req_id
        if squeeze:
            inner = fut
            fut = Future()
            fut.request_id = req_id
            inner.add_done_callback(
                lambda f, out=fut: _squeeze_result(f, out)
            )
            req = _Request(rows, inner, t_submit, req_id, k, fid,
                           priority, deadline)
        else:
            req = _Request(rows, fut, t_submit, req_id, k, fid,
                           priority, deadline)
        with self._cond:
            if self._stopping and (
                self._thread is None or not self._thread.is_alive()
            ):
                # no worker; caller is expected to flush() manually
                pass
            self._queue.append(req)
            self._cond.notify()
        return fut

    def search(self, queries, timeout: Optional[float] = None, *,
               k: Optional[int] = None, fid: Optional[int] = None,
               priority: Optional[int] = None,
               deadline_s: Optional[float] = None):
        """Synchronous convenience wrapper around :meth:`submit`.

        ``timeout`` doubles as the server-side deadline when
        ``deadline_s`` is not given: a caller that stops waiting at
        ``timeout`` must not leave its request occupying a batch slot
        and running on device — the expired work is dropped (typed
        :class:`~raft_tpu.serve.overload.DeadlineExceeded`) at the next
        batch cut instead.
        """
        if deadline_s is None and timeout is not None:
            deadline_s = timeout
        fut = self.submit(queries, k=k, fid=fid, priority=priority,
                          deadline_s=deadline_s)
        if self._thread is None or not self._thread.is_alive():
            self.flush()
        try:
            return fut.result(timeout=timeout)
        except _FutureTimeout:
            # py3.10's futures.TimeoutError is not the builtin; normalize
            # so callers catch one type whether the client-side wait or
            # the server-side deadline expiry (DeadlineExceeded, also a
            # TimeoutError) fired first
            raise TimeoutError(
                f"no result within {timeout}s (request still queued or "
                "in flight; its deadline will expire it at the next cut)"
            ) from None

    # -- batching core -------------------------------------------------------
    def flush(self) -> int:
        """Dispatch everything queued right now; returns batches issued.

        Routes through the same path traffic takes: serial dispatch in
        this thread at depth 1, the pipeline at depth > 1 — so a flush
        racing in-flight batches cannot reorder result delivery (the
        completion thread resolves strictly in submission order) and
        never holds ``_dispatch_lock`` across a device wait it did not
        pay for.  Returns only after every batch it dispatched has
        resolved its futures and recorded its metrics."""
        n_batches = 0
        last: Optional[_InFlight] = None
        while True:
            with self._cond:
                if not self._queue:
                    break
                batch = self._take_batch_locked()
            batch = self._admit(batch)
            if not batch:
                continue
            if self.pipeline_depth == 1:
                self._dispatch(batch)
            else:
                rec = self._dispatch_pipelined(batch)
                if rec is not None:
                    last = rec
            n_batches += 1
        if last is not None:
            # FIFO completion: the last record's done event implies every
            # earlier one dispatched here has fully completed too
            last.done.wait()
        return n_batches

    def _take_batch_locked(self) -> List[_Request]:
        """Pop a prefix of the queue totalling at most max_batch rows."""
        taken, rows = [], 0
        while self._queue:
            nxt = self._queue[0]
            if taken and rows + nxt.rows.shape[0] > self.max_batch:
                break
            taken.append(self._queue.popleft())
            rows += nxt.rows.shape[0]
        return taken

    def _coalesce_locked(self) -> List[_Request]:
        """Wait (condition held) for stragglers up to the oldest queued
        request's deadline, then pop a batch; [] if the queue emptied
        under us (a racing flush took everything)."""
        if not self._queue:
            return []
        deadline = self._queue[0].t_submit + self.max_delay_s
        while (
            sum(r.rows.shape[0] for r in self._queue) < self.max_batch
            and not self._stopping
        ):
            remaining = deadline - time.perf_counter()
            if remaining <= 0:
                break
            self._cond.wait(timeout=remaining)
            if not self._queue:
                return []
        if not self._queue:
            return []
        return self._take_batch_locked()

    def _admit(self, batch: List[_Request]) -> List[_Request]:
        """Batch-cut admission: expire deadlines and, with a controller
        installed, shed under pressure.  Runs at every cut site, OUTSIDE
        the queue condition — resolving a rejected future runs its done
        callbacks inline.  Returns the requests that may dispatch."""
        if not batch:
            return batch
        ctrl = self.admission
        index = self.metrics.name or "default"
        if ctrl is None:
            alive = expire_deadlines(
                batch, index=index, metrics=self.metrics,
            )
            self._last_admit_level = 0
            if len(alive) != len(batch) and obs_explain.enabled():
                alive_ids = {id(r) for r in alive}
                obs_explain.observe_admission(
                    index,
                    expired=[r for r in batch if id(r) not in alive_ids],
                )
            return alive
        decision = ctrl.decide(
            batch, queue_rows=self.queue_depth(), max_batch=self.max_batch,
        )
        # recorded where the decision is already made (no re-derivation on
        # the completion path); read by the same thread that dispatches
        self._last_admit_level = decision.level
        if self.degraded is not None:
            self.degraded.step(decision.level > 0)
        if (decision.shed or decision.expired) and obs_explain.enabled():
            # shed / expired requests never reach a batch record — archive
            # their minimal plans here (futures already carry the typed
            # errors; this only observes)
            obs_explain.observe_admission(
                index, shed=decision.shed, expired=decision.expired,
                level=decision.level,
            )
        return list(decision.admitted)

    def _worker(self) -> None:
        # continuous admission (ragged + pipeline): claim the in-flight
        # window slot BEFORE cutting the batch.  While a full window
        # blocks this thread, submit() keeps appending — the eventual
        # batch packs everything that arrived during the stall instead of
        # a fixed pre-window cut, so fill rises (and padding waste falls)
        # exactly when the device is the bottleneck.
        continuous = self.ragged is not None and self.pipeline_depth > 1
        while True:
            with self._cond:
                while not self._queue and not self._stopping:
                    self._cond.wait()
                if self._stopping:
                    return
                if not continuous:
                    # coalescing window: wait for stragglers, bounded by
                    # the oldest request's deadline
                    batch = self._coalesce_locked()
                    if not batch:
                        continue
            if continuous:
                self._inflight_sem.acquire()
                with self._cond:
                    batch = self._coalesce_locked()
                batch = self._admit(batch)
                if not batch:
                    self._inflight_sem.release()
                    continue
                self._dispatch_pipelined(batch, sem_held=True)
            else:
                batch = self._admit(batch)
                if not batch:
                    continue
                if self.pipeline_depth > 1:
                    self._dispatch_pipelined(batch)
                else:
                    with self._dispatch_lock:
                        self._dispatch_locked(batch)

    def _dispatch(self, batch: List[_Request]) -> None:
        with self._dispatch_lock:
            self._dispatch_locked(batch)

    def _record_flight(
        self,
        *,
        seq: int,
        batch: List[_Request],
        n: int,
        bucket: int,
        compiles: int,
        t_pickup: float,
        t_done: float,
        stages_s: Dict[str, float],
        waits_s: Dict[str, float],
        error: Optional[str] = None,
        kernel_path: str = "unknown",
        hedged: bool = False,
        admit_level: int = 0,
        page: Optional[Dict[str, object]] = None,
        dispatch_info: Optional[Dict[str, object]] = None,
    ) -> None:
        """Feed one completed (or failed) batch to the flight recorder
        (and, when explain collection is on, to the query archive's tail
        sampler — the same dict, one extra member scan).

        ``stages_s`` holds the post-pickup stage durations in execution
        order (the Chrome-trace builder lays them end to end from
        ``t_pickup``); ``waits_s`` the pre-pickup waits (queue, in-flight
        window).  All values come from stamps the dispatch paths already
        take — this reconstructs, it does not measure.
        """
        if not spans.enabled():
            return
        stages_ms = {k: v * 1e3 for k, v in {**waits_s, **stages_s}.items()}
        explain_on = obs_explain.enabled()
        record = {
            "seq": seq,
            "index": self.metrics.name,
            "bucket": bucket,
            "rows": n,
            "compiles": compiles,
            "request_ids": [req.req_id for req in batch],
            "t_pickup": t_pickup,
            "t_done": t_done,
            "stages_s": stages_s,
            "waits_s": waits_s,
            "kernel_path": kernel_path,
            "hedged": hedged,
            "requests": [
                {
                    "id": req.req_id,
                    "rows": req.rows.shape[0],
                    "submit": req.t_submit,
                    "batched": t_pickup,
                    "resolve": t_done,
                    "queue_ms": (t_pickup - req.t_submit) * 1e3,
                    "latency_ms": (t_done - req.t_submit) * 1e3,
                    "stages_ms": stages_ms,
                    # ragged descriptor: what this request actually asked
                    # for inside the packed dispatch
                    **(
                        {"k": req.k, "fid": req.fid}
                        if self.ragged is not None else {}
                    ),
                    **(
                        {"priority": req.priority} if explain_on else {}
                    ),
                }
                for req in batch
            ],
            "error": error,
        }
        if explain_on:
            # explain enrichment: decisions already made/stamped this
            # dispatch — no clocks, no host syncs, one snapshot read
            record["admission_level"] = admit_level
            record["page"] = page
            record["dispatch"] = dispatch_info
            record["effort"] = (
                self.effort.snapshot() if self.effort is not None else None
            )
        flight.record_batch(record)
        if explain_on:
            obs_explain.observe_batch(record)

    def _dispatch_locked(self, batch: List[_Request]) -> None:
        if not batch:
            return
        seq = next(self._batch_seq)
        t_start = time.perf_counter()
        # queue-wait ends the moment the batch is picked up: submit → here
        queue_waits = [t_start - r.t_submit for r in batch]
        n = sum(r.rows.shape[0] for r in batch)
        bucket = self.bucket_for(n)
        padded = np.zeros((bucket, self.dim), dtype=np.float32)
        off = 0
        for req in batch:
            m = req.rows.shape[0]
            padded[off : off + m] = req.rows
            off += m
        t_pad = time.perf_counter() - t_start
        sp = None
        err_stage = "dispatch"
        try:
            c0 = compile_count(thread=True)
            with trace_range("serve.batch") as sp:
                t0 = time.perf_counter()
                # dispatch: host-side tracing + enqueue of the executable
                dist, ids = self._invoke(padded, batch)
                t1 = time.perf_counter()
                err_stage = "device"
                # device: waiting for the result to materialize — the serial
                # path's one intended sync (the pipelined path moves it to
                # the completion thread)
                jax.block_until_ready((dist, ids))  # raft-tpu: ignore[HOSTSYNC] serial-path batch barrier
                t2 = time.perf_counter()
                if sp is not None:
                    sp.add_stage("queue", max(queue_waits, default=0.0))
                    sp.add_stage("pad", t_pad)
                    sp.add_stage("dispatch", t1 - t0)
                    sp.add_stage("device", t2 - t1)
            compiles = compile_count(thread=True) - c0
            dist = np.asarray(dist)  # raft-tpu: ignore[HOSTSYNC] staged copy-out after the barrier
            ids = np.asarray(ids)  # raft-tpu: ignore[HOSTSYNC] staged copy-out after the barrier
        except Exception as exc:  # noqa: BLE001 — fail the waiting futures
            self._record_flight(
                seq=seq, batch=batch, n=n, bucket=bucket,
                compiles=compile_count(thread=True) - c0,
                t_pickup=t_start, t_done=time.perf_counter(),
                stages_s={"pad": t_pad},
                waits_s={"queue": max(queue_waits, default=0.0)},
                error=repr(exc),
                admit_level=self._last_admit_level,
            )
            self.metrics.record_error(err_stage, len(batch))
            obs_events.publish(
                "batch_error", "batch_exception",
                index=self.metrics.name, bucket=bucket, cause=err_stage,
                requests=len(batch), error=repr(exc),
            )
            for req in batch:
                req.future.set_exception(exc)
            return
        done = time.perf_counter()
        off = 0
        lats = []
        for req in batch:
            req.future.set_result(self._result_view(req, dist, ids, off))
            off += req.rows.shape[0]
            lats.append(done - req.t_submit)
        observer = self.observer
        if observer is not None:
            # futures are already resolved; the observer (quality auditor)
            # sees only the real rows and must itself be non-blocking
            try:
                observer(padded[:n], dist[:n], ids[:n])
            except Exception:  # noqa: BLE001 — auditing never fails serving
                pass
        self.metrics.record_queue_depth(self.queue_depth())
        self.metrics.record_batch(
            n, bucket, lats, compiles,
            stages={
                "queue": queue_waits,
                "pad": (t_pad,),
                "dispatch": (t1 - t0,),
                "device": (t2 - t1,),
            },
            request_ids=[r.req_id for r in batch],
            kernel_path=self._last_kernel_path,
        )
        if self._perf is not None:
            # ledger entry rides the t1/t2 stamps already taken above —
            # zero new clock calls on the hot path
            backend, ver = self._perf_meta()
            self._perf.record(
                index=self.metrics.name or "default", backend=backend,
                bucket=bucket, kernel_path=self._last_kernel_path,
                version=ver, device_s=t2 - t1, rows=n, padded_rows=bucket,
            )
        self._record_flight(
            seq=seq, batch=batch, n=n, bucket=bucket, compiles=compiles,
            t_pickup=t_start, t_done=done,
            stages_s={
                "pad": t_pad,
                "dispatch": t1 - t0,
                "device": t2 - t1,
                "copy_out": done - t2,
            },
            waits_s={"queue": max(queue_waits, default=0.0)},
            kernel_path=self._last_kernel_path,
            hedged=self._last_hedged,
            admit_level=self._last_admit_level,
            page=self._last_page_stats,
            dispatch_info=self._last_dispatch_info,
        )
        if compiles and self._warm:
            # a recompile on the warmed hot path is a shape leak: capture
            # the surrounding traffic while it is still in the ring
            obs_events.publish(
                "hot_recompile",
                index=self.metrics.name, bucket=bucket, compiles=compiles,
            )
        if sp is not None:
            slowlog.maybe_record(
                sp,
                latency_s=max(lats, default=0.0),
                detail={
                    "index": self.metrics.name,
                    "requests": len(batch),
                    "bucket": bucket,
                    "compiles": compiles,
                    "request_ids": [r.req_id for r in batch],
                    **self._explain_summary(
                        self._last_kernel_path, self._last_page_stats
                    ),
                },
            )

    def _explain_summary(self, kernel_path: str,
                         page: Optional[Dict[str, object]]):
        """Slow-log enrichment: the explain summary (effort level and its
        source, kernel path, page hit ratio) so slow lines are actionable
        without an archive lookup.  Purely additive keys — the existing
        entry fields stay byte-compatible."""
        return obs_explain.summary_line({
            "kernel_path": kernel_path,
            "effort": (
                self.effort.snapshot() if self.effort is not None else None
            ),
            "page": page,
        })

    # -- pipelined dispatch (pipeline_depth > 1) -----------------------------
    @property
    def inflight(self) -> int:
        """Device batches dispatched but not yet completed."""
        with self._inflight_lock:
            return self._inflight

    def device_busy_s(self) -> float:
        """Seconds the device had at least one batch outstanding.

        Pipelined path: exact union of the [enqueue, ready] intervals
        (FIFO completion keeps the incremental union O(1)).  Serial path:
        the sum of recorded device-stage durations, which is the same
        quantity because nothing overlaps at depth 1.  Benches derive the
        device-idle fraction as ``1 - device_busy_s / wall``."""
        if self.pipeline_depth > 1:
            with self._inflight_lock:
                return self._busy_s
        return self.metrics.stage_totals().get("device", 0.0)

    def _staging_buffer(self, bucket: int) -> np.ndarray:
        """Next slot of the bucket's staging ring (dispatch lock held).

        Safe to reuse without copying: at most ``pipeline_depth`` batches
        are ever in flight and completion is FIFO, so a slot's previous
        occupant — ``pipeline_depth`` same-bucket dispatches ago, hence at
        least ``pipeline_depth`` global dispatches ago — has fully
        completed (semaphore released only after copy-out and observer)
        before the slot comes around again."""
        ring = self._staging.get(bucket)
        if ring is None:
            ring = self._staging[bucket] = [None] * self.pipeline_depth
            self._staging_idx[bucket] = 0
        i = self._staging_idx[bucket]
        self._staging_idx[bucket] = (i + 1) % self.pipeline_depth
        buf = ring[i]
        if buf is None:
            buf = ring[i] = np.empty((bucket, self.dim), dtype=np.float32)
        return buf

    def _ensure_completion_thread(self) -> None:
        # only called under _dispatch_lock, so no start/start race
        t = self._completion_thread
        if t is not None and t.is_alive():
            return
        t = threading.Thread(
            target=self._completer, name="raft-tpu-serve-completer",
            daemon=True,
        )
        self._completion_thread = t
        t.start()

    def _dispatch_pipelined(self, batch: List[_Request], *,
                            sem_held: bool = False) -> Optional[_InFlight]:
        """Stage 1+2: pad into a staging buffer, enqueue device work, hand
        the record to the completion thread.  Never blocks on the device;
        blocks only on the in-flight window (``inflight_wait``).  Returns
        the in-flight record, or None for an empty batch or a dispatch-
        stage failure (which fails only this batch's futures).

        ``sem_held``: the continuous-admission worker already claimed the
        window slot before forming the batch — its wait overlapped
        admission, so this path records ``inflight_wait`` 0."""
        if not batch:
            if sem_held:
                self._inflight_sem.release()
            return None
        t_arrive = time.perf_counter()
        if not sem_held:
            # acquire the window slot BEFORE the dispatch lock: a full
            # window must stall this dispatcher without also blocking the
            # completion thread's progress (it never takes either)
            self._inflight_sem.acquire()
        t_acquired = time.perf_counter()
        with self._dispatch_lock:
            rec = _InFlight(batch)
            rec.seq = next(self._batch_seq)
            rec.t_pickup = t_acquired
            rec.inflight_wait = t_acquired - t_arrive
            # queue-wait ends when the batch is picked up for dispatch
            rec.queue_waits = [t_acquired - r.t_submit for r in batch]
            n = sum(r.rows.shape[0] for r in batch)
            bucket = self.bucket_for(n)
            t0 = time.perf_counter()
            padded = self._staging_buffer(bucket)
            off = 0
            for req in batch:
                m = req.rows.shape[0]
                padded[off : off + m] = req.rows
                off += m
            if off < bucket:
                # zero the tail so depth>1 results stay bit-identical to
                # the serial path's freshly-zeroed pad
                padded[off:] = 0.0
            rec.n, rec.bucket, rec.padded = n, bucket, padded
            rec.t_pad = time.perf_counter() - t0
            # detached span: opened here, closed by the completion thread
            rec.sp = spans.open_span("serve.batch")
            try:
                c0 = compile_count(thread=True)
                t1 = time.perf_counter()
                dist, ids = self._invoke(padded, batch)
                t2 = time.perf_counter()
                rec.t_dispatch = t2 - t1
                # compiles happen synchronously at trace/enqueue time, so
                # the bracket closes here, not after the device wait
                rec.compiles = compile_count(thread=True) - c0
                rec.dist, rec.ids = dist, ids
                rec.hedged = self._last_hedged
                rec.kernel_path = self._last_kernel_path
                # explain stamps: instance state is only valid on this
                # thread (dispatch lock held) — carry them on the record
                # for the completion thread
                rec.admit_level = self._last_admit_level
                rec.page = self._last_page_stats
                rec.dispatch_info = self._last_dispatch_info
            except Exception as exc:  # noqa: BLE001 — fail only this batch
                spans.finish_span(rec.sp)
                self._inflight_sem.release()
                self._record_flight(
                    seq=rec.seq, batch=batch, n=n, bucket=bucket,
                    compiles=compile_count(thread=True) - c0,
                    t_pickup=t_acquired, t_done=time.perf_counter(),
                    stages_s={"pad": rec.t_pad},
                    waits_s={
                        "queue": max(rec.queue_waits, default=0.0),
                        "inflight_wait": rec.inflight_wait,
                    },
                    error=repr(exc),
                )
                self.metrics.record_error("dispatch", len(batch))
                obs_events.publish(
                    "batch_error", "batch_exception",
                    index=self.metrics.name, bucket=bucket,
                    cause="dispatch", requests=len(batch), error=repr(exc),
                )
                for req in batch:
                    req.future.set_exception(exc)
                return None
            rec.t_enqueued = time.perf_counter()
            self._ensure_completion_thread()
            with self._inflight_lock:
                self._inflight += 1
                inflight = self._inflight
            self.metrics.record_pipeline(self.pipeline_depth, inflight)
            self._inflight_q.put(rec)
        return rec

    def _completer(self) -> None:
        """Stage 3: block on the oldest in-flight batch, copy out, resolve
        futures in submission order, run observer/metrics/slow-log."""
        while True:
            rec = self._inflight_q.get()
            if rec is None:
                return
            try:
                self._complete(rec)
            finally:
                with self._inflight_lock:
                    self._inflight -= 1
                    inflight = self._inflight
                # release AFTER _complete: the staging slot must not be
                # reusable until copy-out and the observer are done with it
                self._inflight_sem.release()
                self.metrics.record_pipeline(self.pipeline_depth, inflight)
                rec.done.set()

    def _complete(self, rec: _InFlight) -> None:
        batch = rec.batch
        t3 = time.perf_counter()
        try:
            # the pipelined path's intended sync point: the completion
            # thread blocks on the oldest in-flight batch off the dispatch
            # path, then copies results out
            jax.block_until_ready((rec.dist, rec.ids))  # raft-tpu: ignore[HOSTSYNC] completion-thread batch barrier
            t4 = time.perf_counter()
            dist = np.asarray(rec.dist)  # raft-tpu: ignore[HOSTSYNC] staged copy-out after the barrier
            ids = np.asarray(rec.ids)  # raft-tpu: ignore[HOSTSYNC] staged copy-out after the barrier
        except Exception as exc:  # noqa: BLE001 — fail only this batch
            spans.finish_span(rec.sp)
            self._record_flight(
                seq=rec.seq, batch=batch, n=rec.n, bucket=rec.bucket,
                compiles=rec.compiles,
                t_pickup=rec.t_pickup, t_done=time.perf_counter(),
                stages_s={"pad": rec.t_pad, "dispatch": rec.t_dispatch},
                waits_s={
                    "queue": max(rec.queue_waits, default=0.0),
                    "inflight_wait": rec.inflight_wait,
                },
                error=repr(exc),
                kernel_path=rec.kernel_path,
                hedged=rec.hedged,
                admit_level=rec.admit_level,
                page=rec.page,
                dispatch_info=rec.dispatch_info,
            )
            self.metrics.record_error("device", len(batch))
            obs_events.publish(
                "batch_error", "batch_exception",
                index=self.metrics.name, bucket=rec.bucket, cause="device",
                requests=len(batch), error=repr(exc),
            )
            for req in batch:
                req.future.set_exception(exc)
            return
        t_device = t4 - t3
        # device-busy union for the idle-fraction estimate: FIFO completion
        # means intervals arrive ordered by start time.  Hedged batches
        # already reported their members' windows via _note_device_interval
        # — adding [t_enqueued, t4] again would double-count the pair.
        if not rec.hedged:
            with self._inflight_lock:
                if t4 > self._busy_until:
                    self._busy_s += t4 - max(rec.t_enqueued, self._busy_until)
                    self._busy_until = t4
        if rec.sp is not None:
            rec.sp.add_stage("queue", max(rec.queue_waits, default=0.0))
            rec.sp.add_stage("pad", rec.t_pad)
            rec.sp.add_stage("inflight_wait", rec.inflight_wait)
            rec.sp.add_stage("dispatch", rec.t_dispatch)
            rec.sp.add_stage("device", t_device)
        spans.finish_span(rec.sp)
        done = time.perf_counter()
        off = 0
        lats = []
        for req in batch:
            req.future.set_result(self._result_view(req, dist, ids, off))
            off += req.rows.shape[0]
            lats.append(done - req.t_submit)
        observer = self.observer
        if observer is not None:
            # the staging slot outlives this call only until the semaphore
            # releases, but the auditor holds samples longer — hand it a
            # copy of the real rows (dist/ids are fresh arrays already)
            try:
                observer(rec.padded[: rec.n].copy(), dist[: rec.n],
                         ids[: rec.n])
            except Exception:  # noqa: BLE001 — auditing never fails serving
                pass
        self.metrics.record_queue_depth(self.queue_depth())
        self.metrics.record_batch(
            rec.n, rec.bucket, lats, rec.compiles,
            stages={
                "queue": rec.queue_waits,
                "pad": (rec.t_pad,),
                "inflight_wait": (rec.inflight_wait,),
                "dispatch": (rec.t_dispatch,),
                "device": (t_device,),
            },
            request_ids=[r.req_id for r in batch],
            kernel_path=rec.kernel_path,
        )
        if self._perf is not None:
            # same t3/t4 stamps the "device" stage above is built from, so
            # per-key ledger totals reconcile with stage_totals()["device"]
            backend, ver = self._perf_meta()
            self._perf.record(
                index=self.metrics.name or "default", backend=backend,
                bucket=rec.bucket, kernel_path=rec.kernel_path,
                version=ver, device_s=t_device, rows=rec.n,
                padded_rows=rec.bucket,
            )
        self._record_flight(
            seq=rec.seq, batch=batch, n=rec.n, bucket=rec.bucket,
            compiles=rec.compiles,
            t_pickup=rec.t_pickup, t_done=done,
            stages_s={
                "pad": rec.t_pad,
                "dispatch": rec.t_dispatch,
                "completer_wait": max(0.0, t3 - rec.t_enqueued),
                "device": t_device,
                "copy_out": done - t4,
            },
            waits_s={
                "queue": max(rec.queue_waits, default=0.0),
                "inflight_wait": rec.inflight_wait,
            },
            kernel_path=rec.kernel_path,
            hedged=rec.hedged,
            admit_level=rec.admit_level,
            page=rec.page,
            dispatch_info=rec.dispatch_info,
        )
        if rec.compiles and self._warm:
            # a recompile on the warmed hot path is a shape leak: capture
            # the surrounding traffic while it is still in the ring
            obs_events.publish(
                "hot_recompile",
                index=self.metrics.name, bucket=rec.bucket,
                compiles=rec.compiles,
            )
        if rec.sp is not None:
            slowlog.maybe_record(
                rec.sp,
                latency_s=max(lats, default=0.0),
                detail={
                    "index": self.metrics.name,
                    "requests": len(batch),
                    "bucket": rec.bucket,
                    "compiles": rec.compiles,
                    "request_ids": [r.req_id for r in batch],
                    **self._explain_summary(rec.kernel_path, rec.page),
                },
            )


def _squeeze_result(inner: Future, outer: Future) -> None:
    exc = inner.exception()
    if exc is not None:
        outer.set_exception(exc)
        return
    dist, ids = inner.result()
    outer.set_result((dist[0], ids[0]))
