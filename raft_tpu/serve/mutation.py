"""Streaming mutation for served indexes: upsert + tombstone delete.

ANN structures (IVF lists, CAGRA graphs) are batch-built; rebuilding per
write is not an online option.  The serving answer here is the classic
side-buffer + tombstone design:

* **delete(ids)** flips bits in a tombstone :class:`~raft_tpu.core.bitset.
  Bitset` over the main index's id space.  Every neighbors backend grew a
  ``deleted_mask`` argument for exactly this — tombstoned rows are
  filtered *inside* the main search (surfacing as id −1 at the worst
  distance), so deletes are visible immediately without touching the
  built structure.
* **upsert(vectors)** appends to a host-side growing buffer.  Queries scan
  the side buffer brute-force (it is small by construction — a background
  rebuild folds it into the main index; see :meth:`MutableIndex.rebuild`)
  and the two candidate lists merge through one
  :func:`~raft_tpu.ops.matrix.select_k`.
* Upserting an existing id tombstones the old row first, so an id never
  yields two results.

Shape discipline: the side buffer is padded to a power-of-two capacity
(occupancy tracked host-side, dead slots masked via the same Bitset
filter), so the merged search only ever sees O(log growth) distinct side
shapes — compiles stay off the steady-state hot path.

Thread-safety: mutations and snapshot-taking are guarded by a lock;
searches run on an immutable snapshot taken under that lock, so a search
never observes a half-applied mutation (and a hot-swap never tears a
batch).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core.bitset import Bitset, RowFilter
from raft_tpu.core import serialize as ser
from raft_tpu.core.trace import trace_range, traced
from raft_tpu.distance import DISTANCE_TYPES
from raft_tpu.kernels.toolkit import next_pow2
from raft_tpu.ops.matrix import mask_row_k, select_k

KINDS = ("brute_force", "ivf_flat", "ivf_pq", "cagra")

_SERVE_SERIALIZATION_VERSION = 1

_MIN_SIDE_CAP = 8


def _kind_module(kind: str):
    from raft_tpu import neighbors

    if kind not in KINDS:
        raise ValueError(f"unknown index kind {kind!r}; expected one of {KINDS}")
    return getattr(neighbors, kind)


def _infer_kind(index) -> str:
    mod = type(index).__module__.rsplit(".", 1)[-1]
    if mod not in KINDS:
        raise ValueError(
            f"cannot infer index kind from {type(index)!r}; pass kind="
        )
    return mod


# canonical pow2 helper lives in kernels.toolkit; the private alias stays
# importable (compactor sizes its shadow side buffers through it)
_next_pow2 = next_pow2


def _bitset_from_np(mask: np.ndarray) -> Bitset:
    """Pack a host bool mask into a Bitset with numpy-only packing
    (``Bitset.from_mask`` would run jnp scatter ops for the same job)."""
    n = mask.shape[0]
    nw = (n + 31) // 32
    padded = np.zeros(nw * 32, np.uint8)
    padded[:n] = mask
    words = np.packbits(padded, bitorder="little").view(np.uint32)
    return Bitset(jnp.asarray(words), n)


@dataclass(frozen=True)
class _Snapshot:
    """Immutable view a search runs against (see thread-safety note)."""

    tombstones: Optional[Bitset]     # over main rows, None when no deletes
    side_data: Optional[jax.Array]   # [cap, dim] padded, None when empty
    side_ids: Optional[jax.Array]    # [cap] global ids (-1 on dead slots)
    side_live: Optional[Bitset]      # pass-filter over side slots
    generation: int
    main_ids: Optional[jax.Array] = None  # row → global id, None = identity


class MutableIndex:
    """A served index: main (built) structure + tombstones + side buffer.

    Parameters
    ----------
    index:
        A built ``brute_force``/``ivf_flat``/``ivf_pq``/``cagra`` index.
        Main rows are assumed to carry ids ``0..index.size-1`` (what the
        builders assign).
    kind:
        Backend name; inferred from the index type when omitted.
    search_params:
        Per-kind ``SearchParams`` for the main search (ignored for
        brute_force).  Defaults to the backend's defaults.
    main_ids:
        Optional ``[index.size]`` int array mapping main *row* i to its
        global id.  A compacted shadow rebuild packs surviving rows
        densely (builders assign 0..m-1) but must keep serving the
        original ids — the map is applied after the main search and
        before the side-buffer merge.  Tombstones stay row-indexed
        (the in-search filter tests the backend's stored ids, which are
        rows).  ``None`` (the default, and what direct builds want)
        means identity.
    """

    def __init__(self, index, *, kind: Optional[str] = None, search_params=None,
                 main_ids: Optional[np.ndarray] = None):
        self.kind = kind if kind is not None else _infer_kind(index)
        mod = _kind_module(self.kind)  # validates kind
        self.index = index
        self.metric = index.metric
        self.dim = int(index.dim)
        self.main_size = int(index.size)
        if search_params is None and self.kind != "brute_force":
            search_params = mod.SearchParams()
        self.search_params = search_params

        if main_ids is not None:
            main_ids = np.asarray(main_ids, dtype=np.int64).reshape(-1)
            if main_ids.shape[0] != self.main_size:
                raise ValueError(
                    f"main_ids has {main_ids.shape[0]} entries for "
                    f"{self.main_size} main rows"
                )
            if np.array_equal(main_ids, np.arange(self.main_size)):
                main_ids = None  # identity: keep the remap off the search

        self._lock = threading.Lock()
        # row → global id map; immutable post-construction like the main
        # structure, so its device copy is built once here (not per snapshot)
        self._main_ids = main_ids
        self._main_ids_dev = (
            jnp.asarray(main_ids.astype(np.int32))
            if main_ids is not None else None
        )
        # main-row tombstones, host-side; packed lazily into a Bitset
        self._deleted = np.zeros((self.main_size,), dtype=bool)
        self._n_deleted = 0
        # rows tombstoned at construction (compaction padding sentinels):
        # part of the filter, but not mutation backlog — pending_mutations
        # subtracts them so a fresh compaction doesn't re-trigger itself
        self._n_structural = 0
        # side buffer, host-side source of truth
        self._side_data = np.zeros((0, self.dim), dtype=np.float32)
        self._side_ids = np.zeros((0,), dtype=np.int64)
        self._side_live = np.zeros((0,), dtype=bool)
        self._side_count = 0          # occupied slots (live or dead)
        self._next_id = (
            self.main_size if main_ids is None
            else (int(main_ids.max()) + 1 if main_ids.size else 0)
        )
        self._generation = 0
        # monotonic stamp of when the mutation backlog last became
        # non-empty; None while empty.  Feeds the freshness SLI: age of
        # the oldest un-compacted mutation, not a per-row watermark.
        self._backlog_since: Optional[float] = None
        # set by a compaction promote: mutations arriving after the
        # hot-swap forward to the replacement so they are never lost
        self._retired_to: Optional["MutableIndex"] = None
        self._snapshot_cache: Optional[_Snapshot] = None
        self._refresh_snapshot_locked()

    # -- introspection -------------------------------------------------------
    @property
    def size(self) -> int:
        """Live vectors (main minus tombstones, plus live side rows)."""
        with self._lock:
            return (
                self.main_size - self._n_deleted + int(self._side_live.sum())
            )

    @property
    def generation(self) -> int:
        """Monotonic mutation counter (bumps on every upsert/delete)."""
        with self._lock:
            return self._generation

    def device_bytes(self) -> int:
        """Bytes held by this index's arrays (main structure + serve
        state).  Feeds the per-version live-buffer gauges
        (:func:`raft_tpu.obs.cost.refresh_live_buffer_gauges`): the number
        an operator compares across versions to spot a swapped-out index
        whose arrays never freed."""

        def _nb(x) -> int:
            nb = getattr(x, "nbytes", None)
            return int(nb) if isinstance(nb, (int, np.integer)) else 0

        total = sum(_nb(v) for v in vars(self.index).values())
        total += _nb(self._main_ids) + _nb(self._main_ids_dev)
        with self._lock:
            total += _nb(self._side_data) + _nb(self._side_ids)
            total += _nb(self._side_live) + _nb(self._deleted)
            snap = self._snapshot_cache
        if snap is not None:
            for arr in (snap.side_data, snap.side_ids):
                total += _nb(arr)
            for bs in (snap.tombstones, snap.side_live):
                if bs is not None:
                    total += _nb(bs.words)
        return total

    def contains(self, id_: int) -> bool:
        with self._lock:
            if self._retired_to is not None:
                succ = self._retired_to
            else:
                if self._main_ids is None:
                    if 0 <= id_ < self.main_size and not self._deleted[id_]:
                        return True
                else:
                    rows = np.flatnonzero(self._main_ids == id_)
                    if rows.size and not self._deleted[rows[0]]:
                        return True
                hits = (self._side_ids == id_) & self._side_live
                return bool(hits.any())
        return succ.contains(id_)

    # -- mutation ------------------------------------------------------------
    @traced("serve.upsert")
    def upsert(self, vectors, ids=None) -> np.ndarray:
        """Insert (or replace) vectors; returns their global ids.

        Without ``ids`` fresh ids are allocated past the main index's
        range.  With ``ids``, any existing row under the same id (main or
        side) is tombstoned first — upsert semantics.
        """
        vectors = np.atleast_2d(np.asarray(vectors, dtype=np.float32))
        if vectors.ndim != 2 or vectors.shape[1] != self.dim:
            raise ValueError(
                f"expected vectors of dim {self.dim}, got {vectors.shape}"
            )
        m = vectors.shape[0]
        with self._lock:
            if self._retired_to is not None:
                # compaction promoted a successor while the caller held a
                # reference to this version: forward so the write lands in
                # the serving index instead of vanishing with this one
                succ = self._retired_to
            else:
                if ids is None:
                    ids = np.arange(
                        self._next_id, self._next_id + m, dtype=np.int64
                    )
                    self._next_id += m
                else:
                    ids = np.atleast_1d(np.asarray(ids, dtype=np.int64))
                    if ids.shape != (m,):
                        raise ValueError(
                            f"ids shape {ids.shape} does not match {m} vectors"
                        )
                    self._delete_locked(ids)
                    self._next_id = max(self._next_id, int(ids.max()) + 1)
                self._reserve_locked(self._side_count + m)
                sl = slice(self._side_count, self._side_count + m)
                self._side_data[sl] = vectors
                self._side_ids[sl] = ids
                self._side_live[sl] = True
                self._side_count += m
                self._bump_locked()
                return ids
        return succ.upsert(vectors, ids)

    @traced("serve.delete")
    def delete(self, ids) -> int:
        """Tombstone ids (main or side); returns how many were live."""
        ids = np.atleast_1d(np.asarray(ids, dtype=np.int64))
        with self._lock:
            if self._retired_to is None:
                n = self._delete_locked(ids)
                self._bump_locked()
                return n
            succ = self._retired_to
        return succ.delete(ids)

    def _delete_locked(self, ids: np.ndarray) -> int:
        n_removed = 0
        if self._main_ids is None:
            rows = ids[(ids >= 0) & (ids < self.main_size)]
        else:
            rows = np.flatnonzero(np.isin(self._main_ids, ids))
        if rows.size:
            was_live = ~self._deleted[rows]
            n_removed += int(np.unique(rows[was_live]).size)
            self._deleted[rows] = True
            self._n_deleted = int(self._deleted.sum())
        if self._side_count:
            hits = np.isin(self._side_ids, ids) & self._side_live
            n_removed += int(hits.sum())
            self._side_live[hits] = False
        return n_removed

    def _reserve_locked(self, n: int) -> None:
        cap = self._side_data.shape[0]
        if n <= cap:
            return
        new_cap = max(_MIN_SIDE_CAP, _next_pow2(n))
        grown = np.zeros((new_cap, self.dim), dtype=np.float32)
        grown[:cap] = self._side_data
        self._side_data = grown
        ids = np.full((new_cap,), -1, dtype=np.int64)
        ids[:cap] = self._side_ids
        self._side_ids = ids
        live = np.zeros((new_cap,), dtype=bool)
        live[:cap] = self._side_live
        self._side_live = live

    def _bump_locked(self) -> None:
        self._generation += 1
        deletes = self._n_deleted - self._n_structural
        side = int(self._side_live.sum()) if self._side_count else 0
        if deletes <= 0 and side <= 0:
            self._backlog_since = None
        elif self._backlog_since is None:
            self._backlog_since = time.monotonic()
        self._refresh_snapshot_locked()

    def _refresh_snapshot_locked(self) -> None:
        """Rebuild the search snapshot NOW, at mutation time.

        Mutations are host-side API calls, so this always runs in an eager
        context — building lazily on first search instead would stage the
        jnp constants as tracers when that search happens inside a
        shard_map/jit trace (the replica path) and leak them through the
        cache."""
        tomb = _bitset_from_np(self._deleted) if self._n_deleted else None
        if self._side_count:
            side_data = jnp.asarray(self._side_data)
            side_ids = jnp.asarray(
                np.where(self._side_live, self._side_ids, -1).astype(np.int32)
            )
            side_live = _bitset_from_np(self._side_live)
        else:
            side_data = side_ids = side_live = None
        self._snapshot_cache = _Snapshot(
            tomb, side_data, side_ids, side_live, self._generation,
            self._main_ids_dev,
        )

    # -- search --------------------------------------------------------------
    def _snapshot(self) -> _Snapshot:
        with self._lock:
            return self._snapshot_cache

    def _main_search(self, queries, k, tombstones, sample_filter=None,
                     search_params=None):
        mod = _kind_module(self.kind)
        if self.kind == "brute_force":
            return mod.search(
                self.index, queries, k,
                deleted_mask=tombstones, sample_filter=sample_filter,
            )
        params = self.search_params if search_params is None \
            else search_params
        return mod.search(
            params, self.index, queries, k,
            deleted_mask=tombstones, sample_filter=sample_filter,
        )

    def _side_passes(self, snap: _Snapshot, sample_filter):
        """Slot-space view of ``sample_filter`` for the side-buffer scan.

        The caller's filter is keyed by *global* ids; the side scan tests
        *slot* positions.  Gather each slot's bit through ``side_ids`` and
        AND with slot liveness.  Ids past the filter's bit range pass —
        a filter constrains only ids it covers, and upserted rows get ids
        allocated past any pre-registered filter's range.
        """
        if sample_filter is None:
            return snap.side_live
        live = snap.side_live.to_mask()       # [cap] bool
        sid = jnp.clip(snap.side_ids, 0)      # dead slots (-1) die via live
        in_range = snap.side_ids < jnp.int32(sample_filter.n_bits)
        word_ix = jnp.clip(sid // 32, 0, sample_filter.words.shape[-1] - 1)
        bit_ix = (sid % 32).astype(jnp.uint32)
        if isinstance(sample_filter, RowFilter):
            bit = (
                sample_filter.words[:, word_ix] >> bit_ix[None, :]
            ) & jnp.uint32(1)
            mask = jnp.where(in_range[None, :], bit == 1, True) & live[None, :]
            return RowFilter.from_mask_rows(mask)
        bit = (sample_filter.words[word_ix] >> bit_ix) & jnp.uint32(1)
        return Bitset.from_mask(jnp.where(in_range, bit == 1, True) & live)

    def _main_filter_rows(self, snap: _Snapshot, sample_filter):
        """Row-space view of ``sample_filter`` for a compacted main index.

        After compaction the backend's stored rows are dense (promotion
        renumbered them) while the caller's filter stays keyed by
        *global* ids — the ids results are remapped to and the ids the
        :class:`~raft_tpu.serve.ragged.FilterRegistry` was built over.
        Gather each stored row's bit through the compaction id map
        (``snap.main_ids``), exactly like :meth:`_side_passes` does
        through ``side_ids``.  Uncovered ids pass (a filter constrains
        only ids it covers); padding sentinels (gid −1) also pass here
        but never surface — promotion registered them as structural
        tombstones, which compose via ``deleted_mask``.
        """
        gids = snap.main_ids                  # [rows] int32, -1 = padding
        g = jnp.clip(gids, 0)
        covered = (gids >= 0) & (gids < jnp.int32(sample_filter.n_bits))
        word_ix = jnp.clip(g // 32, 0, sample_filter.words.shape[-1] - 1)
        bit_ix = (g % 32).astype(jnp.uint32)
        if isinstance(sample_filter, RowFilter):
            bit = (
                sample_filter.words[:, word_ix] >> bit_ix[None, :]
            ) & jnp.uint32(1)
            mask = jnp.where(covered[None, :], bit == 1, True)
            return RowFilter.from_mask_rows(mask)
        bit = (sample_filter.words[word_ix] >> bit_ix) & jnp.uint32(1)
        return Bitset.from_mask(jnp.where(covered, bit == 1, True))

    def search(self, queries, k: int, *, sample_filter=None,
               row_k=None, search_params=None
               ) -> Tuple[jax.Array, jax.Array]:
        """Merged top-k over main (tombstone-filtered) + side buffer.

        Returns (distances [q, k], ids [q, k]); pruned/padding slots are
        id −1 at the worst distance, like the backend searches.

        ``sample_filter`` (a :class:`~raft_tpu.core.bitset.Bitset`, or a
        :class:`~raft_tpu.core.bitset.RowFilter` with one pass-row per
        query — the ragged path's form) restricts results by global id;
        it composes with tombstones inside the main search and is remapped
        to slot space for the side scan (and, on a compacted index, to
        dense row space for the main search — filters survive
        compaction).  ``row_k`` (``[q] int32``) caps each row's results
        below ``k`` as *data* — positions past a row's own k surface as
        id −1 at the worst distance, with no new executable per distinct
        k.  ``search_params`` overrides the index's own params for this
        call (the degraded-mode ladder's hook); every distinct params
        value is a distinct jit variant, so overriders must warm what
        they pass.
        """
        queries = jnp.asarray(queries, jnp.float32)
        if queries.ndim != 2 or queries.shape[1] != self.dim:
            raise ValueError(
                f"queries shape {queries.shape} vs index dim {self.dim}"
            )
        snap = self._snapshot()
        main_filter = sample_filter
        if sample_filter is not None and snap.main_ids is not None:
            # compacted index: remap the global-id filter through the
            # compaction id map so the dense-row backend tests the right
            # bits.  Costs one [q, main_rows] mask per batch — shaped by
            # the bucket and the fixed id map only, so nothing recompiles.
            main_filter = self._main_filter_rows(snap, sample_filter)
        select_min = DISTANCE_TYPES[self.metric] != "inner_product"
        with trace_range("serve.mutable_search"):
            dist, ids = self._main_search(
                queries, k, snap.tombstones, main_filter, search_params
            )
            if snap.main_ids is not None:
                # compacted index: the backend returned dense row ids;
                # remap to the global ids callers know (-1 stays -1)
                ids = jnp.where(
                    ids >= 0, snap.main_ids[jnp.clip(ids, 0)], -1
                )
            if snap.side_data is None:
                if row_k is not None:
                    dist, ids = mask_row_k(
                        dist, ids, row_k, select_min=select_min
                    )
                return dist, ids
            from raft_tpu.neighbors import brute_force

            cap = snap.side_data.shape[0]
            k_side = min(k, cap)
            s_dist, s_slot = brute_force.knn(
                snap.side_data, queries, k_side,
                metric=self.metric,
                sample_filter=self._side_passes(snap, sample_filter),
            )
            # slot → global id (-1 stays -1)
            s_ids = jnp.where(s_slot >= 0, snap.side_ids[s_slot], -1)
            return select_k(
                jnp.concatenate([dist, s_dist], axis=1),
                k,
                select_min=select_min,
                input_indices=jnp.concatenate(
                    [ids.astype(jnp.int32), s_ids.astype(jnp.int32)], axis=1
                ),
                row_k=row_k,
            )

    # -- maintenance ---------------------------------------------------------
    def pending_mutations(self) -> Tuple[int, int]:
        """(tombstoned main rows, live side rows) — rebuild pressure.

        Construction-time padding sentinels (compacted indexes) are
        excluded: they are filter state, not backlog."""
        with self._lock:
            return (
                self._n_deleted - self._n_structural,
                int(self._side_live.sum()),
            )

    def backlog_age_s(self) -> float:
        """Seconds since the mutation backlog last became non-empty.

        0.0 while the backlog is empty — this is the freshness SLI: how
        long the oldest un-compacted mutation has been waiting for a
        rebuild, the thing the freshness SLO bounds."""
        with self._lock:
            deletes = self._n_deleted - self._n_structural
            side = int(self._side_live.sum()) if self._side_count else 0
            if deletes <= 0 and side <= 0:
                self._backlog_since = None
                return 0.0
            if self._backlog_since is None:
                self._backlog_since = time.monotonic()
            return time.monotonic() - self._backlog_since

    def live_vectors(self) -> Tuple[np.ndarray, np.ndarray]:
        """Materialize (vectors, ids) of every live row — rebuild input.

        Main rows keep their original ids; the caller rebuilding into a
        fresh index typically renumbers (builders assign 0..n-1).
        """
        with self._lock:
            keep = ~self._deleted
            main_rows = np.asarray(self._main_dataset())[keep]
            if self._main_ids is None:
                main_ids = np.nonzero(keep)[0].astype(np.int64)
            else:
                main_ids = self._main_ids[keep]
            side_rows = self._side_data[self._side_live]
            side_ids = self._side_ids[self._side_live]
        return (
            np.concatenate([main_rows, side_rows], axis=0),
            np.concatenate([main_ids, side_ids], axis=0),
        )

    def iter_main_rows(self, chunk_rows: int = 65536):
        """Yield ``(row_indices, rows)`` chunks of the main dataset.

        The memory-bounded path a compaction rebuild uses instead of
        :meth:`live_vectors`: each step materializes at most roughly
        ``chunk_rows`` decoded float32 rows (plus one list-data slab for
        the IVF kinds), never the whole dataset.  The main structure is
        immutable, so iteration needs no lock; row indices are positions
        0..main_size-1 — map through the id map (if any) and the caller's
        captured tombstone mask to get live global ids.
        """
        chunk_rows = max(1, int(chunk_rows))
        if self.kind in ("brute_force", "cagra"):
            data = self.index.dataset
            for a in range(0, self.main_size, chunk_rows):
                b = min(a + chunk_rows, self.main_size)
                yield (
                    np.arange(a, b, dtype=np.int64),
                    np.asarray(data[a:b], dtype=np.float32),
                )
            return
        # IVF kinds: rows live scattered across padded lists — chunk over
        # lists so each step slices a bounded slab of list_data
        list_index = np.asarray(self.index.list_index)
        n_lists, cap = list_index.shape
        lists_per = max(1, chunk_rows // max(cap, 1))
        if self.kind == "ivf_pq":
            rot = np.asarray(self.index.rotation, dtype=np.float32)
            scale = float(self.index.scan_scale)
        for l0 in range(0, n_lists, lists_per):
            l1 = min(l0 + lists_per, n_lists)
            idx = list_index[l0:l1]
            valid = idx >= 0
            if not valid.any():
                continue
            data = np.asarray(self.index.list_data[l0:l1], dtype=np.float32)
            rows = data[valid]
            if self.kind == "ivf_pq":
                # decoded reconstructions live in rotated space (possibly
                # int8 scan cache, hence scan_scale); invert the rotation
                rows = (rows * scale) @ rot
            yield idx[valid].astype(np.int64), rows

    def _main_dataset(self) -> np.ndarray:
        """Recover the main rows in id order (for rebuild/consistency)."""
        if self.kind in ("brute_force", "cagra"):
            return np.asarray(self.index.dataset)
        # IVF variants: scatter padded lists back by source id
        out = np.zeros((self.main_size, self.dim), dtype=np.float32)
        data = np.asarray(self.index.list_data, dtype=np.float32)
        idx = np.asarray(self.index.list_index)
        valid = idx >= 0
        if self.kind == "ivf_pq":
            # decoded reconstructions live in rotated space (possibly int8
            # scan cache, hence scan_scale); invert the orthonormal rotation
            rot = np.asarray(self.index.rotation, dtype=np.float32)
            out[idx[valid]] = (data[valid] * float(self.index.scan_scale)) @ rot
        else:
            out[idx[valid]] = data[valid]
        return out

    # -- persistence ---------------------------------------------------------
    def save(self, path: str) -> None:
        """Snapshot serve state to ``path`` + main index to ``path.main``."""
        mod = _kind_module(self.kind)
        with self._lock:
            scalars = {
                "kind": self.kind,
                "main_size": self.main_size,
                "side_count": self._side_count,
                "next_id": self._next_id,
                "generation": self._generation,
                "n_structural": self._n_structural,
                "dim": self.dim,
            }
            arrays = {
                "deleted": self._deleted,
                "side_data": self._side_data,
                "side_ids": self._side_ids,
                "side_live": self._side_live,
            }
            if self._main_ids is not None:
                # compacted indexes serve remapped ids; dropping the map on
                # restore would silently re-serve dense row ids
                arrays["main_ids"] = self._main_ids
            tiered = getattr(self.index, "paged", None)
            if tiered is not None:
                # paged layout survives the roundtrip: load re-paginates at
                # the same page size and re-warms the saved residency set
                # (tier *placement*; slot numbers are allocator-internal)
                scalars["paged"] = 1
                scalars["page_rows"] = int(tiered.store.page_rows)
                scalars["pinned"] = int(bool(tiered.stats()["pinned"]))
                arrays["resident_pages"] = tiered.resident_pages()
            ser.save_tree(
                path, "serve_mutable", _SERVE_SERIALIZATION_VERSION,
                scalars, arrays,
            )
        if self.kind == "cagra":
            mod.save(path + ".main", self.index, include_dataset=True)
        else:
            mod.save(path + ".main", self.index)

    @classmethod
    def load(cls, path: str, *, search_params=None) -> "MutableIndex":
        scalars, arrays = ser.load_tree(
            path, "serve_mutable", _SERVE_SERIALIZATION_VERSION
        )
        mod = _kind_module(scalars["kind"])
        index = mod.load(path + ".main")
        if scalars.get("paged"):
            from raft_tpu.store import paginate_index

            tiered = paginate_index(
                index, page_rows=int(scalars["page_rows"]),
                name=f"load:{scalars['kind']}",
            )
            if int(scalars.get("pinned", 0)):
                tiered.pin_identity()
            else:
                resident = np.asarray(arrays.get("resident_pages", ()))
                if resident.size:
                    tiered.ensure_resident(resident)
        # files written before the id map existed have no "main_ids" key —
        # they were identity-mapped by construction
        out = cls(
            index, kind=scalars["kind"], search_params=search_params,
            main_ids=arrays.get("main_ids"),
        )
        with out._lock:
            out._deleted = np.asarray(arrays["deleted"], dtype=bool)
            out._n_deleted = int(out._deleted.sum())
            out._side_data = np.asarray(arrays["side_data"], dtype=np.float32)
            out._side_ids = np.asarray(arrays["side_ids"], dtype=np.int64)
            out._side_live = np.asarray(arrays["side_live"], dtype=bool)
            out._side_count = int(scalars["side_count"])
            out._next_id = int(scalars["next_id"])
            out._generation = int(scalars["generation"])
            # older files predate compaction padding; they had none
            out._n_structural = int(scalars.get("n_structural", 0))
            out._refresh_snapshot_locked()
        return out
