"""raft_tpu.serve — online ANN query serving.

The offline library answers "given a batch, search"; this package answers
the online question: single-query requests arriving over time, against
indexes that change while being served.  Five pieces:

- :mod:`~raft_tpu.serve.batcher` — dynamic micro-batching into a padded
  power-of-two bucket ladder, so every request hits an already-compiled
  executable (zero recompiles after warmup).
- :mod:`~raft_tpu.serve.mutation` — ``MutableIndex``: tombstone deletes
  (filtered inside the backend searches) + a brute-force side buffer for
  upserts, merged through one ``select_k``.
- :mod:`~raft_tpu.serve.registry` — named, versioned indexes with atomic
  hot-swap and snapshot/restore.
- :mod:`~raft_tpu.serve.metrics` — QPS / p50 / p99 / batch-fill, the
  queue/pad/dispatch/device stage breakdown, and a *real* recompile
  counter (jax.monitoring backend-compile events); every instance also
  reports into the process-wide :mod:`raft_tpu.obs` registry.
- :mod:`~raft_tpu.serve.replica` — query-sharded multi-chip dispatch over
  a replicated index (comms/ mesh).
- :mod:`~raft_tpu.serve.compactor` — online compaction: a background
  worker that folds tombstones + side buffer back into the main
  structure via memory-budgeted shadow rebuilds, recall-gated atomic
  promotion, and zero post-swap recompiles.
- :mod:`~raft_tpu.serve.ragged` — continuous ragged batching: one packed
  dispatch per capacity bucket for heterogeneous requests; per-request
  ``k`` and registered filter ids ride as descriptor *data* instead of
  executable shapes, retiring the per-(bucket × k × filter) variant
  lattice (``SearchService(ragged=True)`` / ``RAFT_TPU_RAGGED=1``).
- :mod:`~raft_tpu.serve.shard` — ``ShardedIndex``: the index itself
  partitioned across the mesh axis (brute-force rows / IVF lists), each
  shard running the existing local search with one cross-shard tie-stable
  ``select_k`` merge — capacity ≈ N× one chip instead of throughput ≈ N×.
- :mod:`~raft_tpu.serve.overload` — overload-safe serving: priority
  classes and deadlines on every request, an ``AdmissionController``
  shedding lowest-priority-first under pressure (typed ``Shed`` /
  ``DeadlineExceeded`` rejections, never silent), a
  ``DegradedModeManager`` stepping search effort down with hysteresis,
  and ``HedgedDispatcher`` racing replica members for p0 tail latency
  (``SearchService(overload=True)`` / ``RAFT_TPU_OVERLOAD=1``).

``SearchService`` (:mod:`~raft_tpu.serve.service`) assembles them, and
carries the obs v2 hooks: attach a :class:`raft_tpu.obs.QualityAuditor`
for online recall auditing off the hot path, read ``healthz()`` /
``readyz()`` for OK / DEGRADED / UNHEALTHY verdicts, and every warmup
books XLA cost/memory figures per bucket into the registry.  See
``docs/serving.md`` for the guided tour.
"""

from raft_tpu.serve.batcher import MicroBatcher
from raft_tpu.serve.build import build_sharded, knn_graph_sharded
from raft_tpu.serve.compactor import CompactionPolicy, Compactor
from raft_tpu.serve.effort import EffortArbiter
from raft_tpu.serve.metrics import (
    ServingMetrics,
    compile_count,
    install_compile_listener,
)
from raft_tpu.serve.mutation import MutableIndex
from raft_tpu.serve.overload import (
    AdmissionController,
    DeadlineExceeded,
    DegradedModeManager,
    HedgedDispatcher,
    OverloadConfig,
    Shed,
)
from raft_tpu.serve.ragged import FilterRegistry, RaggedSearcher, RaggedSpec
from raft_tpu.serve.registry import IndexRegistry
from raft_tpu.serve.replica import (
    ReplicaGroup,
    make_replicated_search,
    replicated_search,
)
from raft_tpu.serve.service import SearchService
from raft_tpu.serve.shard import ShardedIndex, shard_index

__all__ = [
    "AdmissionController",
    "CompactionPolicy",
    "Compactor",
    "DeadlineExceeded",
    "DegradedModeManager",
    "EffortArbiter",
    "FilterRegistry",
    "HedgedDispatcher",
    "IndexRegistry",
    "MicroBatcher",
    "MutableIndex",
    "OverloadConfig",
    "RaggedSearcher",
    "RaggedSpec",
    "ReplicaGroup",
    "SearchService",
    "ServingMetrics",
    "Shed",
    "ShardedIndex",
    "build_sharded",
    "compile_count",
    "knn_graph_sharded",
    "install_compile_listener",
    "make_replicated_search",
    "replicated_search",
    "shard_index",
]
