"""Overload-safe serving: priority classes, deadlines, admission
control, degraded-mode search, and hedged dispatch.

The SLO engine (:mod:`raft_tpu.obs.slo`) *measures* overload — burn
rates, error budgets — but measuring changes nothing: a saturated queue
degrades every request equally until latency collapses.  This module
closes the loop.  Requests carry a **priority class** (0=interactive,
1=standard, 2=batch, 3=background) and an optional **deadline**, riding
next to ``k``/``fid`` in the batcher's request records (host-side
metadata — no new executable shapes, so the zero-recompile contract is
untouched).  Three actuators consume them:

- :class:`AdmissionController` — at every batch cut it expires
  past-deadline requests and, under pressure, sheds the lowest
  priorities first.  Pressure is the max of three signals: the oldest
  queued request's wait versus ``admit_wait_s``, queue depth versus
  ``queue_factor × max_batch``, and active ``slo_burn`` alerts observed
  on the obs bus.  Shed and expired futures resolve with the typed
  :class:`Shed` / :class:`DeadlineExceeded` errors — work is never
  silently dropped — and every shedding cut publishes one
  ``admission_shed`` bus event (a trigger kind, so it opens or joins an
  incident timeline).
- :class:`DegradedModeManager` — steps search *effort* down under
  sustained pressure and restores it hysteretically: after
  ``degrade_after_s`` of continuous pressure the level rises (halving
  ``n_probes`` / cagra's ``itopk_size`` per level, dropping ivf_pq's
  LUT to bf16 at level ≥ 2 — the refine-off analog), and only after
  ``restore_after_s`` of continuous calm does it step back.  Enter and
  exit edges publish ``degraded_enter`` / ``degraded_exit`` events.
  Every level's executables are warmed with the bucket ladder, so a
  level flip never recompiles on the hot path.
- :class:`HedgedDispatcher` — for batches carrying priority-0 traffic,
  races a hedge member (a second, independently-dispatched searcher —
  e.g. the replica-group collective vs a direct local search) after a
  p99-derived delay.  First completion wins; the loser's result is
  discarded host-side.  The fire is published as a ``hedge_fired``
  context event and counted, so tail-latency spend is attributable.

All thresholds live in :class:`OverloadConfig` (``RAFT_TPU_OVERLOAD_*``
env knobs).  The controllers are deliberately clock-injectable
(``now=`` parameters) so tests drive synthetic time, never sleeps.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, fields as dc_fields, replace as dc_replace
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax

from raft_tpu.core import env as _env
from raft_tpu.core.trace import traced
from raft_tpu.obs import events as obs_events
from raft_tpu.obs.registry import default_registry

#: priority classes, lowest number = most important
N_PRIORITIES = 4
PRIORITY_NAMES = ("interactive", "standard", "batch", "background")


class Shed(RuntimeError):
    """The request was shed by admission control before dispatch.

    Raised out of the request's future (never silently dropped).
    Clients should treat it as explicit backpressure: retry later or
    with a higher priority class.
    """

    def __init__(self, priority: int, level: int, index: str = ""):
        self.priority = int(priority)
        self.level = int(level)
        self.index = index
        super().__init__(
            f"shed at admission (priority={priority} "
            f"[{PRIORITY_NAMES[priority]}], pressure level={level}, "
            f"index={index!r})"
        )


class DeadlineExceeded(TimeoutError):
    """The request's deadline passed before it reached the device.

    Subclasses :class:`TimeoutError` so callers already catching
    client-side timeouts handle server-side expiry the same way.
    """

    def __init__(self, late_s: float, index: str = ""):
        self.late_s = float(late_s)
        self.index = index
        super().__init__(
            f"deadline exceeded {late_s * 1e3:.1f} ms before dispatch "
            f"(index={index!r})"
        )


@dataclass(frozen=True)
class OverloadConfig:
    """Thresholds for admission control, degradation, and hedging.

    ``admit_wait_s`` and ``queue_factor`` define pressure level 1; each
    doubling of a signal past its threshold raises the level (×2 → 2,
    ×4 → 3), and an active ``slo_burn`` alert adds one more.  Level n
    sheds priority classes ≥ ``4 - n``: background first, interactive
    never.
    """

    admit_wait_s: float = 0.25
    queue_factor: float = 8.0
    degrade_after_s: float = 1.0
    restore_after_s: float = 5.0
    max_degrade_level: int = 2
    hedge: bool = False
    hedge_delay_mult: float = 3.0
    hedge_min_delay_s: float = 0.005

    @classmethod
    def from_env(cls) -> "OverloadConfig":
        return cls(
            admit_wait_s=_env.env_float(
                "RAFT_TPU_OVERLOAD_ADMIT_WAIT_S", cls.admit_wait_s),
            queue_factor=_env.env_float(
                "RAFT_TPU_OVERLOAD_QUEUE_FACTOR", cls.queue_factor),
            degrade_after_s=_env.env_float(
                "RAFT_TPU_OVERLOAD_DEGRADE_AFTER_S", cls.degrade_after_s),
            restore_after_s=_env.env_float(
                "RAFT_TPU_OVERLOAD_RESTORE_AFTER_S", cls.restore_after_s),
            max_degrade_level=_env.env_int(
                "RAFT_TPU_OVERLOAD_MAX_DEGRADE", cls.max_degrade_level),
            hedge=_env.env_bool("RAFT_TPU_OVERLOAD_HEDGE", cls.hedge),
            hedge_delay_mult=_env.env_float(
                "RAFT_TPU_OVERLOAD_HEDGE_MULT", cls.hedge_delay_mult),
            hedge_min_delay_s=_env.env_float(
                "RAFT_TPU_OVERLOAD_HEDGE_MIN_S", cls.hedge_min_delay_s),
        )


def validate_priority(priority) -> int:
    """Normalize/validate a submit-time priority (None → standard)."""
    if priority is None:
        return 1
    p = int(priority)
    if not 0 <= p < N_PRIORITIES:
        raise ValueError(
            f"priority must be in [0, {N_PRIORITIES}), got {priority!r}"
        )
    return p


def priority_name(priority) -> str:
    """Human label for a priority class (explain plans, log lines)."""
    try:
        return PRIORITY_NAMES[int(priority)]
    except (TypeError, ValueError, IndexError):
        return "unknown"


def expire_deadlines(batch: Sequence, *, now: Optional[float] = None,
                     index: str = "", metrics=None) -> List:
    """Return the still-alive requests of ``batch``, resolving expired
    ones' futures with :class:`DeadlineExceeded`.  The deadline-only
    actuator used when no :class:`AdmissionController` is installed —
    expired work must never occupy a device slot regardless of overload
    wiring."""
    now = time.perf_counter() if now is None else now
    alive: List = []
    expired: List = []
    for req in batch:
        deadline = getattr(req, "deadline", None)
        if deadline is not None and now > deadline:
            expired.append(req)
        else:
            alive.append(req)
    if expired:
        for req in expired:
            req.future.set_exception(
                DeadlineExceeded(now - req.deadline, index=index)
            )
        if metrics is not None:
            metrics.record_error("deadline", len(expired))
        default_registry().counter(
            "raft_tpu_serve_deadline_expired_total",
            help="requests expired at batch cut (deadline passed before "
                 "dispatch)",
        ).inc(len(expired), index=index)
    return alive


@dataclass(frozen=True)
class AdmissionDecision:
    """Outcome of one batch-cut admission pass."""

    admitted: Tuple
    shed: Tuple
    expired: Tuple
    level: int


class AdmissionController:
    """Sheds lowest-priority-first at batch-cut time under pressure.

    Pressure is recomputed per cut from the batch itself (oldest wait,
    queue depth) plus the latched set of active ``slo_burn`` alerts for
    this index, maintained by a bus subscription (``recovered=True``
    edges clear their reason).  Shedding strictly respects priority
    order — level 1 sheds only background (3), level 2 sheds batch+
    (≥ 2), level 3 sheds standard+ (≥ 1); interactive (0) is never shed,
    only deadline-expired.
    """

    def __init__(self, config: Optional[OverloadConfig] = None, *,
                 name: str = "default", metrics=None, bus=None):
        self.config = config if config is not None \
            else OverloadConfig.from_env()
        self.name = name
        self.metrics = metrics
        self._lock = threading.Lock()
        self._burning: set = set()
        self.shed_total = 0
        self.expired_total = 0
        self.last_level = 0
        bus = obs_events.default_bus() if bus is None else bus
        self._sub = bus.subscribe(
            self._on_burn, kinds=frozenset({"slo_burn"}),
            name=f"admission:{name}",
        )

    # -- slo_burn latch ------------------------------------------------------
    def _on_burn(self, event) -> None:
        idx = event.fields.get("index")
        if idx is not None and idx != self.name:
            return
        with self._lock:
            if event.recovered:
                self._burning.discard(event.reason)
            else:
                self._burning.add(event.reason)

    def burning(self) -> bool:
        """True while any un-recovered ``slo_burn`` alert is latched."""
        with self._lock:
            return bool(self._burning)

    def close(self) -> None:
        """Detach the bus subscription (service stop / index removal)."""
        self._sub.unsubscribe()

    # -- pressure ------------------------------------------------------------
    def pressure_level(self, *, oldest_wait_s: float, queue_rows: int,
                       max_batch: int) -> int:
        """0 (calm) … 3 (severe): max over the wait and depth signals
        (each doubling past threshold = +1 level) plus one level while
        an SLO burn alert is active."""
        cfg = self.config
        level = 0
        signals = (
            (oldest_wait_s, cfg.admit_wait_s),
            (float(queue_rows), cfg.queue_factor * max(1, max_batch)),
        )
        for value, threshold in signals:
            if threshold <= 0.0:
                continue
            ratio = value / threshold
            if ratio >= 4.0:
                level = max(level, 3)
            elif ratio >= 2.0:
                level = max(level, 2)
            elif ratio >= 1.0:
                level = max(level, 1)
        if self.burning():
            level = min(3, level + 1)
        return level

    # -- the batch-cut decision ----------------------------------------------
    @traced("serve.admission.decide")
    def decide(self, batch: Sequence, *, queue_rows: int = 0,
               max_batch: int = 1,
               now: Optional[float] = None) -> AdmissionDecision:
        """Expire deadlines, then shed by priority if under pressure.

        Resolves every shed/expired future before returning — callers
        dispatch ``decision.admitted`` and nothing else.
        """
        now = time.perf_counter() if now is None else now
        oldest = 0.0
        for req in batch:
            oldest = max(oldest, now - req.t_submit)
        level = self.pressure_level(
            oldest_wait_s=oldest, queue_rows=queue_rows,
            max_batch=max_batch,
        )
        min_shed_priority = N_PRIORITIES - level  # 1→3, 2→2, 3→1
        admitted: List = []
        shed: List = []
        expired: List = []
        for req in batch:
            deadline = getattr(req, "deadline", None)
            if deadline is not None and now > deadline:
                expired.append(req)
            elif level > 0 and req.priority >= min_shed_priority:
                shed.append(req)
            else:
                admitted.append(req)
        with self._lock:
            self.last_level = level
            self.shed_total += len(shed)
            self.expired_total += len(expired)
        if expired or shed:
            self._resolve(shed, expired, level, now)
        return AdmissionDecision(
            tuple(admitted), tuple(shed), tuple(expired), level
        )

    def _resolve(self, shed: Sequence, expired: Sequence, level: int,
                 now: float) -> None:
        # futures first: a slow bus subscriber must not delay the
        # client-visible rejection
        for req in expired:
            req.future.set_exception(
                DeadlineExceeded(now - req.deadline, index=self.name)
            )
        for req in shed:
            req.future.set_exception(
                Shed(req.priority, level, index=self.name)
            )
        reg = default_registry()
        by_priority: Dict[int, int] = {}
        for req in shed:
            by_priority[req.priority] = by_priority.get(req.priority, 0) + 1
        for priority, count in by_priority.items():
            reg.counter(
                "raft_tpu_serve_shed_total",
                help="requests shed by admission control",
            ).inc(count, index=self.name, priority=str(priority))
        if expired:
            reg.counter(
                "raft_tpu_serve_deadline_expired_total",
                help="requests expired at batch cut (deadline passed "
                     "before dispatch)",
            ).inc(len(expired), index=self.name)
        if self.metrics is not None:
            if shed:
                self.metrics.record_error("shed", len(shed))
            if expired:
                self.metrics.record_error("deadline", len(expired))
        if shed:
            obs_events.publish(
                "admission_shed", f"admission_{self.name}",
                index=self.name, level=level,
                shed={str(p): c for p, c in sorted(by_priority.items())},
                expired=len(expired), burning=self.burning(),
            )


def derive_degraded_params(params, level: int):
    """Reduced-effort variant of a backend ``SearchParams`` at a
    degradation level.  The semantics live with each backend's typed
    :class:`~raft_tpu.neighbors.effort.EffortSpec` (``degraded(level)``):
    halve ``n_probes`` (ivf_flat / ivf_pq) and cagra's ``itopk_size``
    per level, drop ivf_pq's LUT to bf16 at level ≥ 2.  Param types
    without an EffortSpec fall back to a field-name walk with the same
    rules; fully unknown types pass through unchanged (brute_force has
    no effort knob)."""
    if level <= 0 or params is None:
        return params
    from raft_tpu.neighbors import effort as _effort  # lazy: serve is importable without the backends

    spec = _effort.spec_for_params(params)
    if spec is not None:
        return spec.degraded(level).apply(params)
    try:
        names = {f.name for f in dc_fields(params)}
    except TypeError:
        return params
    kw: Dict[str, object] = {}
    if "n_probes" in names:
        kw["n_probes"] = max(1, int(params.n_probes) >> level)
    if "itopk_size" in names:
        kw["itopk_size"] = max(32, int(params.itopk_size) >> level)
    if "lut_dtype" in names and level >= 2:
        kw["lut_dtype"] = "bfloat16"
    if not kw:
        return params
    return dc_replace(params, **kw)


class DegradedModeManager:
    """Hysteretic search-effort ladder for one served index.

    ``step(overloaded)`` is called once per batch cut with the admission
    verdict.  The level rises one notch after ``degrade_after_s`` of
    *sustained* pressure and falls one notch after ``restore_after_s``
    of sustained calm — flapping load cannot flap effort.  Enter edges
    publish ``degraded_enter`` (a trigger kind: the decision lands in
    an incident timeline); exits publish ``degraded_exit``, flagged
    recovered once the ladder is back at full effort.
    """

    def __init__(self, config: Optional[OverloadConfig] = None, *,
                 name: str = "default"):
        self.config = config if config is not None \
            else OverloadConfig.from_env()
        self.name = name
        self._lock = threading.Lock()
        self._level = 0
        self._pressure_since: Optional[float] = None
        self._calm_since: Optional[float] = None
        self._derived: Dict[Tuple[int, int], object] = {}

    @property
    def level(self) -> int:
        with self._lock:
            return self._level

    def levels(self) -> Tuple[int, ...]:
        """Every level warmup must cover (0 … max)."""
        return tuple(range(self.config.max_degrade_level + 1))

    @contextmanager
    def pinned(self, level: int):
        """Force a level without events or hysteresis (warmup ladders,
        tests)."""
        with self._lock:
            prev, self._level = self._level, int(level)
        try:
            yield
        finally:
            with self._lock:
                self._level = prev

    @traced("serve.degrade.step")
    def step(self, overloaded: bool, now: Optional[float] = None) -> int:
        """Advance the hysteresis clock; returns the (possibly new)
        level.  ``now`` is monotonic seconds — tests pass a synthetic
        clock."""
        now = time.monotonic() if now is None else now
        cfg = self.config
        entered = exited = None
        with self._lock:
            if overloaded:
                self._calm_since = None
                if self._pressure_since is None:
                    self._pressure_since = now
                elif (self._level < cfg.max_degrade_level
                        and now - self._pressure_since >= cfg.degrade_after_s):
                    self._level += 1
                    self._pressure_since = now  # re-arm for the next notch
                    entered = self._level
            else:
                self._pressure_since = None
                if self._calm_since is None:
                    self._calm_since = now
                elif (self._level > 0
                        and now - self._calm_since >= cfg.restore_after_s):
                    self._level -= 1
                    self._calm_since = now
                    exited = self._level
            level = self._level
        if entered is not None or exited is not None:
            default_registry().gauge(
                "raft_tpu_serve_degraded_level",
                help="current degraded-search level (0 = full effort)",
            ).set(float(level), index=self.name)
        if entered is not None:
            obs_events.publish(
                "degraded_enter", f"degraded_{self.name}",
                index=self.name, level=entered,
            )
        if exited is not None:
            obs_events.publish(
                "degraded_exit", f"degraded_{self.name}",
                recovered=(exited == 0), index=self.name, level=exited,
            )
        return level

    def params_for(self, index):
        """The search params the current level prescribes for ``index``,
        or None at full effort (callers fall back to the index's own).
        Derived params are cached per (base params, level) so the same
        object identity feeds the jit cache every time — a fresh
        dataclass per call would still hash equal, but identity-stable
        params keep host-side overhead flat."""
        level = self.level
        if level <= 0:
            return None
        base = getattr(index, "search_params", None)
        if base is None:
            return None
        key = (id(base), level)
        with self._lock:
            derived = self._derived.get(key)
        if derived is None:
            derived = derive_degraded_params(base, level)
            with self._lock:
                self._derived[key] = derived
        return derived


class HedgedDispatcher:
    """Tail-latency hedge across two independently-dispatched members.

    ``members[0]`` is the primary searcher; if it has not completed
    within a p99-derived delay (``hedge_delay_mult × p99``, floored at
    ``hedge_min_delay_s``), ``members[1]`` is fired and the first
    completion wins.  The loser is cancelled host-side: its thread keeps
    the device busy until its own completion, but its result is
    discarded and nothing downstream waits on it.  Dispatch blocks until
    the winner's arrays are ready — hedging is reserved for batches
    carrying priority-0 traffic, where serializing the cut is the point.
    """

    def __init__(self, members: Sequence[Callable],
                 config: Optional[OverloadConfig] = None, *,
                 name: str = "default", metrics=None):
        if len(members) < 2:
            raise ValueError(
                f"hedging needs >= 2 members, got {len(members)}"
            )
        self.members: Tuple[Callable, ...] = tuple(members)
        self.config = config if config is not None \
            else OverloadConfig.from_env()
        self.name = name
        self.metrics = metrics
        self.fired_total = 0
        self.hedge_wins = 0
        # optional per-member device-interval sink, ``fn(t_start, t_end)``
        # in perf_counter seconds: the batcher points this at its busy
        # interval-union so a mirrored hedge pair's overlapping device
        # windows MERGE instead of double-counting in device_busy_s()
        self.on_interval: Optional[Callable[[float, float], None]] = None

    def delay_s(self) -> float:
        """Hedge delay: ``p99 × mult`` from the live latency reservoir,
        floored at the configured minimum (cold start: floor only)."""
        delay = 0.0
        if self.metrics is not None:
            p99_ms = self.metrics.snapshot().get("p99_ms")
            if p99_ms:
                delay = float(p99_ms) * 1e-3 * self.config.hedge_delay_mult
        return max(self.config.hedge_min_delay_s, delay)

    def warm(self, *args) -> None:
        """Run every member once (the batcher's warmup calls this per
        bucket so a hedge fire never meets a cold executable)."""
        for fn in self.members:
            out = fn(*args)
            jax.block_until_ready(out)  # raft-tpu: ignore[HOSTSYNC] warmup barrier, off the serving path

    @traced("serve.hedge.dispatch")
    def dispatch(self, *args):
        """Race the primary against a delayed hedge; first completion
        wins.  Raises the primary's error only if every started member
        failed."""
        done = threading.Event()
        lock = threading.Lock()
        state = {"out": None, "member": -1, "errors": [], "started": 1}

        def run(i: int) -> None:
            try:
                t_s = time.perf_counter()
                out = self.members[i](*args)
                jax.block_until_ready(out)  # raft-tpu: ignore[HOSTSYNC] winner selection needs device completion
                sink = self.on_interval
                if sink is not None:
                    # report THIS member's device window; the union sink
                    # dedupes the mirrored pair's overlap
                    sink(t_s, time.perf_counter())
            except Exception as exc:  # noqa: BLE001 — raced, re-raised below
                with lock:
                    state["errors"].append(exc)
                    all_failed = (state["out"] is None
                                  and len(state["errors"])
                                  >= state["started"])
                if all_failed:
                    done.set()
                return
            with lock:
                if state["out"] is None:
                    state["out"], state["member"] = out, i
            done.set()

        primary = threading.Thread(
            target=run, args=(0,), name=f"hedge-primary-{self.name}",
            daemon=True,
        )
        primary.start()
        fired = False
        if not done.wait(self.delay_s()):
            with lock:
                state["started"] = 2
                still_pending = state["out"] is None and not state["errors"]
            if still_pending:
                fired = True
                threading.Thread(
                    target=run, args=(1,),
                    name=f"hedge-{self.name}", daemon=True,
                ).start()
                self.fired_total += 1
                default_registry().counter(
                    "raft_tpu_serve_hedge_fired_total",
                    help="hedge dispatches fired after the delay",
                ).inc(index=self.name)
                obs_events.publish(
                    "hedge_fired", f"hedge_{self.name}",
                    index=self.name, delay_s=self.delay_s(),
                )
            done.wait()
        with lock:
            out, member = state["out"], state["member"]
            errors = list(state["errors"])
        if out is None:
            raise errors[0]
        if fired and member == 1:
            self.hedge_wins += 1
            default_registry().counter(
                "raft_tpu_serve_hedge_wins_total",
                help="hedge dispatches where the hedge member won",
            ).inc(index=self.name)
        return out
