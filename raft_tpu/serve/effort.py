"""Single-writer search-effort arbitration for one served index.

Before this module, search effort had two independent writers racing on
the hot path: PR 11's :class:`~raft_tpu.serve.overload.DegradedModeManager`
(overload ladder) derived params inside the batcher, and anything else
that wanted to move effort had to overwrite the same ``SearchParams``
last-writer-wins.  The :class:`EffortArbiter` closes that hole:

- exactly **one** place computes the effective effort level and the
  derived ``SearchParams`` the dispatch uses (``apply(index)``);
- the SLO autotuner (:mod:`raft_tpu.obs.autotune`) is the only *writer*
  (``set_autotune_level``);
- the overload ladder is a **clamp, not a second writer**: its shed
  level is read at apply time and floors the effective effort reduction,
  so a load spike can always force effort down but can never fight the
  autotuner over the same field.

Effective level = ``max(autotune level, overload shed level)``, capped
at the warmed ladder depth.  Derived params are identity-cached per
``(base params, level)`` — the same object feeds the jit cache every
dispatch — and every level in ``levels()`` is precompiled by the
batcher's warmup ladder, so moving effort re-dispatches an already
compiled variant (zero post-warmup recompiles; knob values never ride
as static jit args — the RECOMPILE rule enforces this).

Lock discipline: one leaf lock guarding the arbiter's own fields only —
never held across the degraded manager's lock, event publication, or
param derivation (LOCKORDER-clean by construction).
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Dict, Optional, Tuple

from raft_tpu.core.trace import traced
from raft_tpu.serve.overload import OverloadConfig, derive_degraded_params


class EffortArbiter:
    """Arbitrates every actuator's search-effort intent for one index
    into a single effective ladder level and one derived params object.
    """

    def __init__(self, degraded=None, *, max_level: Optional[int] = None,
                 name: str = "default"):
        self.name = name
        #: overload ladder read as a clamp (may be None: no overload
        #: protection configured)
        self.degraded = degraded
        if max_level is None:
            cfg = degraded.config if degraded is not None \
                else OverloadConfig.from_env()
            max_level = cfg.max_degrade_level
        self.max_level = int(max_level)
        self._lock = threading.Lock()  # leaf lock: own fields only
        self._autotune_level = 0
        self._pin: Optional[int] = None
        self._derived: Dict[Tuple[int, int], object] = {}

    # -- ladder ---------------------------------------------------------

    def levels(self) -> Tuple[int, ...]:
        """Every effort level warmup must precompile (0 … max)."""
        return tuple(range(self.max_level + 1))

    @contextmanager
    def pinned(self, level: int):
        """Force an effective level, bypassing both writers (warmup
        ladders, tests)."""
        with self._lock:
            prev, self._pin = self._pin, int(level)
        try:
            yield
        finally:
            with self._lock:
                self._pin = prev

    def set_pin(self, level: Optional[int]) -> Optional[int]:
        """Operator pin: force the effective level until explicitly
        cleared with ``None`` — the persistent sibling of the scoped
        :meth:`pinned` (the gateway's ``POST /admin/effort_pin`` uses
        it).  Clamped to the warmed ladder so a pin can never dispatch
        an uncompiled variant; returns the stored pin."""
        with self._lock:
            if level is None:
                self._pin = None
            else:
                self._pin = max(0, min(int(level), self.max_level))
            return self._pin

    # -- the single writer ---------------------------------------------

    @property
    def autotune_level(self) -> int:
        with self._lock:
            return self._autotune_level

    def set_autotune_level(self, level: int) -> int:
        """The autotuner's intent — the one mutating entry point.
        Clamped to the warmed ladder; returns the stored level."""
        level = max(0, min(int(level), self.max_level))
        with self._lock:
            self._autotune_level = level
        return level

    # -- reads ----------------------------------------------------------

    def effective_level(self) -> int:
        """Arbitrated level: autotune intent floored by the overload
        shed level (clamp semantics), capped at the warmed ladder."""
        with self._lock:
            if self._pin is not None:
                return self._pin
            level = self._autotune_level
        if self.degraded is not None:
            level = max(level, self.degraded.level)
        return min(level, self.max_level)

    @traced("serve.effort.apply")
    def apply(self, index):
        """The search params the arbitrated level prescribes for
        ``index``, or None at full effort (callers fall back to the
        index's own).  Identity-cached per (base params, level) so the
        jit cache sees a stable object every dispatch."""
        level = self.effective_level()
        if level <= 0:
            return None
        base = getattr(index, "search_params", None)
        if base is None:
            return None
        key = (id(base), level)
        with self._lock:
            derived = self._derived.get(key)
        if derived is None:
            derived = derive_degraded_params(base, level)
            with self._lock:
                self._derived[key] = derived
        return derived

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            autotune = self._autotune_level
            pinned = self._pin
        degraded = self.degraded.level if self.degraded is not None else 0
        effective = self.effective_level()
        # who set the effective level — the attribution per-query explain
        # plans surface ("effort level and who set it")
        if pinned is not None:
            source = "pinned"
        elif effective <= 0:
            source = "full_effort"
        elif degraded > autotune:
            source = "overload_clamp"
        else:
            source = "autotune"
        return {
            "autotune_level": autotune,
            "degraded_level": degraded,
            "effective_level": effective,
            "max_level": self.max_level,
            "source": source,
        }
