"""Index registry: named, versioned indexes with atomic hot-swap.

The registry is the level of indirection that lets an offline rebuild
replace a live index without pausing traffic: queries resolve the name to
a concrete :class:`~raft_tpu.serve.mutation.MutableIndex` *once per
dispatched batch* (see ``SearchService``), so a swap is atomic at batch
granularity — every result row in a batch comes from exactly one index
version, and in-flight batches keep the old version alive by reference
until they finish.  Swapping same-shaped indexes also costs zero
recompiles, since the compiled executables key on shapes, not weights.

Snapshots write one file per index (via ``MutableIndex.save``) plus a
manifest binding names to versions, through ``core.serialize`` — restore
round-trips tombstones and side buffers, not just the built structure.
"""

from __future__ import annotations

import os
import threading
import weakref
from typing import Dict, List, Optional, Tuple

from raft_tpu.core import serialize as ser
from raft_tpu.obs import events as obs_events
from raft_tpu.serve.mutation import MutableIndex

_MANIFEST_VERSION = 1
_MANIFEST_NAME = "MANIFEST"


class IndexRegistry:
    """Thread-safe name → (index, version) map with atomic replacement."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: Dict[str, Tuple[MutableIndex, int]] = {}
        # weak history of every version ever bound: the live-buffer
        # accounting (obs.cost.refresh_live_buffer_gauges) walks this to
        # tell "swapped out and freed" from "swapped out and leaked" —
        # weak refs so the history itself never pins an old version
        self._history: "weakref.WeakValueDictionary[Tuple[str, int], MutableIndex]" = (
            weakref.WeakValueDictionary()
        )

    # -- registration / swap -------------------------------------------------
    def register(
        self, name: str, index: MutableIndex, *, version: Optional[int] = None
    ) -> int:
        """Bind ``name`` to ``index`` atomically; returns the new version.

        Re-registering an existing name IS the hot-swap: the version
        auto-increments (unless given) and readers see either the old or
        the new index, never a mix.
        """
        from raft_tpu.serve.shard import ShardedIndex

        if not isinstance(index, (MutableIndex, ShardedIndex)):
            raise TypeError(
                f"registry holds MutableIndex or ShardedIndex, got "
                f"{type(index)!r}; wrap built indexes with "
                "MutableIndex(index) or ShardedIndex.from_index(index)"
            )
        with self._lock:
            prev = self._entries.get(name)
            if version is None:
                version = prev[1] + 1 if prev is not None else 1
            # tuple replacement is a single reference store — atomic for
            # readers holding no lock
            self._entries[name] = (index, version)
            self._history[(name, version)] = index
        # context event, published outside the lock: annotates any open
        # incident so "quality degraded right after version 7 went live"
        # reads off one timeline.  First-time registration is bootstrap,
        # not a swap — no event.
        if prev is not None:
            obs_events.publish(
                "registry_swap",
                index=name, version=version, prev_version=prev[1],
            )
        return version

    def swap(self, name: str, index: MutableIndex) -> int:
        """Hot-swap an existing name; raises KeyError if unknown."""
        with self._lock:
            if name not in self._entries:
                raise KeyError(f"no index named {name!r} to swap")
            version = self._entries[name][1] + 1
            self._entries[name] = (index, version)
            self._history[(name, version)] = index
        obs_events.publish(
            "registry_swap",
            index=name, version=version, prev_version=version - 1,
        )
        return version

    def unregister(self, name: str) -> None:
        with self._lock:
            del self._entries[name]

    # -- resolution ----------------------------------------------------------
    def get(self, name: str) -> MutableIndex:
        with self._lock:
            return self._entries[name][0]

    def get_versioned(self, name: str) -> Tuple[MutableIndex, int]:
        """(index, version) resolved atomically — batch-dispatch entry."""
        with self._lock:
            return self._entries[name]

    def version(self, name: str) -> int:
        with self._lock:
            return self._entries[name][1]

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._entries)

    def live_versions(self) -> Dict[Tuple[str, int], MutableIndex]:
        """Every (name, version) whose index object is still reachable —
        current versions plus any swapped-out version something still
        pins (an in-flight batch, or a leak)."""
        with self._lock:
            return dict(self._history)

    def __contains__(self, name: str) -> bool:
        with self._lock:
            return name in self._entries

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    # -- persistence ---------------------------------------------------------
    def snapshot(self, directory: str) -> None:
        """Write every index + a name→version manifest under ``directory``."""
        os.makedirs(directory, exist_ok=True)
        with self._lock:
            entries = dict(self._entries)
        scalars = {"count": len(entries)}
        for i, name in enumerate(sorted(entries)):
            index, version = entries[name]
            scalars[f"name_{i}"] = name
            scalars[f"version_{i}"] = version
            index.save(os.path.join(directory, f"{name}.idx"))
        ser.save_tree(
            os.path.join(directory, _MANIFEST_NAME),
            "serve_registry", _MANIFEST_VERSION, scalars, {},
        )

    @classmethod
    def restore(cls, directory: str) -> "IndexRegistry":
        scalars, _ = ser.load_tree(
            os.path.join(directory, _MANIFEST_NAME),
            "serve_registry", _MANIFEST_VERSION,
        )
        reg = cls()
        for i in range(int(scalars["count"])):
            name = scalars[f"name_{i}"]
            version = int(scalars[f"version_{i}"])
            index = MutableIndex.load(os.path.join(directory, f"{name}.idx"))
            reg.register(name, index, version=version)
        return reg
