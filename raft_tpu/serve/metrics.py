"""Serving metrics: QPS, latency percentiles, batch fill, recompiles.

TPU serving lives or dies on shape stability — one stray query shape on the
hot path triggers an XLA compile measured in *seconds* while the request
(and everything queued behind it) waits.  The recompile counter here is
therefore not a proxy: ``jax.monitoring`` emits
``/jax/core/compile/backend_compile_duration`` exactly once per real
backend compile and never on executable-cache hits, so the batcher can
bracket every dispatch with :func:`compile_count` and attribute compiles to
the serving path.  A non-zero ``recompiles`` after warmup is a bug, and
``tests/test_serve.py`` pins it at zero.

Latency keeps a bounded reservoir (last ``_RESERVOIR`` request latencies)
— percentile math stays O(reservoir), not O(uptime).  QPS is measured over
the same window from completion timestamps.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, Optional

import numpy as np

_RESERVOIR = 4096

# ---- process-wide XLA compile counter -------------------------------------

_compile_count = 0
_listener_installed = False
_listener_lock = threading.Lock()


def _on_event_duration(name: str, duration: float, **kwargs) -> None:
    global _compile_count
    if name == "/jax/core/compile/backend_compile_duration":
        _compile_count += 1


def install_compile_listener() -> None:
    """Register the jax.monitoring listener (idempotent, process-wide)."""
    global _listener_installed
    with _listener_lock:
        if _listener_installed:
            return
        import jax.monitoring

        jax.monitoring.register_event_duration_secs_listener(_on_event_duration)
        _listener_installed = True


def compile_count() -> int:
    """Total XLA backend compiles observed in this process so far."""
    install_compile_listener()
    return _compile_count


class ServingMetrics:
    """Per-service request/batch counters + latency reservoir.

    Thread-safe; the batcher's worker thread records, any thread snapshots.
    """

    def __init__(self, reservoir: int = _RESERVOIR):
        self._lock = threading.Lock()
        self._latencies = deque(maxlen=reservoir)   # seconds, per request
        self._done_ts = deque(maxlen=reservoir)     # completion timestamps
        self.requests = 0
        self.batches = 0
        self.recompiles = 0        # compiles attributed to serve dispatches
        self.warmup_compiles = 0   # compiles spent in explicit warmup
        self._fill_real = 0        # sum of real rows over all batches
        self._fill_padded = 0      # sum of padded bucket rows

    # -- recording ----------------------------------------------------------
    def record_batch(
        self,
        n_real_rows: int,
        bucket_rows: int,
        latencies_s,
        compiles: int,
    ) -> None:
        """One dispatched batch: ``latencies_s`` holds one submit→complete
        latency per coalesced request (queue wait included)."""
        now = time.perf_counter()
        with self._lock:
            self.requests += len(latencies_s)
            self.batches += 1
            self.recompiles += compiles
            self._fill_real += n_real_rows
            self._fill_padded += bucket_rows
            for lat in latencies_s:
                self._latencies.append(lat)
                self._done_ts.append(now)

    def record_warmup(self, compiles: int) -> None:
        with self._lock:
            self.warmup_compiles += compiles

    def reset_hot_path(self) -> None:
        """Zero the hot-path recompile attribution (called after warmup)."""
        with self._lock:
            self.recompiles = 0

    # -- reading ------------------------------------------------------------
    def snapshot(self) -> Dict[str, object]:
        """One dict with the headline serving numbers (JSON-safe)."""
        with self._lock:
            lat = np.asarray(self._latencies, dtype=np.float64)
            ts = np.asarray(self._done_ts, dtype=np.float64)
            out: Dict[str, object] = {
                "requests": self.requests,
                "batches": self.batches,
                "recompiles": self.recompiles,
                "warmup_compiles": self.warmup_compiles,
                "batch_fill": (
                    self._fill_real / self._fill_padded
                    if self._fill_padded
                    else None
                ),
            }
        if lat.size:
            out["p50_ms"] = float(np.percentile(lat, 50) * 1e3)
            out["p99_ms"] = float(np.percentile(lat, 99) * 1e3)
            span = float(ts.max() - ts.min())
            # a single instant (or one request) has no measurable rate
            out["qps"] = float(lat.size / span) if span > 0 else None
        else:
            out["p50_ms"] = out["p99_ms"] = out["qps"] = None
        return out


def timed_percentiles(latencies_s, qs=(50, 99)) -> Optional[Dict[str, float]]:
    """Helper for benches: {'p50_ms': ..., 'p99_ms': ...} or None if empty."""
    arr = np.asarray(list(latencies_s), dtype=np.float64)
    if not arr.size:
        return None
    return {f"p{q}_ms": float(np.percentile(arr, q) * 1e3) for q in qs}
