"""Serving metrics: QPS, latency percentiles, batch fill, recompiles.

TPU serving lives or dies on shape stability — one stray query shape on the
hot path triggers an XLA compile measured in *seconds* while the request
(and everything queued behind it) waits.  The recompile counter here is
therefore not a proxy: ``jax.monitoring`` emits
``/jax/core/compile/backend_compile_duration`` exactly once per real
backend compile and never on executable-cache hits, so the batcher can
bracket every dispatch with :func:`compile_count` and attribute compiles to
the serving path.  A non-zero ``recompiles`` after warmup is a bug, and
``tests/test_serve.py`` pins it at zero.

Latency keeps a bounded reservoir (last ``_RESERVOIR`` request latencies)
— percentile math stays O(reservoir), not O(uptime).  QPS is measured over
the same window from completion timestamps.  The batcher additionally
reports *stage* reservoirs (queue-wait / pad / dispatch / device), so a
p99 excursion decomposes into "where" without a profiler.

:class:`ServingMetrics` is also a :mod:`raft_tpu.obs` registry client:
named instances mirror requests/batches/recompiles into process-wide
counters and request/stage latencies into labeled histograms, and appear
as a ``serve.<name>`` provider section in ``obs.snapshot()``.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, Iterable, Mapping, Optional

import numpy as np

from raft_tpu import obs

_RESERVOIR = 4096

#: stage names the batcher reports, in display order.  ``inflight_wait``
#: only appears at pipeline_depth > 1: it is the time a formed batch
#: waited for an in-flight window slot (device backpressure), measured
#: before the dispatch stage.
STAGES = ("queue", "pad", "inflight_wait", "dispatch", "device")

# ---- process-wide XLA compile counter -------------------------------------

_compile_count = 0
_compile_count_by_thread: Dict[int, int] = {}
_listener_installed = False
_listener_lock = threading.Lock()
# jax invokes duration listeners from whatever thread triggered the compile;
# the count must increment under a lock (int += is not atomic across the
# read-modify-write) — and NOT _listener_lock, which install_compile_listener
# holds while jax might already be delivering events
_count_lock = threading.Lock()


def _on_event_duration(name: str, duration: float, *args, **kwargs) -> None:
    # *args soaks up extra positional context newer jax versions pass to
    # duration listeners; a strict (name, duration) signature would raise
    # inside jax.monitoring and silently kill the listener
    global _compile_count
    if name == "/jax/core/compile/backend_compile_duration":
        tid = threading.get_ident()
        with _count_lock:
            _compile_count += 1
            _compile_count_by_thread[tid] = (
                _compile_count_by_thread.get(tid, 0) + 1
            )


def install_compile_listener() -> None:
    """Register the jax.monitoring listener (idempotent, process-wide)."""
    global _listener_installed
    with _listener_lock:
        if _listener_installed:
            return
        import jax.monitoring

        jax.monitoring.register_event_duration_secs_listener(_on_event_duration)
        _listener_installed = True


def compile_count(thread: bool = False) -> int:
    """XLA backend compiles observed in this process so far.

    ``thread=True`` restricts the count to compiles triggered *by the
    calling thread* — jax delivers the duration event synchronously on
    the compiling thread, so a dispatch bracket on the batcher thread
    stays blind to concurrent compiles from background work (a
    compaction shadow rebuild, a warmup on another service).  The
    default process-total keeps the old semantics for benches and
    single-threaded callers.
    """
    install_compile_listener()
    with _count_lock:
        if thread:
            return _compile_count_by_thread.get(threading.get_ident(), 0)
        return _compile_count


class ServingMetrics:
    """Per-service request/batch counters + latency reservoirs.

    Thread-safe; the batcher's worker thread records, any thread snapshots.
    With a ``name`` the instance doubles as an obs registry client: the
    same numbers flow into ``raft_tpu_serve_*`` counters/histograms labeled
    ``index=<name>`` and the instance registers a ``serve.<name>``
    provider so ``obs.snapshot()`` carries the full serving picture.
    """

    def __init__(self, reservoir: int = _RESERVOIR,
                 name: Optional[str] = None):
        self._lock = threading.Lock()
        self._latencies = deque(maxlen=reservoir)   # seconds, per request
        self._done_ts = deque(maxlen=reservoir)     # completion timestamps
        self._stage_lat: Dict[str, deque] = {
            s: deque(maxlen=reservoir) for s in STAGES
        }
        self.name = name
        self.requests = 0
        self.batches = 0
        self.errors: Dict[str, int] = {}   # failed requests by cause
        self.recompiles = 0        # compiles attributed to serve dispatches
        self.warmup_compiles = 0   # compiles spent in explicit warmup
        self._fill_real = 0        # sum of real rows over all batches
        self._fill_padded = 0      # sum of padded bucket rows
        self._pad_waste = 0        # sum of (bucket - real) padding rows
        # bucket → [real rows, padded rows]: per-capacity-bucket fill, the
        # figure that shows where the pad ladder's waste concentrates
        self._bucket_fill: Dict[int, list] = {}
        self._queue_depth = 0      # rows queued at the last dispatch
        self._pipeline_depth = 1   # in-flight window size (1 = serial)
        self._inflight = 0         # device batches currently in flight
        self._inflight_peak = 0    # high-water mark of the above
        # kernel_path → dispatched batches: the live pallas/xla A/B tally
        self._kernel_paths: Dict[str, int] = {}
        if name is not None:
            obs.default_registry().register_provider(
                f"serve.{name}", self.snapshot
            )

    def close(self) -> None:
        """Detach from the obs registry (batcher teardown).  Only removes
        the provider if it is still this instance's — a hot-replaced
        batcher's teardown must not detach its successor."""
        if self.name is not None:
            obs.default_registry().unregister_provider(
                f"serve.{self.name}", expected=self.snapshot
            )

    # -- recording ----------------------------------------------------------
    def record_batch(
        self,
        n_real_rows: int,
        bucket_rows: int,
        latencies_s,
        compiles: int,
        stages: Optional[Mapping[str, Iterable[float]]] = None,
        request_ids: Optional[Iterable[int]] = None,
        kernel_path: Optional[str] = None,
    ) -> None:
        """One dispatched batch: ``latencies_s`` holds one submit→complete
        latency per coalesced request (queue wait included); ``stages``
        maps stage name → iterable of per-batch (or per-request, for
        ``queue``) stage durations in seconds; ``request_ids`` (parallel
        to ``latencies_s``) attaches each latency observation's request id
        as a histogram exemplar, so a fat p99 bucket names the request;
        ``kernel_path`` is the leg the dispatch actually routed to
        (pallas/xla/...), stamped live by the kernels thread-local and
        carried as a label on the latency and stage histograms."""
        now = time.perf_counter()
        with self._lock:
            self.requests += len(latencies_s)
            self.batches += 1
            self.recompiles += compiles
            if kernel_path is not None:
                self._kernel_paths[kernel_path] = (
                    self._kernel_paths.get(kernel_path, 0) + 1
                )
            self._fill_real += n_real_rows
            self._fill_padded += bucket_rows
            self._pad_waste += max(0, bucket_rows - n_real_rows)
            fill = self._bucket_fill.setdefault(int(bucket_rows), [0, 0])
            fill[0] += n_real_rows
            fill[1] += bucket_rows
            for lat in latencies_s:
                self._latencies.append(lat)
                self._done_ts.append(now)
            if stages:
                for s, vals in stages.items():
                    dq = self._stage_lat.setdefault(
                        s, deque(maxlen=self._latencies.maxlen)
                    )
                    for v in vals:
                        dq.append(float(v))
        self._mirror_batch(n_real_rows, bucket_rows, latencies_s, compiles,
                           stages, request_ids, kernel_path)

    def _mirror_batch(self, n_real_rows, bucket_rows, latencies_s, compiles,
                      stages, request_ids=None, kernel_path=None) -> None:
        """Feed the obs registry (no-op for anonymous instances)."""
        if self.name is None:
            return
        reg = obs.default_registry()
        label = {"index": self.name}
        # latency/stage histograms carry the dispatch's kernel leg so the
        # pallas-vs-xla comparison reads straight off the live series;
        # counters keep index-only labels (cardinality discipline)
        hist_label = (
            dict(label, kernel_path=kernel_path)
            if kernel_path is not None else label
        )
        reg.counter(
            "raft_tpu_serve_requests_total", help="served requests"
        ).inc(len(latencies_s), **label)
        reg.counter(
            "raft_tpu_serve_batches_total", help="dispatched batches"
        ).inc(**label)
        if compiles:
            reg.counter(
                "raft_tpu_serve_recompiles_total",
                help="hot-path XLA compiles (should stay 0 after warmup)",
            ).inc(compiles, **label)
        lat_h = reg.histogram(
            "raft_tpu_serve_request_seconds",
            help="submit-to-complete request latency",
        )
        ids = list(request_ids) if request_ids is not None else None
        for i, lat in enumerate(latencies_s):
            # the request id rides along as a per-bucket exemplar: the
            # OpenMetrics scrape links the bucket to a flight-recorder entry
            ex = f"req-{ids[i]}" if ids is not None and i < len(ids) else None
            lat_h.observe(lat, exemplar=ex, **hist_label)
        reg.counter(
            "raft_tpu_serve_pad_waste_rows",
            help="padding rows dispatched but never asked for (bucket "
                 "minus real rows) — the pad ladder's tax; ragged "
                 "continuous admission exists to push this down",
        ).inc(max(0, bucket_rows - n_real_rows), **label)
        if stages:
            st_h = reg.histogram(
                "raft_tpu_serve_stage_seconds",
                help="per-stage serving latency (queue/pad/dispatch/device)",
            )
            for s, vals in stages.items():
                for v in vals:
                    st_h.observe(v, stage=s, **hist_label)
            queue = [float(v) for v in stages.get("queue", ())]
            if queue:
                reg.gauge(
                    "raft_tpu_serve_admit_wait_seconds",
                    help="mean submit-to-batch admission wait of the last "
                         "dispatched batch (continuous admission widens "
                         "this only while the device window is full)",
                ).set(sum(queue) / len(queue), **label)

    def record_error(self, cause: str, count: int = 1) -> None:
        """``count`` requests failed at stage ``cause`` (``"dispatch"``:
        the search callable raised; ``"device"``: the device-side
        completion raised).  Failed requests never reach
        :meth:`record_batch`, so without this the availability SLO would
        read a dead index as 100% available.  Mirrored per cause as
        ``raft_tpu_serve_errors_total{index=,cause=}``."""
        with self._lock:
            self.errors[cause] = self.errors.get(cause, 0) + int(count)
        if self.name is not None:
            obs.default_registry().counter(
                "raft_tpu_serve_errors_total",
                help="failed served requests by failure cause",
            ).inc(count, index=self.name, cause=cause)

    def record_queue_depth(self, depth: int) -> None:
        """Rows still queued at dispatch time — the health/backpressure
        signal.  Mirrored as a gauge for named instances."""
        with self._lock:
            self._queue_depth = int(depth)
        if self.name is not None:
            obs.default_registry().gauge(
                "raft_tpu_serve_queue_depth",
                help="rows waiting for dispatch at the last batch boundary",
            ).set(depth, index=self.name)

    def record_pipeline(self, depth: int, inflight: int) -> None:
        """Pipeline window state: ``depth`` is the configured bound,
        ``inflight`` the batches currently dispatched but not completed.
        The peak is retained so a concurrency test (or an operator) can
        assert the in-flight window was never overrun.  Mirrored as
        ``raft_tpu_serve_pipeline_depth`` / ``raft_tpu_serve_inflight_batches``
        gauges for named instances."""
        with self._lock:
            self._pipeline_depth = int(depth)
            self._inflight = int(inflight)
            self._inflight_peak = max(self._inflight_peak, int(inflight))
        if self.name is not None:
            reg = obs.default_registry()
            reg.gauge(
                "raft_tpu_serve_pipeline_depth",
                help="configured in-flight window bound (1 = serial dispatch)",
            ).set(depth, index=self.name)
            reg.gauge(
                "raft_tpu_serve_inflight_batches",
                help="device batches dispatched but not yet completed",
            ).set(inflight, index=self.name)

    def record_warmup(self, compiles: int) -> None:
        with self._lock:
            self.warmup_compiles += compiles

    def reset_hot_path(self) -> None:
        """Zero the hot-path recompile attribution (called after warmup)."""
        with self._lock:
            self.recompiles = 0

    # -- reading ------------------------------------------------------------
    def snapshot(self) -> Dict[str, object]:
        """One dict with the headline serving numbers (JSON-safe)."""
        with self._lock:
            lat = np.asarray(self._latencies, dtype=np.float64)
            ts = np.asarray(self._done_ts, dtype=np.float64)
            stage_arrs = {
                s: np.asarray(dq, dtype=np.float64)
                for s, dq in self._stage_lat.items()
            }
            out: Dict[str, object] = {
                "requests": self.requests,
                "batches": self.batches,
                "errors": dict(self.errors),
                "recompiles": self.recompiles,
                "warmup_compiles": self.warmup_compiles,
                "queue_depth": self._queue_depth,
                "pipeline_depth": self._pipeline_depth,
                "inflight": self._inflight,
                "inflight_peak": self._inflight_peak,
                "batch_fill": (
                    self._fill_real / self._fill_padded
                    if self._fill_padded
                    else None
                ),
                "pad_waste_rows": self._pad_waste,
                # per-capacity-bucket fill (str keys: JSON-safe)
                "bucket_fill": {
                    str(b): (f[0] / f[1] if f[1] else None)
                    for b, f in sorted(self._bucket_fill.items())
                },
                # dispatched batches per routed kernel leg (live A/B)
                "kernel_paths": dict(self._kernel_paths),
            }
        if lat.size:
            out["p50_ms"] = float(np.percentile(lat, 50) * 1e3)
            out["p99_ms"] = float(np.percentile(lat, 99) * 1e3)
            span = float(ts.max() - ts.min())
            # a single instant (or one request) has no measurable rate
            out["qps"] = float(lat.size / span) if span > 0 else None
        else:
            out["p50_ms"] = out["p99_ms"] = out["qps"] = None
        out["stages"] = {
            s: {
                "p50_ms": float(np.percentile(a, 50) * 1e3),
                "p99_ms": float(np.percentile(a, 99) * 1e3),
            }
            for s, a in stage_arrs.items()
            if a.size
        }
        return out

    def stage_totals(self) -> Dict[str, float]:
        """Sum of each stage reservoir in seconds.

        Input to the bench's device-idle-fraction estimate: the ``device``
        total approximates how long the device had work outstanding.
        Approximate once a reservoir wraps (bounded at construction), so
        benches must keep their batch count under the reservoir size for
        the number to be exact."""
        with self._lock:
            return {
                s: float(sum(dq)) for s, dq in self._stage_lat.items() if dq
            }


def timed_percentiles(latencies_s, qs=(50, 99)) -> Optional[Dict[str, float]]:
    """Helper for benches: {'p50_ms': ..., 'p99_ms': ...} or None if empty."""
    arr = np.asarray(list(latencies_s), dtype=np.float64)
    if not arr.size:
        return None
    return {f"p{q}_ms": float(np.percentile(arr, q) * 1e3) for q in qs}
