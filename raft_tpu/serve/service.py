"""SearchService: the assembled online query-serving front end.

One object wires the serve stack together: an
:class:`~raft_tpu.serve.registry.IndexRegistry` of named
:class:`~raft_tpu.serve.mutation.MutableIndex` es, one
:class:`~raft_tpu.serve.batcher.MicroBatcher` per served name (each with
its own bucket ladder + :class:`~raft_tpu.serve.metrics.ServingMetrics`),
and optionally a :class:`~raft_tpu.serve.replica.ReplicaGroup` for
query-sharded multi-chip dispatch.

The atomicity contract lives here: a batcher's ``search_fn`` resolves the
registry **once per dispatched batch**, so every row of a coalesced batch
is answered by exactly one index version — :meth:`swap` never tears a
batch, and in-flight batches pin the old version by reference until they
complete.  Swapping a same-shaped index costs zero recompiles (compiled
executables key on shapes, not weights); ``tests/test_serve.py`` pins
both properties.

Typical lifecycle::

    svc = SearchService(k=10)
    svc.add_index("wiki", MutableIndex(built), warmup=True)
    dists, ids = svc.search("wiki", query_vec)     # sync
    fut = svc.submit("wiki", query_vec)            # async, coalesced
    svc.get("wiki").upsert(new_rows)               # visible immediately
    svc.swap("wiki", MutableIndex(rebuilt))        # atomic hot-swap
    svc.stats("wiki")                              # qps/p50/p99/recompiles
    svc.stop()
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Sequence, Union

import numpy as np

from raft_tpu import obs
from raft_tpu.core import env as _env
from raft_tpu.core.trace import traced
from raft_tpu.obs import autotune as obs_autotune
from raft_tpu.obs import cost as obs_cost
from raft_tpu.obs import explain as obs_explain
from raft_tpu.obs import gateway as obs_gateway
from raft_tpu.obs import health as obs_health
from raft_tpu.obs import incidents as obs_incidents
from raft_tpu.obs import perf as obs_perf
from raft_tpu.obs import slo as obs_slo
from raft_tpu.obs import spans as obs_spans
from raft_tpu.obs.quality import QualityAuditor
from raft_tpu.serve.batcher import MicroBatcher
from raft_tpu.serve.compactor import CompactionPolicy, Compactor
from raft_tpu.serve.effort import EffortArbiter
from raft_tpu.serve.metrics import ServingMetrics, install_compile_listener
from raft_tpu.serve.mutation import MutableIndex
from raft_tpu.serve.overload import (
    AdmissionController,
    DeadlineExceeded,
    DegradedModeManager,
    HedgedDispatcher,
    OverloadConfig,
    Shed,
)
from raft_tpu.serve.ragged import FilterRegistry, RaggedSearcher, RaggedSpec
from raft_tpu.serve.registry import IndexRegistry
from raft_tpu.serve.replica import ReplicaGroup
from raft_tpu.serve.shard import ShardedIndex


class _AuditorTap:
    """Late-bound recall tap for the autotuner: reads the service's
    *current* auditor per call, so :meth:`SearchService.attach_auditor`
    takes effect on already-watched indexes."""

    def __init__(self, service: "SearchService"):
        self._service = service

    def recall_ewma(self, name: str) -> Optional[float]:
        auditor = self._service.auditor
        return auditor.recall_ewma(name) if auditor is not None else None


class SearchService:
    """Serve named mutable indexes through per-index micro-batchers."""

    def __init__(
        self,
        registry: Optional[IndexRegistry] = None,
        *,
        k: int = 10,
        min_bucket: int = 1,
        max_batch: int = 64,
        max_delay_ms: float = 2.0,
        replicas: Optional[ReplicaGroup] = None,
        start: bool = True,
        auditor: Optional[QualityAuditor] = None,
        cost_accounting: Optional[bool] = None,
        pipeline_depth: Optional[int] = None,
        compaction: Union[None, bool, CompactionPolicy, Compactor] = None,
        slo: Union[
            None, bool, Sequence[obs_slo.SloSpec], obs_slo.SloEngine
        ] = None,
        ragged: Union[None, bool, RaggedSpec] = None,
        overload: Union[None, bool, OverloadConfig] = None,
        autotune: Union[None, bool, obs_autotune.Autotuner] = None,
        gateway: Union[
            None, bool, obs_gateway.GatewayConfig,
            obs_gateway.OperationalGateway,
        ] = None,
    ):
        install_compile_listener()
        # full pipeline: XLA event attribution + span/slowlog snapshot
        # sections — the service is the component that promises "where did
        # the milliseconds go" has an answer
        obs.install()
        self.registry = registry if registry is not None else IndexRegistry()
        self.k = int(k)
        self.min_bucket = min_bucket
        self.max_batch = max_batch
        self.max_delay_ms = max_delay_ms
        self.replicas = replicas
        self.auditor = auditor
        self.cost_accounting = cost_accounting
        # None defers to the batcher's RAFT_TPU_PIPELINE_DEPTH / default;
        # 1 forces the serial dispatch path for every served index
        self.pipeline_depth = pipeline_depth
        # ragged=None: RAFT_TPU_RAGGED decides.  True: spec from env.
        # A RaggedSpec is used as-is.  When set, every added index serves
        # through one packed heterogeneous dispatch per capacity bucket —
        # per-request k (<= spec.k_max) and registered filter ids ride as
        # descriptor data, not shapes (see raft_tpu.serve.ragged).
        if ragged is None:
            ragged = _env.env_bool("RAFT_TPU_RAGGED", False)
        if ragged is True:
            ragged = RaggedSpec.from_env()
        elif ragged is False:
            ragged = None
        self.ragged: Optional[RaggedSpec] = ragged
        if self.ragged is not None and replicas is not None:
            raise NotImplementedError(
                "ragged mode and replica dispatch are mutually exclusive: "
                "the replica path has no descriptor-column leg yet"
            )
        self._filter_regs: Dict[str, Optional[FilterRegistry]] = {}
        # overload=None: RAFT_TPU_OVERLOAD decides.  True: config from
        # env.  An OverloadConfig is used as-is.  When set, every added
        # index gets an AdmissionController (priority shedding + deadline
        # expiry at batch cut, driven by queue pressure and slo_burn
        # events) and a DegradedModeManager (hysteretic search-effort
        # ladder; local dispatch only — the replica path has no params
        # leg).  Hedged priority-0 dispatch additionally needs replicas
        # and config.hedge.  Deadline-only expiry runs even without this.
        if overload is None:
            overload = _env.env_bool("RAFT_TPU_OVERLOAD", False)
        if overload is True:
            overload = OverloadConfig.from_env()
        elif overload is False:
            overload = None
        self.overload: Optional[OverloadConfig] = overload
        self._admission: Dict[str, AdmissionController] = {}
        self._degraded: Dict[str, DegradedModeManager] = {}
        self._hedgers: Dict[str, HedgedDispatcher] = {}
        # autotune=None: RAFT_TPU_AUTOTUNE decides.  True: controller
        # from env (frontier via RAFT_TPU_FRONTIER_PATH).  A prebuilt
        # Autotuner is adopted as-is (caller owns its start state).
        # Every added index gets an EffortArbiter — the single writer of
        # effective search effort: the autotuner steps its level, the
        # overload ladder clamps it, and the dispatch reads exactly one
        # arbitrated SearchParams (local dispatch only — the replica
        # path has no params leg).
        self.autotuner: Optional[obs_autotune.Autotuner] = None
        if isinstance(autotune, obs_autotune.Autotuner):
            self.autotuner = autotune
        else:
            if autotune is None:
                autotune = _env.env_bool("RAFT_TPU_AUTOTUNE", False)
            if autotune:
                self.autotuner = obs_autotune.Autotuner()
                if start:
                    self.autotuner.start()
        self._effort: Dict[str, EffortArbiter] = {}
        self._start = start
        self._lock = threading.Lock()
        self._batchers: Dict[str, MicroBatcher] = {}
        self._ks: Dict[str, int] = {}  # effective k per served name
        # compaction=None/False: no worker.  True: policy from env.  A
        # CompactionPolicy: worker with that policy.  A prebuilt Compactor
        # is adopted as-is (its own start state respected).
        self.compactor: Optional[Compactor] = None
        if isinstance(compaction, Compactor):
            self.compactor = compaction
        elif isinstance(compaction, CompactionPolicy):
            self.compactor = Compactor(self, compaction, start=start)
        elif compaction:
            self.compactor = Compactor(
                self,
                start=start and not CompactionPolicy.disabled_by_env(),
            )
        # slo=None/False: no engine.  True: default objectives added per
        # served index (watch_index on add_index).  A sequence of SloSpec:
        # engine with exactly those objectives.  A prebuilt SloEngine is
        # adopted as-is (caller owns its start state).
        self.slo_engine: Optional[obs_slo.SloEngine] = None
        self._slo_auto = False  # add default specs on add_index?
        if isinstance(slo, obs_slo.SloEngine):
            self.slo_engine = slo
        elif slo is True:
            self.slo_engine = obs_slo.SloEngine(service=self)
            self._slo_auto = True
            if start:
                self.slo_engine.start()
        elif slo:
            self.slo_engine = obs_slo.SloEngine(tuple(slo), service=self)
            if start:
                self.slo_engine.start()
        # incident timelines carry a service snapshot at open/close —
        # registry versions and queue depths, the facts an operator wants
        # next to "what fired"
        obs_incidents.default_manager().add_context_source(
            "service", self._incident_context
        )
        # gateway=None: RAFT_TPU_GATEWAY decides.  True: bind config from
        # env.  A GatewayConfig binds a fresh server; a prebuilt
        # OperationalGateway is adopted as-is (and pointed at this
        # service if it has none).  The gateway only calls the pull APIs
        # above — owning it here is lifecycle, not coupling.
        self.gateway: Optional[obs_gateway.OperationalGateway] = None
        if isinstance(gateway, obs_gateway.OperationalGateway):
            self.gateway = gateway
            if self.gateway.service is None:
                self.gateway.service = self
        elif isinstance(gateway, obs_gateway.GatewayConfig):
            self.gateway = obs_gateway.OperationalGateway(
                self, config=gateway
            )
        else:
            if gateway is None:
                gateway = _env.env_bool("RAFT_TPU_GATEWAY", False)
            if gateway:
                self.gateway = obs_gateway.OperationalGateway(self)
        if self.gateway is not None and start:
            self.gateway.start()

    # -- index management ----------------------------------------------------
    def add_index(
        self, name: str, index, *, warmup: bool = False, k: Optional[int] = None
    ) -> int:
        """Register ``index`` under ``name`` and start its batcher.

        ``index`` may be a raw built index (wrapped automatically), a
        :class:`MutableIndex`, or a
        :class:`~raft_tpu.serve.shard.ShardedIndex` (served as-is — the
        cross-shard dispatch is baked into its ``search``).  With
        ``warmup`` the whole bucket ladder is compiled before the method
        returns, so the first real query is already on the hot path.
        """
        if not isinstance(index, (MutableIndex, ShardedIndex)):
            index = MutableIndex(index)
        if (
            _env.env_bool("RAFT_TPU_PAGED", False)
            and isinstance(index, MutableIndex)
            and getattr(index.index, "paged", None) is None
        ):
            # opt-in paged serving: move the main payload behind the
            # budget-enforced page store (BudgetExceeded propagates — a
            # misconfigured budget should fail registration loudly, not
            # serve unpaged silently); structurally unpageable indexes
            # (VPQ datasets, unknown kinds) keep the monolithic layout
            from raft_tpu.store import paginate_index

            try:
                paginate_index(index.index, name=name)
            except ValueError:
                pass
        k = self.k if k is None else int(k)
        if self.ragged is not None and k > self.ragged.k_max:
            raise ValueError(
                f"default k={k} exceeds the ragged spec's k_max="
                f"{self.ragged.k_max}"
            )
        version = self.registry.register(name, index)
        admission = degraded = hedger = effort = None
        if self.overload is not None:
            admission = AdmissionController(self.overload, name=name)
            if self.replicas is None:
                # degraded-mode search threads reduced-effort params into
                # the local dispatch; the replica path has no params leg
                degraded = DegradedModeManager(self.overload, name=name)
            if self.overload.hedge and self.replicas is not None:
                hedger = HedgedDispatcher(
                    self.replicas.member_searchers(name, k),
                    self.overload, name=name,
                )
        if self.replicas is None and (
            degraded is not None or self.autotuner is not None
        ):
            # the single effort-arbitration point: the dispatch reads
            # effective params from here (degraded shed level = clamp,
            # autotuner = writer); plain services skip it entirely
            effort = EffortArbiter(degraded, name=name)
        with self._lock:
            self._ks[name] = k
            old = self._batchers.pop(name, None)
            old_admission = self._admission.pop(name, None)
            self._degraded.pop(name, None)
            self._hedgers.pop(name, None)
            self._effort.pop(name, None)
            if admission is not None:
                self._admission[name] = admission
            if degraded is not None:
                self._degraded[name] = degraded
            if hedger is not None:
                self._hedgers[name] = hedger
            if effort is not None:
                self._effort[name] = effort
            if self.ragged is not None:
                freg = None
                if self.ragged.filters and isinstance(index, MutableIndex):
                    # filter id space: the main index's global ids.  Side
                    # rows upserted later get ids past this range and pass
                    # every filter (uncovered = unconstrained).
                    freg = FilterRegistry(max(1, index.main_size))
                elif self.ragged.filters and isinstance(index, ShardedIndex):
                    # sharded layouts carry dense global row ids; the
                    # packed predicate table replicates to every shard and
                    # ShardedIndex.search rebases it per shard
                    freg = FilterRegistry(max(1, index.size))
                self._filter_regs[name] = freg
                search_fn = RaggedSearcher(
                    self, name, self.ragged, freg, degraded=degraded,
                    effort=effort,
                )
            else:
                search_fn = self._make_search_fn(name, k)
            batcher = MicroBatcher(
                search_fn,
                index.dim,
                min_bucket=self.min_bucket,
                max_batch=self.max_batch,
                max_delay_ms=self.max_delay_ms,
                metrics=ServingMetrics(name=name),
                start=self._start,
                observer=self._make_observer(name),
                cost_accounting=self.cost_accounting,
                pipeline_depth=self.pipeline_depth,
                ragged=self.ragged,
                admission=admission,
                degraded=degraded,
                hedger=hedger,
                effort=effort,
                perf_meta=self._make_perf_meta(name),
            )
            self._batchers[name] = batcher
        if old is not None:
            old.stop()
        if old_admission is not None:
            old_admission.close()
        if self.slo_engine is not None and self._slo_auto and old is None:
            self.slo_engine.watch_index(name)
        if self.autotuner is not None and effort is not None:
            self.autotuner.watch_index(
                name, effort, index=index,
                auditor=_AuditorTap(self),
                slo=self.slo_engine,
                perf=obs_perf.default_ledger(),
            )
        if warmup:
            batcher.warmup()
        return version

    def effort_arbiter(self, name: str) -> Optional[EffortArbiter]:
        """The index's effort-arbitration point (None: plain service with
        neither overload degraded mode nor an autotuner)."""
        with self._lock:
            return self._effort.get(name)

    def _make_search_fn(self, name: str, k: int):
        def search_fn(queries):
            # resolve once per BATCH: the whole padded batch is answered
            # by one index version (hot-swap atomicity boundary)
            index, _version = self.registry.get_versioned(name)
            if self.replicas is not None:
                return self.replicas.search(name, queries, k)
            arb = self._effort.get(name)
            if arb is not None and isinstance(index, MutableIndex):
                params = arb.apply(index)
                if params is not None:
                    # arbitrated reduced-effort params (autotuner level
                    # clamped by the overload ladder); warmed per level
                    # by the batcher's level-pinned warmup
                    return index.search(queries, k, search_params=params)
            return index.search(queries, k)

        return search_fn

    def _make_perf_meta(self, name: str):
        """``(backend, version)`` supplier for the perf ledger's
        executable key.  Resolved per dispatch, so a hot-swap
        re-attributes device time to the successor kind/version from its
        first batch — the ledger's A/B story survives swaps."""

        def perf_meta():
            try:
                index, version = self.registry.get_versioned(name)
            except KeyError:  # removed mid-flight
                return ("unknown", "0")
            return (getattr(index, "kind", "unknown") or "unknown",
                    str(version))

        return perf_meta

    def _make_observer(self, name: str):
        """Batcher observer feeding the quality auditor, if any.

        Reads ``self.auditor`` per call so :meth:`attach_auditor` takes
        effect on already-running batchers.  The (index, version) pair is
        resolved here, right after the dispatch — a swap racing between
        the dispatch and the observation can attribute one audited batch
        to the successor version, which the auditor's per-version EWMA
        reset absorbs.
        """

        def observer(queries, dists, ids):
            auditor = self.auditor
            if auditor is None:
                return
            index, version = self.registry.get_versioned(name)
            auditor.observe(name, version, index, queries, ids)

        return observer

    def attach_auditor(self, auditor: Optional[QualityAuditor]) -> None:
        """Install (or remove, with ``None``) the online recall auditor.

        Existing batchers pick it up immediately — their observer closures
        read ``self.auditor`` at call time.
        """
        self.auditor = auditor

    @traced("serve.swap")
    def swap(self, name: str, index) -> int:
        """Atomically replace the index behind ``name`` (see module doc).

        The existing batcher (and its warmed executables) is kept: a
        same-shaped replacement serves its next batch with no recompile.
        A :class:`~raft_tpu.serve.shard.ShardedIndex` swaps in unwrapped —
        replicated → sharded layout changes are atomic the same way.
        """
        if not isinstance(index, (MutableIndex, ShardedIndex)):
            index = MutableIndex(index)
        with self._lock:
            if name not in self._batchers:
                raise KeyError(f"no served index named {name!r}")
            if index.dim != self._batchers[name].dim:
                raise ValueError(
                    f"swap dim mismatch for {name!r}: "
                    f"{index.dim} != {self._batchers[name].dim}"
                )
        return self.registry.swap(name, index)

    def get(self, name: str) -> MutableIndex:
        """The live index (for upsert/delete — visible to the next batch)."""
        return self.registry.get(name)

    def register_filter(self, name: str, mask) -> int:
        """Register a sample filter for ragged serving; returns its fid.

        ``mask`` is a bool array (or :class:`~raft_tpu.core.bitset.Bitset`)
        over ``name``'s global id space; requests pass the returned fid to
        :meth:`submit`/:meth:`search`.  Register before :meth:`warmup` —
        the table gather is host-side so registration never changes an XLA
        trace, but cagra's pinned search width and the fused Pallas leg
        key on filter-derived host values and would spend one compile per
        bucket on the next dispatch (reported as ``hot_recompile``).
        """
        if self.ragged is None:
            raise RuntimeError(
                "register_filter needs SearchService(ragged=...)"
            )
        with self._lock:
            freg = self._filter_regs.get(name)
        if freg is None:
            raise RuntimeError(
                f"no filter registry for {name!r}: the index kind is not "
                "filterable or the spec has filters=False"
            )
        return freg.register(mask)

    def remove_index(self, name: str) -> None:
        with self._lock:
            batcher = self._batchers.pop(name)
            self._ks.pop(name, None)
            self._filter_regs.pop(name, None)
            admission = self._admission.pop(name, None)
            self._degraded.pop(name, None)
            self._hedgers.pop(name, None)
            self._effort.pop(name, None)
        batcher.stop()
        if admission is not None:
            admission.close()
        self.registry.unregister(name)
        if self.slo_engine is not None and self._slo_auto:
            self.slo_engine.unwatch_index(name)
        if self.autotuner is not None:
            self.autotuner.unwatch_index(name)
        # retire the index's archived plans + explain metric series (the
        # same stale-series hygiene the SLO/autotune unwatch paths follow)
        obs_explain.default_archive().unwatch_index(name)

    def names(self):
        return self.registry.names()

    # -- querying ------------------------------------------------------------
    def _batcher(self, name: str) -> MicroBatcher:
        with self._lock:
            return self._batchers[name]

    def _ragged_args(self, name: str, k: Optional[int], fid: Optional[int]):
        """Validate and default the per-request ragged descriptor."""
        if self.ragged is None:
            if k is not None or fid is not None:
                raise ValueError(
                    "per-request k/fid need SearchService(ragged=...)"
                )
            return None, None
        if k is None:
            with self._lock:
                k = self._ks[name]
        if fid is not None and fid != 0:
            with self._lock:
                freg = self._filter_regs.get(name)
            if freg is None or not freg.contains(fid):
                raise ValueError(
                    f"fid {fid} is not registered for {name!r} "
                    "(register_filter returns valid fids)"
                )
        return k, fid

    def submit(self, name: str, queries, *, k: Optional[int] = None,
               fid: Optional[int] = None,
               priority: Optional[int] = None,
               deadline_s: Optional[float] = None):
        """Async search; returns a Future of (distances, ids).

        Ragged mode only: ``k`` (defaults to the index's configured k,
        ceiling ``spec.k_max``) and ``fid`` (a :meth:`register_filter`
        handle; 0/None = unfiltered) shape THIS request inside the packed
        batch — heterogeneous mixes coalesce into one dispatch.

        Any mode: ``priority`` (0=interactive … 3=background, default 1)
        and ``deadline_s`` (server-side budget from now) ride as request
        metadata — under overload the admission controller sheds the
        lowest priorities first and expired requests never reach the
        device; their futures resolve with the typed
        :class:`~raft_tpu.serve.overload.Shed` /
        :class:`~raft_tpu.serve.overload.DeadlineExceeded` errors.
        """
        k, fid = self._ragged_args(name, k, fid)
        return self._batcher(name).submit(
            queries, k=k, fid=fid, priority=priority, deadline_s=deadline_s
        )

    @traced("serve.search")
    def search(self, name: str, queries, timeout: Optional[float] = None,
               *, k: Optional[int] = None, fid: Optional[int] = None,
               priority: Optional[int] = None,
               deadline_s: Optional[float] = None):
        """Sync search through the batcher (coalesces with live traffic).

        ``timeout`` doubles as the server-side deadline when
        ``deadline_s`` is not given — a request its caller has stopped
        waiting for is dropped at the next batch cut instead of running
        on device."""
        k, fid = self._ragged_args(name, k, fid)
        return self._batcher(name).search(
            queries, timeout=timeout, k=k, fid=fid,
            priority=priority, deadline_s=deadline_s,
        )

    @traced("serve.explain")
    def explain(self, name: str, queries, *, k: Optional[int] = None,
                fid: Optional[int] = None, priority: Optional[int] = None,
                deadline_s: Optional[float] = None,
                timeout: Optional[float] = None) -> obs_explain.ExplainPlan:
        """EXPLAIN ANALYZE one real request; returns its
        :class:`~raft_tpu.obs.explain.ExplainPlan`.

        The request runs through the **normal** batched path — it
        coalesces with live traffic and is answered by the same
        executables, so the plan describes production behaviour, not a
        simulation.  The plan joins the enriched flight-recorder batch
        record (admission pressure, arbitrated effort level and its
        source, capacity bucket, kernel path, page-cache interaction,
        stage timeline) with a few deep-only host-side probes taken
        *after* the dispatch completes: a coarse-probe replay for the
        IVF kinds, per-shard contribution counts for a
        :class:`~raft_tpu.serve.shard.ShardedIndex`, and the recall
        auditor's verdict.  Works without ``RAFT_TPU_EXPLAIN`` — the
        gate is forced open for this request only — but needs the
        observability pipeline on.  A shed or deadline-expired request
        still yields a plan (its admission section says why it never
        reached the device).
        """
        if not obs_spans.enabled():
            raise RuntimeError(
                "SearchService.explain needs the observability pipeline "
                "on (RAFT_TPU_OBS=1 or obs.enable())"
            )
        k, fid = self._ragged_args(name, k, fid)
        batcher = self._batcher(name)
        archive = obs_explain.default_archive()
        outcome, error, result = "ok", None, None
        with obs_explain.deep_scope():
            fut = batcher.submit(
                queries, k=k, fid=fid, priority=priority,
                deadline_s=deadline_s,
            )
            req_id = fut.request_id
            archive.watch(req_id)
            try:
                try:
                    result = fut.result(timeout)
                except Shed as exc:
                    outcome, error = "shed", exc
                except DeadlineExceeded as exc:
                    outcome, error = "deadline_expired", exc
                except Exception as exc:  # noqa: BLE001 — reported in plan
                    outcome, error = "error", exc
                # the archive entry lands on the completion thread right
                # after the future resolves; poll briefly for it
                entry = archive.find(req_id)
                give_up = time.monotonic() + 2.0
                while entry is None and time.monotonic() < give_up:
                    time.sleep(0.001)
                    entry = archive.find(req_id)
            finally:
                archive.unwatch(req_id)
        if entry is None:
            # record never landed (obs raced off mid-flight): degrade to
            # a minimal plan — an operator entry point must not raise here
            sections: Dict[str, object] = {
                "request": {"id": req_id},
                "outcome": {"outcome": outcome, "error": None,
                            "sampled_reason": "deep"},
                "available": False,
            }
        else:
            sections = entry["plan"]
        if outcome != "ok":
            sections["outcome"] = {
                **(sections.get("outcome") or {}),
                "outcome": outcome,
                "error": repr(error),
            }
        self._explain_deep_sections(name, queries, sections, result)
        return obs_explain.ExplainPlan(sections)

    def _explain_deep_sections(self, name, queries, sections, result):
        """Append the deep-only plan sections: coarse-probe replay,
        shard contributions, audit verdict, result payload.  Host-side
        and off the hot path by construction — the dispatch already
        completed, so the host pulls here stall nothing."""
        try:
            index, version = self.registry.get_versioned(name)
        except KeyError:  # removed mid-explain
            return
        sections.setdefault("bucket", {})["version"] = version
        if isinstance(index, MutableIndex) and index.kind in (
            "ivf_flat", "ivf_pq"
        ):
            prev = sections.get("probe")
            try:
                info = self._probe_replay(name, index, queries)
            except Exception as exc:  # noqa: BLE001 — section degrades
                info = {"available": False, "error": repr(exc)}
            if isinstance(prev, dict) and prev.get("params"):
                info.setdefault("params", prev["params"])
            sections["probe"] = info
        from raft_tpu.serve.shard import ShardedIndex as _Sharded

        if isinstance(index, _Sharded) and result is not None:
            info = index.explain_contributions(np.asarray(result[1]))
            if getattr(index, "graph_mode", False):
                # graph-mode CAGRA: per-shard hop/halo accounting from an
                # exchange-free traversal replay of this query batch
                try:
                    info["traversal"] = index.explain_traversal(queries)
                except Exception as exc:  # noqa: BLE001 — section degrades
                    info["traversal"] = {
                        "available": False, "error": repr(exc)
                    }
            sections["shards"] = info
        auditor = self.auditor
        if auditor is not None:
            ewma = auditor.recall_ewma(name)
            threshold = auditor.threshold
            sections["audit"] = {
                "recall_ewma": ewma,
                "threshold": threshold,
                "verdict": (
                    "unaudited" if ewma is None
                    else "ok" if ewma >= threshold else "below_threshold"
                ),
            }
        else:
            sections["audit"] = {"available": False}
        if result is not None:
            dists, ids = result
            sections["results"] = {
                "ids": np.asarray(ids).tolist(),
                "distances": [
                    round(float(v), 6)
                    for v in np.asarray(dists, dtype=np.float64).reshape(-1)
                ],
            }

    def _probe_replay(self, name, index, queries):
        """Re-run the coarse pass host-side for one explained request:
        same math the search executable re-derives in-trace
        (deterministic — both agree), so the probed list ids and their
        candidate counts can be reported without adding outputs to the
        warmed executables (which would change shapes and recompile)."""
        from raft_tpu.neighbors._common import coarse_select

        base = index.index
        params = None
        with self._lock:
            arb = self._effort.get(name)
        if arb is not None:
            # the same arbitrated effort params the dispatch read
            params = arb.apply(index)
        if params is None:
            params = index.search_params
        centers = base.centers
        n_lists = int(centers.shape[0])
        n_probes = int(getattr(params, "n_probes", 0) or 0)
        n_probes = max(1, min(n_probes or n_lists, n_lists))
        q = np.asarray(queries, np.float32)
        if q.ndim == 1:
            q = q[None, :]
        probes = np.asarray(
            coarse_select(q, centers, index.metric, n_probes)
        )
        sizes = np.asarray(base.list_sizes)
        probed = np.unique(probes.reshape(-1))
        total = float(sizes.sum())
        return {
            "n_probes": n_probes,
            "n_lists": n_lists,
            "probed_lists": [int(p) for p in probed],
            "candidates": int(sizes[probes.reshape(-1)].sum()),
            "coverage": round(
                float(sizes[probed].sum()) / total, 4
            ) if total > 0 else None,
        }

    @traced("serve.warmup")
    def warmup(self, name: Optional[str] = None) -> int:
        """Compile the bucket ladder(s); returns total compiles spent."""
        names = [name] if name is not None else self.names()
        return sum(self._batcher(n).warmup() for n in names)

    @traced("serve.flush")
    def flush(self, name: Optional[str] = None) -> int:
        """Dispatch everything queued for ``name`` (or all indexes).

        Routed through each batcher's pipeline: returns only after the
        flushed batches have resolved their futures, and a flush racing
        in-flight traffic cannot reorder result delivery."""
        names = [name] if name is not None else self.names()
        return sum(self._batcher(n).flush() for n in names)

    # -- compaction ----------------------------------------------------------
    def compact_now(self, name: str) -> Dict[str, object]:
        """Run one synchronous compaction pass for ``name``, bypassing the
        policy thresholds and any abort cooldown (operator escape hatch).
        Requires the service to own a compactor (``compaction=`` knob)."""
        if self.compactor is None:
            raise RuntimeError(
                "no compactor attached; construct the service with "
                "compaction=True (or a CompactionPolicy)"
            )
        return self.compactor.trigger_now(name)

    def pause_compaction(self) -> None:
        """Suspend automatic compaction triggering (a running pass
        finishes; :meth:`compact_now` still works)."""
        if self.compactor is not None:
            self.compactor.pause()

    def resume_compaction(self) -> None:
        if self.compactor is not None:
            self.compactor.resume()

    def drain_compaction(self, timeout: Optional[float] = None) -> bool:
        """Block until no compaction pass is in flight; True on success
        (vacuously so when no compactor is attached)."""
        if self.compactor is None:
            return True
        return self.compactor.drain(timeout=timeout)

    # -- observability -------------------------------------------------------
    def stats(self, name: str) -> Dict[str, object]:
        """Metrics snapshot + index version/size for one served name.

        Includes the per-stage latency breakdown under ``stages`` —
        queue-wait / pad / dispatch / device p50+p99 — so a p99 excursion
        decomposes without a profiler session.
        """
        index, version = self.registry.get_versioned(name)
        out = self._batcher(name).metrics.snapshot()
        deleted, side = index.pending_mutations()
        out.update(
            name=name,
            version=version,
            kind=index.kind,
            size=index.size,
            pending_deletes=deleted,
            side_rows=side,
        )
        ctrl = self._admission.get(name)
        if ctrl is not None:
            out.update(
                admission_level=ctrl.last_level,
                shed_requests=ctrl.shed_total,
                deadline_expired=ctrl.expired_total,
            )
        mgr = self._degraded.get(name)
        if mgr is not None:
            out["degraded_level"] = mgr.level
        arb = self._effort.get(name)
        if arb is not None:
            out.update(
                autotune_level=arb.autotune_level,
                effective_effort_level=arb.effective_level(),
            )
        hedger = self._hedgers.get(name)
        if hedger is not None:
            out.update(
                hedges_fired=hedger.fired_total,
                hedge_wins=hedger.hedge_wins,
            )
        return out

    def _refresh_capacity_gauges(self) -> None:
        """Re-derive the per-version live-buffer gauges from the registry's
        weak version history.  Gauges are pull-refreshed (not provider-fed)
        because ``to_prometheus()`` does not run providers — every export
        path below calls this first."""
        try:
            obs_cost.refresh_live_buffer_gauges(self.registry)
        except Exception:  # capacity accounting must never break serving
            pass
        try:
            obs_cost.refresh_mutation_gauges(self.registry)
        except Exception:  # mutation pressure gauges likewise
            pass
        try:
            obs_cost.refresh_page_gauges(self.registry)
        except Exception:  # page-residency gauges likewise
            pass
        try:
            # wasted-time fraction + measured roofline utilization per
            # executable key — pull-refreshed on the same scrape path
            obs_perf.default_ledger().refresh_gauges()
        except Exception:  # perf accounting must never break serving
            pass

    def _incident_context(self) -> Dict[str, object]:
        """Snapshot attached to incident timelines at open/close.

        Deliberately lock-light: registry versions and queue depths only —
        no index or compactor internals, so a context capture triggered by
        a publish from inside the serve stack cannot re-enter the lock the
        publisher holds."""
        indexes: Dict[str, object] = {}
        for name in self.registry.names():
            try:
                _index, version = self.registry.get_versioned(name)
            except KeyError:  # removed between names() and here
                continue
            entry: Dict[str, object] = {"version": version}
            try:
                entry["queue_depth"] = self._batcher(name).queue_depth()
            except KeyError:
                pass
            indexes[name] = entry
        ctx: Dict[str, object] = {"indexes": indexes}
        if self.slo_engine is not None:
            ctx["slo"] = self.slo_engine.health()
        if self.autotuner is not None:
            ctx["autotune"] = self.autotuner.health()
        return ctx

    def healthz(self) -> Dict[str, object]:
        """Aggregated health verdict: OK / DEGRADED / UNHEALTHY.

        One :class:`raft_tpu.obs.health.IndexProbe` per served name —
        warmup state, hot-path recompiles, queue depth vs capacity, the
        pipeline's in-flight window occupancy vs its ``pipeline_depth``
        bound (also scrapeable as ``raft_tpu_serve_pipeline_depth`` /
        ``raft_tpu_serve_inflight_batches``), and the auditor's recall
        EWMA when an auditor is attached — folded
        with the device-memory headroom check by
        :func:`raft_tpu.obs.health.build_report`.  Also publishes the
        ``raft_tpu_health`` gauge (0=OK, 1=DEGRADED, 2=UNHEALTHY) so the
        verdict is scrapeable.

        A transition *into* UNHEALTHY auto-dumps the flight recorder
        (debounced), and the report's ``flight`` key carries the latest
        dump's JSON + Chrome-trace paths — the payload that announces the
        incident also says where the evidence landed.

        With an SLO engine attached (``slo=`` knob) the report also folds
        in the error-budget check: an exhausted budget is DEGRADED, and
        the detail names the offending objectives under ``slo``.

        The measured perf ledger folds in the same way: an executable key
        inside its regression-debounce window (a live ``perf_regression``)
        reports DEGRADED under the report's ``perf`` key.
        """
        self._refresh_capacity_gauges()
        auditor = self.auditor
        pinned_min = (
            set(self.autotuner.health().get("pinned_min_effort", ()))
            if self.autotuner is not None else set()
        )
        probes: Dict[str, obs_health.IndexProbe] = {}
        for name in self.names():
            try:
                b = self._batcher(name)
            except KeyError:  # removed between names() and here
                continue
            compaction: Dict[str, object] = {}
            if self.compactor is not None:
                try:
                    compaction = self.compactor.stats(name)
                except Exception:
                    compaction = {}
            last_abort = compaction.get("last_abort")
            ctrl = self._admission.get(name)
            mgr = self._degraded.get(name)
            probes[name] = obs_health.IndexProbe(
                warm=b.warm,
                recompiles=b.metrics.recompiles,
                queue_depth=b.queue_depth(),
                max_batch=b.max_batch,
                pipeline_depth=b.pipeline_depth,
                inflight=b.inflight,
                admission_level=(
                    ctrl.last_level if ctrl is not None else None
                ),
                degraded_level=mgr.level if mgr is not None else None,
                autotune_level=(
                    self._effort[name].autotune_level
                    if self.autotuner is not None and name in self._effort
                    else None
                ),
                autotune_pinned_min=name in pinned_min,
                recall_ewma=(
                    auditor.recall_ewma(name) if auditor is not None else None
                ),
                recall_threshold=(
                    auditor.threshold if auditor is not None else None
                ),
                compaction_backlog=compaction.get("backlog"),
                compaction_trigger=compaction.get("trigger"),
                compaction_last_abort=(
                    str(last_abort.get("reason", "unknown"))
                    if isinstance(last_abort, dict)
                    else None
                ),
            )
        from raft_tpu.store.budget import default_budget

        page_budget = default_budget()
        return obs_health.build_report(
            probes,
            registry=obs.default_registry(),
            slo=(
                self.slo_engine.health()
                if self.slo_engine is not None else None
            ),
            perf=obs_perf.default_ledger().health_slice(),
            budget=(
                page_budget.snapshot() if page_budget is not None else None
            ),
        )

    def readyz(self) -> Dict[str, object]:
        """Readiness: every served index warmed (bucket ladder compiled).

        Unlike :meth:`healthz` this is a gate, not a diagnosis — a load
        balancer should withhold traffic until ``ready`` is true, then
        switch to ``healthz`` for liveness.
        """
        warm = {n: self._batcher(n).warm for n in self.names()}
        return {"ready": bool(warm) and all(warm.values()), "indexes": warm}

    def metrics(self) -> Dict[str, object]:
        """The whole observability picture in one JSON-safe dict.

        ``indexes`` holds each served name's :meth:`stats` (request p50/p99
        + per-stage breakdown); ``registry`` is the process-wide
        :func:`raft_tpu.obs.snapshot` — span histograms, XLA compile events
        attributed to the span that caused them, cache hit/miss counts,
        the slow-query log, and each index's ``serve.<name>`` section;
        ``health`` is the :meth:`healthz` report.
        """
        out = {
            "indexes": {n: self.stats(n) for n in self.names()},
            "health": self.healthz(),
            "registry": obs.snapshot(),
            # measured perf ledger, surfaced at the top level too (it also
            # rides registry["perf"]): hotspot ranking + regression state
            "perf": obs_perf.default_ledger().snapshot(),
        }
        if self.slo_engine is not None:
            out["slo"] = self.slo_engine.snapshot()
        return out

    def prometheus(self) -> str:
        """The process metrics registry in Prometheus text format.

        Refreshes the pull-style gauges first (live-buffer bytes per index
        version, ``raft_tpu_health``) — the exporter itself never runs
        providers, so the refresh has to happen on the scrape path.
        """
        try:
            self.healthz()  # publishes raft_tpu_health + capacity gauges
        except Exception:
            pass
        return obs.to_prometheus()

    def openmetrics(self) -> str:
        """The registry as OpenMetrics text, exemplars included.

        Same refresh path as :meth:`prometheus`; serve this form to
        scrapers that negotiate ``application/openmetrics-text`` — each
        latency bucket's retained request-id exemplar links it to the
        matching flight-recorder timeline (see :meth:`healthz`'s
        ``flight`` key for the latest dump location).
        """
        try:
            self.healthz()
        except Exception:
            pass
        return obs.to_openmetrics()

    # -- lifecycle -----------------------------------------------------------
    def stop(self) -> None:
        # gateway first: stop answering external probes and admin verbs
        # before the subsystems they read start going down (a scrape
        # mid-teardown would race half-stopped state)
        if self.gateway is not None:
            self.gateway.close()
        # autotuner before the SLO engine: its ticks read slo health
        if self.autotuner is not None:
            self.autotuner.stop()
        if self.slo_engine is not None:
            self.slo_engine.stop()
        try:
            obs_incidents.default_manager().remove_context_source("service")
        except Exception:  # bus already reset (test teardown ordering)
            pass
        # compactor first: a pass mid-flight may still submit warmup work
        # through the batchers it is about to go down with
        if self.compactor is not None:
            self.compactor.stop()
        with self._lock:
            batchers = list(self._batchers.values())
            controllers = list(self._admission.values())
        for b in batchers:
            b.stop()
        # after the batchers: a draining batch may still cut through the
        # admission path, which wants its burn latch live
        for ctrl in controllers:
            ctrl.close()

    def __enter__(self) -> "SearchService":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()
